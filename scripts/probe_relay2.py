#!/usr/bin/env python
"""Second-round link probes: sustained H2D drain rate and true device compute.

The relay buffers H2D writes and defers execution; wall-clock truth only
appears when a D2H read forces a drain. So:

- sustained_drain: push ~1 GB of device_puts, then read one tiny value; total
  bytes / total wall time = the link's REAL sustained rate (the recycle-mode
  throughput ceiling).
- resnet_compute_true: upload one batch, dispatch N forwards, read one tiny
  output: wall ~= N * compute, bounding per-batch device time.

Run: python scripts/probe_relay2.py  (each experiment in its own process)
"""

from __future__ import annotations

import json
import subprocess
import sys
import textwrap

EXPERIMENTS = {
    "sustained_drain": """
        import time, json
        import numpy as np, jax, jax.numpy as jnp
        mb, iters = 32, 32   # ~1 GB total
        arr = np.random.default_rng(0).integers(0, 255, (mb << 20,), np.uint8)
        t0 = time.perf_counter()
        devs = []
        for i in range(iters):
            devs.append(jax.device_put(arr))
        jax.block_until_ready(devs)
        t_buffered = time.perf_counter() - t0
        s = jnp.sum(devs[-1][:8].astype(jnp.int32))  # tiny dependent read
        val = int(s)  # forces full drain
        t_total = time.perf_counter() - t0
        print(json.dumps({"exp": "sustained_drain", "mb_total": mb * iters,
                          "buffered_s": round(t_buffered, 2),
                          "total_s": round(t_total, 2),
                          "real_mbps": round(mb * iters / t_total, 1)}))
    """,
    "resnet_compute_true": """
        import time, json
        import numpy as np, jax
        from tpuserve.config import ModelConfig
        from tpuserve.models import build
        from tpuserve.runtime import build_runtime
        B, N = 128, 30
        cfg = ModelConfig(name="r", family="resnet50", batch_buckets=[B],
                          parallelism="single", dtype="bfloat16", wire_size=224)
        model = build(cfg)
        rt = build_runtime(model)
        batch = np.random.default_rng(0).integers(0, 255, (B, 224, 224, 3), np.uint8)
        exe = rt.executables[(B,)][0]
        sh = jax.tree_util.tree_leaves(exe.batch_sharding)[0]
        dev = jax.device_put(batch, sh)
        # settle the pipeline: one forward + tiny read
        out = exe.compiled(rt.params_per_mesh[0], dev)
        float(np.asarray(out["probs"])[0, 0])
        t0 = time.perf_counter()
        for _ in range(N):
            out = exe.compiled(rt.params_per_mesh[0], dev)
        float(np.asarray(out["probs"])[0, 0])  # tiny read drains the chain
        dt = time.perf_counter() - t0
        per_batch_ms = dt / N * 1e3
        print(json.dumps({"exp": "resnet_compute_true", "batch": B, "n": N,
                          "per_batch_ms": round(per_batch_ms, 2),
                          "imgs_per_s_compute": round(B / (per_batch_ms / 1e3), 1)}))
    """,
}


def main() -> int:
    for name, code in EXPERIMENTS.items():
        proc = subprocess.run(
            [sys.executable, "-c", textwrap.dedent(code)],
            capture_output=True, text=True, timeout=2400, cwd="/root/repo",
        )
        line = proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() else ""
        try:
            print(line if line.startswith("{") else json.dumps(
                {"exp": name, "error": proc.stderr[-1500:]}), flush=True)
        except Exception:
            pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
