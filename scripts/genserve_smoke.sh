#!/usr/bin/env bash
# Generation-engine smoke (ISSUE 9): a short closed loop of MIXED-length
# generative load through the REAL server on the CPU backend proving the
# iteration-level engine end to end:
#   1. zero errors under sustained mixed-length prompt load;
#   2. the continuous-batching counters move: gen_early_exits_total > 0
#      (short sequences retire while longer ones keep running) and
#      gen_fold_ins_total > 0 (queued requests join a mid-flight block);
#   3. steady state recompiles NOTHING: the runtime_compiles_total delta
#      across warm load + a :reload publish (which runs the engine's
#      staged canary — a short real generation) is exactly 0;
#   4. the /stats genserve block is well-formed and the slot ledger is
#      exactly balanced after drain (active 0, free = slots).
# Run by CI next to the chaos/reload/pipeline/cache/roofline drills; see
# docs/PERFORMANCE.md "The generation engine".
set -euo pipefail
cd "$(dirname "$0")/.." || exit 1
export JAX_PLATFORMS=cpu
# Race-detection pass rides along (docs/ANALYSIS.md): the engine's step
# loop is deliberately lock-free (event-loop-only state), so the witness
# proves no stage-executor path holds a lock across an await either.
export TPUSERVE_LOCK_WITNESS=1
export TPUSERVE_RETRACE_WITNESS=1

python - <<'EOF'
import asyncio

import aiohttp
from aiohttp import web

from tpuserve.bench.loadgen import run_load, synthetic_prompt_pool
from tpuserve.config import GenserveConfig, ModelConfig, ServerConfig
from tpuserve.server import ServerState, make_app

cfg = ServerConfig(
    decode_threads=2,
    startup_canary=False,
    genserve=GenserveConfig(enabled=True, slots=4),
    models=[ModelConfig(
        name="textgen", family="textgen", batch_buckets=[1, 2, 4],
        dtype="float32", parallelism="single",
        request_timeout_ms=60_000.0,
        options=dict(layers=1, d_model=64, heads=2, d_ff=128,
                     vocab_size=512, prompt_len=16, max_new_tokens=32),
    )],
)


async def scrape(base: str, session) -> tuple[dict, dict]:
    async with session.get(f"{base}/metrics") as r:
        text = await r.text()
    metrics = {}
    for line in text.splitlines():
        if line.startswith("#") or " " not in line:
            continue
        k, v = line.rsplit(" ", 1)
        try:
            metrics[k] = float(v)
        except ValueError:
            pass
    async with session.get(f"{base}/stats") as r:
        stats = await r.json()
    return metrics, stats


async def main() -> None:
    state = ServerState(cfg)
    state.build()
    runner = web.AppRunner(make_app(state), access_log=None)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    base = f"http://127.0.0.1:{runner.addresses[0][1]}"
    # MIXED output lengths are the point: short completions must exit
    # early past long ones for the engine counters to move.
    pool = synthetic_prompt_pool(32, max_new=(2, 32))
    url = f"{base}/v1/models/textgen:generate"
    try:
        res = await run_load(url, pool, "application/json",
                             duration_s=2.0, warmup_s=0.5, concurrency=8)
        assert res.n_err == 0 and res.n_ok > 0, res.summary()
        async with aiohttp.ClientSession() as s:
            m0, _ = await scrape(base, s)
            res2 = await run_load(url, pool, "application/json",
                                  duration_s=2.0, warmup_s=0.0,
                                  concurrency=8)
            assert res2.n_err == 0 and res2.n_ok > 0, res2.summary()
            # Reload mid-steady-state: the engine's staged canary runs a
            # short REAL generation against the candidate, and the publish
            # must not compile anything.
            async with s.post(f"{base}/admin/models/textgen:reload") as r:
                body = await r.json()
                assert r.status == 200, body
                assert body["canary_ok"] is True, body
            res3 = await run_load(url, pool, "application/json",
                                  duration_s=1.0, warmup_s=0.0,
                                  concurrency=8)
            assert res3.n_err == 0 and res3.n_ok > 0, res3.summary()
            m1, stats = await scrape(base, s)

        key = 'runtime_compiles_total{model="textgen"}'
        assert m0.get(key, 0) >= 3, f"gen programs not registered: {m0}"
        delta = m1.get(key, 0) - m0.get(key, 0)
        assert delta == 0, f"steady state recompiled: delta={delta}"
        early = m1.get('gen_early_exits_total{model="textgen"}', 0)
        folds = m1.get('gen_fold_ins_total{model="textgen"}', 0)
        iters = m1.get('gen_iterations_total{model="textgen"}', 0)
        assert early > 0, f"no early exits under mixed lengths: {m1}"
        assert folds > 0, f"no mid-flight fold-ins: {m1}"
        assert iters > 0
        gs = stats["genserve"]["textgen"]
        assert gs["mode"] == "genserve" and gs["slots"] == 4, gs
        assert gs["active"] == 0 and gs["free"] == 4, gs  # ledger balanced
        assert gs["step_ewma_ms"] and gs["step_ewma_ms"] > 0, gs
        served = [v for k, v in m1.items()
                  if k.startswith("runtime_variant_batches_total") and v > 0]
        assert served, f"no gen program serving counters moved: {m1}"
        # Retrace witness (TPUSERVE_RETRACE_WITNESS=1): armed, barrier
        # declared, zero violations — a post-barrier compile or unblessed
        # device->host fetch would have raised mid-load, not just here.
        rw = stats["robustness"]["retrace_witness"]
        assert rw["enabled"] and rw["barrier_declared"], rw
        assert rw["violations"] == [], rw
        print(f"genserve smoke OK: {res2.throughput:.1f} req/s, "
              f"compiles delta 0 (total {m1[key]:.0f}), "
              f"early_exits {early:.0f}, fold_ins {folds:.0f}, "
              f"iterations {iters:.0f}, retrace witness clean "
              f"(warmup {rw['warmup_compiles']}, "
              f"sanctioned {rw['sanctioned_compiles']})")
    finally:
        await runner.cleanup()


asyncio.run(main())
EOF
