#!/usr/bin/env bash
# Generation-on-the-mesh smoke (ISSUE 20): the replica-per-chip engine
# group and the tensor-parallel decode leg through the REAL server on the
# CPU backend (8 forced host devices standing in for chips):
#   1. replica leg: mixed-length load over a 4-replica engine group with a
#      mid-load :reload (staged canary fanned to EVERY replica) — zero
#      errors, runtime_compiles_total delta exactly 0, and every replica's
#      /stats per_replica row shows nonzero steps (least-loaded placement
#      keeps all chips generating; a flat-zero row is a starved chip);
#   2. sharded leg: the SAME prompts/seeds/temperatures through a
#      parallelism='sharded' tp=2 server and a single-mesh server must
#      produce byte-identical tokens (greedy AND sampled — the
#      jax_threefry_partitionable seam), with a mid-load :reload on the
#      sharded leg also at compile delta 0;
#   3. both legs run under the lock witness AND the retrace witness: a
#      post-warmup compile or unblessed device->host fetch raises
#      mid-load rather than slipping into the numbers.
# Honest label: CPU backend, forced host devices — this gates PLACEMENT,
# PARITY, and the zero-recompile obligation, not chip throughput.
# Run by CI next to the genserve/paged-KV smokes; see docs/PERFORMANCE.md
# "Generation on the mesh".
set -euo pipefail
cd "$(dirname "$0")/.." || exit 1
export JAX_PLATFORMS=cpu
export TPUSERVE_LOCK_WITNESS=1
export TPUSERVE_RETRACE_WITNESS=1
# 8 fake chips; keep any other XLA_FLAGS the environment set.
case "${XLA_FLAGS:-}" in
  *xla_force_host_platform_device_count*) ;;
  *) export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8" ;;
esac

python - <<'EOF'
import asyncio
import json

import aiohttp
from aiohttp import web

from tpuserve.bench.loadgen import run_load, synthetic_prompt_pool
from tpuserve.config import (GenserveConfig, ModelConfig, ParallelConfig,
                             ServerConfig)
from tpuserve.server import ServerState, make_app

TG_OPTS = dict(layers=1, d_model=64, heads=2, d_ff=128, vocab_size=512,
               prompt_len=16, max_new_tokens=32)

# Mixed greedy + sampled lanes: the sampled ones cross the sharded gumbel
# draw, the seam jax_threefry_partitionable exists for.
PARITY_REQS = [
    {"prompt": "hello mesh", "seed": 0, "max_new_tokens": 8},
    {"prompt": "the quick brown fox jumps over the lazy dog", "seed": 7,
     "max_new_tokens": 12, "temperature": 0.8},
    {"prompt": "one two three four five six seven", "seed": 3,
     "max_new_tokens": 10, "temperature": 0.4},
]


def server_cfg(parallelism: str, n_chips: int, **model_over) -> ServerConfig:
    return ServerConfig(
        decode_threads=2,
        startup_canary=False,
        genserve=GenserveConfig(enabled=True, slots=2, kv_paging=True,
                                kv_page_tokens=8),
        parallel=ParallelConfig(mode=parallelism, n_chips=n_chips),
        models=[ModelConfig(
            name="textgen", family="textgen", batch_buckets=[1, 2, 4],
            dtype="float32", parallelism="single",
            request_timeout_ms=60_000.0, options=dict(TG_OPTS),
            **model_over)])


async def scrape(base, session):
    async with session.get(f"{base}/metrics") as r:
        text = await r.text()
    metrics = {}
    for line in text.splitlines():
        if line.startswith("#") or " " not in line:
            continue
        k, v = line.rsplit(" ", 1)
        try:
            metrics[k] = float(v)
        except ValueError:
            pass
    async with session.get(f"{base}/stats") as r:
        stats = await r.json()
    return metrics, stats


class Leg:
    def __init__(self, cfg):
        self.state = ServerState(cfg)

    async def __aenter__(self):
        self.state.build()
        self.runner = web.AppRunner(make_app(self.state), access_log=None)
        await self.runner.setup()
        site = web.TCPSite(self.runner, "127.0.0.1", 0)
        await site.start()
        return f"http://127.0.0.1:{self.runner.addresses[0][1]}"

    async def __aexit__(self, *exc):
        await self.runner.cleanup()


async def generate_all(base, session):
    toks = []
    for req in PARITY_REQS:
        async with session.post(
                f"{base}/v1/models/textgen:generate", data=json.dumps(req),
                headers={"Content-Type": "application/json"}) as r:
            body = await r.json()
            assert r.status == 200, body
            toks.append(body["tokens"])
    return toks


async def reload_ok(base, session):
    async with session.post(f"{base}/admin/models/textgen:reload") as r:
        body = await r.json()
        assert r.status == 200 and body["canary_ok"] is True, body


async def replica_leg():
    """4-replica engine group: balance + compile delta 0 across a reload."""
    async with Leg(server_cfg("replica", 4)) as base:
        pool = synthetic_prompt_pool(32, max_new=(2, 32))
        url = f"{base}/v1/models/textgen:generate"
        res = await run_load(url, pool, "application/json",
                             duration_s=2.0, warmup_s=0.5, concurrency=8)
        assert res.n_err == 0 and res.n_ok > 0, res.summary()
        async with aiohttp.ClientSession() as s:
            m0, _ = await scrape(base, s)
            res2 = await run_load(url, pool, "application/json",
                                  duration_s=1.5, warmup_s=0.0,
                                  concurrency=8)
            assert res2.n_err == 0, res2.summary()
            # Mid-load reload: the staged canary runs a short REAL
            # generation on EVERY replica, then publish — no compiles.
            await reload_ok(base, s)
            res3 = await run_load(url, pool, "application/json",
                                  duration_s=1.0, warmup_s=0.0,
                                  concurrency=8)
            assert res3.n_err == 0, res3.summary()
            m1, stats = await scrape(base, s)

        key = 'runtime_compiles_total{model="textgen"}'
        delta = m1.get(key, 0) - m0.get(key, 0)
        assert delta == 0, f"replica leg recompiled: delta={delta}"
        gs = stats["genserve"]["textgen"]
        assert gs["replicas"] == 4 and gs["slots"] == 8, gs
        assert gs["active"] == 0 and gs["free"] == 8, gs  # ledger balanced
        rows = gs["per_replica"]
        assert [r["replica"] for r in rows] == [0, 1, 2, 3], rows
        steps = [r["steps_total"] for r in rows]
        assert all(s > 0 for s in steps), f"starved replica: {steps}"
        for r in rows:  # every page pool came home
            assert r["kv"]["free"] == r["kv"]["usable"], rows
        for i in range(4):
            k = f'gen_replica_steps_total{{model="textgen",replica="{i}"}}'
            assert m1.get(k, 0) > 0, f"missing metric row {k}"
        rw = stats["robustness"]["retrace_witness"]
        assert rw["enabled"] and rw["barrier_declared"], rw
        assert rw["violations"] == [], rw
        return res2.throughput, steps, m1[key]


async def sharded_leg():
    """tp=2 sharded decode: token parity vs the single mesh + delta 0."""
    async with Leg(server_cfg("single", 1)) as base:
        async with aiohttp.ClientSession() as s:
            single_toks = await generate_all(base, s)
    async with Leg(server_cfg("sharded", 4, tp=2)) as base:
        async with aiohttp.ClientSession() as s:
            m0, _ = await scrape(base, s)
            sharded_toks = await generate_all(base, s)
            await reload_ok(base, s)
            again = await generate_all(base, s)
            m1, stats = await scrape(base, s)
        key = 'runtime_compiles_total{model="textgen"}'
        delta = m1.get(key, 0) - m0.get(key, 0)
        assert delta == 0, f"sharded leg recompiled: delta={delta}"
        assert sharded_toks == single_toks, (
            f"sharded decode diverged from single mesh:\n"
            f"  single:  {single_toks}\n  sharded: {sharded_toks}")
        assert again == single_toks, "parity broke across the reload"
        sig = stats["parallel"]["textgen"]["signature"]
        assert sig == "sharded@d2", sig
        rw = stats["robustness"]["retrace_witness"]
        assert rw["enabled"] and rw["violations"] == [], rw
    return sig


async def main():
    tput, steps, compiles = await replica_leg()
    sig = await sharded_leg()
    print(f"meshgen smoke OK: replica leg {tput:.1f} req/s, "
          f"per-replica steps {steps} (all nonzero), compile delta 0 "
          f"(total {compiles:.0f}); sharded leg {sig} token-identical to "
          f"single mesh across a mid-load reload, compile delta 0; "
          f"lock + retrace witnesses clean [cpu backend, 8 forced host "
          f"devices]")


asyncio.run(main())
EOF
