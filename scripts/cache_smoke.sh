#!/usr/bin/env bash
# Cache smoke (ISSUE 5): short closed loops through the REAL server on the
# CPU backend proving the demand-shaping layer end to end:
#   1. hit-heavy workload (one repeated payload): zero errors, hit rate > 0,
#      and single-flight coalescing visible in the counters;
#   2. lifecycle churn: a :reload publish makes the very next identical
#      request a MISS (version-keyed entries: zero stale-version hits);
#   3. miss-only workload (distinct pool > capacity): throughput within
#      noise of an identical cache-OFF server — the cache lookup must not
#      tax the miss path.
# Run by CI next to the chaos/reload/pipeline drills; see
# docs/PERFORMANCE.md "Result cache & coalescing".
set -euo pipefail
cd "$(dirname "$0")/.." || exit 1
export JAX_PLATFORMS=cpu
# Race-detection pass rides along (docs/ANALYSIS.md): the new cache and
# adaptive-scheduler paths run under witnessed locks + per-suspension
# held-lock checks; a violation raises and fails the smoke.
export TPUSERVE_LOCK_WITNESS=1

python - <<'EOF'
import asyncio
import sys

from aiohttp import web
import aiohttp

from tpuserve.bench.loadgen import run_load, synthetic_image_npy, synthetic_pool
from tpuserve.config import CacheConfig, ModelConfig, ServerConfig
from tpuserve.server import ServerState, make_app

NPY = "application/x-npy"


def build(cache_enabled: bool) -> ServerState:
    cfg = ServerConfig(
        decode_threads=2,
        startup_canary=False,
        cache=CacheConfig(enabled=cache_enabled, capacity=8),
        models=[ModelConfig(
            name="toy", family="toy", batch_buckets=[1, 2, 4],
            deadline_ms=5.0, dtype="float32", num_classes=10,
            parallelism="single", request_timeout_ms=10_000.0,
            wire_size=8, max_inflight=2,
        )],
    )
    state = ServerState(cfg)
    state.build()
    return state


async def serve(state):
    runner = web.AppRunner(make_app(state), access_log=None)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    return runner, f"http://127.0.0.1:{runner.addresses[0][1]}"


async def closed(base, payload, **kw):
    res = await run_load(f"{base}/v1/models/toy:classify", payload, NPY,
                         warmup_s=0.5, **kw)
    assert res.n_err == 0, f"errors during smoke: {res.summary()}"
    assert res.n_ok > 0, res.summary()
    return res


async def main() -> None:
    payload = synthetic_image_npy(edge=8)
    pool = synthetic_pool("npy", 32, edge=8)  # 32 distinct >> capacity 8

    # --- cache-ON server: hit-heavy, then reload churn, then miss-only ----
    state = build(cache_enabled=True)
    runner, base = await serve(state)
    try:
        hit_res = await closed(base, payload, duration_s=3.0, concurrency=8)
        cache = state.caches["toy"].stats()
        assert cache["hits"] > 0, f"hit-heavy run produced no hits: {cache}"
        rate = cache["hits"] / (cache["hits"] + cache["misses"]
                                + cache["coalesced"])
        assert rate > 0.5, f"hit-heavy hit rate suspiciously low: {cache}"

        # Lifecycle churn: publish a new version, then repeat the SAME
        # payload — a version-keyed cache can only answer it with a miss.
        async with aiohttp.ClientSession() as s:
            async with s.post(f"{base}/v1/models/toy:classify", data=payload,
                              headers={"Content-Type": NPY}) as r:
                assert r.status == 200
            pre = state.caches["toy"].stats()
            async with s.post(f"{base}/admin/models/toy:reload") as r:
                assert r.status == 200, await r.text()
            async with s.post(f"{base}/v1/models/toy:classify", data=payload,
                              headers={"Content-Type": NPY}) as r:
                assert r.status == 200
            post = state.caches["toy"].stats()
        # The repeat after the publish MUST be a miss (no stale hit). The
        # reload's own canary may add misses too; hits must not move.
        assert post["misses"] > pre["misses"], (pre, post)
        assert post["hits"] == pre["hits"], \
            f"stale-version cache hit after reload: {pre} -> {post}"

        miss_on = await closed(base, pool, duration_s=4.0, concurrency=8)
        delta = state.caches["toy"].stats()
    finally:
        await runner.cleanup()

    # --- cache-OFF server: identical miss-only loop -----------------------
    state_off = build(cache_enabled=False)
    runner, base = await serve(state_off)
    try:
        miss_off = await closed(base, pool, duration_s=4.0, concurrency=8)
    finally:
        await runner.cleanup()

    on, off = miss_on.throughput, miss_off.throughput
    # Within noise: CI boxes jitter, so the gate is deliberately loose; the
    # real number ships to stderr for eyeballs.
    assert on >= 0.5 * off, \
        f"miss-only throughput collapsed with cache on: {on:.1f} vs {off:.1f}/s"
    print(f"cache smoke OK: hit-heavy={hit_res.throughput:.1f}/s "
          f"(hit rate {rate:.2f}), miss-only on/off="
          f"{on:.1f}/{off:.1f} img/s, cache={delta}")


asyncio.run(main())
EOF
