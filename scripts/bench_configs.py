#!/usr/bin/env python
"""Measure benchmark configs 2-5 end-to-end over HTTP on the local chip.

BASELINE.json names five judged configs; `bench.py` measures config 1
(ResNet-50, the headline metric). This script produces measured rows for
the others — MobileNetV3-Large (replica/latency mode), BERT-base (text,
(batch, seq) buckets), its Switch-MoE expert-parallel variant (bert-moe),
EfficientDet-D0 (detection + on-device NMS), and Stable Diffusion 1.5
(txt2img, device-resident denoise loop) — using the
same method as bench.py: real aiohttp server, out-of-process load generator,
closed-loop peak + per-phase breakdown on stderr. Results are recorded in
BASELINE.md ("Per-config measured rows").

Run one family in this process (it owns the TPU for its lifetime):

    python scripts/bench_configs.py --family bert

Run all five sequentially (each in a fresh subprocess so param memory and
the PJRT session are released between families):

    python scripts/bench_configs.py
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# Per-family serving config + load shape. Wire sizes follow the same
# deployment philosophy as bench.py (host decodes to a compact wire; device
# resizes): each row records its wire so the number carries its context.
FAMILIES: dict[str, dict] = {
    "mobilenetv3": dict(
        model=dict(name="mobilenetv3", family="mobilenetv3",
                   parallelism="replica", batch_buckets=[1, 2, 4, 8],
                   deadline_ms=2.0, dtype="bfloat16", wire_size=160,
                   wire_format="yuv420", request_timeout_ms=60_000.0),
        payload="jpeg", verb="classify", concurrency=24, duration=15.0,
    ),
    "bert": dict(
        model=dict(name="bert", family="bert", batch_buckets=[8, 16, 32],
                   seq_buckets=[64, 128], deadline_ms=10.0, dtype="bfloat16",
                   request_timeout_ms=60_000.0),
        payload="text", verb="classify", concurrency=96, duration=15.0,
    ),
    # Switch-MoE BERT (expert-parallel serving variant): same load shape as
    # the dense row so the MoE overhead is directly readable (VERDICT r3
    # weak 8 — EP had no bench row). 8 experts, top-1 routing; on one chip
    # the experts are resident (no all-to-all); on a tp>1 mesh the expert
    # dim shards over "model".
    "bert-moe": dict(
        model=dict(name="bert-moe", family="bert", batch_buckets=[8, 16, 32],
                   seq_buckets=[64, 128], deadline_ms=10.0, dtype="bfloat16",
                   request_timeout_ms=60_000.0,
                   options={"moe_experts": 8}),
        payload="text", verb="classify", concurrency=96, duration=15.0,
    ),
    "efficientdet": dict(
        model=dict(name="efficientdet", family="efficientdet",
                   batch_buckets=[4, 8], deadline_ms=20.0, dtype="bfloat16",
                   image_size=512, wire_size=320, wire_format="yuv420",
                   request_timeout_ms=120_000.0),
        payload="jpeg", verb="detect", concurrency=24, duration=20.0,
    ),
    # Measured shape (BASELINE.md "SD 1.5 chip profile", 2026-07-30): CFG
    # batching b=1 -> 4 cuts per-image device cost 617 -> 457 ms (the MXU
    # fills at 8 CFG lanes), and concurrency 8 keeps the pipelined
    # dispatcher's next batch assembled while the current one denoises —
    # the r4 shape (buckets [1], concurrency 2) left the device idle
    # between readbacks. unet_attention stays dense: the flash variant
    # measured 2.4-2.8x SLOWER at SD head dims (same table).
    "sd15": dict(
        model=dict(name="sd15", family="sd15", batch_buckets=[1, 2, 4],
                   deadline_ms=150.0, dtype="bfloat16", image_size=512,
                   request_timeout_ms=600_000.0, options={"steps": 20}),
        payload="prompt", verb="generate", concurrency=8, duration=120.0,
        warmup=0.0,
    ),
}


def make_payload(kind: str, fam: dict) -> tuple[bytes, str]:
    from tpuserve.bench.loadgen import synthetic_image_jpeg

    if kind == "jpeg":
        return synthetic_image_jpeg(fam["model"]["wire_size"]), "image/jpeg"
    if kind == "text":
        return (json.dumps({"text": "the plot was thin but the acting carried "
                                    "every scene of it"}).encode(),
                "application/json")
    if kind == "prompt":
        return (json.dumps({"prompt": "a mountain lake at sunset, oil painting",
                            "seed": 7}).encode(), "application/json")
    raise ValueError(kind)


async def drive(name: str, fam: dict, port: int) -> dict:
    payload, ctype = make_payload(fam["payload"], fam)
    with tempfile.NamedTemporaryFile(suffix=".bin", delete=False) as f:
        f.write(payload)
        path = f.name
    try:
        proc = await asyncio.create_subprocess_exec(
            sys.executable, "-m", "tpuserve", "bench",
            "--url", f"http://127.0.0.1:{port}",
            "--model", name, "--verb", fam["verb"],
            "--duration", str(fam["duration"]),
            "--warmup", str(fam.get("warmup", 4.0)),
            "--concurrency", str(fam["concurrency"]),
            "--payload", path, "--content-type", ctype,
            stdout=asyncio.subprocess.PIPE, cwd=REPO,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        out, _ = await proc.communicate()
        return json.loads(out.decode())
    finally:
        os.unlink(path)


def run_family(name: str) -> int:
    from aiohttp import web

    from tpuserve.config import ModelConfig, ServerConfig
    from tpuserve.server import ServerState, make_app

    fam = FAMILIES[name]
    quantize = os.environ.get("BENCHC_QUANTIZE") or None
    if quantize:
        if quantize not in ("int8", "int8c"):
            raise SystemExit(
                f"BENCHC_QUANTIZE must be 'int8' or 'int8c', got {quantize!r}")
        # Applies to every family this invocation runs — stated in the
        # header and the result line so rows can't be mistaken for bf16.
        fam["model"]["quantize"] = quantize
    # Chip-level row first (fresh subprocess, device-resident chained loop,
    # XLA-counted FLOPs -> MFU): the "is it fast, not just correct" axis
    # the wire-bound HTTP row cannot answer (VERDICT r4 missing 1).
    # BENCHC_CHIP=0 skips it (e.g. when only the host path is under test).
    chip = {}
    if os.environ.get("BENCHC_CHIP", "1") != "0":
        from tpuserve.bench.probes import measure_chip_img_s

        chip = measure_chip_img_s(
            family=name,
            mcfg_extra={"quantize": quantize} if quantize else None)
        print(f"# {name}: chip probe {chip}", file=sys.stderr)

    port = int(os.environ.get("BENCH_PORT", 18441))
    cfg = ServerConfig(
        host="127.0.0.1", port=port, decode_inline=True, startup_canary=False,
        compilation_cache_dir=os.path.join(REPO, ".jaxcache"),
        models=[ModelConfig(**fam["model"])],
    )
    t0 = time.time()
    state = ServerState(cfg)
    state.build()
    build_s = round(time.time() - t0, 1)
    print(f"# {name}: build+compile+prewarm {build_s}s quantize={quantize}",
          file=sys.stderr)

    async def run() -> dict:
        runner = web.AppRunner(make_app(state), access_log=None)
        await runner.setup()
        site = web.TCPSite(runner, cfg.host, cfg.port)
        await site.start()
        try:
            return await drive(name, fam, port)
        finally:
            await runner.cleanup()

    res = asyncio.run(run())
    s = state.metrics.summary()
    for key in sorted(s["latency"]):
        v = s["latency"][key]
        print(f"#   {key}: n={v['n']} p50={v['p50_ms']:.1f} "
              f"p99={v['p99_ms']:.1f}", file=sys.stderr)
    line = {"config": name, "build_s": build_s, "quantize": quantize,
            "wire": f"{fam['model'].get('wire_format', 'json')}"
                    f"@{fam['model'].get('wire_size', '-')}"
                    if fam["payload"] == "jpeg" else "json",
            **res}
    if chip and "error" not in chip:
        line.update({
            "chip_items_s": chip.get("img_s"),
            "chip_ms_per_batch": chip.get("ms_per_batch"),
            "chip_bucket": chip.get("bucket"),
            "chip_gflops_per_item": chip.get("gflops_per_item"),
            "chip_tflops_s": chip.get("achieved_tflops_s"),
            "chip_mfu_pct": chip.get("mfu_pct"),
        })
    elif chip:
        line["chip_error"] = chip["error"]
    print(json.dumps(line))
    return 0 if res.get("n_ok", 0) > 0 else 1


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--family", choices=sorted(FAMILIES))
    args = ap.parse_args()
    if args.family:
        return run_family(args.family)
    rc = 0
    for name in ("mobilenetv3", "bert", "bert-moe", "efficientdet", "sd15"):
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--family", name],
            cwd=REPO)
        rc = rc or proc.returncode
    return rc


if __name__ == "__main__":
    sys.exit(main())
