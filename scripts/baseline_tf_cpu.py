#!/usr/bin/env python
"""Measure the TF-CPU SavedModel baseline for BASELINE.md config 1
(VERDICT r2 item 6; SURVEY §6 "first measurement action").

Builds Keras-applications ResNet50 (random weights — no pretrained artifacts
in this container), exports a SavedModel, reloads its serving signature, and
measures single-image (batch=1) and batch=32 inference rates on the host CPU
— the reference-shaped execution path (TF SavedModel, no CUDA available).

Prints one JSON line; paste the numbers into BASELINE.md.
"""

import json
import os
import sys
import tempfile
import time

os.environ.setdefault("CUDA_VISIBLE_DEVICES", "-1")

import numpy as np


def bench(fn, x, warmup=3, seconds=10.0) -> dict:
    for _ in range(warmup):
        fn(x)
    n, t0 = 0, time.perf_counter()
    lat = []
    while time.perf_counter() - t0 < seconds:
        t1 = time.perf_counter()
        fn(x)
        lat.append(time.perf_counter() - t1)
        n += 1
    dur = time.perf_counter() - t0
    imgs = n * x.shape[0]
    return {
        "imgs_per_s": round(imgs / dur, 1),
        "p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 1),
        "p99_ms": round(float(np.percentile(lat, 99)) * 1e3, 1),
        "n_calls": n,
    }


def main() -> int:
    import tensorflow as tf

    with tempfile.TemporaryDirectory(prefix="rn50_baseline_") as tmp:
        model = tf.keras.applications.ResNet50(weights=None)
        model.export(os.path.join(tmp, "sm"), verbose=False)
        loaded = tf.saved_model.load(os.path.join(tmp, "sm"))
        serve = loaded.signatures["serving_default"]

        rng = np.random.default_rng(0)
        out = {"metric": "tf_cpu_resnet50_savedmodel", "host_cpus": os.cpu_count()}
        for b in (1, 32):
            x = tf.constant(rng.uniform(0, 1, (b, 224, 224, 3)).astype(np.float32))
            out[f"batch{b}"] = bench(lambda t: serve(t), x)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
