#!/usr/bin/env bash
# Multichip smoke (ISSUE 7): a REAL server on 8 forced host devices proving
# the multi-chip serving path end to end on CPU CI:
#   1. replica-per-chip: the [parallel] block overrides the model onto 8
#      single-device replicas and under sustained load EVERY replica's
#      replica_batches_total moves — no starved chips, zero request errors;
#   2. steady state recompiles NOTHING: the runtime_compiles_total delta
#      across warm load PLUS a :reload landing MID-LOAD is exactly 0, and
#      the reload answers 200 while every concurrent request succeeds
#      (version-atomic across replicas — tests/test_multichip.py proves
#      the per-response version discipline; this proves it live);
#   3. sharded-batch: a second server serves one executable over the whole
#      8-device mesh (sharded@d8), zero errors, per-chip share reported.
# Run by CI next to the chaos/reload/pipeline/cache/roofline drills; see
# docs/PERFORMANCE.md "Serving on the mesh".
set -euo pipefail
cd "$(dirname "$0")/.." || exit 1
export JAX_PLATFORMS=cpu
# 8 fake host devices (the standard JAX trick the test suite also uses);
# keep any other XLA_FLAGS the environment set.
case "${XLA_FLAGS:-}" in
  *xla_force_host_platform_device_count*) ;;
  *) export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8" ;;
esac
# Race-detection pass rides along (docs/ANALYSIS.md): replica dispatch,
# publish/rollback, and the staging pools all run under witnessed locks.
export TPUSERVE_LOCK_WITNESS=1

python - <<'EOF'
import asyncio

import aiohttp
from aiohttp import web

from tpuserve.bench.loadgen import run_load, synthetic_pool
from tpuserve.config import ModelConfig, ParallelConfig, ServerConfig
from tpuserve.server import ServerState, make_app

NPY = "application/x-npy"
N = 8


def make_cfg(mode: str) -> ServerConfig:
    return ServerConfig(
        decode_threads=2,
        startup_canary=False,
        # The override is the point: the model says "single", the
        # [parallel] block puts the deployment on the mesh.
        parallel=ParallelConfig(mode=mode),
        models=[ModelConfig(
            name="toy", family="toy",
            batch_buckets=[1, 2] if mode == "replica" else [8, 16],
            deadline_ms=2.0, dtype="float32", num_classes=10,
            parallelism="single", request_timeout_ms=10_000.0,
            wire_size=8, max_inflight=2,
        )],
    )


async def scrape(base: str, session) -> tuple[dict, dict]:
    async with session.get(f"{base}/metrics") as r:
        text = await r.text()
    metrics = {}
    for line in text.splitlines():
        if line.startswith("#") or " " not in line:
            continue
        k, v = line.rsplit(" ", 1)
        try:
            metrics[k] = float(v)
        except ValueError:
            pass
    async with session.get(f"{base}/stats") as r:
        stats = await r.json()
    return metrics, stats


async def serve(cfg):
    state = ServerState(cfg)
    state.build()
    runner = web.AppRunner(make_app(state), access_log=None)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    return state, runner, f"http://127.0.0.1:{runner.addresses[0][1]}"


async def replica_leg() -> None:
    state, runner, base = await serve(make_cfg("replica"))
    pool = synthetic_pool("npy", 32, edge=8)
    url = f"{base}/v1/models/toy:classify"
    try:
        rt = state.runtimes["toy"]
        assert rt.mode == "replica" and rt.n_replicas == N, rt.describe()

        # Warm load, then the measured window the compile delta spans.
        res = await run_load(url, pool, NPY, duration_s=2.0, warmup_s=0.5,
                             concurrency=4 * N)
        assert res.n_err == 0 and res.n_ok > 0, res.summary()
        async with aiohttp.ClientSession() as s:
            m0, _ = await scrape(base, s)

            # Reload lands MID-LOAD: version-atomic publish across all 8
            # replicas with zero request errors and zero recompiles.
            async def reload_midway():
                await asyncio.sleep(0.8)
                async with s.post(f"{base}/admin/models/toy:reload") as r:
                    assert r.status == 200, await r.text()
                    return await r.json()

            res2, info = await asyncio.gather(
                run_load(url, pool, NPY, duration_s=2.5, warmup_s=0.0,
                         concurrency=4 * N),
                reload_midway())
            assert res2.n_err == 0 and res2.n_ok > 0, res2.summary()
            assert info["version"] == 2, info
            m1, stats = await scrape(base, s)

        key = 'runtime_compiles_total{model="toy"}'
        assert m0.get(key, 0) > 0, f"no compiles recorded at startup: {m0}"
        delta = m1.get(key, 0) - m0.get(key, 0)
        assert delta == 0, f"steady state recompiled: delta={delta}"

        # EVERY replica served batches — a zero row is a starved chip.
        per_rep = [m1.get(
            f'replica_batches_total{{model="toy",replica="{i}"}}', 0.0)
            for i in range(N)]
        assert all(v > 0 for v in per_rep), f"starved replica(s): {per_rep}"

        par = stats["parallel"]["toy"]
        assert par["signature"] == f"replica@{N}", par
        assert par["n_chips"] == N and len(par["replica_batches_total"]) == N
        rows = stats["pipeline"]["models"]["toy"]["per_replica"]
        assert len(rows) == N and all("occupancy" in r for r in rows)
        print(f"multichip replica leg OK: {res2.throughput:.1f}/s over "
              f"{N} replicas, per-replica batches {per_rep}, "
              f"compile delta 0, reload v{info['version']} mid-load")
    finally:
        await runner.cleanup()


async def sharded_leg() -> None:
    state, runner, base = await serve(make_cfg("sharded"))
    pool = synthetic_pool("npy", 32, edge=8)
    url = f"{base}/v1/models/toy:classify"
    try:
        rt = state.runtimes["toy"]
        assert rt.mode == "sharded" and rt.n_chips == N, rt.describe()
        assert rt.parallel_signature == f"sharded@d{N}"
        res = await run_load(url, pool, NPY, duration_s=2.0, warmup_s=0.5,
                             concurrency=4 * N)
        assert res.n_err == 0 and res.n_ok > 0, res.summary()
        async with aiohttp.ClientSession() as s:
            _, stats = await scrape(base, s)
        par = stats["parallel"]["toy"]
        assert par["signature"] == f"sharded@d{N}", par
        assert par["n_chips"] == N and par["batches_per_chip"] > 0, par
        print(f"multichip sharded leg OK: {res.throughput:.1f}/s on "
              f"sharded@d{N}, {par['batches_per_chip']} batches/chip")
    finally:
        await runner.cleanup()


async def main() -> None:
    await replica_leg()
    await sharded_leg()
    print("multichip smoke OK")


asyncio.run(main())
EOF
