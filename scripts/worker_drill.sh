#!/usr/bin/env bash
# Kill-worker chaos drill (ISSUE 8): a REAL router + 2 worker processes
# under closed-loop load; SIGKILL one worker mid-load and assert the
# process-split's promises hold (docs/ROBUSTNESS.md "Process failure
# domains"):
#   1. availability >= 99% across the whole run, kill included (in-flight
#      requests on the victim are retried onto the survivor);
#   2. the supervisor respawns the victim within the backoff budget;
#   3. zero torn/duplicate responses: a validator byte-compares every 200
#      body against a pre-kill reference throughout.
# Runs the real `python -m tpuserve chaos --drill worker_kill` CLI; wired
# into chaos_smoke.sh and CI next to the reload/pipeline/cache drills.
set -euo pipefail
cd "$(dirname "$0")/.." || exit 1
export JAX_PLATFORMS=cpu
# Race-detection pass rides along (docs/ANALYSIS.md): router, supervisor,
# and both workers all run under witnessed locks.
export TPUSERVE_LOCK_WITNESS=1

CFG="$(mktemp /tmp/tpuserve_worker_drill.XXXXXX.toml)"
OUT="$(mktemp /tmp/tpuserve_worker_drill.XXXXXX.json)"
BB="$(mktemp -d /tmp/tpuserve_worker_drill_bb.XXXXXX)"
trap 'rm -f "$CFG" "$OUT"; rm -rf "$BB"' EXIT

cat > "$CFG" <<EOF
decode_threads = 2
startup_canary = false
drain_timeout_s = 5.0

[events]
dir = "$BB"
snapshot_interval_s = 0.3

[router]
enabled = true
workers = 2
retry_max = 2
hedge_ms = 200.0
health_interval_s = 0.2
respawn_initial_s = 0.5
respawn_max_s = 5.0

[[model]]
name = "toy"
family = "toy"
batch_buckets = [1, 2]
deadline_ms = 2.0
dtype = "float32"
num_classes = 10
parallelism = "single"
request_timeout_ms = 10000.0
wire_size = 8
EOF

python -m tpuserve chaos --config "$CFG" --drill worker_kill \
    --duration 12 --warmup 1 --concurrency 8 --kill-after 1 \
    --respawn-budget 90 --min-availability 0.99 | tee "$OUT"

python - "$OUT" <<'EOF'
import json, sys

s = json.load(open(sys.argv[1]))
kill = s["kill"]
integ = s["integrity"]
assert s["availability"] >= 0.99, f"availability {s['availability']}"
assert kill.get("respawn_s") is not None, f"no respawn within budget: {kill}"
budget = s["router"]["respawn_backoff_initial_s"] + 60.0
assert kill["respawn_s"] <= budget, f"respawn {kill['respawn_s']}s > {budget}s"
assert integ["validated"] > 0, integ
assert integ["mismatched"] == 0, f"torn/mixed responses: {integ}"
assert s["workers"]["healthy"] == 2, s["workers"]
assert s["workers"]["deaths_total"] == 1, s["workers"]
assert s["router"]["retries_total"] >= 1, \
    "the SIGKILL mid-load should have forced at least one router retry"
# Postmortem evidence (ISSUE 15): the drill summary must carry a record
# naming the injected SIGKILL, with the victim's stderr tail and its
# black-box event snapshot — Chaos Eng P6: the injected failure must be
# diagnosable from the artifact alone.
pms = [p for p in s.get("postmortems", []) if p.get("signal") == "SIGKILL"]
assert pms, f"no SIGKILL postmortem in the drill summary: {s.get('postmortems')}"
pm = pms[0]
assert pm["component"] == "worker" and pm["pid"] == kill["killed_pid"], pm
assert pm.get("stderr_tail"), "postmortem carries no stderr tail"
assert pm.get("snapshot") and pm["snapshot"].get("events"), \
    "postmortem carries no black-box event snapshot"
print(f"worker drill OK: availability {s['availability']}, "
      f"respawn {kill['respawn_s']}s, "
      f"{int(s['router']['retries_total'])} retries absorbed, "
      f"{integ['validated']} validated responses, 0 torn, "
      f"postmortem names {pm['signal']} with "
      f"{len(pm['stderr_tail'])}B stderr + "
      f"{len(pm['snapshot']['events'])} snapshot events")
EOF

echo "worker drill OK"
