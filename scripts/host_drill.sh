#!/usr/bin/env bash
# Kill-a-host chaos drill (ISSUE 13): a REAL router over 2 host failure
# domains x 2 workers each (each host = a supervisor subprocess owning its
# worker fleet in its own process group), closed-loop load, then ONE
# killpg(SIGKILL) takes out an entire host mid-load — agent and both
# workers at once, exactly a machine losing power. Gates
# (docs/ROBUSTNESS.md "Host failure domains"):
#   1. availability >= 99% across the whole run, kill included (the host
#      breaker + retries route around the dead domain in milliseconds);
#   2. zero torn/duplicate responses: a validator byte-compares every 200
#      body against a pre-kill reference throughout;
#   3. the dead host re-absorbs (agent respawned, all its workers healthy)
#      within the backoff budget;
#   4. per-worker compile delta 0 on every SURVIVING worker — losing a
#      sibling domain must not perturb the survivors' variant registries.
# A second leg runs the cross-router sharded-cache suite (router kill,
# cross-router coalescing) under the same witness.
# Runs the real `python -m tpuserve chaos --drill host_kill` CLI; wired
# into chaos_smoke.sh and CI next to the worker/reload/fleet drills.
set -euo pipefail
cd "$(dirname "$0")/.." || exit 1
export JAX_PLATFORMS=cpu
# Race-detection pass rides along (docs/ANALYSIS.md): router, host agents,
# peers, and all four workers run under witnessed locks.
export TPUSERVE_LOCK_WITNESS=1

CFG="$(mktemp /tmp/tpuserve_host_drill.XXXXXX.toml)"
OUT="$(mktemp /tmp/tpuserve_host_drill.XXXXXX.json)"
BB="$(mktemp -d /tmp/tpuserve_host_drill_bb.XXXXXX)"
trap 'rm -f "$CFG" "$OUT"; rm -rf "$BB"' EXIT

cat > "$CFG" <<EOF
decode_threads = 2
startup_canary = false
drain_timeout_s = 5.0
watchdog_interval_s = 0.2

[events]
dir = "$BB"
snapshot_interval_s = 0.3

[router]
enabled = true
hosts = 2
workers = 2
retry_max = 3
hedge_ms = 200.0
health_interval_s = 0.2
respawn_initial_s = 0.5
respawn_max_s = 5.0
host_breaker_threshold = 3

[[model]]
name = "toy"
family = "toy"
batch_buckets = [1, 2]
deadline_ms = 2.0
dtype = "float32"
num_classes = 10
parallelism = "single"
request_timeout_ms = 10000.0
wire_size = 8
EOF

python -m tpuserve chaos --config "$CFG" --drill host_kill \
    --duration 14 --warmup 1 --concurrency 8 --kill-after 1 \
    --respawn-budget 90 --min-availability 0.99 | tee "$OUT"

python - "$OUT" <<'EOF'
import json, sys

s = json.load(open(sys.argv[1]))
kill = s["kill"]
integ = s["integrity"]
w = s["workers"]
assert s["availability"] >= 0.99, f"availability {s['availability']}"
assert kill.get("workers_killed") == 2, f"did not kill a full host: {kill}"
assert kill.get("reabsorb_s") is not None, f"host not re-absorbed: {kill}"
budget = s["router"]["respawn_backoff_initial_s"] + 60.0
assert kill["reabsorb_s"] <= budget, f"reabsorb {kill['reabsorb_s']}s > {budget}s"
assert integ["validated"] > 0, integ
assert integ["mismatched"] == 0, f"torn/mixed responses: {integ}"
assert w["hosts_up"] == 2 and w["healthy"] == 4, w
assert w["host_deaths_total"] == 1 and w["deaths_total"] >= 2, w
assert s["router"]["retries_total"] >= 1, \
    "killing a whole host mid-load should have forced at least one retry"
deltas = s["compile_deltas"]
assert deltas and all(d == 0 for d in deltas.values()), \
    f"surviving workers recompiled: {deltas}"
# Postmortem evidence (ISSUE 15): killpg'ing a whole domain must leave a
# host-level record naming the SIGKILL, with the agent's stderr tail and
# the lost workers' black-box snapshots read from their slot files (the
# dead agent can't report them over the pipe).
pms = [p for p in s.get("postmortems", [])
       if p.get("signal") == "SIGKILL" and p.get("component") == "host"]
assert pms, f"no host SIGKILL postmortem: {s.get('postmortems')}"
pm = pms[0]
assert pm["id"] == f"host{kill['killed_host']}", pm
assert pm.get("workers_lost") == kill["workers_killed"], pm
assert pm.get("stderr_tail"), "host postmortem carries no agent stderr tail"
assert any(wrow.get("snapshot") and wrow["snapshot"].get("events")
           for wrow in pm.get("workers", [])), \
    "no lost worker's black-box snapshot survived the host kill"
print(f"host drill OK: availability {s['availability']}, "
      f"host {kill['killed_host']} ({kill['workers_killed']} workers) "
      f"re-absorbed in {kill['reabsorb_s']}s, "
      f"{int(s['router']['retries_total'])} retries absorbed, "
      f"{integ['validated']} validated responses 0 torn, "
      f"survivor compile deltas {sorted(deltas.values())}")
EOF

echo "== cross-router sharded cache (2 routers, SO_REUSEPORT, router kill) =="
python -m pytest tests/test_multirouter.py -q -p no:cacheprovider

echo "host drill OK"
