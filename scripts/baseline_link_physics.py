#!/usr/bin/env python
"""Measure the dev-box link physics + pure-chip compute that bench.py and
ModelConfig.session_mode cite (BASELINE.md "Link physics").

Three numbers, each measured in a fresh subprocess (the tunnel's H2D behavior
is process-stateful):

1. h2d_virgin_mbps: sustained host->device rate before any D2H read.
2. h2d_after_d2h_mbps: the same measurement after one device->host readback
   (r2 claimed a permanent post-D2H slowdown; the r3 re-measurement with fair
   warm-up did not reproduce it — both probes stay to keep checking).
3. chip_resnet50: device-resident ResNet-50 bf16 inference rate at several
   batch sizes (inputs already on device) — the compute ceiling with zero
   wire involvement, and the raw ms/batch curve behind BASELINE.md's
   latency-budget table.

The H2D probes come from ``tpuserve.bench.probes`` — the same source bench.py
uses for its wire-ceiling math, so the two can never disagree.

Prints one JSON line; paste into BASELINE.md.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpuserve.bench.probes import measure_chip_img_s, measure_h2d_mbps  # noqa: E402


def main() -> int:
    out: dict = {}
    for key, mode in (("h2d_virgin_mbps", "virgin"),
                      ("h2d_after_d2h_mbps", "after_d2h")):
        r = measure_h2d_mbps(mode, timeout=900)
        out[key] = round(r["mbps"], 1) if "mbps" in r else r  # keep error dicts
    # Several batch sizes: feeds the BASELINE.md latency-budget table
    # (ms/batch vs batch is the raw input to the p50<=15ms operating-point
    # derivation) as well as the headline chip ceiling at 256.
    out["chip_resnet50"] = {
        str(b): measure_chip_img_s(batch=b) for b in (16, 32, 64, 128, 256)
    }
    print(json.dumps(out))
    bad = any(isinstance(v, dict) and "error" in v for v in out.values())
    bad = bad or any("error" in r for r in out["chip_resnet50"].values())
    return int(bad)


if __name__ == "__main__":
    sys.exit(main())
