#!/usr/bin/env python
"""Measure the dev-box link physics + pure-chip compute that bench.py and
ModelConfig.session_mode cite (BASELINE.md "Link physics").

Three numbers, each measured in a fresh subprocess (the tunnel's H2D behavior
is process-stateful):

1. h2d_virgin_mbps: sustained host->device rate before any D2H read.
2. h2d_after_d2h_mbps: the same measurement after one device->host readback
   (r2 claimed a permanent post-D2H slowdown; the r3 re-measurement with fair
   warm-up did not reproduce it — both probes stay to keep checking).
3. chip_resnet50: device-resident ResNet-50 bf16 inference rate (batch 256,
   inputs already on device) — the compute ceiling with zero wire
   involvement.

The H2D probes come from ``tpuserve.bench.probes`` — the same source bench.py
uses for its wire-ceiling math, so the two can never disagree.

Prints one JSON line; paste into BASELINE.md.
"""

import json
import os
import subprocess
import sys
import textwrap

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpuserve.bench.probes import measure_h2d_mbps  # noqa: E402

CHIP_PROBE = textwrap.dedent("""
    import time, json, numpy as np, jax, jax.numpy as jnp
    import sys
    sys.path.insert(0, %r)
    from tpuserve.config import ModelConfig
    from tpuserve.models import build
    cfg = ModelConfig(name="r", family="resnet50", dtype="bfloat16",
                      batch_buckets=[256])
    m = build(cfg)
    params = m.init_params(jax.random.key(0))
    # Timing caveats on the tunneled dev TPU: block_until_ready returns
    # before remote execution finishes, and a dependent per-batch scalar
    # read adds ~190 ms of relay RTT. The honest method is a
    # device-resident fori_loop of N forwards with a forced dependency
    # chain between iterations (defeats loop-invariant hoisting), one
    # scalar read at the end.
    N = 32

    @jax.jit
    def many(params, x):
        def body(i, carry):
            x, acc = carry
            out = m.forward(params, x)
            s = out["probs"][0, 0].astype(jnp.float32)
            x = x + (s * 0).astype(x.dtype)
            return (x, acc + s)
        _, acc = jax.lax.fori_loop(0, N, body, (x, jnp.float32(0)))
        return acc

    x = jax.device_put(np.random.default_rng(0).integers(
        0, 255, (256, 256, 256, 3), np.uint8))
    float(many(params, x))  # compile + warm
    t0 = time.perf_counter()
    float(many(params, x))
    dur = time.perf_counter() - t0
    print(json.dumps({"img_s": round(256 * N / dur, 1),
                      "ms_per_batch": round(dur / N * 1e3, 2)}))
""")


def run_chip() -> dict:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    p = subprocess.run([sys.executable, "-c", CHIP_PROBE % repo],
                       capture_output=True, text=True, timeout=900)
    if p.returncode != 0:
        return {"error": p.stderr.strip()[-300:]}
    return json.loads(p.stdout.strip().splitlines()[-1])


def main() -> int:
    out: dict = {}
    for key, mode in (("h2d_virgin_mbps", "virgin"),
                      ("h2d_after_d2h_mbps", "after_d2h")):
        r = measure_h2d_mbps(mode, timeout=900)
        out[key] = round(r["mbps"], 1) if "mbps" in r else r  # keep error dicts
    out["chip_resnet50"] = run_chip()
    print(json.dumps(out))
    return int(any(isinstance(v, dict) and "error" in v for v in out.values()))


if __name__ == "__main__":
    sys.exit(main())
