#!/usr/bin/env bash
# Reload drill (ISSUE 2 acceptance bound): inject reload_corrupt at 100%,
# hammer POST :reload throughout a CPU load run, and assert availability
# stays >= 99% with the original model version still live (every reload
# rejected at the integrity gate; no candidate ever published). Run by
# scripts/chaos_smoke.sh and the CI workflow; see docs/ROBUSTNESS.md
# "Model lifecycle & rollback".
set -euo pipefail
cd "$(dirname "$0")/.." || exit 1
export JAX_PLATFORMS=cpu
# The reload drill runs witnessed (docs/ANALYSIS.md): hot-swap under load is
# exactly where a lifecycle-vs-runtime lock inversion would hide.
export TPUSERVE_LOCK_WITNESS=1

cfg="$(mktemp -t reload_drill_cfg_XXXX)"
out="$(mktemp -t reload_drill_out_XXXX)"
trap 'rm -f "$cfg" "$out"' EXIT
cat > "$cfg" <<'EOF'
decode_threads = 2

[[model]]
name = "toy"
family = "toy"
batch_buckets = [1, 2, 4]
deadline_ms = 5.0
dtype = "float32"
num_classes = 10
parallelism = "single"
request_timeout_ms = 10000.0
wire_size = 8

[faults]
enabled = true
seed = 7

[[faults.rule]]
kind = "reload_corrupt"
model = "toy"
probability = 1.0
EOF

python -m tpuserve chaos --config "$cfg" --duration 5 --warmup 1 \
    --concurrency 8 --drill reload --drill-interval 0.25 \
    --min-availability 0.99 > "$out"

python - "$out" <<'EOF'
import json, sys

s = json.load(open(sys.argv[1]))
drill, lc = s["reload_drill"], s["lifecycle"]["toy"]
assert drill["attempts"] > 0, s
assert drill["ok"] == 0 and drill["rolled_back"] == 0, drill
assert lc["live_version"] == 1, lc
assert all(h["status"] in ("live", "rejected") for h in lc["history"]), lc
print(f"reload drill OK: availability={s['availability']} "
      f"reloads attempted={drill['attempts']} rejected={drill['rejected']} "
      f"live_version={lc['live_version']}")
EOF
