#!/usr/bin/env bash
# Telemetry smoke (ISSUE 14): a REAL router + 2-host fleet (1 worker per
# host) under closed-loop load, gating the telemetry plane's contract
# (docs/OBSERVABILITY.md "The telemetry plane"):
#   1. fleet aggregation is EXACT: requests_total summed out of
#      /metrics/fleet equals the sum of the per-worker counters, and
#      /stats/fleet shows every source up;
#   2. the SLO burn-rate engine FIRES under an injected worker_slow
#      latency fault (every early request blows the 250 ms objective) and
#      returns to ok after the fault exhausts and the bad windows age out;
#   3. /stats/history is non-empty on the router AND on a worker (via the
#      /workers/{wid}/stats/history proxy), with derived rates;
#   4. /metrics is OpenMetrics-enveloped (# EOF, content negotiation);
#   5. runtime_compiles_total delta is exactly 0 across the loaded window
#      (telemetry adds no specializations).
# Witnessed (TPUSERVE_LOCK_WITNESS=1): the sampler thread, SLO engine,
# and fleet scrape run against every lock family under load, so the run
# doubles as a race-detection pass.
set -euo pipefail
cd "$(dirname "$0")/.." || exit 1
export JAX_PLATFORMS=cpu
export TPUSERVE_LOCK_WITNESS=1

PORT=18571
TMPD="$(mktemp -d /tmp/telemetry_smoke_XXXX)"
CFG="$TMPD/cfg.toml"
cat > "$CFG" <<EOF
host = "127.0.0.1"
port = $PORT
decode_threads = 2
startup_canary = false
drain_timeout_s = 5.0

[telemetry]
sample_interval_s = 0.25
burn_windows_s = [2.0, 4.0, 30.0]

[router]
enabled = true
workers = 1
hosts = 2
retry_max = 2
health_interval_s = 0.2

[[model]]
name = "toy"
family = "toy"
batch_buckets = [1, 2, 4]
deadline_ms = 2.0
dtype = "float32"
num_classes = 10
parallelism = "single"
request_timeout_ms = 10000.0
wire_size = 8

[model.slo]
latency_ms = 250.0
availability = 0.999
burn_alert = 10.0

[faults]
enabled = true
seed = 5

[[faults.rule]]
kind = "worker_slow"
model = "toy"
probability = 1.0
count = 40
delay_ms = 900.0
EOF

python -m tpuserve serve --config "$CFG" &
SERVER_PID=$!
cleanup() {
  rc=$?
  if [ "$rc" -ne 0 ]; then
    # Red-run forensics (ISSUE 15): dump the live flight data so CI can
    # upload it as an artifact — diagnosable without a rerun.
    scripts/debug_dump.sh "http://127.0.0.1:$PORT" telemetry_smoke || true
  fi
  kill -9 "$SERVER_PID" 2>/dev/null || true
  rm -rf "$TMPD"
}
trap cleanup EXIT

for _ in $(seq 1 180); do
  if curl -fsS "http://127.0.0.1:$PORT/healthz" >/dev/null 2>&1; then break; fi
  sleep 0.5
done
curl -fsS "http://127.0.0.1:$PORT/healthz" >/dev/null

# Compile-delta window opens after startup compiles, before any load.
curl -fsS "http://127.0.0.1:$PORT/workers/0/metrics" > "$TMPD/w0_before.txt"
curl -fsS "http://127.0.0.1:$PORT/workers/1/metrics" > "$TMPD/w1_before.txt"

python - "$TMPD" "http://127.0.0.1:$PORT" <<'EOF'
import io
import json
import re
import sys
import threading
import time
import urllib.request

import numpy as np

tmpd, base = sys.argv[1], sys.argv[2]


def get(path, accept=None):
    req = urllib.request.Request(base + path)
    if accept:
        req.add_header("Accept", accept)
    with urllib.request.urlopen(req, timeout=30) as r:
        return r.status, dict(r.headers), r.read()


def post(path, body, ctype="application/x-npy"):
    req = urllib.request.Request(base + path, data=body,
                                 headers={"Content-Type": ctype})
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def npy(seed):
    buf = io.BytesIO()
    np.save(buf, np.random.default_rng(seed).integers(
        0, 255, (8, 8, 3), dtype=np.uint8))
    return buf.getvalue()


# Closed-loop load: 6 worker threads posting distinct payloads until told
# to stop. The first ~80 requests ride the worker_slow fault (900 ms vs
# the 250 ms objective) — the bad traffic the burn engine must fire on.
stop = threading.Event()
counts = {"ok": 0, "err": 0}
lock = threading.Lock()


def loader(tid):
    i = 0
    while not stop.is_set():
        status, body = post("/v1/models/toy:classify", npy(tid * 10_000 + i))
        with lock:
            counts["ok" if status == 200 else "err"] += 1
        if status != 200:
            print(f"load error {status}: {body[:200]}", file=sys.stderr)
        i += 1


threads = [threading.Thread(target=loader, args=(t,), daemon=True)
           for t in range(6)]
for t in threads:
    t.start()


def alert_state():
    _, _, raw = get("/alerts")
    data = json.loads(raw)
    return data["models"].get("toy", {}).get("state"), data


# Gate 2a: the alert FIRES while the latency fault serves.
state, data = None, None
deadline = time.time() + 30.0
while time.time() < deadline:
    state, data = alert_state()
    if state == "firing":
        break
    time.sleep(0.25)
assert state == "firing", f"burn alert never fired: {json.dumps(data)}"
burn = data["models"]["toy"]["burn"]
print(f"alert FIRING: burn={burn}")
assert burn["2s"] and burn["2s"] > 10.0, burn

# Gate 2b: the fault exhausts (count 40/worker) under continuing load and
# the alert returns to ok once the bad windows age out.
state = None
deadline = time.time() + 60.0
while time.time() < deadline:
    state, data = alert_state()
    if state == "ok":
        break
    time.sleep(0.25)
assert state == "ok", \
    f"alert never cleared after the fault: {json.dumps(data)}"
print(f"alert cleared to ok (served={counts['ok']})")

stop.set()
for t in threads:
    t.join(10.0)
assert counts["ok"] > 100 and counts["err"] == 0, counts
time.sleep(1.0)  # quiesce: no request in flight during the sum gates

# Gate 4: OpenMetrics envelope + content negotiation on the router.
_, headers, raw = get("/metrics")
assert headers["Content-Type"].startswith("text/plain; version=0.0.4"), \
    headers["Content-Type"]
assert raw.decode().rstrip().endswith("# EOF"), "missing # EOF terminator"
_, headers, _ = get("/metrics", accept="application/openmetrics-text; "
                                       "version=1.0.0")
assert headers["Content-Type"].startswith(
    "application/openmetrics-text; version=1.0.0"), headers["Content-Type"]

# Gate 1: fleet-summed counters == Σ per-worker counters, EXACTLY.
RE_REQ = re.compile(r'^requests_total\{model="toy"\} ([0-9.e+]+)$', re.M)


def req_total(text):
    m = RE_REQ.search(text)
    return float(m.group(1)) if m else 0.0


_, _, fleet_raw = get("/metrics/fleet")
fleet_text = fleet_raw.decode()
per_worker = 0.0
for wid in (0, 1):
    _, _, wraw = get(f"/workers/{wid}/metrics")
    with open(f"{tmpd}/w{wid}_after.txt", "w", encoding="utf-8") as f:
        f.write(wraw.decode())
    per_worker += req_total(wraw.decode())
fleet_sum = req_total(fleet_text)
assert fleet_sum == per_worker > 0, (fleet_sum, per_worker)
assert 'fleet_source_up{proc="worker0"} 1' in fleet_text, "source gauges"
print(f"fleet sum exact: {fleet_sum} == {per_worker}")

_, _, raw = get("/stats/fleet")
rollup = json.loads(raw)
assert rollup["stale"] == [] and rollup["down_domains"] == [], rollup
assert rollup["models"]["toy"]["requests_total"] == fleet_sum, rollup

# Gate 3: history non-empty on the router AND a worker, rates derived.
_, _, raw = get("/stats/history?metric=router_requests_total&window_s=120")
series = json.loads(raw)["series"]
assert series and len(series[0]["t"]) >= 2, series
assert series[0]["increase"] > 0, series[0]
_, _, raw = get("/workers/0/stats/history?metric=requests_total")
wseries = json.loads(raw)["series"]
assert wseries and len(wseries[0]["t"]) >= 2, wseries
assert "rate_per_s" in wseries[0], wseries[0]
print(f"history: router n={len(series[0]['t'])} "
      f"worker n={len(wseries[0]['t'])}")
EOF

# Gate 5: compile delta 0 on every worker across the loaded window.
python - "$TMPD" <<'EOF'
import re
import sys

tmpd = sys.argv[1]


def compiles(path):
    total = 0.0
    with open(path, encoding="utf-8") as f:
        for line in f:
            m = re.match(r'^runtime_compiles_total\{[^}]*\} ([0-9.e+]+)', line)
            if m:
                total += float(m.group(1))
    return total


for wid in (0, 1):
    before = compiles(f"{tmpd}/w{wid}_before.txt")
    after = compiles(f"{tmpd}/w{wid}_after.txt")
    assert before > 0, f"worker {wid}: no compiles recorded at startup?"
    assert after == before, \
        f"worker {wid}: compile delta {after - before} != 0"
    print(f"worker {wid}: compile delta 0 ({before} at startup)")
EOF

kill -TERM "$SERVER_PID" 2>/dev/null || true
wait "$SERVER_PID" 2>/dev/null || true
echo "telemetry smoke OK"
