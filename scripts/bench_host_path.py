#!/usr/bin/env python
"""Measure the HOST side of the serving path: can HTTP + decode + batcher +
scatter carry the 12k img/s north star? (VERDICT r3 next 1; SURVEY.md §7
hard part 6, §2 C11/C12.)

Two measurements, both TPU-free:

1. **Decode microbench** (in-process, single core): items/s for each host
   decode operation on identical inputs — PIL JPEG->RGB, the native libjpeg
   C shim JPEG->YUV420 planes, the PIL YUV fallback, and npy tensor parse.
   This is the C12 justification number (shim vs PIL).

2. **Serving loopback bench**: the real aiohttp server + batcher serving the
   toy model on the CPU backend over 127.0.0.1, driven by the out-of-process
   load generator with single-image JPEG POSTs, single-image npy, and
   batched npy bodies. The key metric is **items per server-CPU-second**
   (utime+stime deltas from /proc/<pid>/stat), which is contention-free even
   though the load generator shares this 1-vCPU box: it answers "how many
   images does ONE host core push through the full HTTP->decode->batch->
   scatter->respond path", which extrapolates to any core count.

The toy model's device compute is a ~6k-param MLP (negligible), so server
CPU time is host-path work. Its 8x8 wire shape means the host ALSO pays a
PIL resize per JPEG that the real yuv420 path does not — the extrapolation
is conservative. Results land in BASELINE.md ("Host-path ceiling").

Usage: python scripts/bench_host_path.py   (prints one JSON line; ~2 min)
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PORT = int(os.environ.get("HOSTBENCH_PORT", 18471))
EDGE = int(os.environ.get("HOSTBENCH_EDGE", 160))  # matches bench.py wire
DURATION = float(os.environ.get("HOSTBENCH_DURATION", 8))
CLIENT_BATCH = int(os.environ.get("HOSTBENCH_CLIENT_BATCH", 64))


def synth_jpeg(edge: int) -> bytes:
    from tpuserve.bench.loadgen import synthetic_image_jpeg

    return synthetic_image_jpeg(edge)


# -- 1. decode microbench -----------------------------------------------------

def microbench(fn, payload, min_s: float = 1.5) -> float:
    """items/s for fn(payload) on this core (adaptive iteration count)."""
    fn(payload)  # warm (imports, shim dlopen)
    n, t0 = 0, time.perf_counter()
    while True:
        for _ in range(20):
            fn(payload)
        n += 20
        dt = time.perf_counter() - t0
        if dt >= min_s:
            return n / dt


def run_microbench() -> dict:
    import numpy as np

    from tpuserve import native, preproc
    from tpuserve.bench.loadgen import synthetic_image_npy

    jpeg = synth_jpeg(EDGE)
    npy = synthetic_image_npy(EDGE)
    out = {
        "jpeg_bytes": len(jpeg),
        "pil_jpeg_to_rgb_per_s": microbench(
            lambda p: preproc.decode_image(p, "image/jpeg", edge=EDGE), jpeg),
        "npy_parse_per_s": microbench(
            lambda p: preproc.decode_image(p, "application/x-npy", edge=EDGE), npy),
        "pil_yuv420_fallback_per_s": microbench(
            lambda p: preproc.rgb_to_yuv420(
                preproc.decode_image(p, "image/jpeg", edge=EDGE)), jpeg),
    }
    if native.decode_yuv420(jpeg, EDGE) is not None:
        out["native_yuv420_per_s"] = microbench(
            lambda p: native.decode_yuv420(p, EDGE), jpeg)
        out["native_vs_pil_yuv_speedup"] = round(
            out["native_yuv420_per_s"] / out["pil_yuv420_fallback_per_s"], 2)
    else:
        out["native_yuv420_per_s"] = None  # shim not built on this host
    return out


# -- 2. serving loopback bench ------------------------------------------------

SERVER_SRC = """
import jax
jax.config.update("jax_platforms", "cpu")   # undo sitecustomize's axon pin
import sys
from tpuserve.cli import main
sys.exit(main(["serve", "--config", %(cfg)r]))
"""

SERVER_TOML = """
port = %(port)d
decode_threads = 2
decode_inline = true
startup_canary = false

[[model]]
name = "toy"
family = "toy"
batch_buckets = [64, 128]
deadline_ms = 2.0
dtype = "float32"
num_classes = 10
parallelism = "single"
request_timeout_ms = 30000.0
max_inflight = 4
"""


def cpu_seconds(pid: int) -> float:
    with open(f"/proc/{pid}/stat") as f:
        parts = f.read().rsplit(") ", 1)[1].split()
    utime, stime = int(parts[11]), int(parts[12])
    return (utime + stime) / os.sysconf("SC_CLK_TCK")


def fetch_stats() -> dict:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{PORT}/stats", timeout=5) as r:
        return json.loads(r.read())


def phase_totals(stats: dict) -> dict:
    """{phase: (n, total_ms)} for the toy model."""
    out = {}
    for key, v in stats["latency"].items():
        if "model=toy" in key:
            phase = key.split("phase=")[1].rstrip("}")
            out[phase] = (v["n"], v["n"] * v["mean_ms"])
    return out


def run_loadgen(payload_path: str, ctype: str, duration: float, warmup: float,
                concurrency: int, batch: int = 0, rate: float = 0) -> dict:
    args = [sys.executable, "-m", "tpuserve", "bench",
            "--url", f"http://127.0.0.1:{PORT}", "--model", "toy",
            "--verb", "classify", "--duration", str(duration),
            "--warmup", str(warmup), "--concurrency", str(concurrency),
            "--payload", payload_path, "--content-type", ctype]
    if batch > 1:
        args += ["--batch", str(batch)]
    if rate:
        args += ["--rate", str(rate)]
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    out = subprocess.run(args, capture_output=True, text=True, cwd=REPO,
                         env=env, timeout=600)
    if out.returncode != 0:
        raise RuntimeError(
            f"loadgen failed: stdout={out.stdout[-400:]} "
            f"stderr={out.stderr[-400:]}")
    return json.loads(out.stdout)


def run_serving_bench() -> dict:
    from tpuserve.bench.loadgen import (
        synthetic_image_npy,
        synthetic_image_npy_batch,
    )

    with tempfile.TemporaryDirectory() as td:
        cfg_path = os.path.join(td, "host.toml")
        with open(cfg_path, "w") as f:
            f.write(SERVER_TOML % {"port": PORT})
        log_path = os.environ.get("HOSTBENCH_SRV_LOG", "/tmp/hostbench_srv.log")
        srv_log = open(log_path, "w")
        srv = subprocess.Popen(
            [sys.executable, "-c", SERVER_SRC % {"cfg": cfg_path}],
            cwd=REPO, stdout=srv_log, stderr=subprocess.STDOUT)
        srv_log.close()  # the child holds the fd now
        try:
            for _ in range(120):
                if srv.poll() is not None:
                    raise RuntimeError(
                        f"server exited rc={srv.returncode} at startup "
                        f"(see {log_path}; stale process on port {PORT}?)")
                try:
                    with urllib.request.urlopen(
                            f"http://127.0.0.1:{PORT}/healthz", timeout=1):
                        break
                except Exception:  # noqa: BLE001
                    time.sleep(0.5)
            else:
                raise RuntimeError("server never became healthy")

            payloads = {
                "jpeg_single": (synth_jpeg(EDGE), "image/jpeg", 0),
                "npy_single": (synthetic_image_npy(EDGE), "application/x-npy", 0),
                "npy_batch": (synthetic_image_npy_batch(EDGE, CLIENT_BATCH),
                              "application/x-npy", CLIENT_BATCH),
            }
            results = {}
            for name, (payload, ctype, batch) in payloads.items():
                ppath = os.path.join(td, f"{name}.bin")
                with open(ppath, "wb") as f:
                    f.write(payload)
                # Concurrency is in REQUESTS: batched bodies carry batch x
                # items each, so scale down to keep ~2-4 device buckets in
                # flight instead of flooding the queue into shedding.
                conc = 256 if batch <= 1 else max(2, 512 // batch)
                # Priming run (compiles nothing — warms sockets/paths), then
                # the measured run with zero warmup so the CPU window is
                # exactly the measurement window.
                run_loadgen(ppath, ctype, 2, 1, conc // 2, batch)
                s0, c0, t0 = fetch_stats(), cpu_seconds(srv.pid), time.time()
                res = run_loadgen(ppath, ctype, DURATION, 0, conc, batch)
                s1, c1, t1 = fetch_stats(), cpu_seconds(srv.pid), time.time()
                items = res["throughput_per_s"] * res.get("duration_s", DURATION)
                cpu = c1 - c0
                p0, p1 = phase_totals(s0), phase_totals(s1)
                phases = {}
                for ph in p1:
                    dn = p1[ph][0] - p0.get(ph, (0, 0))[0]
                    dt_ms = p1[ph][1] - p0.get(ph, (0, 0))[1]
                    if dn > 0:
                        phases[ph] = round(dt_ms / dn, 3)
                results[name] = {
                    "throughput_per_s": res["throughput_per_s"],
                    "p50_ms": res["p50_ms"],
                    "p99_ms": res["p99_ms"],
                    "errors": res["n_err"],
                    "server_cpu_s": round(cpu, 2),
                    "wall_s": round(t1 - t0, 2),
                    "server_cpu_ms_per_item": round(1e3 * cpu / items, 3)
                    if items else None,
                    "items_per_cpu_core_s": round(items / cpu, 1) if cpu else None,
                    "phase_mean_ms": phases,
                }
            # Batcher-added latency at a non-saturating rate (feeds the
            # latency budget): open loop at ~40% of jpeg saturation.
            rate = max(1, int(0.4 * results["jpeg_single"]["throughput_per_s"]))
            ppath = os.path.join(td, "jpeg_single.bin")
            open_res = run_loadgen(ppath, "image/jpeg", min(DURATION, 6), 1,
                                   256, 0, rate=rate)
            results["jpeg_open_loop"] = {
                "offered_per_s": open_res.get("offered_rate_per_s"),
                "throughput_per_s": open_res["throughput_per_s"],
                "p50_ms": open_res["p50_ms"],
                "p99_ms": open_res["p99_ms"],
            }
            return results
        finally:
            srv.terminate()
            srv.wait(timeout=10)


def main() -> int:
    out = {"edge": EDGE, "microbench": run_microbench(),
           "serving": run_serving_bench()}
    target = 12_000.0
    for fmt in ("jpeg_single", "npy_single", "npy_batch"):
        per_core = out["serving"][fmt]["items_per_cpu_core_s"]
        if per_core:
            out["serving"][fmt]["cores_for_12k_img_s"] = round(
                target / per_core, 1)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
