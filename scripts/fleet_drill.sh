#!/usr/bin/env bash
# Fleet isolation drill (ISSUE 10): one REAL multi-model server with the
# fleet scheduler armed, closed-loop load on every model concurrently,
# and one model poisoned with device_error @ 100% (docs/ROBUSTNESS.md
# "Fleet isolation & SLO admission"):
#   1. the victim's circuit breaker opens (its traffic degrades to fast
#      503s, not slow 500s);
#   2. every SURVIVOR holds availability >= 99% with p99 within budget —
#      the poisoned model's failing dispatches never starve the others;
#   3. zero lock-order findings: the whole run is witnessed.
# A second leg proves the warm/cold weight-paging contract end-to-end:
# a cold-declared model boots with zero device params, serves after
# staging, idle-demotes, and re-warms with a runtime_compiles_total
# delta of 0. Runs the real `python -m tpuserve chaos --drill fleet`
# CLI; wired into chaos_smoke.sh and CI next to the other drills.
set -euo pipefail
cd "$(dirname "$0")/.." || exit 1
export JAX_PLATFORMS=cpu
# Race-detection pass rides along (docs/ANALYSIS.md): scheduler,
# batchers, and the load all run under witnessed locks.
export TPUSERVE_LOCK_WITNESS=1

CFG="$(mktemp /tmp/tpuserve_fleet_drill.XXXXXX.toml)"
OUT="$(mktemp /tmp/tpuserve_fleet_drill.XXXXXX.json)"
trap 'rm -f "$CFG" "$OUT"' EXIT

cat > "$CFG" <<'EOF'
decode_threads = 2
startup_canary = false
drain_timeout_s = 5.0

[scheduler]
enabled = true

[[model]]
name = "victim"
family = "toy"
batch_buckets = [1, 2, 4]
deadline_ms = 2.0
dtype = "float32"
num_classes = 10
parallelism = "single"
request_timeout_ms = 10000.0
wire_size = 8
breaker_threshold = 3

[[model]]
name = "survivor_a"
family = "toy"
batch_buckets = [1, 2, 4]
deadline_ms = 2.0
dtype = "float32"
num_classes = 10
parallelism = "single"
request_timeout_ms = 10000.0
wire_size = 8

[[model]]
name = "survivor_b"
family = "toy"
batch_buckets = [1, 2, 4]
deadline_ms = 2.0
dtype = "float32"
num_classes = 10
parallelism = "single"
request_timeout_ms = 10000.0
wire_size = 8
EOF

echo "== fleet drill (device_error @ 100% on 'victim', 3-model closed loop) =="
python -m tpuserve chaos --config "$CFG" --drill fleet --model victim \
    --duration 10 --warmup 1 --concurrency 6 \
    --min-availability 0.99 | tee "$OUT"

python - "$OUT" <<'EOF'
import json, sys

s = json.load(open(sys.argv[1]))
assert s["drill"] == "fleet" and s["victim"] == "victim"
assert s["victim_breaker_open"], \
    f"victim breaker must open: {s['victim_breaker']}"
assert s["availability"] >= 0.99, \
    f"worst survivor availability {s['availability']}"
p99_budget_ms = 2000.0
for name, row in s["models"].items():
    if row["role"] != "survivor":
        continue
    assert row["availability"] >= 0.99, (name, row["availability"])
    assert row["n_ok"] > 0, (name, "served nothing")
    assert row["p99_ms"] <= p99_budget_ms, (name, row["p99_ms"])
assert s["models"]["victim"]["availability"] < 0.5, \
    "the poison must actually be hitting the victim"
assert any(f["kind"] == "device_error" and f["fired"] > 0
           for f in s["faults"]), s["faults"]
assert s["scheduler"]["models"]["victim"]["state"] == "warm"
print("fleet drill OK: victim breaker "
      f"{s['victim_breaker']['state']}, worst survivor availability "
      f"{s['availability']}, survivor p99s "
      + str({n: r["p99_ms"] for n, r in s["models"].items()
             if r["role"] == "survivor"}))
EOF

echo "== weight paging (cold boot -> warm -> idle demote -> zero-recompile re-warm) =="
python -m pytest -q -p no:cacheprovider \
    tests/test_scheduler.py::test_cold_start_warm_demote_rewarm_zero_recompiles \
    tests/test_scheduler.py::test_warm_endpoint_http

echo "fleet drill OK"
