#!/usr/bin/env bash
# Failure-forensics dump (ISSUE 15 satellite): called from a smoke
# script's failure path with the live server's base URL, pulls the flight
# data — /debug/events, /debug/postmortems, /debug/slow (+ /stats) — into
# $TPUSERVE_CI_DUMP_DIR so CI can upload it as an artifact and a red run
# is diagnosable without a rerun. Best-effort by design: the server may
# already be dead, and a dump failure must never mask the real failure.
#   usage: debug_dump.sh <base_url> [label]
set -u
BASE="${1:?usage: debug_dump.sh <base_url> [label]}"
LABEL="${2:-smoke}"
OUTDIR="${TPUSERVE_CI_DUMP_DIR:-/tmp/tpuserve-ci-dumps}/${LABEL}-$$"
mkdir -p "$OUTDIR" || exit 0
echo "debug_dump: pulling flight data from $BASE into $OUTDIR" >&2
for page in "debug/events" "debug/postmortems" "debug/slow" \
            "debug/audit" "alerts" "stats" "stats/history"; do
  fname="${page//\//_}.json"
  curl -fsS --max-time 10 "$BASE/$page" -o "$OUTDIR/$fname" 2>/dev/null \
    || echo "unreachable: $BASE/$page" > "$OUTDIR/$fname.unreachable"
done
exit 0
