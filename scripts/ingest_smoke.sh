#!/usr/bin/env bash
# Ingest smoke (ISSUE 11): a REAL `tpuserve serve` process with
# ingest_loops = 3 (one main + two SO_REUSEPORT ingest event-loop threads)
# driven by the framed-wire loadgen (`tpuserve bench --wire frame`), gating:
#   1. zero request errors AND zero unexpected malformed-frame counts (a
#      deliberate garbage frame answers a machine-readable 400, never 500,
#      with frame_errors_total ticking exactly once);
#   2. EVERY accept loop serving a nonzero request count
#      (ingest_requests_total{loop=} balance — a silent loop is a broken
#      listener);
#   3. zero assembly-arena overflow (the zero-copy frame views land in
#      pooled arena buffers, not one-shot allocations);
#   4. runtime_compiles_total delta exactly 0 across the loaded window
#      (the framed multi-item path introduces no new specializations).
# Witnessed (TPUSERVE_LOCK_WITNESS=1): the ingest threads + main-loop hop
# double as a race-detection pass. See docs/PERFORMANCE.md "The ingest
# fast path".
set -euo pipefail
cd "$(dirname "$0")/.." || exit 1
export JAX_PLATFORMS=cpu
export TPUSERVE_LOCK_WITNESS=1

PORT=18461
N_LOOPS=3
TMPD="$(mktemp -d /tmp/ingest_smoke_XXXX)"
CFG="$TMPD/cfg.toml"
cat > "$CFG" <<EOF
host = "127.0.0.1"
port = $PORT
ingest_loops = $N_LOOPS
decode_threads = 2
startup_canary = false

[[model]]
name = "toy"
family = "toy"
batch_buckets = [1, 2, 4]
deadline_ms = 2.0
dtype = "float32"
num_classes = 10
parallelism = "single"
request_timeout_ms = 10000.0
EOF

python -m tpuserve serve --config "$CFG" &
SERVER_PID=$!
cleanup() {
  rc=$?
  if [ "$rc" -ne 0 ]; then
    # Red-run forensics (ISSUE 15): dump the live flight data so CI can
    # upload it as an artifact — diagnosable without a rerun.
    scripts/debug_dump.sh "http://127.0.0.1:$PORT" ingest_smoke || true
  fi
  kill -9 "$SERVER_PID" 2>/dev/null || true
  rm -rf "$TMPD"
}
trap cleanup EXIT

for _ in $(seq 1 60); do
  if curl -fsS "http://127.0.0.1:$PORT/healthz" >/dev/null 2>&1; then break; fi
  sleep 0.5
done
curl -fsS "http://127.0.0.1:$PORT/healthz" >/dev/null

# Pre-load scrape: the compile-delta window opens AFTER startup compiles.
curl -fsS "http://127.0.0.1:$PORT/metrics" > "$TMPD/metrics0.txt"

# Framed-wire closed loop: 2 items per POST, 8 distinct bodies, toy edge 8.
python -m tpuserve bench --url "http://127.0.0.1:$PORT" \
  --model toy --verb classify --duration 4 --warmup 1 --concurrency 16 \
  --wire frame --frame-kind rgb8 --edge 8 --batch 2 --distinct 8 \
  > "$TMPD/load.json"
echo "load: $(cat "$TMPD/load.json")"

# One deliberately malformed frame: machine-readable 400, never 500.
BAD_STATUS=$(curl -s -o "$TMPD/bad.json" -w '%{http_code}' \
  -X POST "http://127.0.0.1:$PORT/v1/models/toy:classify" \
  -H "Content-Type: application/x-tpuserve-frame" --data-binary garbage)
echo "malformed frame -> $BAD_STATUS: $(cat "$TMPD/bad.json")"

curl -fsS "http://127.0.0.1:$PORT/metrics" > "$TMPD/metrics1.txt"
curl -fsS "http://127.0.0.1:$PORT/stats" > "$TMPD/stats.json"

python - "$TMPD" "$BAD_STATUS" "$N_LOOPS" <<'EOF'
import json
import sys

tmpd, bad_status, n_loops = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])


def scrape(path):
    out = {}
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if line.startswith("#") or " " not in line:
                continue
            k, v = line.rsplit(" ", 1)
            try:
                out[k] = float(v)
            except ValueError:
                pass
    return out


m0 = scrape(f"{tmpd}/metrics0.txt")
m1 = scrape(f"{tmpd}/metrics1.txt")
with open(f"{tmpd}/load.json", encoding="utf-8") as f:
    load = json.load(f)
with open(f"{tmpd}/stats.json", encoding="utf-8") as f:
    stats = json.load(f)

# 1. zero errors on the framed run; the one injected garbage frame 400s.
assert load["n_ok"] > 0 and load["n_err"] == 0, load
assert load.get("items_per_request") == 2, load
assert bad_status == 400, f"malformed frame answered {bad_status}, want 400"
fe = m1.get('frame_errors_total{model="toy"}', 0)
assert fe == 1, f"frame_errors_total={fe}, want exactly the 1 injected"

# 2. every accept loop served requests (and bytes) — balance, not one hot loop.
per_loop = [m1.get(f'ingest_requests_total{{loop="{i}"}}', 0.0)
            for i in range(n_loops)]
assert all(v > 0 for v in per_loop), f"silent accept loop(s): {per_loop}"
ing = stats["ingest"]["loops"]
assert set(ing) == {str(i) for i in range(n_loops)}, ing
assert all(ing[str(i)]["bytes"] > 0 for i in range(n_loops)), ing

# 3. zero arena overflow: frame views assembled into pooled buffers.
overflow = m1.get('arena_overflow_total{model="toy"}', 0.0)
assert overflow == 0, f"arena overflow under framed load: {overflow}"
arena = stats["pipeline"]["models"]["toy"]["arena"]
assert arena is not None and arena["overflow_total"] == 0, arena

# 4. compile delta 0 across the loaded window: startup compiled everything.
key = 'runtime_compiles_total{model="toy"}'
assert m0.get(key, 0) > 0, "no compiles recorded at startup?"
delta = m1.get(key, 0) - m0.get(key, 0)
assert delta == 0, f"framed load recompiled: delta={delta}"

print(f"ingest smoke OK: {load['throughput_per_s']:.1f} items/s over "
      f"{n_loops} accept loops, per-loop requests {per_loop}, "
      "1 garbage frame -> 400, arena overflow 0, compile delta 0")
EOF

kill -TERM $SERVER_PID
wait $SERVER_PID 2>/dev/null || true
trap 'rm -rf "$TMPD"' EXIT
echo "ingest smoke OK"
