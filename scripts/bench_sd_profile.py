#!/usr/bin/env python
"""Profile the SD 1.5 denoise pipeline component-by-component on the chip
(VERDICT r4 missing 2 / next 3): before this script, config 5 was the one
honestly device-bound family whose device time had never been split.

Method: the chained-fori timing used by the chip probes, applied to each
component separately — CLIP text encode (2B CFG batch), one UNet step
(2B), VAE decode (B) — at full SD 1.5 size (512 px, bf16). Per-image cost
reconstructs as (text + steps * unet + vae) / B, cross-checkable against
the whole-forward chip probe. The UNet step runs twice: dense spatial
self-attention vs the Pallas flash path (options.unet_attention = "flash",
head dims zero-padded to lane alignment), which is the candidate fix for
the level-0 4096-token attention's HBM traffic.

One JSON line per measurement on stdout; markdown rows on stderr for
BASELINE.md ("SD 1.5 chip profile").

    python scripts/bench_sd_profile.py --batches 1 2 4 --iters 8
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from tpuserve.bench.probes import chained_rate_ms as rate_ms  # noqa: E402
from tpuserve.config import ModelConfig  # noqa: E402
from tpuserve.models import build  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batches", type=int, nargs="+", default=[1, 2, 4])
    ap.add_argument("--iters", type=int, default=8)
    ap.add_argument("--steps", type=int, default=20)
    args = ap.parse_args()

    cfg = ModelConfig(name="sd", family="sd15", batch_buckets=[1],
                      dtype="bfloat16", image_size=512,
                      options={"steps": args.steps})
    m = build(cfg)
    mf = build(ModelConfig(name="sdf", family="sd15", batch_buckets=[1],
                           dtype="bfloat16", image_size=512,
                           options={"steps": args.steps,
                                    "unet_attention": "flash"}))
    params = m.init_params(jax.random.key(0))
    d_txt = m.text_encoder.d_model
    rng = np.random.default_rng(0)

    for b in args.batches:
        b2 = 2 * b  # CFG: cond + uncond lanes in one UNet/text call
        ids2 = jnp.asarray(np.ones((b2, 77), np.int32))
        lat2 = jnp.asarray(rng.standard_normal(
            (b2, m.latent, m.latent, 4)).astype(np.float32))
        t2 = jnp.full((b2,), 500, jnp.int32)
        ctx2 = jnp.asarray(rng.standard_normal(
            (b2, 77, d_txt)).astype(np.float32)).astype(m.dtype)
        lat1 = lat2[:b]

        row = {"batch": b}
        row["text_ms"] = round(rate_ms(
            lambda p, ids: m.text_encoder.apply(p, ids),
            (params["text"], ids2), args.iters), 2)
        row["unet_dense_ms"] = round(rate_ms(
            lambda p, x, t, c: m.unet.apply(p, x, t, c),
            (params["unet"], lat2, t2, ctx2), args.iters), 2)
        row["unet_flash_ms"] = round(rate_ms(
            lambda p, x, t, c: mf.unet.apply(p, x, t, c),
            (params["unet"], lat2, t2, ctx2), args.iters), 2)
        row["vae_ms"] = round(rate_ms(
            lambda p, z: m.vae.apply(p, z),
            (params["vae"], lat1), args.iters), 2)
        for impl in ("dense", "flash"):
            unet = row[f"unet_{impl}_ms"]
            total = row["text_ms"] + args.steps * unet + row["vae_ms"]
            row[f"image_ms_{impl}"] = round(total / b, 1)
            row[f"img_s_{impl}"] = round(1000.0 * b / total, 3)
        print(json.dumps(row), flush=True)
        print(f"# | {b} | {row['text_ms']} | {row['unet_dense_ms']} | "
              f"{row['unet_flash_ms']} | {row['vae_ms']} | "
              f"{row['image_ms_dense']} | {row['image_ms_flash']} |",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
