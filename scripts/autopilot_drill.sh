#!/usr/bin/env bash
# Hostile-tenant autopilot drill (ISSUE 16): a REAL router fleet (2 host
# domains x 2 workers, one slot per host dormant as scale-up headroom)
# with the self-healing controller engaged and per-tenant containment on.
# Unattended, two things go wrong at once:
#   - tenant "hostile" floods far past its device-seconds quota and
#     request rate (and ignores Retry-After);
#   - a seeded [faults] worker_slow rule arms itself MID-load (after_s),
#     pinned to one boot-active worker — a single-host latency fault.
# Gates (docs/OPERATIONS.md "Self-operating fleet"):
#   1. containment: the hostile overage is 429'd at admission with
#      tenant_* shed reasons, while the victim tenant's availability
#      holds >= 97% through flood + fault, no operator in the loop;
#   2. reaction: the controller acts (scale_up under pressure and/or
#      shed-on-burn) within the run, first action inside the load window;
#   3. audit: every controller decision — rollbacks included — is
#      readable from GET /debug/audit as an autopilot:* verb, fetched
#      over HTTP from the live fleet.
# A second leg runs the pure-policy + tenant-ledger suites.
# Runs the real `python -m tpuserve chaos --drill autopilot` CLI; wired
# into chaos_smoke.sh and CI next to the worker/host/fleet drills.
set -euo pipefail
cd "$(dirname "$0")/.." || exit 1
export JAX_PLATFORMS=cpu
# Race-detection pass rides along (docs/ANALYSIS.md): router, host agents,
# the controller tick, and all workers run under witnessed locks.
export TPUSERVE_LOCK_WITNESS=1

CFG="$(mktemp /tmp/tpuserve_autopilot_drill.XXXXXX.toml)"
OUT="$(mktemp /tmp/tpuserve_autopilot_drill.XXXXXX.json)"
BB="$(mktemp -d /tmp/tpuserve_autopilot_drill_bb.XXXXXX)"
trap 'rm -f "$CFG" "$OUT"; rm -rf "$BB"' EXIT

cat > "$CFG" <<EOF
decode_threads = 2
startup_canary = false
drain_timeout_s = 5.0
watchdog_interval_s = 0.2

[telemetry]
sample_interval_s = 0.25
burn_windows_s = [5.0, 30.0, 120.0]

[events]
dir = "$BB"
snapshot_interval_s = 0.3

[router]
enabled = true
hosts = 2
workers = 2
active_workers = 1
retry_max = 3
hedge_ms = 500.0
health_interval_s = 0.2
respawn_initial_s = 0.5
respawn_max_s = 5.0

[autopilot]
enabled = true
interval_s = 0.25
hysteresis_ticks = 2
cooldown_s = 3.0
window_s = 30.0
max_actions_per_window = 8
follow_up_s = 5.0
pressure_high = 1.5
pressure_low = 0.05

[tenants]
enabled = true
window_s = 30.0
slo_latency_ms = 2000.0
slo_availability = 0.99

[[tenants.tenant]]
name = "hostile"
api_key = "drill-hostile-key"
weight = 1.0
quota_device_s = 3.0
rate_per_s = 40.0

[[tenants.tenant]]
name = "victim"
api_key = "drill-victim-key"
weight = 4.0

[faults]
enabled = true
seed = 7

# Single-host latency fault, armed mid-load: worker 2 is host 1's
# boot-active slot (wid = host * workers + i with active_workers = 1).
[[faults.rule]]
kind = "worker_slow"
model = "*"
probability = 1.0
delay_ms = 250.0
after_s = 6.0
worker = 2

[[model]]
name = "toy"
family = "toy"
batch_buckets = [1, 2]
deadline_ms = 2.0
dtype = "float32"
num_classes = 10
parallelism = "single"
request_timeout_ms = 10000.0
wire_size = 8
EOF

python -m tpuserve chaos --config "$CFG" --drill autopilot \
    --duration 18 --warmup 1 --concurrency 12 \
    --min-availability 0.97 | tee "$OUT"

python - "$OUT" <<'EOF'
import json, sys

s = json.load(open(sys.argv[1]))
hostile, victim = s["tenants"]["hostile"], s["tenants"]["victim"]
ap, audit = s["autopilot"], s["audit"]

# 1. Containment: the flood was 429'd with tenant_* reasons, and the
#    overage cost the hostile tenant, never the victim.
assert hostile["n_429"] > 0, f"hostile flood never shed: {hostile}"
t_reasons = {r: n for r, n in hostile["reasons"].items()
             if r.startswith("tenant_")}
assert t_reasons, f"no tenant_* shed reason on hostile 429s: {hostile}"
assert s["availability"] >= 0.97, \
    f"victim availability {s['availability']} under flood + fault"
assert victim["n_429"] == 0, \
    f"victim was rate/quota-shed — containment leaked: {victim}"

# 2. Reaction: the controller acted unattended, within the load window,
#    and its scale/shed verbs are the ones that matter here.
assert ap["actions_total"] >= 1, f"controller never acted: {ap}"
acted = set(ap["action_kinds"])
assert acted & {"scale_up", "shed_on"}, \
    f"no scale_up/shed_on under pressure+burn: {ap['action_kinds']}"
assert ap["first_action_s"] is not None and ap["first_action_s"] <= 18.0, \
    f"first controller action outside the load window: {ap['first_action_s']}"
assert ap["errors_total"] == 0, f"controller actuation errors: {ap}"
assert ap["http_status"] == 200, \
    f"GET /debug/autopilot returned {ap['http_status']}"

# 3. Audit: every decision (rollbacks included) readable from the live
#    /debug/audit endpoint as an autopilot:* verb.
assert audit["complete"], f"decisions missing from /debug/audit: {audit}"
assert audit["autopilot_records"] >= ap["actions_total"] or \
    audit["autopilot_records"] >= audit["decisions_total"], audit
# Decisions carry their triggering signal values into the trail.
assert all(d.get("signals") for d in ap["decisions"]), \
    "a controller decision recorded no triggering signals"

# Per-tenant SLO burn stayed green for the victim (never 'firing').
v_slo = s.get("tenant_slo", {}).get("victim", {})
assert v_slo.get("state", "ok") != "firing", \
    f"victim SLO burned during the drill: {v_slo}"

# The ledger charged the hostile tenant real device-seconds.
usage = s["usage"]["tenants"]
assert usage["hostile"]["device_seconds_total"] > 0, usage
assert s["tenants_endpoint_status"] == 200, \
    f"GET /tenants returned {s['tenants_endpoint_status']}"

print(f"autopilot drill OK: victim availability {s['availability']}, "
      f"hostile shed {hostile['n_429']}x ({t_reasons}), "
      f"controller {ap['actions_total']} actions {dict(ap['action_kinds'])} "
      f"first at {ap['first_action_s']}s, "
      f"{audit['autopilot_records']} audit records "
      f"(rollbacks {ap['rollbacks_total']})")
EOF

echo "== pure-policy + tenant-ledger suites =="
python -m pytest tests/test_autopilot.py tests/test_tenants.py -q \
    -p no:cacheprovider

echo "autopilot drill OK"
