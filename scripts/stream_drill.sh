#!/usr/bin/env bash
# Mid-stream chaos drill (ISSUE 17): a REAL router + 2 worker processes
# serving a generative model under MIXED streaming + unary load; SIGKILL
# one worker mid-stream and assert the fail-safe stream semantics hold
# (docs/ROBUSTNESS.md "Streaming failure semantics"):
#   1. every stream that STARTED on the dead worker ends in a well-formed
#      error terminal — zero torn streams (silent truncation is the one
#      forbidden outcome);
#   2. zero duplicate or reordered tokens: the first-byte latch means no
#      post-latch retry/hedge, byte-audited against a seeded reference
#      (done streams match exactly; error streams are strict prefixes);
#   3. streams that had NOT started retry transparently (unary
#      availability >= 99% across the run, kill included);
#   4. the kill perturbs nothing on the survivor: compile deltas 0;
#   5. the supervisor respawns the victim within the backoff budget.
# Runs the real `python -m tpuserve chaos --drill stream_kill` CLI; wired
# into chaos_smoke.sh and CI next to the worker/host/autopilot drills.
set -euo pipefail
cd "$(dirname "$0")/.." || exit 1
export JAX_PLATFORMS=cpu
# Race-detection pass rides along (docs/ANALYSIS.md): router, supervisor,
# engine stream channels, and both workers run under witnessed locks.
export TPUSERVE_LOCK_WITNESS=1
export TPUSERVE_RETRACE_WITNESS=1

CFG="$(mktemp /tmp/tpuserve_stream_drill.XXXXXX.toml)"
OUT="$(mktemp /tmp/tpuserve_stream_drill.XXXXXX.json)"
BB="$(mktemp -d /tmp/tpuserve_stream_drill_bb.XXXXXX)"
trap 'rm -f "$CFG" "$OUT"; rm -rf "$BB"' EXIT

cat > "$CFG" <<EOF
decode_threads = 2
startup_canary = false
drain_timeout_s = 5.0

[events]
dir = "$BB"
snapshot_interval_s = 0.3

[genserve]
enabled = true
slots = 4
stream_queue = 64
stream_heartbeat_s = 2.0
stream_drain_s = 3.0

[router]
enabled = true
workers = 2
retry_max = 2
hedge_ms = 200.0
health_interval_s = 0.2
respawn_initial_s = 0.5
respawn_max_s = 5.0
stream_idle_timeout_ms = 10000.0
stream_drain_s = 3.0

[[model]]
name = "textgen"
family = "textgen"
batch_buckets = [1, 2, 4]
dtype = "float32"
parallelism = "single"
request_timeout_ms = 60000.0
stream_policy = "drop"

[model.slo]
latency_ms = 5000.0
first_unit_ms = 2000.0

[model.options]
layers = 1
d_model = 64
heads = 2
d_ff = 128
vocab_size = 512
prompt_len = 16
max_new_tokens = 32
EOF

python -m tpuserve chaos --config "$CFG" --drill stream_kill \
    --duration 14 --warmup 1 --concurrency 12 --kill-after 2 \
    --respawn-budget 90 --min-availability 0.99 | tee "$OUT"

python - "$OUT" <<'EOF'
import json, sys

s = json.load(open(sys.argv[1]))
kill = s["kill"]
a = s["stream_audit"]

# Gate 3: un-started streams retried transparently — the unary load's
# availability is the survivors' view of the fleet.
assert s["availability"] >= 0.99, f"availability {s['availability']}"

# Gate 1: zero silent truncations. Every started stream carries exactly
# one terminal; the SIGKILL-cut streams must show the router's appended
# error terminal, never a bare EOF.
assert a["started"] > 0 and a["done"] > 0, a
assert a["torn"] == 0, f"torn streams (silent truncation): {a}"
assert a["error_terminals"] >= 1, \
    f"the mid-stream SIGKILL should cut at least one stream: {a}"
assert a["done"] + a["error_terminals"] == a["started"], a

# Gate 2: zero duplicate/reordered tokens, byte-audited vs the seeded
# reference. The first-byte latch forbids post-latch re-dispatch, so a
# replayed or doubled token shows up as an order violation, a byte
# mismatch on a done stream, or a non-prefix on an error stream.
assert a["order_violations"] == 0, f"duplicate/reordered tokens: {a}"
assert a["mismatched"] == 0, f"done-stream byte mismatch vs reference: {a}"
assert a["non_prefix"] == 0, f"error-stream text not a prefix: {a}"

# Gate 4: the kill recompiles nothing on the survivor.
deltas = s["compile_deltas"]
assert deltas and all(v == 0 for v in deltas.values()), \
    f"survivor recompiled under the kill: {deltas}"

# Gate 5: respawn within budget; fleet healthy at the end.
assert kill.get("respawn_s") is not None, f"no respawn within budget: {kill}"
assert s["workers"]["healthy"] == 2, s["workers"]
assert s["workers"]["deaths_total"] == 1, s["workers"]

# The router's own books must agree with the client-side audit.
r = s["router"]
assert r["streams_total"] >= a["started"], (r, a)
term = r["stream_terminated"]
n_err_rows = sum(v for k, v in term.items()
                 if "reason=done" not in k)
assert n_err_rows >= 1, f"router counted no mid-stream terminations: {term}"

# Postmortem evidence (ISSUE 15): the SIGKILL must be diagnosable from
# the artifact alone.
pms = [p for p in s.get("postmortems", []) if p.get("signal") == "SIGKILL"]
assert pms and pms[0]["pid"] == kill["killed_pid"], s.get("postmortems")

print(f"stream drill OK: availability {s['availability']}, "
      f"{a['started']} streams started ({a['done']} done, "
      f"{a['error_terminals']} error terminals, 0 torn, 0 reordered, "
      f"0 byte mismatches), first-token p99 {a['first_token_p99_ms']}ms, "
      f"gap p99 {a['inter_token_gap_p99_ms']}ms, "
      f"respawn {kill['respawn_s']}s, compile deltas all 0")
EOF

echo "stream drill OK"
