#!/usr/bin/env bash
# Trace smoke (ISSUE 12): a REAL router + 2 worker-process fleet under
# closed-loop load with one injected worker_slow fault (400 ms, once per
# worker), gating the end-to-end tracing contract (docs/OBSERVABILITY.md):
#   1. EVERY response carries a well-formed X-Trace-Id — and an error
#      response repeats it as trace_id in the JSON body;
#   2. the slow request appears in the router's /debug/slow with its
#      trace id, and /debug/trace?trace_id= returns a STITCHED span tree
#      crossing the router→worker hop: one trace id end-to-end, router
#      spans on pid 0 (request + attempt), the worker's full serving tree
#      (request/body_read/parse/queue/compute) on its own pid lane;
#   3. /metrics exemplar lines parse (OpenMetrics exemplar syntax with a
#      32-hex trace id) on both the router and a worker;
#   4. runtime_compiles_total delta is exactly 0 across the traced window
#      (tracing introduces no new specializations).
# Witnessed (TPUSERVE_LOCK_WITNESS=1): recorder + exemplar locks are hit
# from every accept loop, so the run doubles as a race-detection pass.
set -euo pipefail
cd "$(dirname "$0")/.." || exit 1
export JAX_PLATFORMS=cpu
export TPUSERVE_LOCK_WITNESS=1

PORT=18473
TMPD="$(mktemp -d /tmp/trace_smoke_XXXX)"
CFG="$TMPD/cfg.toml"
cat > "$CFG" <<EOF
host = "127.0.0.1"
port = $PORT
decode_threads = 2
startup_canary = false
drain_timeout_s = 5.0
ingest_loops = 2

[trace]
slow_n = 8
error_capacity = 64

[router]
enabled = true
workers = 2
retry_max = 2
health_interval_s = 0.2

[[model]]
name = "toy"
family = "toy"
batch_buckets = [1, 2, 4]
deadline_ms = 2.0
dtype = "float32"
num_classes = 10
parallelism = "single"
request_timeout_ms = 10000.0
wire_size = 8

[faults]
enabled = true
seed = 3

[[faults.rule]]
kind = "worker_slow"
model = "toy"
probability = 1.0
count = 1
delay_ms = 400.0
EOF

python -m tpuserve serve --config "$CFG" &
SERVER_PID=$!
cleanup() {
  rc=$?
  if [ "$rc" -ne 0 ]; then
    # Red-run forensics (ISSUE 15): dump the live flight data so CI can
    # upload it as an artifact — diagnosable without a rerun.
    scripts/debug_dump.sh "http://127.0.0.1:$PORT" trace_smoke || true
  fi
  kill -9 "$SERVER_PID" 2>/dev/null || true
  rm -rf "$TMPD"
}
trap cleanup EXIT

for _ in $(seq 1 120); do
  if curl -fsS "http://127.0.0.1:$PORT/healthz" >/dev/null 2>&1; then break; fi
  sleep 0.5
done
curl -fsS "http://127.0.0.1:$PORT/healthz" >/dev/null

# Pre-load scrape of both workers: the compile-delta window opens AFTER
# startup compiles (the injected slow requests and all traced load must
# recompile nothing).
curl -fsS "http://127.0.0.1:$PORT/workers/0/metrics" > "$TMPD/w0_before.txt"
curl -fsS "http://127.0.0.1:$PORT/workers/1/metrics" > "$TMPD/w1_before.txt"

# Closed-loop load through the router (the worker_slow rules fire on the
# first request each worker serves — those become the recorded slow tail).
python -m tpuserve bench --url "http://127.0.0.1:$PORT" \
  --model toy --verb classify --duration 4 --warmup 1 --concurrency 8 \
  --distinct 8 --edge 8 > "$TMPD/load.json"
echo "load: $(cat "$TMPD/load.json")"

curl -fsS "http://127.0.0.1:$PORT/workers/0/metrics" > "$TMPD/w0_after.txt"
curl -fsS "http://127.0.0.1:$PORT/workers/1/metrics" > "$TMPD/w1_after.txt"
curl -fsS "http://127.0.0.1:$PORT/metrics" > "$TMPD/router_metrics.txt"

python - "$TMPD" "http://127.0.0.1:$PORT" <<'EOF'
import json
import re
import sys
import urllib.request

tmpd, base = sys.argv[1], sys.argv[2]
TID_RE = re.compile(r"^[0-9a-f]{32}$")


def get(path):
    with urllib.request.urlopen(base + path, timeout=30) as r:
        return r.status, dict(r.headers), r.read()


def post(path, body, ctype="application/x-npy"):
    req = urllib.request.Request(base + path, data=body,
                                 headers={"Content-Type": ctype})
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, dict(r.headers), r.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


def npy(seed):
    import io

    import numpy as np

    buf = io.BytesIO()
    np.save(buf, np.random.default_rng(seed).integers(
        0, 255, (8, 8, 3), dtype=np.uint8))
    return buf.getvalue()


with open(f"{tmpd}/load.json", encoding="utf-8") as f:
    load = json.load(f)
assert load["n_ok"] > 0 and load["n_err"] == 0, load

# 1. Every response carries a well-formed X-Trace-Id — all distinct.
seen = set()
for i in range(20):
    status, headers, _ = post("/v1/models/toy:classify", npy(100 + i))
    assert status == 200, status
    tid = headers.get("X-Trace-Id", "")
    assert TID_RE.match(tid), f"bad/missing X-Trace-Id: {tid!r}"
    seen.add(tid)
assert len(seen) == 20, "trace ids must be unique per request"

# ...including error responses, which repeat it in the JSON body.
status, headers, body = post("/v1/models/toy:classify", b"garbage")
assert status == 400, (status, body)
err = json.loads(body)
assert err.get("trace_id") == headers.get("X-Trace-Id"), err
assert TID_RE.match(err["trace_id"]), err

# 2. The injected-slow request is in /debug/slow; its stitched trace
# crosses the router→worker hop with ONE id end-to-end.
_, _, raw = get("/debug/slow")
dump = json.loads(raw)
slow = dump["slow"].get("toy", [])
assert slow, "empty slow reservoir after loaded run"
rec = max(slow, key=lambda r: r["duration_ms"])
assert rec["duration_ms"] >= 300.0, \
    f"worker_slow (400 ms) not the recorded tail: {rec['duration_ms']} ms"
tid = rec["trace_id"]
assert TID_RE.match(tid)

status, _, raw = get(f"/debug/trace?trace_id={tid}")
assert status == 200
events = json.loads(raw)["traceEvents"]
assert events and all(e["args"]["trace_id"] == tid for e in events), \
    "stitched trace must carry one trace id end-to-end"
by_pid = {}
for e in events:
    by_pid.setdefault(e["pid"], set()).add(e["name"])
assert {"request", "attempt"} <= by_pid.get(0, set()), by_pid
worker_pids = sorted(p for p in by_pid if p >= 1)
assert worker_pids, f"no worker-side spans stitched in: {by_pid}"
worker_names = set().union(*(by_pid[p] for p in worker_pids))
assert {"request", "body_read", "parse", "queue", "compute"} <= worker_names, \
    worker_names
# The hop is visible: the worker's request span starts inside the
# router's attempt span.
attempt_ts = min(e["ts"] for e in events
                 if e["pid"] == 0 and e["name"] == "attempt")
worker_ts = min(e["ts"] for e in events
                if e["pid"] >= 1 and e["name"] == "request")
assert worker_ts >= attempt_ts, (attempt_ts, worker_ts)

# 3. Exemplar lines parse on the router AND a worker.
EX_RE = re.compile(
    r'_bucket\{.*le="[^"]+"\} \d+ '
    r'# \{trace_id="[0-9a-f]{32}"\} [0-9.e+-]+ \d+\.\d+$')
for page in ("router_metrics.txt", "w0_after.txt"):
    with open(f"{tmpd}/{page}", encoding="utf-8") as f:
        lines = [ln for ln in f.read().splitlines() if "# {trace_id=" in ln]
    assert lines, f"no exemplar lines in {page}"
    bad = [ln for ln in lines if not EX_RE.search(ln)]
    assert not bad, f"unparseable exemplar lines in {page}: {bad[:3]}"

# 4. Compile delta 0 across the traced window, on every worker.
def scrape(path):
    out = {}
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if line.startswith("#") or " " not in line:
                continue
            if " # {" in line:  # strip exemplar suffix before parsing
                line = line.split(" # {", 1)[0]
            k, v = line.rsplit(" ", 1)
            try:
                out[k] = float(v)
            except ValueError:
                pass
    return out


key = 'runtime_compiles_total{model="toy"}'
for w in (0, 1):
    before = scrape(f"{tmpd}/w{w}_before.txt")
    after = scrape(f"{tmpd}/w{w}_after.txt")
    assert before.get(key, 0) > 0, f"worker {w}: no startup compiles?"
    delta = after.get(key, 0) - before.get(key, 0)
    assert delta == 0, f"worker {w}: traced load recompiled (delta={delta})"

print(f"trace smoke OK: {load['throughput_per_s']:.1f} req/s, "
      f"slow trace {tid[:8]}… stitched across pids {[0] + worker_pids} "
      f"({rec['duration_ms']:.0f} ms), exemplars parse, compile delta 0")
EOF

kill -TERM "$SERVER_PID"
wait "$SERVER_PID" 2>/dev/null || true
trap 'rm -rf "$TMPD"' EXIT
echo "trace smoke OK"
