#!/usr/bin/env bash
# Chaos smoke: the tier-1 fast suite plus the chaos suite (including its
# slow tests) under forced-CPU JAX. Intended for CI and pre-merge runs;
# see docs/ROBUSTNESS.md.
set -euo pipefail
cd "$(dirname "$0")/.." || exit 1
export JAX_PLATFORMS=cpu
# Arm the runtime lock-order witness (docs/ANALYSIS.md): every suite and
# drill below doubles as a race-detection pass — an AB/BA inversion or a
# threading lock held across an await raises and fails the run.
export TPUSERVE_LOCK_WITNESS=1

echo "== tier-1 (fast, -m 'not slow') =="
python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors \
    -p no:cacheprovider

echo "== chaos suite (tests/test_faults.py, all tiers) =="
python -m pytest tests/test_faults.py -q -p no:cacheprovider

echo "== lifecycle suite (tests/test_lifecycle.py) =="
python -m pytest tests/test_lifecycle.py -q -p no:cacheprovider

echo "== reload drill (reload_corrupt @ 100%, availability >= 99%) =="
scripts/reload_drill.sh

echo "== pipeline smoke (closed loop, zero errors, live occupancy) =="
scripts/pipeline_smoke.sh

echo "== cache smoke (hit-heavy / reload churn / miss-only parity) =="
scripts/cache_smoke.sh

echo "== roofline smoke (variant registry / zero recompiles / compute split) =="
scripts/roofline_smoke.sh

echo "== genserve smoke (mixed-length load, early exits + fold-ins, compile delta 0) =="
scripts/genserve_smoke.sh

echo "== pagedkv smoke (slot-count win at fixed KV memory, flat gap p99 under chunked prefill, compile delta 0) =="
scripts/pagedkv_smoke.sh

echo "== meshgen smoke (replica group balanced, sharded==single token parity, reload mid-load, compile delta 0) =="
scripts/meshgen_smoke.sh

echo "== ingest smoke (framed wire, 3 accept loops balanced, compile delta 0) =="
scripts/ingest_smoke.sh

echo "== multichip smoke (8 replicas all serving / sharded mesh / reload mid-load) =="
scripts/multichip_smoke.sh

echo "== trace smoke (X-Trace-Id everywhere, stitched slow trace across the router->worker hop, exemplars, compile delta 0) =="
scripts/trace_smoke.sh

echo "== telemetry smoke (fleet sum exact, burn-rate alert fires + clears, history, compile delta 0) =="
scripts/telemetry_smoke.sh

echo "== events smoke (SIGKILL postmortem with stderr tail + snapshot, audited fleet reload, trace-event interleave, compile delta 0) =="
scripts/events_smoke.sh

echo "== worker drill (SIGKILL a worker mid-load, availability >= 99%) =="
scripts/worker_drill.sh

echo "== stream drill (SIGKILL a worker mid-stream, zero torn streams, byte-audited tokens, availability >= 99%) =="
scripts/stream_drill.sh

echo "== host drill (killpg an entire host mid-load, survivors >= 99%, sharded-cache router kill) =="
scripts/host_drill.sh

echo "== fleet drill (poison one model @ 100%, survivors hold >= 99%) =="
scripts/fleet_drill.sh

echo "== autopilot drill (hostile tenant + mid-load latency fault, controller sheds/scales/contains unattended) =="
scripts/autopilot_drill.sh

echo "chaos smoke OK"
