#!/usr/bin/env python
"""Measure the dev-TPU link physics that sizing decisions rest on.

Each experiment runs in its OWN subprocess (fresh PJRT session): the first
device->host read permanently changes a session's transfer mode, so H2D
numbers must be taken before any D2H in that process.

Run on the TPU box:  python scripts/probe_relay.py
Emits one JSON object per experiment on stdout; a summary table at the end.
Results are recorded in BASELINE.md ("Link physics" section).
"""

from __future__ import annotations

import json
import subprocess
import sys
import textwrap

EXPERIMENTS = {
    # H2D bandwidth in a virgin session (no D2H ever).
    "h2d_virgin": """
        import time, json
        import numpy as np, jax
        mb = 32
        arr = np.random.default_rng(0).integers(0, 255, (mb << 20,), np.uint8)
        out = []
        for i in range(6):
            t0 = time.perf_counter()
            d = jax.device_put(arr)
            jax.block_until_ready(d)
            dt = time.perf_counter() - t0
            out.append(round(mb / dt, 1))
        print(json.dumps({"exp": "h2d_virgin", "mb": mb, "mbps_per_iter": out}))
    """,
    # Cost of D2H reads: the first (mode flip) and steady-state, small + large.
    "d2h_costs": """
        import time, json
        import numpy as np, jax, jax.numpy as jnp
        small = jax.device_put(np.zeros((64, 5), np.float32))
        big = jax.device_put(np.zeros((8 << 20,), np.uint8))  # 8 MB
        jax.block_until_ready((small, big))
        reads = []
        for i in range(5):
            t0 = time.perf_counter(); np.asarray(small); reads.append(round((time.perf_counter()-t0)*1e3, 1))
        t0 = time.perf_counter(); np.asarray(big); big_ms = round((time.perf_counter()-t0)*1e3, 1)
        print(json.dumps({"exp": "d2h_costs", "small_1kb_ms": reads, "big_8mb_ms": big_ms}))
    """,
    # H2D bandwidth AFTER a D2H read (degraded mode?).
    "h2d_after_d2h": """
        import time, json
        import numpy as np, jax
        d = jax.device_put(np.zeros((64,), np.float32)); jax.block_until_ready(d)
        np.asarray(d)  # flip the session
        mb = 32
        arr = np.random.default_rng(0).integers(0, 255, (mb << 20,), np.uint8)
        out = []
        for i in range(4):
            t0 = time.perf_counter()
            dd = jax.device_put(arr); jax.block_until_ready(dd)
            out.append(round(mb / (time.perf_counter() - t0), 1))
        print(json.dumps({"exp": "h2d_after_d2h", "mb": mb, "mbps_per_iter": out}))
    """,
    # ResNet-50 bucket-128 compute time with device-resident input vs with
    # per-batch H2D (rgb8 224 wire) vs full run+fetch cycle.
    "resnet_compute": """
        import time, json
        import numpy as np, jax
        from tpuserve.config import ModelConfig
        from tpuserve.models import build
        from tpuserve.runtime import build_runtime
        B = 128
        cfg = ModelConfig(name="r", family="resnet50", batch_buckets=[B],
                          parallelism="single", dtype="bfloat16", wire_size=224)
        model = build(cfg)
        rt = build_runtime(model)
        batch = np.random.default_rng(0).integers(0, 255, (B, 224, 224, 3), np.uint8)
        exe = rt.executables[(B,)][0]
        dev = jax.device_put(batch, jax.tree_util.tree_leaves(exe.batch_sharding)[0])
        jax.block_until_ready(dev)
        # device-resident repeat: pure compute
        outs = exe.compiled(rt.params_per_mesh[0], dev); jax.block_until_ready(outs)
        t0 = time.perf_counter()
        for _ in range(5):
            outs = exe.compiled(rt.params_per_mesh[0], dev)
        jax.block_until_ready(outs)
        compute_ms = (time.perf_counter() - t0) / 5 * 1e3
        # h2d + dispatch (no fetch)
        t0 = time.perf_counter()
        for _ in range(5):
            o2 = rt.run((B,), batch)
        jax.block_until_ready(o2)
        h2d_compute_ms = (time.perf_counter() - t0) / 5 * 1e3
        # full cycle with per-batch fetch
        t0 = time.perf_counter()
        for _ in range(5):
            o3 = rt.fetch(rt.run((B,), batch))
        cycle_ms = (time.perf_counter() - t0) / 5 * 1e3
        print(json.dumps({"exp": "resnet_compute", "batch": B,
                          "compute_ms": round(compute_ms, 1),
                          "h2d_plus_compute_ms": round(h2d_compute_ms, 1),
                          "full_cycle_ms": round(cycle_ms, 1),
                          "imgs_per_s_cycle": round(B / (cycle_ms / 1e3), 1)}))
    """,
    # Host-side per-image costs on this box (1 CPU core).
    "host_costs": """
        import io, time, json
        import numpy as np
        from tpuserve.bench.loadgen import synthetic_image_jpeg
        from tpuserve import preproc, native
        payload = synthetic_image_jpeg(256)
        def bench(fn, n=60):
            fn()
            t0 = time.perf_counter()
            for _ in range(n): fn()
            return round((time.perf_counter() - t0) / n * 1e3, 2)
        res = {"exp": "host_costs", "jpeg_bytes": len(payload)}
        res["pil_rgb_ms"] = bench(lambda: preproc.decode_image(payload, "image/jpeg", 256))
        res["pil_rgb_yuv_ms"] = bench(lambda: preproc.rgb_to_yuv420(
            preproc.decode_image(payload, "image/jpeg", 256)))
        res["native_yuv_ms"] = bench(lambda: native.decode_yuv420(payload, 256)) \
            if native.available() else None
        import numpy as _np
        arrs = [_np.zeros((224,224,3), _np.uint8) for _ in range(64)]
        res["stack64_ms"] = bench(lambda: _np.stack(arrs), n=100)
        print(json.dumps(res))
    """,
}


def main() -> int:
    results = {}
    for name, code in EXPERIMENTS.items():
        proc = subprocess.run(
            [sys.executable, "-c", textwrap.dedent(code)],
            capture_output=True, text=True, timeout=1200, cwd="/root/repo",
        )
        line = proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() else ""
        try:
            results[name] = json.loads(line)
        except json.JSONDecodeError:
            results[name] = {"exp": name, "error": proc.stderr[-2000:]}
        print(json.dumps(results[name]), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
