#!/usr/bin/env bash
# Pipeline smoke (ISSUE 3): short closed loop through the REAL server on the
# CPU backend, asserting zero errors and live pipeline telemetry — the
# /stats "pipeline" block must show monotone nondecreasing per-stage
# submitted counters, nonzero in-flight occupancy at peak, and arena
# recycling with zero overflow. Run by CI next to the chaos/reload drills;
# see docs/PERFORMANCE.md "Reading the metrics".
set -euo pipefail
cd "$(dirname "$0")/.." || exit 1
export JAX_PLATFORMS=cpu
# Race-detection pass rides along (docs/ANALYSIS.md): witnessed locks +
# per-suspension held-lock checks; a violation raises and fails the smoke.
export TPUSERVE_LOCK_WITNESS=1

python - <<'EOF'
import asyncio
import json
import sys

from aiohttp import web
import aiohttp

from tpuserve.config import ModelConfig, ServerConfig
from tpuserve.server import ServerState, make_app


async def main() -> None:
    cfg = ServerConfig(
        decode_threads=2,
        startup_canary=False,
        models=[ModelConfig(
            name="toy", family="toy", batch_buckets=[1, 2, 4],
            deadline_ms=5.0, dtype="float32", num_classes=10,
            parallelism="single", request_timeout_ms=10_000.0,
            wire_size=8, max_inflight=2,
        )],
    )
    state = ServerState(cfg)
    state.build()
    runner = web.AppRunner(make_app(state), access_log=None)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    samples = []
    try:
        port = runner.addresses[0][1]
        base = f"http://127.0.0.1:{port}"
        from tpuserve.bench.loadgen import run_load, synthetic_image_npy

        payload = synthetic_image_npy(edge=8)

        async def sampler() -> None:
            async with aiohttp.ClientSession() as s:
                while True:
                    await asyncio.sleep(0.3)
                    async with s.get(f"{base}/stats") as r:
                        samples.append((await r.json())["pipeline"])

        task = asyncio.get_running_loop().create_task(sampler())
        try:
            result = await run_load(f"{base}/v1/models/toy:classify",
                                    payload, "application/x-npy",
                                    duration_s=6.0, concurrency=12,
                                    warmup_s=1.0)
        finally:
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass
        async with aiohttp.ClientSession() as s:
            async with s.get(f"{base}/stats") as r:
                samples.append((await r.json())["pipeline"])
    finally:
        await runner.cleanup()

    summary = result.summary()
    assert result.n_err == 0, f"errors during smoke: {summary}"
    assert result.n_ok > 0, summary
    assert len(samples) >= 2, "sampler never observed /stats"

    # Monotone nonzero stage activity: every stage's submitted counter is
    # nondecreasing across samples and nonzero by the end.
    for stage in ("assemble", "h2d", "fetch", "postproc"):
        series = [s["stages"]["submitted_total"][stage] for s in samples]
        assert all(b >= a for a, b in zip(series, series[1:])), (stage, series)
        assert series[-1] > 0, (stage, series)

    toy = samples[-1]["models"]["toy"]
    assert toy["mode"] == "direct", toy
    assert toy["inflight_peak"] >= 1, toy
    assert toy["inflight"] == 0, toy  # drained after the run
    arena = toy["arena"]
    assert arena is not None and arena["overflow_total"] == 0, toy
    assert any(b["pooled"] > 0 for b in arena["buckets"].values()), toy

    print(f"pipeline smoke OK: n_ok={result.n_ok} "
          f"throughput={summary['throughput_per_s']}/s "
          f"submitted={samples[-1]['stages']['submitted_total']} "
          f"inflight_peak={toy['inflight_peak']}")


asyncio.run(main())
EOF
