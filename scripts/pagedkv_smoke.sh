#!/usr/bin/env bash
# Paged-KV + chunked-prefill smoke (ISSUE 18): the REAL server on the CPU
# backend, witnessed (TPUSERVE_LOCK_WITNESS=1), gating the two claims the
# tentpole makes — measured, not asserted:
#   1. SLOT-COUNT WIN AT FIXED MEMORY: the page pool is sized to cover
#      fewer dense worst-case-context slots than the engine serves; under
#      sustained streaming load the measured peak of simultaneously
#      active slots must STRICTLY exceed what a dense slab of the same
#      KV bytes could hold.
#   2. FLAT INTER-TOKEN p99 UNDER MID-LOAD MAX-LENGTH PREFILL: a skewed
#      pool (a max-length prompt injected amid shorts, chunk-prefilled 4
#      tokens per iteration) must keep the streaming inter-token p99
#      within a generous bound of the unloaded pass (ratio 3x + 25 ms
#      absolute slack for CPU-host noise).
# Plus the bookkeeping gates: zero errors, zero torn streams, a :reload
# publish mid-run, runtime_compiles_total delta EXACTLY 0 across slot
# churn + page churn + reload, and a page ledger exactly balanced after
# drain. Wired into chaos_smoke.sh and CI next to genserve_smoke.sh; see
# docs/PERFORMANCE.md "Paged KV & chunked prefill".
set -euo pipefail
cd "$(dirname "$0")/.." || exit 1
export JAX_PLATFORMS=cpu
export TPUSERVE_LOCK_WITNESS=1
export TPUSERVE_RETRACE_WITNESS=1

python - <<'EOF'
import asyncio

import aiohttp
from aiohttp import web

from tpuserve.bench.loadgen import run_stream_load, synthetic_prompt_pool
from tpuserve.config import GenserveConfig, ModelConfig, ServerConfig
from tpuserve.server import ServerState, make_app

# Geometry (the numbers the slot-count gate hangs on): max_ctx = 16 + 16
# = 32 tokens/slot dense; 8 slots; page_tokens=4; kv_pages=49 -> 48
# usable pages = 192 tokens = SIX dense slots' worth of KV. The engine
# must demonstrably run more than six concurrent slots inside that.
SLOTS = 8
MAX_CTX = 32
cfg = ServerConfig(
    decode_threads=2,
    startup_canary=False,
    genserve=GenserveConfig(enabled=True, slots=SLOTS, kv_paging=True,
                            kv_page_tokens=4, kv_pages=49,
                            prefill_chunk=4),
    models=[ModelConfig(
        name="textgen", family="textgen", batch_buckets=[1, 2, 4],
        dtype="float32", parallelism="single",
        request_timeout_ms=60_000.0,
        options=dict(layers=1, d_model=64, heads=2, d_ff=128,
                     vocab_size=512, prompt_len=16, max_new_tokens=16),
    )],
)


async def scrape(base: str, session) -> tuple[dict, dict]:
    async with session.get(f"{base}/metrics") as r:
        text = await r.text()
    metrics = {}
    for line in text.splitlines():
        if line.startswith("#") or " " not in line:
            continue
        k, v = line.rsplit(" ", 1)
        try:
            metrics[k] = float(v)
        except ValueError:
            pass
    async with session.get(f"{base}/stats") as r:
        stats = await r.json()
    return metrics, stats


async def main() -> None:
    state = ServerState(cfg)
    state.build()
    runner = web.AppRunner(make_app(state), access_log=None)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    base = f"http://127.0.0.1:{runner.addresses[0][1]}"
    url = f"{base}/v1/models/textgen:generate"
    # Unloaded pool: uniform shorts. Loaded pool: every 4th body is a
    # MAX-LENGTH (16-word) prompt at the top of the output range — each
    # long admission chunk-prefills across 4 iterations amid live decode.
    pool_short = synthetic_prompt_pool(32, max_new=(2, 16))
    pool_skew = synthetic_prompt_pool(32, max_new=(2, 16), long_every=4,
                                      long_words=16)
    try:
        async with aiohttp.ClientSession() as s:
            m0, _ = await scrape(base, s)
            unloaded = await run_stream_load(
                url, pool_short, "application/json",
                duration_s=2.5, warmup_s=0.5, concurrency=SLOTS)
            # Reload mid-run: the PAGED staged canary (chunked prefill +
            # paged decode against the candidate) publishes v2.
            async with s.post(f"{base}/admin/models/textgen:reload") as r:
                body = await r.json()
                assert r.status == 200 and body["canary_ok"] is True, body
            loaded = await run_stream_load(
                url, pool_skew, "application/json",
                duration_s=2.5, warmup_s=0.5, concurrency=SLOTS)
            m1, stats = await scrape(base, s)

        u, l = unloaded.summary(), loaded.summary()
        assert u["n_ok"] > 0 and u["n_err"] == 0, u
        assert l["n_ok"] > 0 and l["n_err"] == 0, l
        assert u["torn_streams"] == 0 and l["torn_streams"] == 0, (u, l)

        # Gate 3/4: compile delta exactly 0 across load + reload.
        key = 'runtime_compiles_total{model="textgen"}'
        assert m0.get(key, 0) >= 3, f"gen programs not registered: {m0}"
        delta = m1.get(key, 0) - m0.get(key, 0)
        assert delta == 0, f"page/slot churn or reload recompiled: {delta}"

        gs = stats["genserve"]["textgen"]
        kv = gs["kv"]
        # Gate 1: measured peak concurrent slots strictly beats the dense
        # slab the same KV bytes would buy (usable pages * page_tokens
        # tokens vs MAX_CTX tokens per dense slot).
        dense_equiv = (kv["usable"] * kv["page_tokens"]) // MAX_CTX
        peak = gs["peak_active"]
        assert peak > dense_equiv, (
            f"no capacity win: peak {peak} <= dense-equivalent "
            f"{dense_equiv} slots at {kv['usable'] * kv['page_tokens']} "
            f"KV tokens")

        # Gate 2: inter-token p99 stays flat while max-length prompts
        # chunk-prefill mid-load (generous ratio + absolute CPU slack).
        u99, l99 = u["inter_token_gap_p99_ms"], l["inter_token_gap_p99_ms"]
        assert l99 <= 3.0 * u99 + 25.0, (
            f"prefill stalled decoders: loaded p99 {l99:.1f} ms vs "
            f"unloaded {u99:.1f} ms")
        assert kv["prefill_chunks_total"] > 0, kv

        # Ledger exactly balanced after drain: every page came home.
        assert gs["active"] == 0 and gs["free"] == SLOTS, gs
        assert kv["reserved"] == 0 and kv["free"] == kv["usable"], kv

        # Retrace witness: armed through the whole page-churn run with
        # zero violations (a retrace would have raised mid-load).
        rw = stats["robustness"]["retrace_witness"]
        assert rw["enabled"] and rw["barrier_declared"], rw
        assert rw["violations"] == [], rw

        print(f"pagedkv smoke OK: peak slots {peak} > dense-equiv "
              f"{dense_equiv} at {kv['usable'] * kv['page_tokens']} KV "
              f"tokens; gap p99 {l99:.1f} ms loaded vs {u99:.1f} ms "
              f"unloaded ({l['tokens_per_s']:.0f} tok/s); "
              f"prefill chunks {kv['prefill_chunks_total']:.0f}; "
              f"compiles delta 0; ledger balanced "
              f"({kv['free']}/{kv['usable']} free)")
    finally:
        await runner.cleanup()


asyncio.run(main())
EOF
