#!/usr/bin/env bash
# Events smoke (ISSUE 15): a REAL router over 2 host failure domains
# (1 worker each) under closed-loop load, gating the flight-data contract
# (docs/OBSERVABILITY.md "The third pillar"):
#   1. one worker is SIGKILLed mid-load — /debug/postmortems names the
#      injected signal and carries a non-empty stderr tail (the dead
#      process's capture file) plus its black-box event snapshot;
#   2. after the domain re-absorbs, a fleet :reload appears in
#      /debug/audit with per-host outcomes and the bumped generation;
#   3. /debug/trace?trace_id= for a recorded slow request (injected
#      worker_slow) interleaves >= 1 correlated event by trace id;
#   4. /debug/events answers on the router AND through the
#      /workers/{wid}/debug/events proxy, and junk query params 400;
#   5. the SURVIVOR worker's runtime_compiles_total delta is exactly 0
#      across the whole drama (forensics perturb no variant registry).
# On failure, scripts/debug_dump.sh pulls the flight data for CI upload —
# the event plane diagnosing its own red run.
set -euo pipefail
cd "$(dirname "$0")/.." || exit 1
export JAX_PLATFORMS=cpu
export TPUSERVE_LOCK_WITNESS=1

PORT=18671
TMPD="$(mktemp -d /tmp/events_smoke_XXXX)"
CFG="$TMPD/cfg.toml"
cat > "$CFG" <<EOF
host = "127.0.0.1"
port = $PORT
decode_threads = 2
startup_canary = false
drain_timeout_s = 5.0
watchdog_interval_s = 0.2

[trace]
slow_n = 8
error_capacity = 64

[events]
dir = "$TMPD/blackbox"
snapshot_interval_s = 0.3

[router]
enabled = true
hosts = 2
workers = 1
retry_max = 3
health_interval_s = 0.2
respawn_initial_s = 0.3
respawn_max_s = 2.0

[[model]]
name = "toy"
family = "toy"
batch_buckets = [1, 2]
deadline_ms = 2.0
dtype = "float32"
num_classes = 10
parallelism = "single"
request_timeout_ms = 10000.0
wire_size = 8

[faults]
enabled = true
seed = 5

[[faults.rule]]
kind = "worker_slow"
model = "toy"
probability = 1.0
count = 1
delay_ms = 300.0
EOF

python -m tpuserve serve --config "$CFG" &
SERVER_PID=$!
cleanup() {
  rc=$?
  if [ "$rc" -ne 0 ]; then
    scripts/debug_dump.sh "http://127.0.0.1:$PORT" events_smoke || true
  fi
  kill -9 "$SERVER_PID" 2>/dev/null || true
  rm -rf "$TMPD"
}
trap cleanup EXIT

for _ in $(seq 1 120); do
  if curl -fsS "http://127.0.0.1:$PORT/healthz" >/dev/null 2>&1; then break; fi
  sleep 0.5
done
curl -fsS "http://127.0.0.1:$PORT/healthz" >/dev/null

# Victim = worker 0 (host0); survivor = worker 1 (host1). The survivor's
# compile-delta window opens BEFORE the load + kill + reload drama.
VICTIM_PID="$(python - <<'EOF'
import json, urllib.request
s = json.load(urllib.request.urlopen("http://127.0.0.1:18671/stats"))
row = next(w for w in s["workers"]["workers"] if w["worker"] == 0)
print(row["pid"])
EOF
)"
curl -fsS "http://127.0.0.1:$PORT/workers/1/metrics" > "$TMPD/w1_before.txt"

# Closed-loop load in the background (the worker_slow rules fire on each
# worker's first request -> the recorded slow tail), SIGKILL mid-load.
python - "$TMPD/load.json" <<'EOF' &
import io, json, sys, threading, time, urllib.request
import numpy as np

buf = io.BytesIO()
np.save(buf, np.random.default_rng(1).integers(0, 255, (8, 8, 3),
                                               dtype=np.uint8))
payload = buf.getvalue()
ok, err = [0], [0]
stop_at = time.monotonic() + 7.0

def loop(i):
    while time.monotonic() < stop_at:
        req = urllib.request.Request(
            "http://127.0.0.1:18671/v1/models/toy:predict", data=payload,
            headers={"Content-Type": "application/x-npy"})
        try:
            with urllib.request.urlopen(req, timeout=15) as r:
                r.read()
                ok[0] += 1
        except Exception:
            err[0] += 1
        time.sleep(0.01)

threads = [threading.Thread(target=loop, args=(i,)) for i in range(4)]
for t in threads: t.start()
for t in threads: t.join()
json.dump({"ok": ok[0], "err": err[0]}, open(sys.argv[1], "w"))
EOF
LOAD_PID=$!

sleep 2
echo "SIGKILL victim worker 0 (pid $VICTIM_PID) mid-load"
kill -9 "$VICTIM_PID"
wait "$LOAD_PID"
echo "load: $(cat "$TMPD/load.json")"

python - "$TMPD" <<'EOF'
import json, sys, time, urllib.request, urllib.error

tmpd = sys.argv[1]
base = "http://127.0.0.1:18671"


def get(path):
    try:
        with urllib.request.urlopen(base + path, timeout=30) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


load = json.load(open(f"{tmpd}/load.json"))
total = load["ok"] + load["err"]
assert load["ok"] > 0 and load["err"] / max(1, total) < 0.10, load

# -- 1. postmortem names the SIGKILL, with stderr tail + snapshot -----------
rec = None
deadline = time.monotonic() + 30.0
while time.monotonic() < deadline:
    _, body = get("/debug/postmortems")
    sk = [r for r in body["postmortems"] if r.get("signal") == "SIGKILL"]
    if sk:
        rec = sk[0]
        break
    time.sleep(0.2)
assert rec is not None, "no SIGKILL postmortem recorded"
assert rec["component"] == "worker" and rec["exitcode"] == -9, rec
assert rec.get("stderr_tail"), "postmortem carries no stderr tail"
snap = rec.get("snapshot")
assert snap and snap.get("events"), "postmortem carries no event snapshot"
print(f"postmortem OK: {rec['id']} signal={rec['signal']} "
      f"stderr_tail={len(rec['stderr_tail'])}B "
      f"snapshot_events={len(snap['events'])}")

# -- wait for the domain to re-absorb (reload refuses while degraded) -------
deadline = time.monotonic() + 60.0
while time.monotonic() < deadline:
    _, s = get("/stats")
    if s["workers"]["healthy"] == 2 and not s["workers"].get("hosts_up", 2) < 2:
        break
    time.sleep(0.2)
_, s = get("/stats")
assert s["workers"]["healthy"] == 2, s["workers"]

# -- 2. fleet reload lands in the audit trail with per-host outcomes --------
status, body = get("/stats")
req = urllib.request.Request(f"{base}/admin/models/toy:reload", data=b"",
                             method="POST")
with urllib.request.urlopen(req, timeout=120) as r:
    reload_body = json.loads(r.read())
    assert r.status == 200, reload_body
_, audit = get("/debug/audit")
arec = next(a for a in audit["audit"] if a["verb"] == "reload")
assert arec["outcome"] == "ok" and arec["target"] == "toy", arec
assert arec.get("per_host"), f"no per-host outcomes on the audit: {arec}"
assert set(arec["per_host"]) == {"host0", "host1"}, arec
assert arec["generation"] >= 2 and arec["duration_ms"] > 0, arec
print(f"audit OK: reload gen={arec['generation']} "
      f"per_host={sorted(arec['per_host'])}")

# -- 3. slow-trace <-> event interleave by trace id -------------------------
_, slow = get("/debug/slow")
recs = [r for rows in slow["slow"].values() for r in rows
        if r["duration_ms"] >= 250.0]
assert recs, f"no recorded slow request >= 250ms: {slow['slow']}"
tid = recs[0]["trace_id"]
_, tr = get(f"/debug/trace?trace_id={tid}&format=record")
evs = tr.get("events") or []
assert any(e.get("trace_id") == tid for e in evs), \
    f"trace {tid} interleaves no correlated event: {evs}"
with urllib.request.urlopen(f"{base}/debug/trace?trace_id={tid}",
                            timeout=30) as r:
    chrome = json.loads(r.read())
assert any(e["ph"] == "i" for e in chrome["traceEvents"]), \
    "no instant events in the Chrome artifact"
print(f"interleave OK: trace {tid[:8]}… carries "
      f"{sum(1 for e in evs if e.get('trace_id') == tid)} correlated "
      "event(s)")

# -- 4. /debug/events surfaces + junk-param 400s ----------------------------
status, ev = get("/debug/events")
assert status == 200 and ev["events"] and ev["size"] > 0
status, _ = get("/debug/events?level=loud")
assert status == 400, "junk level must 400"
status, wev = get("/workers/1/debug/events")
assert status == 200 and wev["events"], "worker events proxy failed"
assert all(e["pid"] == 2 for e in wev["events"]), "worker 1 lane must be 2"
print(f"events OK: router ring {ev['size']} records, worker proxy "
      f"{len(wev['events'])} records")
EOF

# -- 5. survivor compile delta 0 --------------------------------------------
curl -fsS "http://127.0.0.1:$PORT/workers/1/metrics" > "$TMPD/w1_after.txt"
python - "$TMPD" <<'EOF'
import sys

def compiles(path):
    total = 0.0
    for line in open(path):
        if line.startswith("runtime_compiles_total"):
            total += float(line.rsplit(" ", 1)[1])
    return total

tmpd = sys.argv[1]
before = compiles(f"{tmpd}/w1_before.txt")
after = compiles(f"{tmpd}/w1_after.txt")
assert after - before == 0, \
    f"survivor recompiled: {before} -> {after}"
print(f"compile delta OK: survivor {before} -> {after} (delta 0)")
EOF

kill -TERM "$SERVER_PID" 2>/dev/null || true
wait "$SERVER_PID" 2>/dev/null || true
echo "events smoke OK"
