#!/usr/bin/env bash
# Roofline smoke (ISSUE 6): a short closed loop through the REAL server on
# the CPU backend proving the compute fast path end to end:
#   1. the specialized-variant registry is live: runtime_variants > 0 and
#      per-variant serving counters (runtime_variant_batches_total) move;
#   2. steady state recompiles NOTHING: the runtime_compiles_total delta
#      across warm load + a :reload publish is exactly 0;
#   3. the /stats roofline block is well-formed: every bucket carries a
#      raw-executable ceiling (roofline_probe_iters armed the startup
#      probe) and the serving compute phase splits into device-time vs
#      host-wait with a sane pct-of-ceiling.
# Run by CI next to the chaos/reload/pipeline/cache drills; see
# docs/PERFORMANCE.md "Reading the roofline".
set -euo pipefail
cd "$(dirname "$0")/.." || exit 1
export JAX_PLATFORMS=cpu
# Race-detection pass rides along (docs/ANALYSIS.md): the registry and
# probe paths run under witnessed locks + per-suspension held-lock checks.
export TPUSERVE_LOCK_WITNESS=1

python - <<'EOF'
import asyncio

import aiohttp
from aiohttp import web

from tpuserve.bench.loadgen import run_load, synthetic_pool
from tpuserve.config import ModelConfig, ServerConfig
from tpuserve.server import ServerState, make_app

NPY = "application/x-npy"

cfg = ServerConfig(
    decode_threads=2,
    startup_canary=False,
    roofline_probe_iters=4,
    models=[ModelConfig(
        name="toy", family="toy", batch_buckets=[1, 2, 4],
        deadline_ms=5.0, dtype="float32", num_classes=10,
        parallelism="single", request_timeout_ms=10_000.0,
        wire_size=8, max_inflight=2,
    )],
)


async def scrape(base: str, session) -> tuple[dict, dict]:
    async with session.get(f"{base}/metrics") as r:
        text = await r.text()
    metrics = {}
    for line in text.splitlines():
        if line.startswith("#") or " " not in line:
            continue
        k, v = line.rsplit(" ", 1)
        try:
            metrics[k] = float(v)
        except ValueError:
            pass
    async with session.get(f"{base}/stats") as r:
        stats = await r.json()
    return metrics, stats


async def main() -> None:
    state = ServerState(cfg)
    state.build()
    runner = web.AppRunner(make_app(state), access_log=None)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    base = f"http://127.0.0.1:{runner.addresses[0][1]}"
    pool = synthetic_pool("npy", 16, edge=8)
    try:
        # Warm load, then the measured window the compile delta spans.
        res = await run_load(f"{base}/v1/models/toy:classify", pool, NPY,
                             duration_s=2.0, warmup_s=0.5, concurrency=8)
        assert res.n_err == 0 and res.n_ok > 0, res.summary()
        async with aiohttp.ClientSession() as s:
            m0, _ = await scrape(base, s)
            res2 = await run_load(f"{base}/v1/models/toy:classify", pool, NPY,
                                  duration_s=2.0, warmup_s=0.0, concurrency=8)
            assert res2.n_err == 0 and res2.n_ok > 0, res2.summary()
            # Lifecycle churn rides the same steady state: a publish swaps
            # trees under unchanged shapes, so it may not compile either.
            async with s.post(f"{base}/admin/models/toy:reload") as r:
                assert r.status == 200, await r.text()
            m1, stats = await scrape(base, s)

        key = 'runtime_compiles_total{model="toy"}'
        assert m0.get(key, 0) > 0, f"no compiles recorded at startup: {m0}"
        delta = m1.get(key, 0) - m0.get(key, 0)
        assert delta == 0, f"steady state recompiled: delta={delta}"
        assert m1.get('runtime_variants{model="toy"}', 0) == 3, m1
        served = [v for k, v in m1.items()
                  if k.startswith("runtime_variant_batches_total") and v > 0]
        assert served, f"no specialized-variant serving counters moved: {m1}"

        roof = stats["roofline"]["toy"]
        assert len(roof["variants"]) == 3, roof
        assert set(roof["raw_ms_per_batch"]) == {"[1]", "[2]", "[4]"}, roof
        assert all(v and v > 0 for v in roof["raw_ms_per_batch"].values())
        split = roof["compute_split"]
        assert split["device_ms"] > 0 and split["host_wait_ms"] >= 0, split
        assert 0 < split["pct_of_ceiling"] <= 100, split
        print(f"roofline smoke OK: {res2.throughput:.1f}/s, "
              f"compiles delta 0 (total {m1[key]:.0f}), variants 3, "
              f"compute split {split}")
    finally:
        await runner.cleanup()


asyncio.run(main())
EOF
