#!/usr/bin/env python
"""Measure flash-vs-dense BERT attention at the chip level (VERDICT r4
missing 3 / next 4): the one hand-written Pallas kernel in the repo claimed
"measured on v5e, the kernel wins when head_dim is lane-aligned" with no
measurement on record. This script produces that record.

Method: the shared chip probe (tpuserve.bench.probes.measure_chip_img_s) —
a dependency-chained fori_loop of full serving forwards in a fresh
subprocess per point — over BERT-base replica mode at serving batch sizes
and seq {128, 512, 2048}, attention dense vs flash. Each point reports
seqs/s, ms/batch, and achieved TF/s from XLA's own cost analysis.

Output: one JSON line per point on stdout + a markdown table on stderr for
BASELINE.md ("Flash vs dense, chip level"). The ring/ulysses
``local_impl="auto"`` thresholds in tpuserve/ops/ring_attention.py cite
this table.

    python scripts/bench_flash.py                 # full grid (~10 min)
    python scripts/bench_flash.py --seq 512       # one seq length
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tpuserve.bench.probes import measure_chip_img_s  # noqa: E402

# (seq, batch, iters): batches follow the serving buckets (bench_configs
# uses [8, 16, 32] at seq <= 128); long-context rows shrink the batch the
# way the ring/ulysses serving configs do.
GRID = [
    (128, 32, 64),
    (512, 16, 32),
    (2048, 4, 16),
]


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq", type=int, choices=[s for s, _, _ in GRID])
    args = ap.parse_args()
    grid = [g for g in GRID if args.seq is None or g[0] == args.seq]

    rows = []
    for seq, batch, iters in grid:
        point = {}
        for impl in ("dense", "flash"):
            res = measure_chip_img_s(
                family="bert", bucket=(batch, seq), iters=iters,
                mcfg_extra={"seq_buckets": [seq],
                            "options": {"attention": impl}})
            if "error" in res:
                print(f"# {impl} seq={seq}: ERROR {res['error']}",
                      file=sys.stderr)
                point[impl] = None
                continue
            point[impl] = res
            print(json.dumps({"impl": impl, "seq": seq, **res}), flush=True)
        if point.get("dense") and point.get("flash"):
            speedup = point["flash"]["img_s"] / point["dense"]["img_s"]
            rows.append((seq, batch, point["dense"], point["flash"], speedup))

    if rows:
        print("\n# | seq | batch | dense ms/batch | flash ms/batch | "
              "dense TF/s | flash TF/s | flash speedup |", file=sys.stderr)
        print("# |---|---|---|---|---|---|---|", file=sys.stderr)
        for seq, batch, d, f, sp in rows:
            print(f"# | {seq} | {batch} | {d['ms_per_batch']:.2f} | "
                  f"{f['ms_per_batch']:.2f} | {d['achieved_tflops_s']} | "
                  f"{f['achieved_tflops_s']} | {sp:.2f}x |", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
