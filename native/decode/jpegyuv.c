/* jpegyuv — minimal libjpeg shim that decodes baseline JPEGs straight to
 * YUV 4:2:0 planes, skipping chroma upsampling and YCbCr->RGB conversion.
 *
 * Why (SURVEY.md §2 C12): the serving host ships image bytes to the TPU over
 * a bandwidth-limited link; a JPEG already stores YCbCr with 2x2-subsampled
 * chroma, so shipping the raw planes is byte-identical information at half
 * the bytes of RGB8 (1.5 B/px vs 3 B/px). The YCbCr->RGB conversion + chroma
 * upsample run on-device, fused into the model executable
 * (tpuserve/preproc.py:device_prepare_images_yuv420). Skipping libjpeg's own
 * upsample/color stages also makes this decode ~2x cheaper than a full RGB
 * decode — which matters on a small serving host.
 *
 * API (ctypes-friendly, no Python.h):
 *   jpegyuv_decode(buf, len, y, u, v, edge) -> 0 ok / negative error
 *     Decodes into caller-allocated planes: y[edge*edge],
 *     u,v[(edge/2)*(edge/2)]. The JPEG must be edge x edge (the server's
 *     wire contract; mismatches return -3 and the caller falls back to the
 *     PIL path).  Non-4:2:0 files (incl. grayscale) return -4; 4:4:4 etc.
 *     fall back host-side.
 *   jpegyuv_probe(buf, len, &w, &h, &subsamp) -> 0/neg: header-only probe.
 *
 * Thread-safe: one jpeg_decompress_struct per call, no globals; the GIL is
 * released by ctypes during the call, so decode threads scale.
 */

#include <setjmp.h>
#include <stdint.h>
#include <string.h>
#include <stdio.h>
#include <jpeglib.h>

struct jy_err {
    struct jpeg_error_mgr mgr;
    jmp_buf jb;
    int corrupt; /* count of corrupt-data warnings (e.g. truncated stream) */
};

static void jy_error_exit(j_common_ptr cinfo) {
    struct jy_err *err = (struct jy_err *)cinfo->err;
    longjmp(err->jb, 1);
}

static void jy_emit_message(j_common_ptr cinfo, int msg_level) {
    /* libjpeg "recovers" from truncated/corrupt streams by synthesizing
     * data and emitting a level -1 warning; a serving wire must reject
     * such input instead of silently returning half-garbage planes. */
    if (msg_level == -1)
        ((struct jy_err *)cinfo->err)->corrupt++;
}

int jpegyuv_probe(const uint8_t *buf, long len, int *w, int *h, int *subsamp) {
    struct jpeg_decompress_struct cinfo;
    struct jy_err jerr;

    cinfo.err = jpeg_std_error(&jerr.mgr);
    jerr.mgr.error_exit = jy_error_exit;
    jerr.mgr.emit_message = jy_emit_message;
    jerr.corrupt = 0;
    if (setjmp(jerr.jb)) {
        jpeg_destroy_decompress(&cinfo);
        return -1;
    }
    jpeg_create_decompress(&cinfo);
    jpeg_mem_src(&cinfo, buf, (unsigned long)len);
    if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
        jpeg_destroy_decompress(&cinfo);
        return -2;
    }
    *w = (int)cinfo.image_width;
    *h = (int)cinfo.image_height;
    /* subsamp: 420 iff 3 components, comp0 2x2 sampling, comp1/2 1x1 */
    *subsamp = 0;
    if (cinfo.num_components == 3 &&
        cinfo.comp_info[0].h_samp_factor == 2 &&
        cinfo.comp_info[0].v_samp_factor == 2 &&
        cinfo.comp_info[1].h_samp_factor == 1 &&
        cinfo.comp_info[1].v_samp_factor == 1 &&
        cinfo.comp_info[2].h_samp_factor == 1 &&
        cinfo.comp_info[2].v_samp_factor == 1)
        *subsamp = 420;
    jpeg_destroy_decompress(&cinfo);
    return 0;
}

int jpegyuv_decode(const uint8_t *buf, long len,
                   uint8_t *y, uint8_t *u, uint8_t *v, int edge) {
    struct jpeg_decompress_struct cinfo;
    struct jy_err jerr;
    int half = edge / 2;

    if (edge <= 0 || (edge & 15) != 0)
        return -5; /* wire edges are multiples of 16 (full MCU rows) */

    cinfo.err = jpeg_std_error(&jerr.mgr);
    jerr.mgr.error_exit = jy_error_exit;
    jerr.mgr.emit_message = jy_emit_message;
    jerr.corrupt = 0;
    if (setjmp(jerr.jb)) {
        jpeg_destroy_decompress(&cinfo);
        return -1;
    }
    jpeg_create_decompress(&cinfo);
    jpeg_mem_src(&cinfo, buf, (unsigned long)len);
    if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
        jpeg_destroy_decompress(&cinfo);
        return -2;
    }
    if ((int)cinfo.image_width != edge || (int)cinfo.image_height != edge) {
        jpeg_destroy_decompress(&cinfo);
        return -3;
    }
    if (!(cinfo.num_components == 3 &&
          cinfo.comp_info[0].h_samp_factor == 2 &&
          cinfo.comp_info[0].v_samp_factor == 2 &&
          cinfo.comp_info[1].h_samp_factor == 1 &&
          cinfo.comp_info[1].v_samp_factor == 1 &&
          cinfo.comp_info[2].h_samp_factor == 1 &&
          cinfo.comp_info[2].v_samp_factor == 1)) {
        jpeg_destroy_decompress(&cinfo);
        return -4; /* not 4:2:0; caller falls back */
    }

    cinfo.raw_data_out = TRUE;
    cinfo.do_fancy_upsampling = FALSE;
    jpeg_start_decompress(&cinfo);

    /* raw_data_out delivers one MCU row (16 luma lines / 8 chroma lines)
     * per call, as JSAMPROW pointer tables into the destination planes. */
    {
        JSAMPROW yrows[16], urows[8], vrows[8];
        JSAMPARRAY planes[3] = {yrows, urows, vrows};
        unsigned int lines_per_mcu = cinfo.max_v_samp_factor * DCTSIZE; /* 16 */

        while (cinfo.output_scanline < cinfo.output_height) {
            unsigned int base = cinfo.output_scanline;
            unsigned int i;
            for (i = 0; i < 16; i++) {
                unsigned int row = base + i;
                yrows[i] = y + (row < (unsigned)edge ? row : (unsigned)edge - 1) * (size_t)edge;
            }
            for (i = 0; i < 8; i++) {
                unsigned int row = base / 2 + i;
                urows[i] = u + (row < (unsigned)half ? row : (unsigned)half - 1) * (size_t)half;
                vrows[i] = v + (row < (unsigned)half ? row : (unsigned)half - 1) * (size_t)half;
            }
            if (jpeg_read_raw_data(&cinfo, planes, lines_per_mcu) == 0)
                break;
        }
    }

    jpeg_finish_decompress(&cinfo);
    jpeg_destroy_decompress(&cinfo);
    return jerr.corrupt ? -6 : 0; /* truncated/corrupt stream: reject */
}
