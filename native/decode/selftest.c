/* Standalone harness for the jpegyuv shim — built with ASan in CI
 * (SURVEY.md §5 race detection/sanitizers; the Python test suite covers
 * functional parity, this covers memory safety without Python in the way).
 *
 * Usage: selftest <file.jpg> <edge>
 * Exit 0 on successful decode + plausible plane stats; nonzero otherwise.
 */

#include <stdio.h>
#include <stdlib.h>
#include <stdint.h>

extern int jpegyuv_probe(const uint8_t *buf, long len, int *w, int *h, int *subsamp);
extern int jpegyuv_decode(const uint8_t *buf, long len,
                          uint8_t *y, uint8_t *u, uint8_t *v, int edge);

int main(int argc, char **argv) {
    if (argc != 3) { fprintf(stderr, "usage: selftest f.jpg edge\n"); return 2; }
    int edge = atoi(argv[2]), half = edge / 2;
    FILE *f = fopen(argv[1], "rb");
    if (!f) { perror("open"); return 2; }
    fseek(f, 0, SEEK_END);
    long len = ftell(f);
    fseek(f, 0, SEEK_SET);
    uint8_t *buf = malloc(len);
    if (fread(buf, 1, len, f) != (size_t)len) { fclose(f); return 2; }
    fclose(f);

    int w, h, sub, rc, fail = 0;
    uint8_t *y = malloc((size_t)edge * edge);
    uint8_t *u = malloc((size_t)half * half);
    uint8_t *v = malloc((size_t)half * half);

    if (jpegyuv_probe(buf, len, &w, &h, &sub) != 0) {
        fprintf(stderr, "probe failed\n");
        fail = 1;
    } else {
        printf("probe: %dx%d subsamp=%d\n", w, h, sub);
        rc = jpegyuv_decode(buf, len, y, u, v, edge);
        if (rc != 0) {
            fprintf(stderr, "decode rc=%d\n", rc);
            fail = 1;
        } else {
            long ysum = 0;
            for (long i = 0; i < (long)edge * edge; i++) ysum += y[i];
            printf("decode ok, mean_y=%.1f\n", (double)ysum / (edge * edge));
        }
        /* Truncated input must be rejected (libjpeg pads it with fake EOI
         * and a corrupt-data warning; the shim turns that into -6). */
        if (jpegyuv_decode(buf, len / 2, y, u, v, edge) == 0) {
            fprintf(stderr, "truncated input decoded?!\n");
            fail = 1;
        }
        /* Garbage input likewise. */
        {
            uint8_t junk[64] = {0xff, 0xd8, 1, 2, 3};
            if (jpegyuv_decode(junk, sizeof junk, y, u, v, edge) == 0) {
                fprintf(stderr, "garbage decoded?!\n");
                fail = 1;
            }
        }
    }
    free(y); free(u); free(v); free(buf);
    if (!fail) printf("selftest ok\n");
    return fail;
}
