"""Command-line entry points (SURVEY.md §2 C10).

Usage::

    python -m tpuserve serve  --config serve.toml [--set port=9000 ...]
        ([router] enabled = true starts the router tier + worker processes)
    python -m tpuserve bench  --url http://127.0.0.1:8000 --model resnet50 ...
    python -m tpuserve chaos  --config chaos.toml --min-availability 0.99 \
                              [--drill reload | --drill worker_kill]
    python -m tpuserve import-model --saved-model DIR --family resnet50 --out CKPT
    python -m tpuserve warmup --config serve.toml   (compile + persist XLA cache)
    python -m tpuserve lint                          (concurrency/drift analysis)
    python -m tpuserve describe                      (device/mesh inventory)
"""

from __future__ import annotations

import argparse
import json
import sys


def _add_config_args(p: argparse.ArgumentParser) -> None:
    p.add_argument("--config", default=None, help="TOML config path")
    p.add_argument("--set", dest="overrides", action="append", default=[],
                   help="dot-path override, e.g. --set model.resnet50.deadline_ms=2")


def _parse_opt_args(parser: argparse.ArgumentParser, items: list[str]) -> dict:
    """--opt KEY=VALUE pairs -> {key: TOML-parsed value} (import-model and
    finetune-det share this)."""
    from tpuserve.config import _parse_toml_value

    options = {}
    for item in items:
        if "=" not in item:
            parser.error(f"--opt must look like key=value, got {item!r}")
        key, _, text = item.partition("=")
        options[key.strip()] = _parse_toml_value(text.strip())
    return options


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="tpuserve")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_serve = sub.add_parser("serve", help="start the inference server")
    _add_config_args(p_serve)

    p_bench = sub.add_parser("bench", help="run the HTTP load generator")
    p_bench.add_argument("--url", default="http://127.0.0.1:8000")
    p_bench.add_argument("--model", default="resnet50")
    p_bench.add_argument("--verb", default="predict")
    p_bench.add_argument("--duration", type=float, default=10.0)
    p_bench.add_argument("--warmup", type=float, default=2.0)
    p_bench.add_argument("--concurrency", type=int, default=64,
                         help="closed-loop workers (ignored with --rate)")
    p_bench.add_argument("--rate", type=float, default=None,
                         help="open-loop offered rate (req/s); switches to open-loop mode")
    p_bench.add_argument("--payload", default=None, help="file to POST; default synthetic image")
    p_bench.add_argument("--content-type", default="application/x-npy")
    p_bench.add_argument("--batch", type=int, default=0,
                         help="client-side batch: POST (N,H,W,3) npy bodies; "
                              "throughput counts items")
    p_bench.add_argument("--distinct", type=int, default=0,
                         help="cycle N distinct synthetic payloads — a "
                              "miss-only workload for the result cache when "
                              "N exceeds its capacity; 0/1 repeats one "
                              "payload (hit-heavy once the cache is warm)")
    p_bench.add_argument("--synthetic",
                         choices=["npy", "jpeg", "prompt", "sd-prompt"],
                         default="npy",
                         help="synthetic payload kind for --distinct pools: "
                              "npy/jpeg images, or JSON prompt bodies for "
                              "the generative families (prompt = textgen "
                              "with mixed max_new_tokens, sd-prompt = "
                              "fixed-steps txt2img)")
    p_bench.add_argument("--edge", type=int, default=256,
                         help="synthetic payload image edge for --distinct")
    p_bench.add_argument("--max-new", default="2,32",
                         help="lo,hi range of max_new_tokens for "
                              "--synthetic prompt pools (mixed output "
                              "lengths; ISSUE 9)")
    p_bench.add_argument("--wire", choices=["npy", "frame"], default="npy",
                         help="client wire: npy bodies, or framed binary "
                              "multi-item bodies (application/"
                              "x-tpuserve-frame — zero-copy server parse; "
                              "--batch sets items per frame, --frame-kind "
                              "the pixel layout)")
    p_bench.add_argument("--frame-kind", choices=["yuv420", "rgb8"],
                         default="yuv420",
                         help="--wire frame item layout; must match the "
                              "served model's wire_format")
    p_bench.add_argument("--procs", type=int, default=1,
                         help="load-worker processes; > 1 splits "
                              "--concurrency (and --rate) across workers "
                              "with disjoint synthetic seed ranges and "
                              "merges exact percentiles — so the measured "
                              "bottleneck is the server, not one client "
                              "process's event loop")
    p_bench.add_argument("--seed-base", type=int, default=0,
                         help="first synthetic seed (multi-process workers "
                              "take disjoint ranges automatically)")
    p_bench.add_argument("--dump-latencies", default=None,
                         help="write raw latency samples as JSON to this "
                              "path (the multi-process merge reads them)")
    p_bench.add_argument("--stream", action="store_true",
                         help="closed-loop STREAMING mode (?stream=true, "
                              "SSE): reports first-token p50/p99, "
                              "inter-token-gap p50/p99/max + histogram, "
                              "and exact tokens/s from token event "
                              "timestamps; use with --synthetic prompt "
                              "against a generative model (--rate/--procs "
                              "don't apply)")
    p_bench.add_argument("--long-every", type=int, default=0,
                         help="skew the --synthetic prompt pool: every "
                              "Nth body is a --long-words-word prompt at "
                              "the top of --max-new (0 = uniform pool)")
    p_bench.add_argument("--long-words", type=int, default=16,
                         help="prompt length (words) of the injected "
                              "long bodies for --long-every")

    p_imp = sub.add_parser("import-model", help="convert TF SavedModel -> orbax checkpoint")
    p_imp.add_argument("--saved-model", required=True)
    p_imp.add_argument("--family", required=True)
    p_imp.add_argument("--out", required=True)
    p_imp.add_argument("--opt", action="append", default=[], metavar="KEY=VALUE",
                       help="model option for the import (TOML-parsed value), "
                            "e.g. --opt vocab_file=vocab.txt --opt layers=24")
    p_imp.add_argument("--quantize", choices=["int8"], default=None,
                       help="write a weight-only int8 checkpoint (half the "
                            "bytes); serve it with quantize = \"int8\"")

    p_ft = sub.add_parser(
        "finetune-det",
        help="fine-tune EfficientDet -> full orbax detector checkpoint")
    p_ft.add_argument("--out", required=True)
    p_ft.add_argument("--steps", type=int, default=50)
    p_ft.add_argument("--batch", type=int, default=8)
    p_ft.add_argument("--data", default=None,
                      help=".npz with images/boxes/classes/valid; default "
                           "synthetic rectangles")
    p_ft.add_argument("--weights", default=None,
                      help="EfficientNet-B0 backbone checkpoint to transfer "
                           "from (SavedModel or orbax)")
    p_ft.add_argument("--lr", type=float, default=1e-3)
    p_ft.add_argument("--opt", action="append", default=[], metavar="KEY=VALUE",
                      help="model option/field (TOML-parsed), e.g. "
                           "--opt image_size=512 --opt det_classes=90")

    p_chaos = sub.add_parser(
        "chaos",
        help="serve a fault-injected config on an ephemeral port, drive the "
             "load generator at it, and report availability (staging drills)")
    _add_config_args(p_chaos)
    p_chaos.add_argument("--model", default=None,
                         help="model to load test (default: first configured)")
    p_chaos.add_argument("--duration", type=float, default=10.0)
    p_chaos.add_argument("--warmup", type=float, default=1.0)
    p_chaos.add_argument("--concurrency", type=int, default=16)
    p_chaos.add_argument("--rate", type=float, default=None,
                         help="open-loop offered rate (req/s); default closed loop")
    p_chaos.add_argument("--min-availability", type=float, default=0.0,
                         help="exit non-zero when n_ok/(n_ok+n_err) falls below this")
    p_chaos.add_argument("--drill",
                         choices=["reload", "worker_kill", "host_kill",
                                  "stream_kill", "fleet", "autopilot"],
                         default=None,
                         help="additionally drive a drill during the run: "
                              "'reload' POSTs :reload on an interval so "
                              "reload_* fault rules prove the lifecycle "
                              "gates hold availability; 'worker_kill' "
                              "serves a real router + worker fleet and "
                              "SIGKILLs one worker mid-load; 'host_kill' "
                              "serves >= 2 host failure domains x >= 2 "
                              "workers and SIGKILLs one ENTIRE host's "
                              "process group mid-load (agent + workers — "
                              "a machine death), gating availability on "
                              "the survivors plus a torn/duplicate audit "
                              "and the re-absorb time; 'fleet' loads "
                              "every configured model (>= 3), poisons "
                              "--model with device_error @ 100%, and "
                              "reports per-model isolation — the victim's "
                              "breaker must open while every survivor "
                              "holds its SLO (docs/ROBUSTNESS.md); "
                              "'stream_kill' serves a router + worker "
                              "fleet with a generative model, drives "
                              "mixed streaming + unary load, SIGKILLs "
                              "one worker mid-stream, and byte-audits "
                              "the fail-safe stream semantics: every "
                              "started stream ends in a terminal event "
                              "(zero torn streams, zero duplicate or "
                              "reordered tokens vs a seeded reference) "
                              "while un-started streams retry "
                              "transparently; "
                              "'autopilot' serves a tenant-fenced fleet "
                              "with the self-healing controller engaged, "
                              "turns one tenant hostile mid-load while a "
                              "seeded latency fault fires on one host, and "
                              "gates on unattended containment: hostile "
                              "overage 429'd, victims green, every "
                              "controller action audited "
                              "(docs/OPERATIONS.md)")
    p_chaos.add_argument("--drill-interval", type=float, default=0.5,
                         help="seconds between drill operations")
    p_chaos.add_argument("--kill-after", type=float, default=None,
                         help="worker_kill: seconds after warmup before the "
                              "SIGKILL (default: 25%% of the run)")
    p_chaos.add_argument("--respawn-budget", type=float, default=120.0,
                         help="worker_kill: seconds the killed worker has "
                              "to come back healthy (backoff + boot)")

    p_warm = sub.add_parser("warmup", help="AOT-compile all buckets, persist XLA cache")
    _add_config_args(p_warm)

    p_lint = sub.add_parser(
        "lint",
        help="concurrency + drift static analysis over tpuserve/ "
             "(docs/ANALYSIS.md); fails on findings not in the checked-in "
             "baseline")
    from tpuserve.analysis.cli import add_lint_args

    add_lint_args(p_lint)

    sub.add_parser("describe", help="print device / mesh inventory")

    args = parser.parse_args(argv)

    if args.cmd == "serve":
        from tpuserve.config import default_config, load_config

        if args.config:
            cfg = load_config(args.config, args.overrides)
        else:
            cfg = default_config()
            for ov in args.overrides:
                from tpuserve.config import _apply_override

                _apply_override(cfg, ov)
        if cfg.router.enabled:
            # Router/worker split (docs/ROBUSTNESS.md "Process failure
            # domains"): this process is the device-free front tier; the
            # supervisor spawns the worker processes that build models.
            from tpuserve.workerproc import serve_router

            serve_router(cfg)
        else:
            from tpuserve.server import serve

            serve(cfg)
        return 0

    if args.cmd == "bench":
        from tpuserve.bench.loadgen import run_loadgen_cli

        return run_loadgen_cli(args)

    if args.cmd == "chaos":
        import asyncio

        from tpuserve.config import default_config, load_config
        from tpuserve.server import configure_logging

        cfg = load_config(args.config, args.overrides) if args.config else default_config()
        configure_logging(cfg)
        model = args.model or cfg.models[0].name
        if args.drill == "worker_kill":
            # Multi-process drill: this process stays device-free (the
            # router never touches a chip); the fleet builds the models.
            from tpuserve.workerproc.drill import run_worker_kill_drill

            summary = asyncio.run(run_worker_kill_drill(
                cfg, model, duration_s=args.duration, warmup_s=args.warmup,
                concurrency=args.concurrency, kill_after_s=args.kill_after,
                respawn_budget_s=args.respawn_budget))
        elif args.drill == "host_kill":
            # Host-domain drill (ISSUE 13): SIGKILL one entire host's
            # process group (agent + its workers) mid-load; the surviving
            # hosts must hold availability while the dead domain respawns.
            from tpuserve.workerproc.drill import run_host_kill_drill

            summary = asyncio.run(run_host_kill_drill(
                cfg, model, duration_s=args.duration, warmup_s=args.warmup,
                concurrency=args.concurrency, kill_after_s=args.kill_after,
                reabsorb_budget_s=args.respawn_budget))
        elif args.drill == "stream_kill":
            # Mid-stream chaos drill (ISSUE 17): SIGKILL one worker while
            # streams are in flight; gated availability is the unary
            # load's, and the stream audit (torn/duplicates/byte-diff vs
            # a seeded reference) is asserted by scripts/stream_drill.sh.
            from tpuserve.workerproc.drill import run_stream_kill_drill

            summary = asyncio.run(run_stream_kill_drill(
                cfg, model, duration_s=args.duration, warmup_s=args.warmup,
                concurrency=args.concurrency, kill_after_s=args.kill_after,
                respawn_budget_s=args.respawn_budget))
        elif args.drill == "autopilot":
            # Hostile-tenant drill (ISSUE 16): one tenant floods past its
            # quota while a seeded [faults] latency rule fires mid-load on
            # one host; the gated availability is the WORST VICTIM's —
            # containment must hold without an operator in the loop.
            from tpuserve.workerproc.drill import run_autopilot_drill

            summary = asyncio.run(run_autopilot_drill(
                cfg, model, duration_s=args.duration, warmup_s=args.warmup,
                concurrency=args.concurrency))
        elif args.drill == "fleet":
            # Isolation drill (Clipper P1): --model names the VICTIM; the
            # gated availability is the WORST SURVIVOR's.
            from tpuserve.parallel import init_distributed
            from tpuserve.scheduler import run_fleet_drill

            init_distributed(cfg.distributed)
            summary = asyncio.run(run_fleet_drill(
                cfg, victim=model, duration_s=args.duration,
                warmup_s=args.warmup, concurrency=args.concurrency))
        else:
            from tpuserve.faults import run_chaos
            from tpuserve.parallel import init_distributed
            from tpuserve.server import ServerState

            init_distributed(cfg.distributed)
            state = ServerState(cfg)
            state.build()
            summary = asyncio.run(run_chaos(
                state, model, duration_s=args.duration, warmup_s=args.warmup,
                concurrency=args.concurrency, rate_per_s=args.rate,
                edge=cfg.model(model).wire_size, drill=args.drill,
                drill_interval_s=args.drill_interval))
        print(json.dumps(summary, indent=2))
        return 0 if summary["availability"] >= args.min_availability else 1

    if args.cmd == "import-model":
        from tpuserve import savedmodel

        options = _parse_opt_args(parser, args.opt)
        savedmodel.convert_cli(args.saved_model, args.family, args.out, options,
                               quantize=args.quantize)
        return 0

    if args.cmd == "finetune-det":
        import dataclasses

        from tpuserve.config import ModelConfig
        from tpuserve.train_det import DetTrainConfig, finetune_detector

        opts = _parse_opt_args(parser, args.opt)
        settable = {f.name for f in dataclasses.fields(ModelConfig)} - {
            "name", "family", "weights", "options"}
        fields = {k: opts.pop(k) for k in list(opts) if k in settable}
        cfg = ModelConfig(name="efficientdet", family="efficientdet",
                          weights=args.weights, options=opts, **fields)
        loss = finetune_detector(cfg, args.out, steps=args.steps,
                                 batch_size=args.batch,
                                 tcfg=DetTrainConfig(lr=args.lr),
                                 dataset=args.data)
        print(json.dumps({"final_loss": loss, "checkpoint": args.out}))
        return 0

    if args.cmd == "lint":
        from tpuserve.analysis.cli import run_lint

        return run_lint(args)

    if args.cmd == "warmup":
        from tpuserve.config import default_config, load_config
        from tpuserve.parallel import init_distributed
        from tpuserve.server import ServerState

        cfg = load_config(args.config, args.overrides) if args.config else default_config()
        # Same ordering rule as serve(): on a pod, the cache entries are only
        # useful if they're compiled against the global topology.
        init_distributed(cfg.distributed)
        state = ServerState(cfg)
        state.build()
        print(json.dumps({n: rt.describe() for n, rt in state.runtimes.items()}, indent=2))
        return 0

    if args.cmd == "describe":
        import jax

        from tpuserve.parallel import make_mesh

        mesh = make_mesh()
        print(json.dumps({
            "devices": [str(d) for d in jax.devices()],
            "platform": jax.devices()[0].platform,
            "mesh": {k: int(v) for k, v in mesh.shape.items()},
        }, indent=2))
        return 0

    return 1


if __name__ == "__main__":
    sys.exit(main())
