"""Weight-only int8 quantization for serving (SURVEY.md §2 C5).

``ModelConfig.quantize = "int8"`` stores every large floating-point weight as
int8 plus a per-channel float32 scale and dequantizes inside the compiled
forward. XLA fuses the ``convert + multiply`` into the consuming matmul/conv,
so weights stream from HBM at half the bf16 byte count — the classic
weight-only quantization win for bandwidth-bound small-batch serving — and
param upload/checkpoint size halves with them. The MXU still computes in the
model's compute dtype; activations are untouched.

Scheme: symmetric per-channel absmax. For a weight ``w`` the channel axis is
its last axis (or the second-to-last when the last is size 1, e.g. depthwise
conv kernels); ``scale = absmax(w, other_axes, keepdims) / 127`` and
``q = round(w / scale)``. Keeping the scale's singleton dims makes dequant a
plain broadcast multiply and lets tensor-parallel PartitionSpecs transfer
axis-by-axis (see ``specs_for_tree``). Small (< min_size), integer, and 0/1-D
leaves stay unquantized — biases, norms, and scalars are not worth the
fidelity risk.

Quality is the usual weight-only tradeoff (sub-percent top-1 movement on
conv/transformer classifiers); it is opt-in per model and off by default.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

# Marker keys for a quantized leaf's sub-tree.
QKEY = "q8"
SKEY = "q8_scale"

# Leaves smaller than this stay in the compute dtype.
DEFAULT_MIN_SIZE = 4096


def _channel_axis(shape: tuple[int, ...]) -> int:
    return len(shape) - 1 if shape[-1] > 1 else max(len(shape) - 2, 0)


def eligible(leaf: Any, min_size: int = DEFAULT_MIN_SIZE) -> bool:
    """True when a param leaf should be quantized."""
    shape = getattr(leaf, "shape", ())
    dtype = getattr(leaf, "dtype", None)
    return (
        dtype is not None
        and jnp.issubdtype(dtype, jnp.floating)
        and len(shape) >= 2
        and int(np.prod(shape)) >= min_size
    )


def is_quantized(leaf: Any) -> bool:
    return isinstance(leaf, dict) and QKEY in leaf and SKEY in leaf


def quantize_leaf(w: np.ndarray) -> dict[str, np.ndarray]:
    """Symmetric per-channel int8: {"q8": int8 w-like, "q8_scale": f32}."""
    w = np.asarray(w, dtype=np.float32)
    axis = _channel_axis(w.shape)
    reduce_axes = tuple(i for i in range(w.ndim) if i != axis)
    absmax = np.max(np.abs(w), axis=reduce_axes, keepdims=True)
    scale = (absmax / 127.0).astype(np.float32)
    scale = np.where(scale == 0.0, np.float32(1.0), scale)
    q = np.clip(np.round(w / scale), -127, 127).astype(np.int8)
    return {QKEY: q, SKEY: scale}


def quantize_tree(params: Any, min_size: int = DEFAULT_MIN_SIZE) -> Any:
    """Replace every eligible leaf with its quantized {"q8", "q8_scale"}.

    Idempotent: already-quantized subtrees pass through untouched (otherwise
    a large float scale leaf could itself be re-quantized, corrupting the
    {"q8", "q8_scale"} structure — matters for pre-quantized checkpoints).
    """
    return jax.tree_util.tree_map(
        lambda x: x if is_quantized(x)
        else (quantize_leaf(np.asarray(x)) if eligible(x, min_size) else x),
        params,
        is_leaf=is_quantized,
    )


def has_quantized_leaves(tree: Any) -> bool:
    return any(is_quantized(leaf) for leaf in
               jax.tree_util.tree_leaves(tree, is_leaf=is_quantized))


def specs_for_tree(rules: list[tuple[str, Any]], tree: Any) -> Any:
    """``match_partition_rules`` over a possibly-quantized tree.

    Quantized subtrees are treated as one leaf at their weight's path (so
    rule regexes see the original name, with no ``/q8`` suffix): the int8
    values take the matched spec, the scale the spec entry of its channel
    axis. Because decisions follow the tree's actual quantization state,
    this needs no min_size agreement with whoever quantized it.
    """
    from tpuserve.parallel.partition import _join_path, spec_for_name

    flat, treedef = jax.tree_util.tree_flatten_with_path(
        tree, is_leaf=is_quantized)
    out = []
    for path, leaf in flat:
        name = _join_path(path, "/")
        if is_quantized(leaf):
            w = leaf[QKEY]
            spec = spec_for_name(rules, name, w.shape)
            axis = _channel_axis(w.shape)
            full = tuple(spec) + (None,) * (w.ndim - len(tuple(spec)))
            out.append({QKEY: spec,
                        SKEY: P(*[full[i] if i == axis else None
                                  for i in range(w.ndim)])})
        else:
            out.append(spec_for_name(rules, name, getattr(leaf, "shape", ())))
    return jax.tree_util.tree_unflatten(treedef, out)


def dequantize_tree(params: Any, dtype: Any) -> Any:
    """Jittable: restore quantized leaves to ``dtype`` (broadcast multiply);
    XLA fuses this into each weight's consumer."""
    return jax.tree_util.tree_map(
        lambda x: (x[QKEY].astype(dtype) * x[SKEY].astype(dtype))
        if is_quantized(x) else x,
        params,
        is_leaf=is_quantized,
    )


# -- int8 COMPUTE path (quantize = "int8c") -----------------------------------
#
# Weight-only int8 halves HBM traffic but the MXU still multiplies in bf16.
# v5e's int8 matmul peak is ~2x its bf16 peak (394 vs 197 TOP/s), so for
# matmul-bound serving shapes the second lever is computing IN int8:
# dynamic per-token absmax quantization of the activations, an
# int8 x int8 -> int32 ``lax.dot_general`` on the MXU, and a per-channel
# f32 rescale folded into the output. Models opt sites in by building with
# ``Int8Dense`` (same param paths as ``nn.Dense``) and naming those kernel
# paths in ``int8c_native_kernel_paths()``; the runtime then leaves exactly
# those leaves quantized in the compiled forward and dequantizes the rest
# as in plain "int8" mode. Accuracy is gated the same way as storage int8:
# tests/test_quantize.py drift bounds + the imported-weight parity test.
#
# Measured guidance (BASELINE.md "Int8 COMPUTE", v5e 2026-07-30): int8c
# WINS on matmul-dense transformer sites (BERT FFN: +11.8% at the serving
# bucket) and LOSES on conv sites (ResNet 1x1: 0.78x — per-pixel dynamic
# activation quantization over large spatial activations outweighs the
# int8 MAC saving and breaks conv+BN+ReLU fusion). Default to "int8" for
# conv families; reach for "int8c" where the FLOPs live in big matmuls.

import re  # noqa: E402  (stdlib; used by the int8c path filter below)

import flax.linen as nn  # noqa: E402


def int8_matmul(x: jax.Array, wq: jax.Array, w_scale: jax.Array,
                out_dtype: Any) -> jax.Array:
    """``x @ dequant(wq)`` computed as int8 x int8 -> int32 on the MXU.

    x: (..., K) float; wq: (K, N) int8; w_scale: (1, N) or (N,) f32 (the
    per-channel scale quantize_leaf stores). The activation scale is
    dynamic per token (absmax over the K axis), so no calibration pass is
    needed and padded lanes cannot skew other rows' scales.
    """
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1, keepdims=True)
    s_x = jnp.maximum(amax, 1e-8) / 127.0
    xq = jnp.clip(jnp.round(x.astype(jnp.float32) / s_x),
                  -127, 127).astype(jnp.int8)
    y = jax.lax.dot_general(
        xq, wq, (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    return (y.astype(jnp.float32) * s_x
            * w_scale.reshape(-1).astype(jnp.float32)).astype(out_dtype)


class Int8Dense(nn.Module):
    """Drop-in ``nn.Dense`` whose kernel may arrive int8-quantized.

    Param paths and init are identical to ``nn.Dense`` (``kernel`` f32
    lecun-normal, ``bias`` f32 zeros), so import mappers, partition rules,
    and orbax checkpoints see no structural difference. When the runtime
    hands the compiled forward a tree whose ``kernel`` leaf is the
    ``{"q8", "q8_scale"}`` dict (quantize = "int8c"), the matmul runs
    int8 x int8 -> int32 (``int8_matmul``); a plain float kernel takes the
    ordinary dense path, which keeps CPU tests, random-init serving, and
    non-quantized checkpoints working unchanged.
    """

    features: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (x.shape[-1], self.features), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros,
                          (self.features,), jnp.float32)
        if is_quantized(kernel):
            y = int8_matmul(x, kernel[QKEY], kernel[SKEY], self.dtype)
        else:
            y = jnp.dot(x.astype(self.dtype), kernel.astype(self.dtype))
        return y + bias.astype(self.dtype)


class _Int8QKVProj(nn.Module):
    """One q/k/v projection with ``nn.MultiHeadDotProductAttention``'s
    exact param layout — kernel (d, heads, head_dim), bias (heads,
    head_dim) — so the module slots under the same ``attn/{query,key,
    value}`` paths the import mappers and checkpoints use. A quantized
    kernel (per-head-dim scales, (1, 1, head_dim)) runs int8 on the MXU
    with the scale broadcast across heads."""

    heads: int
    head_dim: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        d = x.shape[-1]
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (d, self.heads, self.head_dim), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros,
                          (self.heads, self.head_dim), jnp.float32)
        if is_quantized(kernel):
            wq = kernel[QKEY].reshape(d, self.heads * self.head_dim)
            scale = jnp.broadcast_to(
                kernel[SKEY].astype(jnp.float32),
                (1, self.heads, self.head_dim)).reshape(-1)
            y = int8_matmul(x, wq, scale, self.dtype)
        else:
            y = jnp.dot(x.astype(self.dtype),
                        kernel.astype(self.dtype).reshape(d, -1))
        y = y.reshape(x.shape[:-1] + (self.heads, self.head_dim))
        return y + bias.astype(self.dtype)


class _Int8OutProj(nn.Module):
    """The attention output projection, MHDPA layout: kernel (heads,
    head_dim, d), bias (d,); int8 path reshapes to a (h*hd, d) matmul."""

    d_model: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, y):  # (..., heads, head_dim)
        h, hd = y.shape[-2], y.shape[-1]
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (h, hd, self.d_model), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros,
                          (self.d_model,), jnp.float32)
        flat = y.reshape(y.shape[:-2] + (h * hd,))
        if is_quantized(kernel):
            wq = kernel[QKEY].reshape(h * hd, self.d_model)
            out = int8_matmul(flat, wq, kernel[SKEY], self.dtype)
        else:
            out = jnp.dot(flat.astype(self.dtype),
                          kernel.astype(self.dtype).reshape(-1, self.d_model))
        return out + bias.astype(self.dtype)


class Int8SelfAttention(nn.Module):
    """Drop-in for ``nn.MultiHeadDotProductAttention(name="attn")(x)``
    self-attention under int8c: q/k/v/out projections may arrive
    int8-quantized (identical param tree to MHDPA — import mappers,
    partition rules, and checkpoints unaffected); the attention math
    itself runs through the caller's ``attention_fn`` exactly as MHDPA
    would call it."""

    heads: int
    dtype: Any = jnp.bfloat16
    attention_fn: Any = None

    @nn.compact
    def __call__(self, x):
        d = x.shape[-1]
        if d % self.heads:
            # Mirror MHDPA's loud failure: a silent floor here would build
            # a structurally different (narrower) attention than the
            # non-quantized path (r5 review finding).
            raise ValueError(
                f"feature dim {d} must be divisible by heads {self.heads}")
        hd = d // self.heads
        q = _Int8QKVProj(self.heads, hd, self.dtype, name="query")(x)
        k = _Int8QKVProj(self.heads, hd, self.dtype, name="key")(x)
        v = _Int8QKVProj(self.heads, hd, self.dtype, name="value")(x)
        o = self.attention_fn(q, k, v)
        return _Int8OutProj(d, self.dtype, name="out")(o)


class Int8Conv1x1(nn.Module):
    """Drop-in twin of ``nn.Conv(features, (1, 1), use_bias=False)`` for
    the int8c path: a 1x1 convolution is a matmul over the channel axis,
    so a quantized kernel runs int8 x int8 -> int32 on the MXU
    (``int8_matmul``) after optional spatial striding (valid for 1x1
    windows: output (i, j) reads exactly input (i*s, j*s)). Param path,
    shape (1, 1, Cin, Cout), and init match ``nn.Conv``, so import
    mappers, partition rules, and checkpoints see no difference; a plain
    float kernel takes the ordinary dense conv-as-matmul path.
    """

    features: int
    strides: tuple = (1, 1)
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (1, 1, x.shape[-1], self.features), jnp.float32)
        sh, sw = self.strides
        if (sh, sw) != (1, 1):
            x = x[:, ::sh, ::sw, :]
        cin = x.shape[-1]
        if is_quantized(kernel):
            wq = kernel[QKEY].reshape(cin, self.features)
            return int8_matmul(x, wq, kernel[SKEY], self.dtype)
        w = kernel.astype(self.dtype).reshape(cin, self.features)
        return jnp.dot(x.astype(self.dtype), w)


def dequantize_tree_except(params: Any, dtype: Any,
                           keep: list[str]) -> Any:
    """Dequantize every quantized leaf EXCEPT those whose '/'-joined path
    matches one of the ``keep`` regexes — those stay {"q8", "q8_scale"} for
    modules (Int8Dense) that compute in int8 natively."""
    from tpuserve.parallel.partition import _join_path

    pats = [re.compile(p) for p in keep]
    flat, treedef = jax.tree_util.tree_flatten_with_path(
        params, is_leaf=is_quantized)
    out = []
    for path, leaf in flat:
        if is_quantized(leaf):
            name = _join_path(path, "/")
            if any(p.search(name) for p in pats):
                out.append(leaf)
            else:
                out.append((leaf[QKEY].astype(dtype)
                            * leaf[SKEY].astype(dtype)))
        else:
            out.append(leaf)
    return jax.tree_util.tree_unflatten(treedef, out)
