"""FleetScheduler: cross-model SLO admission, priority arbitration, and
warm/cold weight paging (ISSUE 10; docs/ROBUSTNESS.md).

The scheduler owns three decisions, all made at admission time, all in
microseconds, all BEFORE a request occupies a queue slot:

1. **Predictive admission** (Clockwork, PAPERS.md P3). Each batcher/engine
   already keeps per-bucket batch-duration EWMAs (PR 5); the scheduler
   generalizes them into ``predict_completion_s(model)`` = raw queue-clear
   estimate + service-time EWMA. A request whose stamped deadline leaves
   less than that (plus ``headroom_ms`` grace) is shed with a fast 504
   ``deadline_unmeetable`` + Retry-After — rejected in microseconds at the
   front door instead of failing in seconds at the back of the queue.

2. **Priority classes + device-time accounting** (Clipper, P1). Dispatch
   timings feed a sliding-window per-model device-seconds ledger. When the
   aggregate predicted queue-clear across the fleet exceeds
   ``overload_clear_s`` the fleet is saturated: batch-class work sheds
   first (503 ``priority_shed``), and the ``min_share`` floor guarantees
   no model's interactive traffic starves — a model consuming more than
   its allowance (1 - min_share x other demanding models) sheds
   (``share_exceeded``) while any other model with queued work sits below
   the floor.

3. **Warm/cold weight paging**. A model declared ``cold_start`` boots with
   zero device params resident; its first request (or an explicit
   ``POST .../{name}:warm``) triggers a warm-up through the lifecycle
   stage→publish path — integrity gates, variant compilation, staged
   canary, atomic publish — so no request is ever answered by unstaged
   weights, and requests during the warming window shed 503
   ``model_warming`` + Retry-After (the breaker machinery's discipline
   applied to the state path). ``idle_demote_s`` of quiet demotes the
   model back to cold, releasing its device params while the compiled
   variant registry stays resident — a re-warm recompiles nothing.

All scheduler state is event-loop-only (admission, the ledger callbacks,
and the sweep task all run on the server loop); there is deliberately no
lock to witness.
"""

from __future__ import annotations

import asyncio
import logging
import math
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Awaitable, Callable

from tpuserve.batcher import clamp_retry_after_s
from tpuserve.config import SchedulerConfig
from tpuserve.obs import PRIORITIES, SCHED_SHED_REASONS, Metrics
from tpuserve.telemetry import events as events_mod

log = logging.getLogger("tpuserve.scheduler")

COLD, WARMING, WARM = "cold", "warming", "warm"


@dataclass
class Shed:
    """One admission refusal: HTTP status, the reason label (one of
    obs.SCHED_SHED_REASONS — also the ``reason`` key in the response
    body, relayed by the router tier), a human message, and the
    Retry-After hint in seconds (None = no hint)."""

    status: int
    reason: str
    message: str
    retry_after: int | None = None


class _Entry:
    """Per-model scheduler state."""

    __slots__ = ("name", "batcher", "mcfg", "runtime", "warm_fn", "state",
                 "ledger", "window_sum", "last_used", "last_warm_s",
                 "next_warm_at", "warm_task", "shed_counters",
                 "device_seconds_total", "degree", "signature")

    def __init__(self, name: str, batcher: Any, mcfg: Any,
                 runtime: Any | None,
                 warm_fn: Callable[[], Awaitable[Any]] | None,
                 metrics: Metrics) -> None:
        self.name = name
        self.batcher = batcher
        self.mcfg = mcfg
        self.runtime = runtime
        self.warm_fn = warm_fn
        self.state = WARM
        # Parallelism placement facts (ISSUE 20): how many chips this
        # model occupies when warm, and the runtime's parallel signature
        # ("replica@4", "sharded@d2", ...). Recycle pools and test doubles
        # without a runtime count as one chip.
        self.degree = max(1, int(getattr(runtime, "n_chips", 1) or 1))
        self.signature = str(getattr(runtime, "parallel_signature",
                                     "single") or "single")
        # Sliding-window device-seconds ledger: (monotonic ts, seconds).
        self.ledger: deque[tuple[float, float]] = deque()
        self.window_sum = 0.0
        self.last_used = time.monotonic()
        self.last_warm_s: float | None = None
        self.next_warm_at = 0.0  # failed-warm backoff (monotonic)
        self.warm_task: asyncio.Task | None = None
        self.shed_counters = {r: metrics.sched_shed_counter(name, r)
                              for r in SCHED_SHED_REASONS}
        self.device_seconds_total = metrics.sched_device_seconds_counter(name)


class FleetScheduler:
    """Cross-model admission arbiter over the per-model batchers/engines.

    The server registers every model at start(); handle_predict consults
    ``resolve_priority`` / ``check_admission`` / ``check_deadline`` before
    a request reaches a batcher, and the batchers feed dispatch timings
    back through the per-model ``device_time_cb`` hook."""

    def __init__(self, cfg: SchedulerConfig, metrics: Metrics) -> None:
        self.cfg = cfg
        self.metrics = metrics
        self._entries: dict[str, _Entry] = {}
        self._sweep_task: asyncio.Task | None = None
        # SLO engine reference (ISSUE 14, tpuserve.telemetry.slo), set by
        # the server when [telemetry] runs: slo_state() reads each model's
        # live burn-rate alert (ok/pending/firing). This is the documented
        # shed-on-burn seam — a future PR sheds batch-class work for a
        # FIRING model instead of waiting for fleet-wide saturation.
        self.slo = None
        # Shed-on-burn engagement set (ISSUE 16): models the autopilot
        # (or an operator) has marked burning. While a model is in here
        # its batch-class work sheds at admission with reason
        # ``burn_shed`` — interactive traffic keeps flowing, the backlog
        # that is burning the budget does not grow.
        self.burn_shed: set[str] = set()

    # -- registration ---------------------------------------------------------
    def register(self, name: str, batcher: Any, mcfg: Any,
                 runtime: Any | None = None,
                 warm_fn: Callable[[], Awaitable[Any]] | None = None,
                 cold: bool = False) -> None:
        """Register one served model. ``warm_fn`` is the coroutine that
        stages weights to live (normally ``ModelLifecycle.reload``);
        ``cold=True`` starts the model in the cold state (no device params
        resident — ServerState.build skipped the load)."""
        e = _Entry(name, batcher, mcfg, runtime, warm_fn, self.metrics)
        if cold:
            e.state = COLD
        self._entries[name] = e
        self.metrics.set_model_state(name, e.state)
        # Ledger feed: the batcher/engine calls this per dispatch with the
        # device-section seconds (event loop only, like all state here).
        batcher.device_time_cb = self._make_recorder(e)

    def _make_recorder(self, e: _Entry):
        def record(seconds: float) -> None:
            now = time.monotonic()
            e.ledger.append((now, seconds))
            e.window_sum += seconds
            e.device_seconds_total.inc(seconds)
            self._trim(e, now)
        return record

    # -- lifecycle ------------------------------------------------------------
    async def start(self) -> None:
        if self.cfg.idle_demote_s > 0 and self._sweep_task is None:
            self._sweep_task = asyncio.get_running_loop().create_task(
                self._sweep_loop())

    async def stop(self) -> None:
        tasks = [t for t in ([self._sweep_task]
                             + [e.warm_task for e in self._entries.values()])
                 if t is not None and not t.done()]
        self._sweep_task = None
        for t in tasks:
            t.cancel()
        for t in tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):  # noqa: PERF203
                pass

    # -- prediction (Clockwork P3) --------------------------------------------
    def predict_completion_s(self, model: str, n_items: int = 1) -> float | None:
        """Predicted seconds until a request admitted NOW completes: the
        raw (unclamped) queue-clear estimate plus the service-time EWMA of
        the bucket covering it, plus — for a paged generation engine
        (ISSUE 18) — the page-pressure term (kv_clear_s), so an exhausted
        page ledger makes deadline_unmeetable fire BEFORE enqueue even
        when the queue itself is empty. None before any duration evidence
        exists (admit optimistically — shedding needs proof)."""
        e = self._entries[model]
        clear = e.batcher.estimate_clear_s() or 0.0
        kv_fn = getattr(e.batcher, "kv_clear_s", None)
        kv = (kv_fn() or 0.0) if callable(kv_fn) else 0.0
        # estimate_clear_s already folds kv pressure in when a queue
        # exists; the standalone term matters when pending == 0.
        if clear <= 0.0:
            clear = kv
        svc = e.batcher.predicted_service_s(n_items)
        if svc is None and clear <= 0.0:
            return None
        return clear + (svc or 0.0)

    # -- priority / ledger ----------------------------------------------------
    def resolve_priority(self, model: str, header: str | None) -> str:
        """The request's priority class: the X-Priority header when
        present (validated), else the model's configured default. Raises
        ValueError (-> 400) on junk."""
        if header is None or header == "":
            e = self._entries.get(model)
            return e.mcfg.priority if e is not None else "interactive"
        value = header.strip().lower()
        if value not in PRIORITIES:
            raise ValueError(
                f"X-Priority must be one of {list(PRIORITIES)}, got {header!r}")
        return value

    def _trim(self, e: _Entry, now: float) -> None:
        horizon = now - self.cfg.window_s
        while e.ledger and e.ledger[0][0] < horizon:
            _, s = e.ledger.popleft()
            e.window_sum -= s

    def share(self, model: str) -> float:
        """The model's fraction of all device-seconds recorded in the
        sliding window (0.0 when the fleet is idle)."""
        now = time.monotonic()
        total = 0.0
        for e in self._entries.values():
            self._trim(e, now)
            total += e.window_sum
        if total <= 0.0:
            return 0.0
        return self._entries[model].window_sum / total

    def saturated(self) -> bool:
        """Aggregate demand exceeds capacity: the summed raw queue-clear
        prediction across warm models exceeds ``overload_clear_s``."""
        agg = sum((e.batcher.estimate_clear_s() or 0.0)
                  for e in self._entries.values() if e.state == WARM)
        return agg > self.cfg.overload_clear_s

    # -- admission ------------------------------------------------------------
    def _shed(self, e: _Entry, status: int, reason: str, message: str,
              retry_after: int | None) -> Shed:
        e.shed_counters[reason].inc()
        return Shed(status, reason, message, retry_after)

    def check_admission(self, model: str, priority: str) -> Shed | None:
        """Pre-body admission: warm/cold state and priority arbitration.
        Returns a Shed to answer immediately, or None to proceed. A cold
        model's first request triggers its warm-up as a side effect."""
        e = self._entries[model]
        if e.state != WARM:
            if e.state == COLD and not self._fits_budget(e):
                return self._shed(
                    e, 503, "chip_budget",
                    f"model {model!r} needs {e.degree} chip(s) but the "
                    f"fleet chip budget ({self.cfg.chip_budget}) is "
                    f"occupied ({self.chips_in_use()} in use)",
                    clamp_retry_after_s(self.cfg.warm_retry_after_s) or 1)
            self._ensure_warming(e)
            eta = max(1, math.ceil(e.last_warm_s
                                   if e.last_warm_s
                                   else self.cfg.warm_retry_after_s))
            return self._shed(
                e, 503, "model_warming",
                f"model {model!r} is {e.state}; weights are being staged",
                eta)
        if priority == "batch" and model in self.burn_shed:
            # Shed-on-burn engaged: the model is burning its error budget,
            # so deferrable work yields before saturation math even runs.
            return self._shed(
                e, 503, "burn_shed",
                f"model {model!r} is burning its SLO error budget; "
                "batch-priority work shed until the alert clears",
                clamp_retry_after_s(self.cfg.overload_clear_s) or 1)
        if not self.saturated():
            return None
        agg_hint = clamp_retry_after_s(sum(
            (x.batcher.estimate_clear_s() or 0.0)
            for x in self._entries.values())) or 1
        if priority == "batch":
            # Low-priority work sheds first under overload (Clipper P1).
            return self._shed(
                e, 503, "priority_shed",
                "fleet saturated; batch-priority work shed first", agg_hint)
        if self.cfg.min_share > 0:
            others = [o for o in self._entries.values()
                      if o is not e and o.state == WARM]
            demanding = [o for o in others if o.batcher.pending > 0]
            starved = [o for o in demanding
                       if self.share(o.name) < self.cfg.min_share]
            allowed = 1.0 - self.cfg.min_share * len(demanding)
            if starved and self.share(model) > allowed:
                # The floor has teeth: the hog yields device time until the
                # starved model's interactive traffic catches up.
                return self._shed(
                    e, 503, "share_exceeded",
                    f"model {model!r} exceeds its device-time allowance "
                    f"({allowed:.2f}) while "
                    f"{', '.join(o.name for o in starved)} is starved",
                    agg_hint)
        return None

    def check_deadline(self, model: str,
                       deadline_at: float | None) -> Shed | None:
        """Post-stamping admission: shed when the deadline provably cannot
        be met (fast 504 ``deadline_unmeetable`` — the Clockwork property:
        reject in microseconds, don't fail in seconds)."""
        if deadline_at is None:
            return None
        pred = self.predict_completion_s(model)
        if pred is None:
            return None
        now = time.perf_counter()
        remaining = deadline_at - now
        if remaining + self.cfg.headroom_ms / 1e3 >= pred:
            return None
        e = self._entries[model]
        hint = clamp_retry_after_s(e.batcher.estimate_clear_s()) \
            or clamp_retry_after_s(pred) or 1
        return self._shed(
            e, 504, "deadline_unmeetable",
            f"deadline_unmeetable: {remaining * 1e3:.0f} ms remaining but "
            f"predicted completion is {pred * 1e3:.0f} ms", hint)

    def touch(self, model: str) -> None:
        """Record model activity (the idle-demotion clock)."""
        self._entries[model].last_used = time.monotonic()

    def slo_state(self, model: str) -> str:
        """The model's live SLO alert state ("ok"/"pending"/"firing";
        "ok" when no engine is attached or the model has no objective) —
        the burn-rate signal admission policy can act on."""
        return self.slo.state_of(model) if self.slo is not None else "ok"

    # -- chip-budget placement (ISSUE 20) -------------------------------------
    def chips_in_use(self) -> int:
        """Chips occupied by non-cold models — warm runtimes hold device
        params on every chip of their degree, warming ones are staging
        onto them."""
        return sum(e.degree for e in self._entries.values()
                   if e.state != COLD)

    def _fits_budget(self, e: _Entry) -> bool:
        """Whether warming ``e`` fits ``chip_budget``, demoting idle
        cold_start models (largest degree first — frees the most chips
        per staging cost) to make room. Placement is by parallelism
        degree: a replica@4 textgen claims 4 chips, a single-chip
        classifier 1, and the budget arbitrates between them."""
        budget = self.cfg.chip_budget
        if budget <= 0 or e.state != COLD:
            return True

        def overflow() -> int:
            return self.chips_in_use() + e.degree - budget

        if overflow() <= 0:
            return True
        victims = sorted(
            (o for o in self._entries.values()
             if o is not e and o.state == WARM and o.mcfg.cold_start
             and o.batcher.pending == 0),
            key=lambda o: -o.degree)
        for o in victims:
            if overflow() <= 0:
                break
            self.demote(o.name)
        return overflow() <= 0

    # -- warm/cold state machine ----------------------------------------------
    def is_warm(self, model: str) -> bool:
        e = self._entries.get(model)
        return e is None or e.state == WARM

    def state_of(self, model: str) -> str:
        return self._entries[model].state

    def _ensure_warming(self, e: _Entry) -> None:
        """Kick the warm-up task if none is running (failed warms back off
        ``warm_retry_after_s`` so a broken checkpoint can't hot-loop
        expensive staging)."""
        if e.warm_fn is None or e.state == WARM:
            return
        if e.warm_task is not None and not e.warm_task.done():
            return
        if time.monotonic() < e.next_warm_at:
            return
        if not self._fits_budget(e):
            return  # admission already shed 503 chip_budget
        e.warm_task = asyncio.get_running_loop().create_task(self._do_warm(e))

    async def _do_warm(self, e: _Entry) -> dict:
        self._set_state(e, WARMING)
        t0 = time.perf_counter()
        try:
            info = await e.warm_fn()
        except asyncio.CancelledError:
            self._set_state(e, COLD)
            raise
        except Exception:
            self._set_state(e, COLD)
            e.next_warm_at = time.monotonic() + self.cfg.warm_retry_after_s
            log.exception("%s: warm-up failed; model stays cold", e.name)
            raise
        e.last_warm_s = time.perf_counter() - t0
        e.last_used = time.monotonic()
        self._set_state(e, WARM)
        log.info("%s: warmed in %.2fs (version %s)", e.name, e.last_warm_s,
                 (info or {}).get("version"))
        return {"model": e.name, "state": WARM,
                "warm_ms": round(e.last_warm_s * 1e3, 1),
                "version": (info or {}).get("version")}

    async def warm(self, model: str) -> dict:
        """Explicit warm-up (``POST .../{name}:warm``): joins the in-flight
        warm task if one is running; returns once the model serves. The
        shared task is shielded so one impatient client disconnecting
        cannot cancel everyone's warm-up."""
        e = self._entries[model]
        if e.state == WARM:
            return {"model": model, "state": WARM, "already_warm": True}
        if e.warm_fn is None:
            raise ValueError(f"model {model!r} has no warm path registered")
        if e.state == COLD and not self._fits_budget(e):
            raise ValueError(
                f"model {model!r} needs {e.degree} chip(s) but the fleet "
                f"chip budget ({self.cfg.chip_budget}) is occupied "
                f"({self.chips_in_use()} in use)")
        e.next_warm_at = 0.0  # explicit ask overrides the failure backoff
        self._ensure_warming(e)
        return await asyncio.shield(e.warm_task)

    def demote(self, model: str) -> bool:
        """Demote a warm cold_start model back to cold, releasing its
        device params (in-flight batches finish on the references they
        captured at dispatch). Returns True when a demotion happened."""
        e = self._entries[model]
        if e.state != WARM or not e.mcfg.cold_start or e.runtime is None:
            return False
        self._set_state(e, COLD)
        e.runtime.release_params()
        log.info("%s: idle-demoted to cold (device params released)", model)
        return True

    def _set_state(self, e: _Entry, state: str) -> None:
        prev, e.state = e.state, state
        self.metrics.set_model_state(e.name, state)
        if prev != state:
            # Paging transitions are rare and load-bearing — exactly what
            # the flight data should carry (ISSUE 15): a postmortem reader
            # can see the victim was mid-warm when it died.
            events_mod.emit("info", "scheduler", "model_state",
                            model=e.name, state=state, previous=prev)

    async def _sweep_loop(self) -> None:
        while True:
            await asyncio.sleep(self.cfg.sweep_interval_s)
            try:
                self.sweep_idle()
            except asyncio.CancelledError:
                raise
            except Exception:  # one bad sweep must not end demotion
                log.exception("scheduler idle sweep failed")

    def sweep_idle(self) -> int:
        """Demote every warm cold_start model idle past ``idle_demote_s``
        with nothing queued or in flight; returns demotions performed."""
        if self.cfg.idle_demote_s <= 0:
            return 0
        now = time.monotonic()
        demoted = 0
        for e in self._entries.values():
            if not e.mcfg.cold_start or e.state != WARM:
                continue
            if now - e.last_used < self.cfg.idle_demote_s:
                continue
            if e.batcher.pending > 0:
                continue
            if self.demote(e.name):
                demoted += 1
        return demoted

    # -- introspection --------------------------------------------------------
    def stats(self) -> dict:
        """The /stats ``scheduler`` block: fleet saturation plus, per
        model, the paging state, priority default, windowed device-time
        share, and the live completion prediction."""
        now = time.monotonic()
        models: dict[str, dict] = {}
        for name, e in self._entries.items():
            self._trim(e, now)
            pred = self.predict_completion_s(name)
            models[name] = {
                "state": e.state,
                "slo_alert": self.slo_state(name),
                "priority": e.mcfg.priority,
                "cold_start": e.mcfg.cold_start,
                "parallel": {"signature": e.signature, "degree": e.degree},
                "share": round(self.share(name), 4),
                "device_seconds_window": round(e.window_sum, 4),
                "device_seconds_total": round(e.device_seconds_total.value, 4),
                "predicted_completion_s": round(pred, 4)
                if pred is not None else None,
                "pending": e.batcher.pending,
                "last_warm_ms": round(e.last_warm_s * 1e3, 1)
                if e.last_warm_s else None,
                "sheds": {r: c.value for r, c in e.shed_counters.items()
                          if c.value},
            }
        return {
            "saturated": self.saturated(),
            "window_s": self.cfg.window_s,
            "overload_clear_s": self.cfg.overload_clear_s,
            "min_share": self.cfg.min_share,
            "idle_demote_s": self.cfg.idle_demote_s,
            "chip_budget": self.cfg.chip_budget,
            "chips_in_use": self.chips_in_use(),
            "models": models,
        }
