"""Fleet-level SLO scheduler (ISSUE 10; docs/ROBUSTNESS.md "Fleet
isolation & SLO admission").

One server fronts many models; without cross-model arbitration one hot
model queue-starves the rest and every model's weights must fit in HBM at
once. This package is the central scheduler between admission
(server.handle_predict / the router tier) and the per-model
batchers/engines:

- :class:`FleetScheduler` — predictive admission (Clockwork, PAPERS.md
  P3: shed work that provably cannot meet its deadline, in microseconds),
  priority classes over a per-model device-seconds ledger (Clipper P1:
  low-priority sheds first; interactive floors hold), and the warm/cold
  weight-paging state machine (cold models boot without device params and
  stage through the lifecycle path on demand).
- :func:`run_fleet_drill` — the isolation drill behind
  ``python -m tpuserve chaos --drill fleet``: poison one model at 100%
  under multi-model load and measure that the victim's breaker opens
  while every other model holds its SLO.
"""

from tpuserve.scheduler.fleet import FleetScheduler, Shed  # noqa: F401
from tpuserve.scheduler.drill import run_fleet_drill  # noqa: F401
