"""The self-healing fleet controller (ISSUE 16 tentpole part 1;
docs/OPERATIONS.md "Self-operating fleet").

Every control signal the fleet emits — SLO burn state per model
(tpuserve.telemetry.slo), queue pressure per host domain, predicted
queue-clear time — and every actuator an operator has — scale a host
domain's worker slots, engage shed-on-burn, warm/demote a model — already
exists. This module closes the loop: a reconcile tick reads the signals
and acts through the SAME audited verbs a human would use, so the audit
trail reads identically whether a person or the controller turned the
knob.

The design splits decision from actuation:

- :class:`AutopilotPolicy` is a PURE function of
  (:class:`Signals`, its own bounded memory): signals in, actions out.
  All time comes from ``Signals.now`` — no clocks, no I/O — so the
  damping machinery (hysteresis, per-knob cooldowns, the windowed action
  budget, rollback-on-worse) is table-testable without a server
  (tests/test_autopilot.py).
- :class:`AutopilotLoop` owns the asyncio tick: collect signals, run the
  policy, actuate, audit every decision with the triggering signal
  values, and keep a bounded decision history for ``/debug/autopilot``.

Damping, because a controller that flaps is worse than no controller:

- **Hysteresis**: a trigger condition must hold ``hysteresis_ticks``
  consecutive ticks before it acts (one noisy sample moves nothing).
- **Cooldown**: the same (action kind, target) pair is untouchable for
  ``cooldown_s`` after an action (rollbacks are exempt — undo never
  waits).
- **Budget**: at most ``max_actions_per_window`` non-rollback actions
  per ``window_s`` — a controller gone wrong is rate-limited by
  construction (Clockwork's centralized-decision discipline, PAPERS P3,
  with a blast-radius bound).
- **Rollback**: every action opens a follow-up watch capturing the
  objective scalar it was supposed to improve; ``follow_up_s`` later the
  objective is re-measured and an action that made things WORSE by more
  than ``rollback_tolerance`` is inverted, audited as a rollback.
"""

from __future__ import annotations

import asyncio
import logging
import time
from collections import deque
from dataclasses import dataclass, field

from tpuserve.config import AutopilotConfig

log = logging.getLogger("tpuserve.autopilot")

# Action kinds and their inverses (the rollback map).
INVERSE = {
    "scale_up": "scale_down",
    "scale_down": "scale_up",
    "shed_on": "shed_off",
    "shed_off": "shed_on",
    "warm": "demote",
    "demote": "warm",
}

_BURN_SCORE = {"ok": 0.0, "pending": 1.0, "firing": 2.0}


@dataclass
class DomainSignal:
    """One host failure domain as the controller sees it."""

    hid: int
    up: bool = True
    # Active worker slots vs the domain's configured ceiling.
    active: int = 1
    max_slots: int = 1
    healthy: int = 1
    # Mean in-flight relays per active healthy slot — the queue-pressure
    # signal scale decisions read.
    pressure: float = 0.0


@dataclass
class ModelSignal:
    """One model as the controller sees it."""

    name: str
    # SLO burn alert state: ok / pending / firing (telemetry.slo).
    burn_state: str = "ok"
    # Is shed-on-burn currently engaged for this model?
    shed_engaged: bool = False
    # Paging state (scheduler warm/cold); warm=True for unpaged models.
    warm: bool = True
    # Collector verdicts for the paging actuator: demand exists for a
    # cold model / a warm model has been idle past the demote threshold.
    wants_warm: bool = False
    idle: bool = False


@dataclass
class Signals:
    """One reconcile tick's complete input. ``now`` is the ONLY clock the
    policy sees — tests drive time by constructing it."""

    now: float
    domains: list[DomainSignal] = field(default_factory=list)
    models: list[ModelSignal] = field(default_factory=list)
    # Fleet-aggregate predicted queue-clear time (s); 0 when unknown.
    predicted_clear_s: float = 0.0


@dataclass
class Action:
    """One controller decision, ready to actuate and audit."""

    kind: str
    target: str  # "host:<hid>" for scale kinds, the model name otherwise
    reason: str
    # Triggering signal values, recorded verbatim into the audit trail.
    signals: dict = field(default_factory=dict)
    # Set on rollback actions: the kind of the action being undone.
    rollback_of: str | None = None


def objective(sig: Signals) -> float:
    """The scalar the controller minimizes: SLO burn dominates (x10 per
    severity step of the worst model), queue pressure breaks ties. Lower
    is better."""
    worst_burn = max((_BURN_SCORE.get(m.burn_state, 0.0)
                      for m in sig.models), default=0.0)
    live = [d for d in sig.domains if d.up]
    mean_pressure = (sum(d.pressure for d in live) / len(live)
                     if live else 0.0)
    return worst_burn * 10.0 + mean_pressure


class _Watch:
    """Follow-up watch for one emitted action."""

    __slots__ = ("action", "objective_before", "due")

    def __init__(self, action: Action, objective_before: float,
                 due: float) -> None:
        self.action = action
        self.objective_before = objective_before
        self.due = due


class AutopilotPolicy:
    """Signals in, actions out — with bounded memory for damping.

    ``decide`` is deterministic given the Signals sequence it has seen;
    nothing here touches a clock, a lock, or the network."""

    def __init__(self, cfg: AutopilotConfig) -> None:
        self.cfg = cfg
        # Consecutive ticks each named trigger condition has held.
        self._streak: dict[str, int] = {}
        # (kind, target) -> monotonic-now the knob unlocks.
        self._cooldown_until: dict[tuple[str, str], float] = {}
        # Timestamps of non-rollback actions (the window budget).
        self._acted_at: deque[float] = deque()
        self._watches: list[_Watch] = []
        self.rollbacks_total = 0
        self.budget_deferrals_total = 0

    # -- damping predicates ---------------------------------------------------
    def _held(self, key: str, condition: bool) -> bool:
        """Track one trigger condition's consecutive-tick streak; True
        when it has held for >= hysteresis_ticks."""
        streak = self._streak.get(key, 0) + 1 if condition else 0
        self._streak[key] = streak
        return streak >= self.cfg.hysteresis_ticks

    def _cooled(self, kind: str, target: str, now: float) -> bool:
        return now >= self._cooldown_until.get((kind, target), 0.0)

    def _budget_open(self, now: float) -> bool:
        while self._acted_at and self._acted_at[0] < now - self.cfg.window_s:
            self._acted_at.popleft()
        return len(self._acted_at) < self.cfg.max_actions_per_window

    def _emit(self, out: list[Action], action: Action, sig: Signals,
              *, rollback: bool = False, streak_key: str | None = None) -> None:
        now = sig.now
        self._cooldown_until[(action.kind, action.target)] = \
            now + self.cfg.cooldown_s
        if rollback:
            self.rollbacks_total += 1
            # The undone knob cools too: without this the original
            # trigger (still held) would re-fire the very same tick and
            # the pair would flap at tick frequency.
            if action.rollback_of is not None:
                self._cooldown_until[(action.rollback_of, action.target)] = \
                    now + self.cfg.cooldown_s
        else:
            self._acted_at.append(now)
            if self.cfg.follow_up_s > 0 and action.kind in INVERSE:
                self._watches.append(_Watch(action, objective(sig),
                                            now + self.cfg.follow_up_s))
        # Acting consumes the streak: the condition must re-accumulate
        # hysteresis_ticks before the same trigger fires again.
        if streak_key is not None:
            self._streak.pop(streak_key, None)
        out.append(action)

    # -- the decision function ------------------------------------------------
    def decide(self, sig: Signals) -> list[Action]:
        out: list[Action] = []
        self._check_rollbacks(sig, out)
        self._decide_shed(sig, out)
        self._decide_scale(sig, out)
        if self.cfg.paging:
            self._decide_paging(sig, out)
        return out

    def _check_rollbacks(self, sig: Signals, out: list[Action]) -> None:
        """Follow-up watches due this tick: invert any action whose
        objective got worse. Rollbacks bypass cooldown AND budget — an
        undo that queues behind the budget is not an undo."""
        due = [w for w in self._watches if sig.now >= w.due]
        if not due:
            return
        self._watches = [w for w in self._watches if sig.now < w.due]
        obj_now = objective(sig)
        for w in due:
            if obj_now <= w.objective_before + self.cfg.rollback_tolerance:
                continue  # held or improved: the action stands
            a = w.action
            self._emit(out, Action(
                kind=INVERSE[a.kind], target=a.target, reason="rollback",
                rollback_of=a.kind,
                signals={"objective_before": round(w.objective_before, 4),
                         "objective_now": round(obj_now, 4),
                         "tolerance": self.cfg.rollback_tolerance,
                         "undoes": a.kind}), sig, rollback=True)

    def _gated_emit(self, out: list[Action], action: Action, sig: Signals,
                    streak_key: str) -> None:
        """Emit one triggered action through cooldown + budget."""
        if not self._cooled(action.kind, action.target, sig.now):
            return
        if not self._budget_open(sig.now):
            self.budget_deferrals_total += 1
            return
        self._emit(out, action, sig, streak_key=streak_key)

    def _decide_shed(self, sig: Signals, out: list[Action]) -> None:
        if not self.cfg.burn_shed:
            return
        for m in sig.models:
            sigvals = {"burn_state": m.burn_state,
                       "shed_engaged": m.shed_engaged}
            if self._held(f"burn_firing:{m.name}",
                          m.burn_state == "firing" and not m.shed_engaged):
                self._gated_emit(out, Action(
                    "shed_on", m.name, "burn_firing", sigvals), sig,
                    f"burn_firing:{m.name}")
            if self._held(f"burn_clear:{m.name}",
                          m.burn_state == "ok" and m.shed_engaged):
                self._gated_emit(out, Action(
                    "shed_off", m.name, "burn_clear", sigvals), sig,
                    f"burn_clear:{m.name}")

    def _decide_scale(self, sig: Signals, out: list[Action]) -> None:
        if not self.cfg.scale:
            return
        any_burning = any(m.burn_state != "ok" for m in sig.models)
        clear_hot = (self.cfg.clear_high_s > 0
                     and sig.predicted_clear_s > self.cfg.clear_high_s)
        for d in sig.domains:
            if not d.up:
                continue
            target = f"host:{d.hid}"
            sigvals = {"pressure": round(d.pressure, 4),
                       "active": d.active, "max_slots": d.max_slots,
                       "predicted_clear_s": round(sig.predicted_clear_s, 4)}
            hot = d.pressure > self.cfg.pressure_high or clear_hot
            if self._held(f"pressure_high:{target}",
                          hot and d.active < d.max_slots):
                self._gated_emit(out, Action(
                    "scale_up", target, "pressure_high", sigvals), sig,
                    f"pressure_high:{target}")
            cold = (d.pressure < self.cfg.pressure_low and not any_burning
                    and not clear_hot)
            if self._held(f"pressure_low:{target}",
                          cold and d.active > self.cfg.min_slots):
                self._gated_emit(out, Action(
                    "scale_down", target, "pressure_low", sigvals), sig,
                    f"pressure_low:{target}")

    def _decide_paging(self, sig: Signals, out: list[Action]) -> None:
        warm_count = sum(1 for m in sig.models if m.warm)
        for m in sig.models:
            sigvals = {"warm": m.warm, "wants_warm": m.wants_warm,
                       "idle": m.idle, "warm_count": warm_count,
                       "max_warm": self.cfg.max_warm}
            budget_ok = (self.cfg.max_warm <= 0
                         or warm_count < self.cfg.max_warm)
            if self._held(f"wants_warm:{m.name}",
                          m.wants_warm and not m.warm and budget_ok):
                self._gated_emit(out, Action(
                    "warm", m.name, "demand_cold", sigvals), sig,
                    f"wants_warm:{m.name}")
                warm_count += 1
            over_budget = (self.cfg.max_warm > 0
                           and warm_count > self.cfg.max_warm)
            if self._held(f"idle_warm:{m.name}",
                          m.warm and (m.idle or over_budget)
                          and not m.wants_warm):
                self._gated_emit(out, Action(
                    "demote", m.name,
                    "warm_budget" if over_budget else "idle", sigvals), sig,
                    f"idle_warm:{m.name}")
                warm_count -= 1

    def describe(self) -> dict:
        return {
            "watches_open": len(self._watches),
            "rollbacks_total": self.rollbacks_total,
            "budget_deferrals_total": self.budget_deferrals_total,
            "actions_in_window": len(self._acted_at),
        }


class AutopilotLoop:
    """The asyncio side: tick -> collect -> decide -> actuate -> audit.

    ``signal_fn()`` returns a :class:`Signals`; ``actuate_fn(action)`` is
    an async callable returning an outcome string ("ok"/"error: ...").
    Both are injected by the owner (the primary router) so this class
    needs no knowledge of supervisors or HTTP."""

    def __init__(self, cfg: AutopilotConfig, signal_fn, actuate_fn,
                 audit=None, metrics=None) -> None:
        self.cfg = cfg
        self.policy = AutopilotPolicy(cfg)
        self.signal_fn = signal_fn
        self.actuate_fn = actuate_fn
        self.audit = audit
        self.metrics = metrics
        self.ticks = 0
        self.actions_total = 0
        self.errors_total = 0
        self._decisions: deque[dict] = deque(maxlen=cfg.history)
        self._task: asyncio.Task | None = None

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _run(self) -> None:
        while True:
            await asyncio.sleep(self.cfg.interval_s)
            try:
                await self.tick()
            except asyncio.CancelledError:
                raise
            except Exception:  # one bad tick must not end the controller
                log.exception("autopilot tick failed")

    async def tick(self) -> list[Action]:
        """One reconcile pass (exposed for drills/tests)."""
        self.ticks += 1
        sig = self.signal_fn()
        actions = self.policy.decide(sig)
        for a in actions:
            t0 = time.monotonic()
            try:
                outcome = await self.actuate_fn(a)
            except Exception as e:  # noqa: BLE001 — audit the failure
                outcome = f"error: {type(e).__name__}: {e}"
            ok = outcome == "ok"
            self.actions_total += 1
            if not ok:
                self.errors_total += 1
            if self.metrics is not None:
                self.metrics.autopilot_action_counter(
                    a.kind, "rollback" if a.rollback_of else
                    ("ok" if ok else "error")).inc()
            rec = {
                "ts": round(time.time(), 3),
                "kind": a.kind,
                "target": a.target,
                "reason": a.reason,
                "outcome": outcome,
                "signals": a.signals,
            }
            if a.rollback_of:
                rec["rollback_of"] = a.rollback_of
            self._decisions.append(rec)
            if self.audit is not None:
                self.audit.record(
                    f"autopilot:{a.kind}", a.target,
                    "rollback" if a.rollback_of and ok else
                    ("ok" if ok else "error"),
                    duration_ms=(time.monotonic() - t0) * 1e3,
                    reason=a.reason, **a.signals)
            log.info("autopilot %s %s (%s): %s",
                     a.kind, a.target, a.reason, outcome)
        return actions

    def describe(self) -> dict:
        """The /debug/autopilot body."""
        return {
            "enabled": self.cfg.enabled,
            "running": self._task is not None,
            "interval_s": self.cfg.interval_s,
            "ticks": self.ticks,
            "actions_total": self.actions_total,
            "errors_total": self.errors_total,
            "policy": self.policy.describe(),
            "decisions": list(self._decisions),
        }
