"""Per-tenant containment: API keys, a weighted device-seconds ledger,
and quota/rate/fair-share admission (ISSUE 16 tentpole part 2;
docs/OPERATIONS.md "Tenant containment").

The PR 10 fleet scheduler keeps a sliding-window device-seconds ledger
per MODEL so one model cannot starve another. Multi-tenancy is the same
ledger grown one dimension: every request carries an ``X-Api-Key``
resolved to a tenant, and admission charges/enforces per TENANT —

1. **Rate** (token bucket, ``rate_per_s``/``burst``): a flood is refused
   at request granularity before it costs anything.
2. **Quota** (``quota_device_s`` per ``window_s`` sliding window of the
   device-time ledger): a tenant that has spent its windowed allowance is
   429'd with a Retry-After derived from when the window actually frees.
3. **Fair share** (``weight`` under fleet saturation): when the fleet is
   saturated (the scheduler's ``overload_clear_s`` signal, threaded in as
   ``saturated_fn``) a tenant consuming more than ``share_slack`` x its
   weighted fraction of the observed window sheds while its neighbors
   keep flowing — Clockwork's centralized-decision discipline (PAPERS P3)
   applied across customers instead of models.

Every refusal is a :class:`tpuserve.scheduler.fleet.Shed` with a
``tenant_*`` reason (obs.TENANT_SHED_REASONS) so the response body, the
shed counters, and the drill's assertions all speak one vocabulary.
State is behind one short witnessed lock: the router admits on its event
loop but charges completion from relay callbacks, and the single-process
server may run multi-loop ingest.
"""

from __future__ import annotations

import time
from collections import deque

from tpuserve.batcher import clamp_retry_after_s
from tpuserve.config import TenantConfig, TenantsConfig
from tpuserve.obs import TENANT_SHED_REASONS, Metrics
from tpuserve.scheduler.fleet import Shed
from tpuserve.utils.locks import new_lock


class _TenantState:
    """One tenant's mutable ledger + token-bucket state."""

    __slots__ = ("cfg", "ledger", "window_sum", "tokens", "refilled_at",
                 "admitted_total", "requests_counter", "shed_counters",
                 "device_counter", "latency_hist", "device_seconds_total")

    def __init__(self, cfg: TenantConfig, metrics: Metrics | None) -> None:
        self.cfg = cfg
        self.ledger: deque[tuple[float, float]] = deque()
        self.window_sum = 0.0
        self.device_seconds_total = 0.0
        self.tokens = cfg.burst or max(1.0, 2.0 * cfg.rate_per_s)
        self.refilled_at = time.monotonic()
        self.admitted_total = 0
        self.requests_counter = (
            metrics.tenant_requests_counter(cfg.name)
            if metrics is not None else None)
        self.shed_counters = (
            {r: metrics.tenant_shed_counter(cfg.name, r)
             for r in TENANT_SHED_REASONS}
            if metrics is not None else None)
        self.device_counter = (
            metrics.tenant_device_seconds_counter(cfg.name)
            if metrics is not None else None)
        self.latency_hist = (
            metrics.tenant_latency_histogram(cfg.name)
            if metrics is not None else None)


class TenantLedger:
    """Resolve API keys to tenants and enforce their containment
    envelopes at admission. One instance per serving process that fronts
    clients (the router tier, or the single-process server)."""

    def __init__(self, cfg: TenantsConfig,
                 metrics: Metrics | None = None) -> None:
        self.cfg = cfg
        self.metrics = metrics
        self._lock = new_lock("scheduler.TenantLedger")
        self._by_key: dict[str, str] = {}
        self._tenants: dict[str, _TenantState] = {}
        for t in cfg.tenants:
            self._by_key[t.api_key] = t.name
            self._tenants[t.name] = _TenantState(t, metrics)
        if cfg.allow_anonymous and cfg.allow_anonymous not in self._tenants:
            # The anonymous tenant rides with default weight and no
            # quota/rate unless configured explicitly.
            anon = TenantConfig(name=cfg.allow_anonymous,
                                api_key="\0anonymous")
            self._tenants[anon.name] = _TenantState(anon, metrics)
        # Fleet-saturation signal for fair-share shedding; threaded in by
        # the owner (router: aggregate pressure; server: scheduler
        # saturated()). None = fair-share shedding never fires.
        self.saturated_fn = None
        self._unknown_counter = (
            metrics.tenant_shed_counter("unknown", "tenant_unknown")
            if metrics is not None else None)

    # -- identity -------------------------------------------------------------
    def resolve(self, api_key: str | None) -> str | None:
        """Tenant name for a presented key; the anonymous tenant when the
        key is absent/unknown and [tenants] allows it; None = reject."""
        if api_key and api_key in self._by_key:
            return self._by_key[api_key]
        if self.cfg.allow_anonymous:
            return self.cfg.allow_anonymous
        return None

    def names(self) -> list[str]:
        return sorted(self._tenants)

    def weight_of(self, tenant: str) -> float:
        st = self._tenants.get(tenant)
        return st.cfg.weight if st is not None else 1.0

    def weights(self) -> dict[str, float]:
        """Tenant -> fairness weight (the cache partitioner's input)."""
        return {n: st.cfg.weight for n, st in self._tenants.items()}

    # -- admission ------------------------------------------------------------
    def shed_unknown(self) -> Shed:
        """The refusal for a request whose key resolves to no tenant."""
        if self._unknown_counter is not None:
            self._unknown_counter.inc()
        return Shed(401, "tenant_unknown",
                    "unknown or missing API key (X-Api-Key)")

    def admit(self, tenant: str) -> Shed | None:
        """Charge one request against the tenant's envelope; a Shed means
        refuse (429 + Retry-After), None means admitted."""
        now = time.monotonic()
        with self._lock:
            st = self._tenants.get(tenant)
            if st is None:
                pass  # fall through to unknown below, outside the lock
            else:
                shed = self._admit_locked(st, now)
                if shed is None:
                    st.admitted_total += 1
                    if st.requests_counter is not None:
                        st.requests_counter.inc()
                elif st.shed_counters is not None:
                    st.shed_counters[shed.reason].inc()
                return shed
        return self.shed_unknown()

    def _admit_locked(self, st: _TenantState, now: float) -> Shed | None:
        cfg = st.cfg
        # 1. Rate: refill-then-spend token bucket.
        if cfg.rate_per_s > 0:
            burst = cfg.burst or max(1.0, 2.0 * cfg.rate_per_s)
            st.tokens = min(burst, st.tokens
                            + (now - st.refilled_at) * cfg.rate_per_s)
            st.refilled_at = now
            if st.tokens < 1.0:
                retry = clamp_retry_after_s((1.0 - st.tokens) / cfg.rate_per_s)
                return Shed(429, "tenant_rate_exceeded",
                            f"tenant {cfg.name!r} over {cfg.rate_per_s:g} "
                            "req/s", retry_after=retry)
            st.tokens -= 1.0
        self._prune_locked(st, now)
        # 2. Quota: windowed device-seconds allowance.
        if cfg.quota_device_s > 0 and st.window_sum >= cfg.quota_device_s:
            oldest = st.ledger[0][0] if st.ledger else now
            retry = clamp_retry_after_s(
                max(1.0, self.cfg.window_s - (now - oldest)))
            return Shed(429, "tenant_quota_exceeded",
                        f"tenant {cfg.name!r} spent its "
                        f"{cfg.quota_device_s:g} device-seconds per "
                        f"{self.cfg.window_s:g}s window", retry_after=retry)
        # 3. Fair share, only under fleet saturation.
        if self.cfg.share_slack > 0 and self.saturated_fn is not None \
                and self.saturated_fn():
            total = sum(t.window_sum for t in self._tenants.values())
            if total > 0 and st.window_sum > 0:
                total_w = sum(t.cfg.weight for t in self._tenants.values())
                fair = cfg.weight / total_w
                if st.window_sum / total > fair * self.cfg.share_slack:
                    return Shed(429, "tenant_share_exceeded",
                                f"tenant {cfg.name!r} over its weighted "
                                "fair share while the fleet is saturated",
                                retry_after=clamp_retry_after_s(1.0))
        return None

    # -- ledger ---------------------------------------------------------------
    def record(self, tenant: str, seconds: float,
               latency_ms: float | None = None) -> None:
        """Charge completed work (a device-time proxy in seconds) and
        optionally the observed latency to the tenant's ledger."""
        if seconds < 0:
            seconds = 0.0
        now = time.monotonic()
        with self._lock:
            st = self._tenants.get(tenant)
            if st is None:
                return
            st.ledger.append((now, seconds))
            st.window_sum += seconds
            st.device_seconds_total += seconds
            self._prune_locked(st, now)
        if st.device_counter is not None and seconds > 0:
            st.device_counter.inc(seconds)
        if st.latency_hist is not None and latency_ms is not None:
            st.latency_hist.observe(latency_ms)

    def _prune_locked(self, st: _TenantState, now: float) -> None:
        cutoff = now - self.cfg.window_s
        while st.ledger and st.ledger[0][0] < cutoff:
            _, s = st.ledger.popleft()
            st.window_sum -= s
        if not st.ledger:
            st.window_sum = 0.0

    # -- reads ----------------------------------------------------------------
    def usage(self) -> dict:
        """The /tenants body: per-tenant envelope + live window usage."""
        now = time.monotonic()
        rows = {}
        with self._lock:
            for name, st in sorted(self._tenants.items()):
                self._prune_locked(st, now)
                cfg = st.cfg
                rows[name] = {
                    "weight": cfg.weight,
                    "quota_device_s": cfg.quota_device_s,
                    "rate_per_s": cfg.rate_per_s,
                    "window_device_s": round(st.window_sum, 4),
                    "device_seconds_total": round(
                        st.device_seconds_total, 4),
                    "admitted_total": st.admitted_total,
                }
        return {"enabled": self.cfg.enabled,
                "window_s": self.cfg.window_s,
                "share_slack": self.cfg.share_slack,
                "tenants": rows}
