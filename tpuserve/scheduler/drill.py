"""Fleet isolation drill (``python -m tpuserve chaos --drill fleet``;
Clipper's isolation story, PAPERS.md P1, measured).

A fleet's availability property is per-model isolation: one misbehaving
model must cost ITS OWN traffic, never the front door. The drill serves
one real multi-model server (>= 3 models, fleet scheduler armed), drives
a closed-loop load generator at EVERY model concurrently, poisons one
victim with ``device_error`` at 100% probability (every dispatch below
the batcher fails — retry, split, and breaker all see real failures),
and measures:

- **victim containment** — the victim's circuit breaker opens, so its
  traffic degrades to fast 503s instead of slow 500s;
- **survivor availability** — every OTHER model holds availability >=
  the bound (default 99%) with its p99 within budget: the poisoned
  model's failing dispatches never starve the survivors' batchers,
  stage executors, or admission;
- the summary's ``availability`` is the MINIMUM across survivors (the
  number the chaos CLI gates), with per-model latency percentiles and
  the scheduler/breaker/injector state attached for the script gates.
"""

from __future__ import annotations

import asyncio
import logging

from tpuserve.config import FaultRuleConfig, ServerConfig

log = logging.getLogger("tpuserve.scheduler")


async def run_fleet_drill(cfg: ServerConfig, victim: str | None = None,
                          duration_s: float = 10.0, warmup_s: float = 1.0,
                          concurrency: int = 8) -> dict:
    """Serve ``cfg``'s models on an ephemeral port with the victim
    poisoned, load every model concurrently, and report per-model
    availability + breaker/scheduler state. The caller (CLI / script)
    owns asserting the bounds."""
    from aiohttp import web

    from tpuserve.bench.loadgen import run_load, synthetic_image_npy
    from tpuserve.server import ServerState, make_app

    if len(cfg.models) < 3:
        raise ValueError(
            f"the fleet drill needs >= 3 models to prove isolation; "
            f"config has {len(cfg.models)}")
    victim = victim or cfg.models[0].name
    if victim not in {m.name for m in cfg.models}:
        raise ValueError(f"victim {victim!r} is not a configured model")

    # Poison the victim: every dispatch below the batcher raises, so the
    # whole recovery ladder (retry -> split -> breaker) runs against real
    # failures. The drill proves the blast radius stops at the victim.
    cfg.faults.enabled = True
    cfg.faults.rules.append(FaultRuleConfig(
        kind="device_error", model=victim, probability=1.0))
    # The drill IS the scheduler's fleet mode; and every measured response
    # must be a real execution — a cache would serve perfect answers on
    # behalf of a poisoned model.
    cfg.scheduler.enabled = True
    cfg.cache.enabled = False

    state = ServerState(cfg)
    state.build()
    app = make_app(state)
    runner = web.AppRunner(app, access_log=None)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    try:
        port = runner.addresses[0][1]
        base = f"http://127.0.0.1:{port}"
        loads = await asyncio.gather(*(
            run_load(f"{base}/v1/models/{m.name}:predict",
                     synthetic_image_npy(edge=m.wire_size),
                     "application/x-npy", duration_s, concurrency, warmup_s)
            for m in cfg.models))
        breakers = {n: br.describe() for n, br in state.breakers.items()}
        sched = state.scheduler.stats() if state.scheduler else {}
        faults = state.injector.snapshot() if state.injector else []
    finally:
        await runner.cleanup()

    models: dict[str, dict] = {}
    survivor_avail = []
    for m, res in zip(cfg.models, loads):
        total = res.n_ok + res.n_err
        avail = round(res.n_ok / total, 5) if total else 0.0
        row = res.summary()
        row["availability"] = avail
        row["role"] = "victim" if m.name == victim else "survivor"
        models[m.name] = row
        if m.name != victim:
            survivor_avail.append(avail)
    return {
        "drill": "fleet",
        "victim": victim,
        "victim_breaker": breakers.get(victim, {}),
        "victim_breaker_open": breakers.get(victim, {}).get("state")
        in ("open", "half_open"),
        # The chaos CLI gates this: the WORST survivor must hold the SLO.
        "availability": min(survivor_avail) if survivor_avail else 0.0,
        "models": models,
        "breakers": breakers,
        "scheduler": sched,
        "faults": faults,
    }
