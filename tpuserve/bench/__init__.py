"""Benchmark harness (SURVEY.md §2 C11): load generator + baselines."""
