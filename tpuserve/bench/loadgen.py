"""Asyncio HTTP load generator (SURVEY.md §2 C11).

Two modes:

- **Closed loop** (``run_load``): ``concurrency`` workers each keep exactly
  one request in flight. Measures peak sustainable throughput; its p50 is
  queueing delay by Little's law, NOT server latency.
- **Open loop** (``run_load_open``): requests are issued on a fixed-rate
  clock regardless of completions, like independent clients. Latency
  percentiles at a stated offered rate are the honest latency metric
  (BASELINE.md's ≤15 ms p50 target is defined this way).

Window accounting, both modes: a request is recorded only if it *completes*
inside the measurement window ``[warmup, warmup + duration)``; throughput
divides by the actual window length. In-flight stragglers at window end are
counted separately (``n_late``) and never inflate throughput.

Workload shaping for the result cache (ISSUE 5): ``payload`` may be a LIST
of bodies, cycled round-robin across issues. A pool of N distinct payloads
larger than the server's cache capacity is a **miss-only** workload (LRU
round-robin thrash: every lookup misses), while the single-payload default
is **hit-heavy** once the cache is warm — ``synthetic_pool`` builds the
distinct bodies, and the CLI exposes it as ``--distinct N``.
"""

from __future__ import annotations

import asyncio
import io
import json
import time
from dataclasses import dataclass, field

import numpy as np

from tpuserve.obs import percentile

# Inter-token gap histogram edges (ms). Log-ish spacing: the interesting
# signal is the tail (a prefill stall parks every decoder for one chunk),
# and a fixed ladder keeps pass-over-pass summaries comparable.
GAP_HIST_EDGES_MS = (1.0, 2.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0)


def gap_histogram(gaps_ms: list[float]) -> dict:
    """Fixed-ladder histogram of inter-token gaps: ``{"<=10": n, ...,
    ">250": n}`` — cheap to eyeball across loadgen passes."""
    counts = [0] * (len(GAP_HIST_EDGES_MS) + 1)
    for g in gaps_ms:
        for i, edge in enumerate(GAP_HIST_EDGES_MS):
            if g <= edge:
                counts[i] += 1
                break
        else:
            counts[-1] += 1
    out = {f"<={edge:g}": counts[i]
           for i, edge in enumerate(GAP_HIST_EDGES_MS)}
    out[f">{GAP_HIST_EDGES_MS[-1]:g}"] = counts[-1]
    return out


@dataclass
class LoadResult:
    mode: str = "closed"
    n_ok: int = 0
    n_err: int = 0
    n_late: int = 0  # completed after the window closed (excluded above)
    duration_s: float = 0.0  # actual measurement window
    offered_rate: float = 0.0  # open loop only: requests/s issued
    # Client-side batching: each POST carries this many items (the server's
    # {"results": [...]} shape). Throughput counts ITEMS; latencies are still
    # whole-request (the time to answer all items in the POST).
    items_per_request: int = 1
    # Size of the distinct-payload pool cycled by the run (0 = one payload).
    distinct_payloads: int = 0
    latencies_ms: list[float] = field(default_factory=list)

    @property
    def throughput(self) -> float:
        if self.duration_s <= 0:
            return 0.0
        return self.n_ok * self.items_per_request / self.duration_s

    def summary(self) -> dict:
        out = {
            "mode": self.mode,
            "n_ok": self.n_ok,
            "n_err": self.n_err,
            "n_late": self.n_late,
            "duration_s": round(self.duration_s, 3),
            "throughput_per_s": round(self.throughput, 1),
            "p50_ms": round(percentile(self.latencies_ms, 0.5), 3),
            "p90_ms": round(percentile(self.latencies_ms, 0.9), 3),
            "p99_ms": round(percentile(self.latencies_ms, 0.99), 3),
        }
        if self.items_per_request != 1:
            out["items_per_request"] = self.items_per_request
        if self.distinct_payloads:
            out["distinct_payloads"] = self.distinct_payloads
        if self.mode == "open":
            out["offered_rate_per_s"] = round(self.offered_rate, 1)
        return out


@dataclass
class StreamLoadResult:
    """Closed-loop STREAMING load (ISSUE 17): per-stream first-token
    latency, inter-token gaps, and exact tokens/s measured from token
    EVENT arrival timestamps — not from request completions, which for a
    stream only say when the last byte landed."""

    mode: str = "stream-closed"
    n_ok: int = 0       # terminal "done" inside the window
    n_err: int = 0      # plain status, "error" terminal, or torn stream
    n_late: int = 0
    duration_s: float = 0.0
    distinct_payloads: int = 0
    tokens: int = 0     # token events that ARRIVED inside the window
    torn: int = 0       # streams ending with no terminal (must be 0)
    terminals: dict = field(default_factory=dict)
    first_token_ms: list[float] = field(default_factory=list)
    gap_ms: list[float] = field(default_factory=list)

    def summary(self) -> dict:
        out = {
            "mode": self.mode,
            "n_ok": self.n_ok,
            "n_err": self.n_err,
            "n_late": self.n_late,
            "duration_s": round(self.duration_s, 3),
            "streams_per_s": round(self.n_ok / self.duration_s, 2)
            if self.duration_s > 0 else 0.0,
            "tokens_per_s": round(self.tokens / self.duration_s, 1)
            if self.duration_s > 0 else 0.0,
            "first_token_p50_ms": round(
                percentile(self.first_token_ms, 0.5), 3),
            "first_token_p99_ms": round(
                percentile(self.first_token_ms, 0.99), 3),
            "inter_token_gap_p50_ms": round(percentile(self.gap_ms, 0.5), 3),
            "inter_token_gap_p99_ms": round(percentile(self.gap_ms, 0.99), 3),
            "inter_token_gap_max_ms": round(max(self.gap_ms), 3)
            if self.gap_ms else 0.0,
            "inter_token_gap_hist_ms": gap_histogram(self.gap_ms),
            "terminals": dict(self.terminals),
            "torn_streams": self.torn,
        }
        if self.distinct_payloads:
            out["distinct_payloads"] = self.distinct_payloads
        return out


class SseParser:
    """Incremental ``text/event-stream`` parser.

    feed() returns complete ``(event, data_text)`` pairs; comment lines
    (the server's ``: hb`` heartbeats) are dropped. Deliberately tolerant
    of a TORN event glued to a later complete one (a worker SIGKILLed
    mid-write, then the router's appended error terminal): each ``event:``
    line starts a fresh pair, so the partial pair surfaces as undecodable
    data for the caller to count — never as a swallowed terminal."""

    def __init__(self) -> None:
        self._buf = b""

    @property
    def pending(self) -> int:
        """Bytes of an incomplete event still buffered (torn-tail audit)."""
        return len(self._buf)

    def feed(self, chunk: bytes) -> list[tuple[str, str]]:
        self._buf += chunk
        out: list[tuple[str, str]] = []
        while b"\n\n" in self._buf:
            block, self._buf = self._buf.split(b"\n\n", 1)
            event: str | None = None
            data: list[bytes] = []
            for line in block.split(b"\n"):
                if line.startswith(b":"):
                    continue  # heartbeat / comment
                if line.startswith(b"event:"):
                    if event is not None:
                        out.append((event, b"\n".join(data).decode(
                            "utf-8", "replace")))
                        data = []
                    event = line[6:].strip().decode("utf-8", "replace")
                elif line.startswith(b"data:"):
                    data.append(line[5:].strip())
            if event is not None:
                out.append((event,
                            b"\n".join(data).decode("utf-8", "replace")))
        return out


async def stream_generate(session, url: str, data: bytes, headers: dict,
                          total_timeout_s: float = 120.0) -> dict:
    """POST one ``?stream=true`` generation and consume the SSE stream to
    EOF. Returns the full per-stream record the drill's byte-audit needs:
    concatenated token text, token indices and arrival times
    (perf_counter), the terminal ("done"/"error"/None), and ``torn`` —
    True when the stream ended with NO terminal event, which is exactly
    the silent truncation the streaming contract forbids."""
    import aiohttp

    rec: dict = {"status": None, "terminal": None, "finish_reason": None,
                 "error": None, "usage": None, "text": "", "indices": [],
                 "token_times": [], "junk": 0, "torn": False,
                 "first_token_ms": None}
    sep = "&" if "?" in url else "?"
    t0 = time.perf_counter()
    try:
        async with session.post(
                f"{url}{sep}stream=true", data=data, headers=headers,
                timeout=aiohttp.ClientTimeout(total=total_timeout_s)) as r:
            rec["status"] = r.status
            if r.status != 200 \
                    or r.headers.get("X-Tpuserve-Stream") != "1":
                await r.read()  # plain (pre-first-unit) answer: no stream
                return rec
            parser = SseParser()
            async for chunk in r.content.iter_any():
                for event, text in parser.feed(chunk):
                    try:
                        obj = json.loads(text) if text else {}
                    except ValueError:
                        rec["junk"] += 1  # torn event (worker died mid-write)
                        continue
                    if event == "token":
                        now = time.perf_counter()
                        if rec["first_token_ms"] is None:
                            rec["first_token_ms"] = (now - t0) * 1e3
                        rec["token_times"].append(now)
                        rec["text"] += obj.get("text", "")
                        rec["indices"].append(obj.get("index"))
                    elif event == "done":
                        rec["terminal"] = "done"
                        rec["finish_reason"] = obj.get("finish_reason")
                        rec["usage"] = obj.get("usage")
                    elif event == "error":
                        rec["terminal"] = "error"
                        rec["error"] = obj.get("error")
            if rec["terminal"] is None:
                rec["torn"] = True  # EOF, no terminal: silent truncation
            rec["junk"] += 1 if parser.pending else 0
    except asyncio.CancelledError:
        raise
    except Exception:  # noqa: BLE001 — transport failure mid-stream
        if rec["status"] == 200:
            rec["torn"] = rec["terminal"] is None
        elif rec["status"] is None:
            rec["status"] = -1  # connect-level failure, never admitted
    return rec


async def run_stream_load(
    url: str,
    payload: "bytes | list[bytes]",
    content_type: str,
    duration_s: float = 10.0,
    concurrency: int = 8,
    warmup_s: float = 2.0,
) -> StreamLoadResult:
    """Closed-loop streaming mode (``bench --stream``): ``concurrency``
    workers each keep one STREAM in flight, parsing token events as they
    arrive. First-token latency and inter-token gaps come from event
    timestamps; tokens/s counts token arrivals inside the window — the
    exact generation rate, not an average smeared over request lifetimes."""
    import aiohttp

    pool = payload if isinstance(payload, (list, tuple)) else None
    result = StreamLoadResult(distinct_payloads=len(pool) if pool else 0)
    headers = {"Content-Type": content_type}
    now = time.perf_counter()
    record_from = now + warmup_s
    stop_at = now + warmup_s + duration_s
    cursor = 0

    async def worker(session) -> None:
        nonlocal cursor
        while time.perf_counter() < stop_at:
            if pool is not None:
                data = pool[cursor % len(pool)]
                cursor += 1
            else:
                data = payload
            rec = await stream_generate(session, url, data, headers)
            # Token arrivals count toward tokens/s regardless of how the
            # stream ended — delivered tokens are delivered work.
            result.tokens += sum(1 for t in rec["token_times"]
                                 if record_from <= t < stop_at)
            t1 = time.perf_counter()
            if t1 < record_from:
                continue
            if t1 >= stop_at:
                result.n_late += 1
                continue
            term = rec["terminal"] or ("torn" if rec["torn"] else "none")
            result.terminals[term] = result.terminals.get(term, 0) + 1
            if rec["torn"]:
                result.torn += 1
            if rec["terminal"] == "done":
                result.n_ok += 1
                if rec["first_token_ms"] is not None:
                    result.first_token_ms.append(rec["first_token_ms"])
                times = rec["token_times"]
                result.gap_ms.extend(
                    (b - a) * 1e3 for a, b in zip(times, times[1:]))
            else:
                result.n_err += 1

    conn = aiohttp.TCPConnector(limit=concurrency * 2)
    async with aiohttp.ClientSession(connector=conn) as session:
        await asyncio.gather(*(asyncio.ensure_future(worker(session))
                               for _ in range(concurrency)))
    result.duration_s = stop_at - record_from
    return result


def closed_loop_concurrency(buckets: list[int], n_chips: int = 1,
                            per_chip_cap: int = 384) -> int:
    """Loadgen connection count for a closed-loop bench run.

    Per chip, keep ~3 top-bucket batches of demand in flight (one
    computing, one in transfer, one assembling — the pipeline's natural
    occupancy), floored at 32 connections and capped at ``per_chip_cap``.
    Scaling by ``n_chips`` is the point (ISSUE 7 satellite): a closed loop
    sized for one chip offers exactly one chip's worth of demand, so an
    8-chip mesh idles 7 chips and the bench under-reports by design."""
    n = max(1, n_chips)
    top = max(buckets) if buckets else 0
    return min(per_chip_cap * n, max(32, 3 * top * n))


def synthetic_image_npy(edge: int = 256, seed: int = 0) -> bytes:
    rng = np.random.default_rng(seed)
    arr = rng.integers(0, 255, (edge, edge, 3), dtype=np.uint8)
    buf = io.BytesIO()
    np.save(buf, arr)
    return buf.getvalue()


def synthetic_image_npy_batch(edge: int = 256, n: int = 8, seed: int = 0) -> bytes:
    """(n, edge, edge, 3) uint8 npy body: one POST carrying a client batch."""
    rng = np.random.default_rng(seed)
    arr = rng.integers(0, 255, (n, edge, edge, 3), dtype=np.uint8)
    buf = io.BytesIO()
    np.save(buf, arr)
    return buf.getvalue()


def synthetic_pool(kind: str, n: int, edge: int = 256,
                   batch: int = 0, seed_base: int = 0) -> list[bytes]:
    """``n`` distinct synthetic payloads (seeds seed_base..seed_base+n-1)
    for miss-only workloads: every body decodes to different pixels, so
    every request is a new cache key. ``kind`` is "jpeg" or "npy";
    ``batch > 1`` builds (batch, edge, edge, 3) npy client batches
    instead. ``seed_base`` gives multi-process load workers disjoint pools
    — two workers cycling the SAME pool would coalesce in the server's
    single-flight layer and share batch slots, inflating a miss-only
    measurement (ISSUE 11 satellite)."""
    if batch > 1:
        return [synthetic_image_npy_batch(edge, batch, seed=seed_base + i)
                for i in range(n)]
    gen = synthetic_image_jpeg if kind == "jpeg" else synthetic_image_npy
    return [gen(edge, seed=seed_base + i) for i in range(n)]


def synthetic_frame(edge: int = 256, n_items: int = 8, kind: str = "yuv420",
                    seed: int = 0) -> bytes:
    """One ``application/x-tpuserve-frame`` body of ``n_items`` distinct
    random images (tpuserve.frame): the framed-wire client batch. yuv420
    frames carry exactly the planes ``rgb_to_yuv420`` would produce from
    the equivalent npy body, so framed and npy loads are answer-identical
    (tests/test_frame.py pins it byte-for-byte)."""
    from tpuserve import frame, preproc

    rng = np.random.default_rng(seed)
    items = []
    for _ in range(n_items):
        rgb = rng.integers(0, 255, (edge, edge, 3), dtype=np.uint8)
        items.append(preproc.rgb_to_yuv420(rgb) if kind == "yuv420" else rgb)
    return frame.encode_frame(items, frame.KIND_BY_WIRE_FORMAT[kind], edge)


def synthetic_frame_pool(n: int, edge: int = 256, n_items: int = 8,
                         kind: str = "yuv420",
                         seed_base: int = 0) -> list[bytes]:
    """``n`` distinct framed bodies (each of ``n_items`` images) — the
    framed-wire miss-only pool (``--wire frame --distinct N``)."""
    return [synthetic_frame(edge, n_items, kind, seed=seed_base + i)
            for i in range(n)]


def synthetic_prompt_pool(n: int, max_new: tuple[int, int] = (2, 32),
                          sd: bool = False, seed: int = 0,
                          long_every: int = 0,
                          long_words: int = 16) -> list[bytes]:
    """``n`` distinct JSON prompt bodies for the generative families.

    Every body carries a distinct (prompt, seed) pair — the generative
    cache-key contract means no two of them can alias — and, for textgen
    (``sd=False``), a ``max_new_tokens`` drawn across ``[lo, hi]`` so the
    offered load has MIXED output lengths. Mixed lengths are the point
    (ISSUE 9): a locked batch runs every lane for its longest member, so
    the iteration-level engine's early-exit gain is only visible when
    short and long completions share a batch. SD bodies (``sd=True``) omit
    the length knob (fixed denoise steps) and vary prompt + seed only.

    ``long_every`` > 0 SKEWS the pool (ISSUE 18): every long_every-th body
    carries a ``long_words``-word prompt (a max-length prefill for the
    default textgen bench geometry) at the top of the max_new range — the
    workload that exposes prefill stalls and KV-footprint ceilings that a
    uniformly short pool never touches."""
    rng = np.random.default_rng(seed)
    words = ("fast serve model token image chip batch fox sky ocean "
             "mountain river night day glass stone").split()
    lo, hi = max_new
    if not sd and (lo < 1 or hi < lo):
        raise ValueError(f"max_new range must satisfy 1 <= lo <= hi, "
                         f"got {max_new}")
    out = []
    for i in range(n):
        is_long = long_every > 0 and i % long_every == long_every - 1
        size = long_words if is_long else int(rng.integers(2, 8))
        prompt = " ".join(rng.choice(words, size=size))
        body: dict = {"prompt": prompt, "seed": i}
        if not sd:
            # Deterministic spread over [lo, hi]: short and long lengths
            # interleave however the pool is cycled.
            body["max_new_tokens"] = hi if is_long else int(
                lo + (i * 7919) % (hi - lo + 1))
        out.append(json.dumps(body).encode())
    return out


def synthetic_image_jpeg(edge: int = 256, seed: int = 0, quality: int = 85) -> bytes:
    """A realistic photo-like JPEG (smooth gradients compress like photos)."""
    from PIL import Image

    rng = np.random.default_rng(seed)
    y, x = np.mgrid[0:edge, 0:edge].astype(np.float32) / edge
    base = np.stack([
        0.5 + 0.5 * np.sin(6.28 * (x + rng.random())),
        0.5 + 0.5 * np.cos(6.28 * (y + rng.random())),
        0.5 + 0.5 * np.sin(6.28 * (x * y + rng.random())),
    ], axis=-1)
    noise = rng.normal(0, 0.05, base.shape)
    arr = np.clip((base + noise) * 255, 0, 255).astype(np.uint8)
    buf = io.BytesIO()
    Image.fromarray(arr).save(buf, format="JPEG", quality=quality)
    return buf.getvalue()


def _record(result: LoadResult, ok: bool, t0: float, t1: float,
            record_from: float, stop_at: float) -> None:
    """Window-clamp one completion: only [record_from, stop_at) counts."""
    if t1 < record_from:
        return  # warmup
    if t1 >= stop_at:
        result.n_late += 1
        return
    if ok:
        result.n_ok += 1
        result.latencies_ms.append((t1 - t0) * 1e3)
    else:
        result.n_err += 1


async def run_load(
    url: str,
    payload: "bytes | list[bytes]",
    content_type: str,
    duration_s: float = 10.0,
    concurrency: int = 64,
    warmup_s: float = 2.0,
    items_per_request: int = 1,
) -> LoadResult:
    """Closed loop: `concurrency` workers, one request in flight each.
    A list ``payload`` is a distinct-body pool cycled round-robin across
    the workers (miss-only cache workloads)."""
    import aiohttp

    pool = payload if isinstance(payload, (list, tuple)) else None
    result = LoadResult(mode="closed", items_per_request=items_per_request,
                        distinct_payloads=len(pool) if pool else 0)
    headers = {"Content-Type": content_type}
    now = time.perf_counter()
    record_from = now + warmup_s
    stop_at = now + warmup_s + duration_s
    cursor = 0  # shared round-robin index over the distinct-payload pool

    async def worker(session: aiohttp.ClientSession) -> None:
        nonlocal cursor
        while time.perf_counter() < stop_at:
            if pool is not None:
                data = pool[cursor % len(pool)]
                cursor += 1
            else:
                data = payload
            t0 = time.perf_counter()
            try:
                async with session.post(url, data=data, headers=headers) as resp:
                    await resp.read()
                    ok = resp.status == 200
            except Exception:
                ok = False
            _record(result, ok, t0, time.perf_counter(), record_from, stop_at)

    conn = aiohttp.TCPConnector(limit=concurrency * 2)
    async with aiohttp.ClientSession(connector=conn) as session:
        workers = [asyncio.ensure_future(worker(session)) for _ in range(concurrency)]
        await asyncio.gather(*workers)
    result.duration_s = stop_at - record_from
    return result


async def run_load_open(
    url: str,
    payload: "bytes | list[bytes]",
    content_type: str,
    rate_per_s: float,
    duration_s: float = 10.0,
    warmup_s: float = 2.0,
    max_inflight: int = 4096,
    items_per_request: int = 1,
) -> LoadResult:
    """Open loop: issue at `rate_per_s` on a fixed clock, independent of
    completions. If the server can't keep up, in-flight grows toward
    ``max_inflight``; beyond it issues are dropped and counted as errors
    (the alternative — silently pausing the clock — would turn the mode
    closed-loop and overstate the server). A list ``payload`` cycles a
    distinct-body pool as in run_load."""
    import aiohttp

    if rate_per_s <= 0:
        raise ValueError(f"rate_per_s must be positive, got {rate_per_s}")
    pool = payload if isinstance(payload, (list, tuple)) else None
    result = LoadResult(mode="open", offered_rate=rate_per_s,
                        items_per_request=items_per_request,
                        distinct_payloads=len(pool) if pool else 0)
    headers = {"Content-Type": content_type}
    interval = 1.0 / rate_per_s
    now = time.perf_counter()
    record_from = now + warmup_s
    stop_at = now + warmup_s + duration_s
    inflight = 0
    issued = 0
    tasks: set[asyncio.Task] = set()

    async def one(session: aiohttp.ClientSession, seq: int) -> None:
        nonlocal inflight
        data = pool[seq % len(pool)] if pool is not None else payload
        t0 = time.perf_counter()
        try:
            async with session.post(url, data=data, headers=headers) as resp:
                await resp.read()
                ok = resp.status == 200
        except Exception:
            ok = False
        finally:
            inflight -= 1
        _record(result, ok, t0, time.perf_counter(), record_from, stop_at)

    conn = aiohttp.TCPConnector(limit=0)  # open loop: no client-side cap
    async with aiohttp.ClientSession(connector=conn) as session:
        next_issue = now
        while next_issue < stop_at:
            delay = next_issue - time.perf_counter()
            if delay > 0:
                await asyncio.sleep(delay)
            if inflight >= max_inflight:
                if time.perf_counter() >= record_from:
                    result.n_err += 1  # shed at the client: server saturated
            else:
                inflight += 1
                t = asyncio.ensure_future(one(session, issued))
                issued += 1
                tasks.add(t)
                t.add_done_callback(tasks.discard)
            next_issue += interval
        if tasks:  # stragglers: counted as n_late by _record
            await asyncio.gather(*tasks, return_exceptions=True)
    result.duration_s = stop_at - record_from
    return result


def merge_load_summaries(parts: list[dict]) -> dict:
    """Combine per-worker load results into one summary (multi-process
    load generation, ISSUE 11 satellite).

    Each part is a worker's ``{"summary": ..., "latencies_ms": [...]}``
    dump. Counts sum; throughput sums (every worker measured its own
    aligned window); percentiles are EXACT over the concatenated latency
    samples — merging percentile-of-percentiles would lie about the tail."""
    if not parts:
        raise ValueError("no load-worker results to merge")
    lats: list[float] = []
    for p in parts:
        lats.extend(p.get("latencies_ms", []))
    summaries = [p["summary"] for p in parts]
    base = summaries[0]
    out = {
        "mode": base["mode"],
        "n_ok": sum(s["n_ok"] for s in summaries),
        "n_err": sum(s["n_err"] for s in summaries),
        "n_late": sum(s["n_late"] for s in summaries),
        "duration_s": max(s["duration_s"] for s in summaries),
        "throughput_per_s": round(
            sum(s["throughput_per_s"] for s in summaries), 1),
        "p50_ms": round(percentile(lats, 0.5), 3),
        "p90_ms": round(percentile(lats, 0.9), 3),
        "p99_ms": round(percentile(lats, 0.99), 3),
        "load_workers": len(parts),
    }
    for key in ("items_per_request", "distinct_payloads",
                "offered_rate_per_s"):
        if key in base:
            out[key] = base[key]
    return out


def _run_loadgen_multiproc(args, procs: int) -> int:
    """Fan the load out over ``procs`` worker processes and merge.

    One asyncio client process tops out around one core of HTTP work —
    against an 8-chip server THAT becomes the bottleneck and the bench
    under-reports the server (ISSUE 11 satellite). Workers split the
    connection count (and open-loop rate) evenly, take DISJOINT synthetic
    seed ranges (coalescing two workers' identical bodies would share
    batch slots), and dump raw latencies for an exact merged summary."""
    import os
    import subprocess
    import sys
    import tempfile

    batch = int(getattr(args, "batch", 0) or 0)
    distinct = int(getattr(args, "distinct", 0) or 0)
    seed_base = int(getattr(args, "seed_base", 0) or 0)
    rate = getattr(args, "rate", None)
    conc = max(1, args.concurrency)
    tmpdir = tempfile.mkdtemp(prefix="tpuserve-loadgen-")
    workers = []
    dumps = []
    for i in range(procs):
        c_i = conc // procs + (1 if i < conc % procs else 0)
        if c_i <= 0:
            continue
        dump = os.path.join(tmpdir, f"worker{i}.json")
        dumps.append(dump)
        argv = [
            sys.executable, "-m", "tpuserve", "bench",
            "--url", args.url, "--model", args.model, "--verb", args.verb,
            "--duration", str(args.duration),
            "--warmup", str(getattr(args, "warmup", 2.0)),
            "--concurrency", str(c_i),
            "--content-type", args.content_type,
            "--synthetic", getattr(args, "synthetic", "npy"),
            "--edge", str(getattr(args, "edge", 256)),
            "--wire", getattr(args, "wire", "npy"),
            "--frame-kind", getattr(args, "frame_kind", "yuv420"),
            "--max-new", str(getattr(args, "max_new", "2,32")),
            "--procs", "1",
            "--seed-base", str(seed_base + i * max(1, distinct)),
            "--dump-latencies", dump,
        ]
        if batch:
            argv += ["--batch", str(batch)]
        if distinct:
            argv += ["--distinct", str(distinct)]
        if getattr(args, "payload", None):
            argv += ["--payload", args.payload]
        if rate:
            argv += ["--rate", str(rate / procs)]
        workers.append(subprocess.Popen(argv, stdout=subprocess.DEVNULL))
    rcs = [w.wait() for w in workers]
    parts = []
    for dump in dumps:
        try:
            with open(dump, encoding="utf-8") as f:
                parts.append(json.load(f))
        except OSError:
            pass  # a crashed worker: its rc already marks the failure
    if not parts:
        print(json.dumps({"error": "every load worker failed",
                          "worker_rcs": rcs}))
        return 1
    merged = merge_load_summaries(parts)
    print(json.dumps(merged))
    return 0 if merged["n_ok"] > 0 and all(rc == 0 for rc in rcs) else 1


def run_loadgen_cli(args) -> int:
    procs = int(getattr(args, "procs", 1) or 1)
    if procs > 1:
        return _run_loadgen_multiproc(args, procs)
    batch = int(getattr(args, "batch", 0) or 0)
    distinct = int(getattr(args, "distinct", 0) or 0)
    seed_base = int(getattr(args, "seed_base", 0) or 0)
    synth = getattr(args, "synthetic", "npy")
    wire = getattr(args, "wire", "npy")
    content_type = args.content_type
    if wire == "frame":
        # Framed-wire client batches (ISSUE 11): each POST is one
        # multi-item application/x-tpuserve-frame body of --batch items
        # (throughput counts items); --distinct cycles a disjoint-seed
        # pool of framed bodies for miss-only workloads.
        from tpuserve import frame

        kind = getattr(args, "frame_kind", "yuv420")
        edge = int(getattr(args, "edge", 256))
        n_items = max(1, batch)
        content_type = frame.CONTENT_TYPE
        if distinct > 1:
            payload = synthetic_frame_pool(distinct, edge, n_items, kind,
                                           seed_base=seed_base)
        else:
            payload = synthetic_frame(edge, n_items, kind, seed=seed_base)
        batch = n_items
    elif distinct > 1 and synth in ("prompt", "sd-prompt"):
        # Generative workload: distinct (prompt, seed) bodies, mixed
        # max_new_tokens for textgen (the engine's early-exit/fold-in
        # counters only move when output lengths mix).
        lo, hi = (int(x) for x in
                  str(getattr(args, "max_new", "2,32")).split(","))
        payload = synthetic_prompt_pool(
            distinct, (lo, hi), sd=synth == "sd-prompt",
            long_every=int(getattr(args, "long_every", 0) or 0),
            long_words=int(getattr(args, "long_words", 16) or 16))
    elif distinct > 1:
        # Miss-only workload: a pool of distinct synthetic bodies, cycled
        # round-robin (a pool larger than the server's cache capacity makes
        # every lookup an LRU miss).
        payload = synthetic_pool(synth, distinct,
                                 int(getattr(args, "edge", 256)), batch,
                                 seed_base=seed_base)
    elif args.payload:
        with open(args.payload, "rb") as f:
            payload = f.read()
    elif batch > 1:
        payload = synthetic_image_npy_batch(n=batch)
    else:
        payload = synthetic_image_npy()
    items = max(1, batch)
    url = f"{args.url}/v1/models/{args.model}:{args.verb}"
    warmup = getattr(args, "warmup", 2.0)
    rate = getattr(args, "rate", None)
    if getattr(args, "stream", False):
        # Streaming closed loop (ISSUE 17): one stream in flight per
        # worker; --rate/--procs don't apply (event timestamps, not
        # request completions, are the measurement).
        result = asyncio.run(run_stream_load(
            url, payload, content_type, args.duration, args.concurrency,
            warmup))
        print(json.dumps(result.summary()))
        return 0 if result.n_ok > 0 else 1
    if rate:
        result = asyncio.run(run_load_open(
            url, payload, content_type, rate, args.duration, warmup,
            items_per_request=items))
    else:
        result = asyncio.run(run_load(
            url, payload, content_type, args.duration, args.concurrency,
            warmup, items_per_request=items))
    dump = getattr(args, "dump_latencies", None)
    if dump:
        # Raw samples for the multi-process merge (exact percentiles).
        with open(dump, "w", encoding="utf-8") as f:
            json.dump({"summary": result.summary(),
                       "latencies_ms": result.latencies_ms}, f)
    print(json.dumps(result.summary()))
    return 0 if result.n_ok > 0 else 1
