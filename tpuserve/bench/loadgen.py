"""Asyncio HTTP load generator (SURVEY.md §2 C11).

Two modes (VERDICT.md r1 item 3):

- **Closed loop** (``run_load``): C workers each keep exactly one request in
  flight. Measures peak sustainable throughput; its p50 is queueing delay by
  Little's law, NOT server latency.
- **Open loop** (``run_load_open``): requests are issued on a fixed-rate
  clock regardless of completions, like independent clients. Latency
  percentiles at a stated offered rate are the honest latency metric.

Both record only requests that *complete inside* the measurement window and
divide by the actual window, so stragglers can't inflate throughput.
"""

from __future__ import annotations

import asyncio
import io
import json
import time
from dataclasses import dataclass, field

import numpy as np

from tpuserve.obs import percentile


@dataclass
class LoadResult:
    n_ok: int = 0
    n_err: int = 0
    duration_s: float = 0.0
    latencies_ms: list[float] = field(default_factory=list)

    @property
    def throughput(self) -> float:
        return self.n_ok / self.duration_s if self.duration_s > 0 else 0.0

    def summary(self) -> dict:
        return {
            "n_ok": self.n_ok,
            "n_err": self.n_err,
            "duration_s": round(self.duration_s, 3),
            "throughput_per_s": round(self.throughput, 1),
            "p50_ms": round(percentile(self.latencies_ms, 0.5), 3),
            "p99_ms": round(percentile(self.latencies_ms, 0.99), 3),
        }


def synthetic_image_npy(edge: int = 256, seed: int = 0) -> bytes:
    rng = np.random.default_rng(seed)
    arr = rng.integers(0, 255, (edge, edge, 3), dtype=np.uint8)
    buf = io.BytesIO()
    np.save(buf, arr)
    return buf.getvalue()


def synthetic_image_jpeg(edge: int = 256, seed: int = 0, quality: int = 85) -> bytes:
    """A realistic photo-like JPEG (smooth gradients compress like photos)."""
    from PIL import Image

    rng = np.random.default_rng(seed)
    y, x = np.mgrid[0:edge, 0:edge].astype(np.float32) / edge
    base = np.stack([
        0.5 + 0.5 * np.sin(6.28 * (x + rng.random())),
        0.5 + 0.5 * np.cos(6.28 * (y + rng.random())),
        0.5 + 0.5 * np.sin(6.28 * (x * y + rng.random())),
    ], axis=-1)
    noise = rng.normal(0, 0.05, base.shape)
    arr = np.clip((base + noise) * 255, 0, 255).astype(np.uint8)
    buf = io.BytesIO()
    Image.fromarray(arr).save(buf, format="JPEG", quality=quality)
    return buf.getvalue()


async def run_load(
    url: str,
    payload: bytes,
    content_type: str,
    duration_s: float = 10.0,
    concurrency: int = 64,
    warmup_s: float = 2.0,
) -> LoadResult:
    import aiohttp

    result = LoadResult()
    stop_at = 0.0
    record_from = 0.0

    async def worker(session: aiohttp.ClientSession) -> None:
        while time.perf_counter() < stop_at:
            t0 = time.perf_counter()
            try:
                async with session.post(
                    url, data=payload, headers={"Content-Type": content_type}
                ) as resp:
                    await resp.read()
                    ok = resp.status == 200
            except Exception:
                ok = False
            t1 = time.perf_counter()
            if t1 < record_from:
                continue
            if ok:
                result.n_ok += 1
                result.latencies_ms.append((t1 - t0) * 1e3)
            else:
                result.n_err += 1

    conn = aiohttp.TCPConnector(limit=concurrency * 2)
    async with aiohttp.ClientSession(connector=conn) as session:
        now = time.perf_counter()
        record_from = now + warmup_s
        stop_at = now + warmup_s + duration_s
        workers = [asyncio.ensure_future(worker(session)) for _ in range(concurrency)]
        await asyncio.gather(*workers)
    result.duration_s = duration_s
    return result


def run_loadgen_cli(args) -> int:
    if args.payload:
        with open(args.payload, "rb") as f:
            payload = f.read()
    else:
        payload = synthetic_image_npy()
    url = f"{args.url}/v1/models/{args.model}:{args.verb}"
    result = asyncio.run(
        run_load(url, payload, args.content_type, args.duration, args.concurrency,
                 warmup_s=getattr(args, "warmup", 2.0))
    )
    print(json.dumps(result.summary()))
    return 0 if result.n_ok > 0 else 1
