"""Link-physics probes shared by bench.py and scripts/baseline_link_physics.py
(BASELINE.md "Link physics").

The dev tunnel's H2D behavior is process-stateful and its timing semantics
are subtle (block_until_ready returns early; only a dependent read reveals
the sustained rate), so every probe runs in a fresh subprocess from ONE
source of truth here — the MiB-vs-MB unit bug of r3 had to be fixed in two
copies of this code; never again.
"""

from __future__ import annotations

import json
import subprocess
import sys
import textwrap

H2D_PROBE_SRC = textwrap.dedent("""
    import time, json, numpy as np, jax, jax.numpy as jnp
    mode = %(mode)r
    CHUNK = %(chunk)d  # every transfer is this shape: compiles warm once
    chunk = np.random.default_rng(0).integers(0, 255, (CHUNK,), np.uint8)

    # Untimed warm-up in EVERY mode: PJRT client init, first-transfer setup,
    # and the dependent read's slice+sum compile (shape-specialized — warming
    # it here keeps XLA compile time out of every measured window).
    warm = jax.device_put(np.zeros((CHUNK,), np.uint8))
    jax.block_until_ready(warm)
    int(jnp.sum(warm[:8].astype(jnp.int32)))

    def timed(k):
        t0 = time.perf_counter()
        devs = [jax.device_put(chunk) for _ in range(k)]
        jax.block_until_ready(devs)
        int(jnp.sum(devs[-1][:8].astype(jnp.int32)))  # dependent read: truth
        return time.perf_counter() - t0

    # Sizing pass (one chunk), then ONE measurement of k chunks sized to
    # ~6 s at the estimated rate. Bounds probe wall time on slow hours (a
    # fixed 80 MiB probe took 40+ s at 2 MB/s) while fast links still
    # measure a large transfer for accuracy.
    t1 = timed(1)
    k = max(1, min(9, round(CHUNK / max(t1, 1e-3) * 6.0 / CHUNK)))
    if mode == "after_d2h":
        np.asarray(warm)       # one full-chunk D2H right before the window
    t2 = timed(k)
    # probe_bytes: total link bytes, including the untimed warm-up chunk
    # (warm-up + sizing + measurement = k+2 chunks; ADVICE r3).
    print(json.dumps({"mbps": k * CHUNK / t2 / 1e6,
                      "probe_bytes": (k + 2) * CHUNK}))
""")


def probe_device_count(timeout: float = 300.0, cwd: str | None = None) -> int:
    """Visible accelerator count, measured in a fresh subprocess.

    The bench needs the chip count BEFORE it shapes load (connection count,
    offered rate scale with it — a v5e-8 driven with a single-chip load
    profile is demand-starved and under-reports by design), but touching
    ``jax.devices()`` in the calling process would take the accelerator
    before the link/chip probes run in their own virgin subprocesses. Same
    fresh-subprocess discipline as every probe here; returns 1 on failure
    (the single-chip shape is the safe under-estimate)."""
    src = ("import json, jax; "
           "print(json.dumps({'n': len(jax.devices())}))")
    try:
        proc = subprocess.run([sys.executable, "-c", src],
                              capture_output=True, text=True,
                              timeout=timeout, cwd=cwd)
        if proc.returncode != 0:
            return 1
        return max(1, int(json.loads(
            proc.stdout.strip().splitlines()[-1])["n"]))
    except Exception:  # noqa: BLE001 — probes must never kill the bench
        return 1


def measure_h2d_mbps(mode: str = "virgin", timeout: float = 600.0,
                     cwd: str | None = None,
                     chunk_bytes: int = 8 << 20) -> dict:
    """Run the H2D probe in a fresh subprocess; mode 'virgin' | 'after_d2h'.

    ``chunk_bytes`` sizes every probe transfer. The default (8 MiB) measures
    the link's best-case streaming rate; pass the serving path's actual
    per-batch transfer size (batch x wire bytes/img) to measure the rate the
    server can really draw — per-transfer latency makes the two differ on
    high-latency links, which is exactly the inconsistency that produced a
    162-percent-of-ceiling bench reading (ISSUE 5 satellite: the ceiling must be
    computed from a rate measured at the serving transfer size).

    Returns {"mbps": float, "probe_bytes": int} or {"error": str}.
    """
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             H2D_PROBE_SRC % {"mode": mode, "chunk": max(1, int(chunk_bytes))}],
            capture_output=True, text=True, timeout=timeout, cwd=cwd,
        )
    except subprocess.TimeoutExpired:
        return {"error": f"probe timed out after {timeout}s"}
    if proc.returncode != 0:
        return {"error": proc.stderr.strip()[-300:]}
    try:
        return json.loads(proc.stdout.strip().splitlines()[-1])
    except Exception as e:  # noqa: BLE001
        return {"error": f"unparseable probe output: {e}"}


# Device-resident serving-forward rate: a dependency-chained fori_loop of N
# full forwards (wire inputs -> on-device preproc -> model -> on-device
# postproc), inputs already on device, one scalar read at the end.
# block_until_ready returns early on the tunneled dev TPU and a per-batch
# readback adds ~190 ms relay RTT, so the chained loop is the only honest
# timing method here. Shared by bench.py (fresh per-run "chip_compute" field —
# VERDICT r3 weak 2 banned the stale hardcoded constant),
# scripts/baseline_link_physics.py, and scripts/bench_configs.py (the
# per-family MFU table, VERDICT r4 missing 1).
#
# Inputs come from the family's own input_signature (token ids for BERT,
# YUV/RGB wire planes for vision, prompt ids + seeds for SD) — the r4 probe
# hard-coded an image tensor and crashed for any non-vision family.
# FLOPs come from XLA's own HloCostAnalysis on the compiled forward; for
# sd15 the denoise fori_loop body is counted once by XLA (verified on this
# jax), so the probe adds the remaining (steps - 1) UNet calls explicitly.
CHIP_PROBE_SRC = textwrap.dedent("""
    import time, json, sys, numpy as np, jax, jax.numpy as jnp
    sys.path.insert(0, %(repo)r)
    cache = %(cache)r
    if cache:
        # Share the serving process's persistent XLA cache: per-bucket
        # roofline probes then cost one compile EVER, not one per bench run.
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    from tpuserve.config import ModelConfig
    from tpuserve.models import build
    mcfg = dict(%(mcfg)r)
    bucket = tuple(%(bucket)r)
    N = %(iters)d
    cfg = ModelConfig(**{"name": "m", "dtype": "bfloat16",
                         "batch_buckets": [bucket[0]],
                         "parallelism": "single", **mcfg})
    m = build(cfg)
    if cfg.quantize:
        # Quantized probes go through the runtime's forward (quantize_tree
        # + the mode's dequant layer) — exactly what serving compiles.
        from tpuserve.runtime import ModelRuntime
        rt = ModelRuntime(m)
        rt.load_and_shard_params()
        params = rt.params_per_mesh[0]
        fwd = rt._forward_fn()
    else:
        params = m.init_params(jax.random.key(0))
        fwd = m.forward

    rng = np.random.default_rng(0)
    def rand_for(l):
        dt = np.dtype(l.dtype)
        if np.issubdtype(dt, np.unsignedinteger):   # image wire planes
            return rng.integers(0, 255, l.shape, dt)
        if np.issubdtype(dt, np.integer):           # token ids / masks / seeds
            return np.ones(l.shape, dt)             # valid for any vocab/mask
        return rng.standard_normal(l.shape).astype(dt)

    x = jax.tree_util.tree_map(rand_for, m.input_signature(bucket))

    @jax.jit
    def many(params, x):
        def body(i, carry):
            x, acc = carry
            out = fwd(params, x)
            s = jax.tree_util.tree_leaves(out)[0].reshape(-1)[0]
            s = s.astype(jnp.float32)
            leaves, treedef = jax.tree_util.tree_flatten(x)
            leaves[0] = leaves[0] + (s * 0).astype(leaves[0].dtype)  # dep chain
            return (jax.tree_util.tree_unflatten(treedef, leaves), acc + s)
        _, acc = jax.lax.fori_loop(0, N, body, (x, jnp.float32(0)))
        return acc

    def flops_from(compiled):
        try:
            ca = compiled.cost_analysis()
            ca = ca[0] if isinstance(ca, list) else ca
            return float(ca.get("flops", 0.0)) if ca else 0.0
        except Exception:
            return 0.0

    x = jax.device_put(x)
    many_c = many.lower(params, x).compile()  # the ONE compile
    # HloCostAnalysis visits a while body once (verified on this jax), so
    # the N-iteration loop's count ~= ONE forward's flops; no second
    # standalone compile of the forward is needed (for sd15 that compile
    # is the whole 20-step denoise — minutes of wall time saved).
    flops = flops_from(many_c)
    if cfg.family == "sd15" and flops:
        if cfg.quantize:
            # m.unet.apply cannot consume quantized {"q8","q8_scale"}
            # leaves; report no FLOPs rather than a silently ~steps-x
            # understated MFU.
            flops = 0.0
        else:
            b2 = 2 * bucket[0]  # CFG runs cond + uncond lanes per step
            lat2 = jnp.zeros((b2, m.latent, m.latent, 4), jnp.float32)
            t2 = jnp.zeros((b2,), jnp.int32)
            ctx2 = jnp.zeros((b2, 77, m.text_encoder.d_model), m.dtype)
            step_c = (jax.jit(m.unet.apply)
                      .lower(params["unet"], lat2, t2, ctx2).compile())
            flops += (m.steps - 1) * flops_from(step_c)

    float(many_c(params, x))  # warm (H2D + first dispatch)
    t0 = time.perf_counter()
    float(many_c(params, x))
    dur = time.perf_counter() - t0
    batch = bucket[0]
    tflops_s = flops * N / dur / 1e12 if flops else None
    print(json.dumps({
        "img_s": round(batch * N / dur, 1),
        "ms_per_batch": round(dur / N * 1e3, 3),
        "batch": batch, "bucket": list(bucket),
        "gflops_per_item": round(flops / batch / 1e9, 2) if flops else None,
        "achieved_tflops_s": round(tflops_s, 2) if tflops_s else None,
        "device": jax.devices()[0].device_kind,
    }))
""")

def chained_rate_ms(f, inputs, iters: int) -> float:
    """ms per call of ``f(*inputs)`` via a dependency-chained fori loop —
    the in-process twin of CHIP_PROBE_SRC's timing core (that template must
    stay self-contained for its fresh-subprocess discipline; any timing-
    method fix must land in BOTH — this module's one-source-of-truth rule).
    Used by scripts/bench_sd_profile.py for component-level splits where
    one process times several functions against shared params."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def many(inputs):
        def body(i, carry):
            inp, acc = carry
            out = f(*inp)
            s = jax.tree_util.tree_leaves(out)[0].reshape(-1)[0]
            s = s.astype(jnp.float32)
            leaves, td = jax.tree_util.tree_flatten(inp)
            leaves[-1] = leaves[-1] + (s * 0).astype(leaves[-1].dtype)
            return (jax.tree_util.tree_unflatten(td, leaves), acc + s)

        _, acc = jax.lax.fori_loop(0, iters, body, (inputs, jnp.float32(0)))
        return acc

    import time as _time

    c = many.lower(inputs).compile()
    float(c(inputs))  # warm
    t0 = _time.perf_counter()
    float(c(inputs))
    return (_time.perf_counter() - t0) / iters * 1e3


# Per-family probe presets: serving-shaped bucket + model options. `family`
# maps a preset name to the registry family when they differ (bert-moe).
CHIP_PROBE_FAMILIES: dict[str, dict] = {
    "resnet50": dict(mcfg={"family": "resnet50"}, bucket=(256,), iters=32),
    "mobilenetv3": dict(mcfg={"family": "mobilenetv3"}, bucket=(256,), iters=32),
    "bert": dict(mcfg={"family": "bert", "seq_buckets": [128]},
                 bucket=(32, 128), iters=64),
    "bert-moe": dict(mcfg={"family": "bert", "seq_buckets": [128],
                           "options": {"moe_experts": 8}},
                     bucket=(32, 128), iters=64),
    "efficientdet": dict(mcfg={"family": "efficientdet", "image_size": 512,
                               "wire_size": 512},
                         bucket=(8,), iters=16),
    "sd15": dict(mcfg={"family": "sd15", "image_size": 512,
                       "options": {"steps": 20}},
                 bucket=(1,), iters=2),
}

# v5e (TPU v5 lite) bf16 peak per chip; the MFU denominator for the chip
# table in BASELINE.md. Other device kinds report achieved TF/s with no MFU.
PEAK_TFLOPS_S = {"TPU v5 lite": 197.0, "TPU v5e": 197.0}


def measure_chip_img_s(batch: int | None = None, family: str = "resnet50",
                       iters: int | None = None, timeout: float = 1800.0,
                       repo: str | None = None,
                       bucket: tuple | None = None,
                       mcfg_extra: dict | None = None,
                       cache_dir: str | None = None) -> dict:
    """Device-resident serving-forward rate + FLOP count, fresh subprocess.

    `family` must be a CHIP_PROBE_FAMILIES preset (the r4 foot-gun of
    accepting any family then crashing on image-only inputs is now a clear
    error up front). `batch`/`bucket`/`iters` override the preset;
    `mcfg_extra` shallow-merges over the preset's ModelConfig kwargs (e.g.
    {"seq_buckets": [512], "options": {"attention": "flash"}} for the
    flash-vs-dense sweep). `cache_dir` points the subprocess at a
    persistent XLA compilation cache (bench.py passes the server's own, so
    per-bucket roofline probes compile once ever, not once per run).

    Returns {"img_s", "ms_per_batch", "batch", "bucket", "gflops_per_item",
    "achieved_tflops_s", "mfu_pct"?, "device"} or {"error": str}.
    """
    import os

    if family not in CHIP_PROBE_FAMILIES:
        return {"error": f"no chip-probe preset for family {family!r}; "
                         f"known: {sorted(CHIP_PROBE_FAMILIES)}"}
    preset = CHIP_PROBE_FAMILIES[family]
    bkt = tuple(bucket) if bucket else preset["bucket"]
    if batch is not None:
        bkt = (batch,) + bkt[1:]
    mcfg = {**preset["mcfg"], **(mcfg_extra or {})}
    repo = repo or os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    src = CHIP_PROBE_SRC % {"repo": repo, "mcfg": mcfg,
                            "bucket": bkt,
                            "iters": iters or preset["iters"],
                            "cache": cache_dir or ""}
    try:
        proc = subprocess.run([sys.executable, "-c", src], capture_output=True,
                              text=True, timeout=timeout, cwd=repo)
    except subprocess.TimeoutExpired:
        return {"error": f"chip probe timed out after {timeout}s"}
    if proc.returncode != 0:
        return {"error": proc.stderr.strip()[-300:]}
    try:
        res = json.loads(proc.stdout.strip().splitlines()[-1])
    except Exception as e:  # noqa: BLE001
        return {"error": f"unparseable probe output: {e}"}
    peak = PEAK_TFLOPS_S.get(res.get("device", ""))
    if peak and res.get("achieved_tflops_s"):
        res["mfu_pct"] = round(100.0 * res["achieved_tflops_s"] / peak, 1)
    return res
