"""Link-physics probes shared by bench.py and scripts/baseline_link_physics.py
(BASELINE.md "Link physics").

The dev tunnel's H2D behavior is process-stateful and its timing semantics
are subtle (block_until_ready returns early; only a dependent read reveals
the sustained rate), so every probe runs in a fresh subprocess from ONE
source of truth here — the MiB-vs-MB unit bug of r3 had to be fixed in two
copies of this code; never again.
"""

from __future__ import annotations

import json
import subprocess
import sys
import textwrap

H2D_PROBE_SRC = textwrap.dedent("""
    import time, json, numpy as np, jax, jax.numpy as jnp
    mode = %r
    CHUNK = 8 << 20  # every transfer is this shape: compiles warm once
    chunk = np.random.default_rng(0).integers(0, 255, (CHUNK,), np.uint8)

    # Untimed warm-up in EVERY mode: PJRT client init, first-transfer setup,
    # and the dependent read's slice+sum compile (shape-specialized — warming
    # it here keeps XLA compile time out of every measured window).
    warm = jax.device_put(np.zeros((CHUNK,), np.uint8))
    jax.block_until_ready(warm)
    int(jnp.sum(warm[:8].astype(jnp.int32)))

    def timed(k):
        t0 = time.perf_counter()
        devs = [jax.device_put(chunk) for _ in range(k)]
        jax.block_until_ready(devs)
        int(jnp.sum(devs[-1][:8].astype(jnp.int32)))  # dependent read: truth
        return time.perf_counter() - t0

    # Sizing pass (one chunk), then ONE measurement of k chunks sized to
    # ~6 s at the estimated rate. Bounds probe wall time on slow hours (a
    # fixed 80 MiB probe took 40+ s at 2 MB/s) while fast links still
    # measure a large transfer for accuracy.
    t1 = timed(1)
    k = max(1, min(9, round(CHUNK / max(t1, 1e-3) * 6.0 / CHUNK)))
    if mode == "after_d2h":
        np.asarray(warm)       # one full-chunk D2H right before the window
    t2 = timed(k)
    # probe_bytes: total link bytes, including the untimed warm-up chunk
    # (warm-up + sizing + measurement = k+2 chunks; ADVICE r3).
    print(json.dumps({"mbps": k * CHUNK / t2 / 1e6,
                      "probe_bytes": (k + 2) * CHUNK}))
""")


def measure_h2d_mbps(mode: str = "virgin", timeout: float = 600.0,
                     cwd: str | None = None) -> dict:
    """Run the H2D probe in a fresh subprocess; mode 'virgin' | 'after_d2h'.

    Returns {"mbps": float, "probe_bytes": int} or {"error": str}.
    """
    try:
        proc = subprocess.run(
            [sys.executable, "-c", H2D_PROBE_SRC % mode],
            capture_output=True, text=True, timeout=timeout, cwd=cwd,
        )
    except subprocess.TimeoutExpired:
        return {"error": f"probe timed out after {timeout}s"}
    if proc.returncode != 0:
        return {"error": proc.stderr.strip()[-300:]}
    try:
        return json.loads(proc.stdout.strip().splitlines()[-1])
    except Exception as e:  # noqa: BLE001
        return {"error": f"unparseable probe output: {e}"}


# Device-resident serving-forward rate: a dependency-chained fori_loop of N
# full forwards (uint8 wire -> on-device resize -> model -> top-k), inputs
# already on device, one scalar read at the end. block_until_ready returns
# early on the tunneled dev TPU and a per-batch readback adds ~190 ms relay
# RTT, so the chained loop is the only honest timing method here. Shared by
# bench.py (fresh per-run "chip_compute" field — VERDICT r3 weak 2 banned the
# stale hardcoded constant) and scripts/baseline_link_physics.py.
CHIP_PROBE_SRC = textwrap.dedent("""
    import time, json, sys, numpy as np, jax, jax.numpy as jnp
    sys.path.insert(0, %(repo)r)
    from tpuserve.config import ModelConfig
    from tpuserve.models import build
    batch = %(batch)d
    cfg = ModelConfig(name="m", family=%(family)r, dtype="bfloat16",
                      batch_buckets=[batch])
    m = build(cfg)
    params = m.init_params(jax.random.key(0))
    N = %(iters)d

    @jax.jit
    def many(params, x):
        def body(i, carry):
            x, acc = carry
            out = m.forward(params, x)
            s = out["probs"][0, 0].astype(jnp.float32)
            x = x + (s * 0).astype(x.dtype)   # forced inter-iteration dep
            return (x, acc + s)
        _, acc = jax.lax.fori_loop(0, N, body, (x, jnp.float32(0)))
        return acc

    x = jax.device_put(np.random.default_rng(0).integers(
        0, 255, (batch, 256, 256, 3), np.uint8))
    float(many(params, x))  # compile + warm
    t0 = time.perf_counter()
    float(many(params, x))
    dur = time.perf_counter() - t0
    print(json.dumps({"img_s": round(batch * N / dur, 1),
                      "ms_per_batch": round(dur / N * 1e3, 3),
                      "batch": batch}))
""")


def measure_chip_img_s(batch: int = 256, family: str = "resnet50",
                       iters: int = 32, timeout: float = 900.0,
                       repo: str | None = None) -> dict:
    """Device-resident serving-forward rate in a fresh subprocess.

    Returns {"img_s": float, "ms_per_batch": float, "batch": int} or
    {"error": str}.
    """
    import os

    repo = repo or os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    src = CHIP_PROBE_SRC % {"repo": repo, "batch": batch, "family": family,
                            "iters": iters}
    try:
        proc = subprocess.run([sys.executable, "-c", src], capture_output=True,
                              text=True, timeout=timeout, cwd=repo)
    except subprocess.TimeoutExpired:
        return {"error": f"chip probe timed out after {timeout}s"}
    if proc.returncode != 0:
        return {"error": proc.stderr.strip()[-300:]}
    try:
        return json.loads(proc.stdout.strip().splitlines()[-1])
    except Exception as e:  # noqa: BLE001
        return {"error": f"unparseable probe output: {e}"}
