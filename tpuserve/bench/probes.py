"""Link-physics probes shared by bench.py and scripts/baseline_link_physics.py
(BASELINE.md "Link physics").

The dev tunnel's H2D behavior is process-stateful and its timing semantics
are subtle (block_until_ready returns early; only a dependent read reveals
the sustained rate), so every probe runs in a fresh subprocess from ONE
source of truth here — the MiB-vs-MB unit bug of r3 had to be fixed in two
copies of this code; never again.
"""

from __future__ import annotations

import json
import subprocess
import sys
import textwrap

H2D_PROBE_SRC = textwrap.dedent("""
    import time, json, numpy as np, jax, jax.numpy as jnp
    mode = %r
    CHUNK = 8 << 20  # every transfer is this shape: compiles warm once
    chunk = np.random.default_rng(0).integers(0, 255, (CHUNK,), np.uint8)

    # Untimed warm-up in EVERY mode: PJRT client init, first-transfer setup,
    # and the dependent read's slice+sum compile (shape-specialized — warming
    # it here keeps XLA compile time out of every measured window).
    warm = jax.device_put(np.zeros((CHUNK,), np.uint8))
    jax.block_until_ready(warm)
    int(jnp.sum(warm[:8].astype(jnp.int32)))

    def timed(k):
        t0 = time.perf_counter()
        devs = [jax.device_put(chunk) for _ in range(k)]
        jax.block_until_ready(devs)
        int(jnp.sum(devs[-1][:8].astype(jnp.int32)))  # dependent read: truth
        return time.perf_counter() - t0

    # Sizing pass (one chunk), then ONE measurement of k chunks sized to
    # ~6 s at the estimated rate. Bounds probe wall time on slow hours (a
    # fixed 80 MiB probe took 40+ s at 2 MB/s) while fast links still
    # measure a large transfer for accuracy.
    t1 = timed(1)
    k = max(1, min(9, round(CHUNK / max(t1, 1e-3) * 6.0 / CHUNK)))
    if mode == "after_d2h":
        np.asarray(warm)       # one full-chunk D2H right before the window
    t2 = timed(k)
    print(json.dumps({"mbps": k * CHUNK / t2 / 1e6,
                      "probe_bytes": (k + 1) * CHUNK}))
""")


def measure_h2d_mbps(mode: str = "virgin", timeout: float = 600.0,
                     cwd: str | None = None) -> dict:
    """Run the H2D probe in a fresh subprocess; mode 'virgin' | 'after_d2h'.

    Returns {"mbps": float, "probe_bytes": int} or {"error": str}.
    """
    proc = subprocess.run(
        [sys.executable, "-c", H2D_PROBE_SRC % mode],
        capture_output=True, text=True, timeout=timeout, cwd=cwd,
    )
    if proc.returncode != 0:
        return {"error": proc.stderr.strip()[-300:]}
    try:
        return json.loads(proc.stdout.strip().splitlines()[-1])
    except Exception as e:  # noqa: BLE001
        return {"error": f"unparseable probe output: {e}"}
