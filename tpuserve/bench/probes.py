"""Link-physics probes shared by bench.py and scripts/baseline_link_physics.py
(BASELINE.md "Link physics").

The dev tunnel's H2D behavior is process-stateful and its timing semantics
are subtle (block_until_ready returns early; only a dependent read reveals
the sustained rate), so every probe runs in a fresh subprocess from ONE
source of truth here — the MiB-vs-MB unit bug of r3 had to be fixed in two
copies of this code; never again.
"""

from __future__ import annotations

import json
import subprocess
import sys
import textwrap

H2D_PROBE_SRC = textwrap.dedent("""
    import time, json, numpy as np, jax, jax.numpy as jnp
    mode = %r
    mb, iters = 16, 5
    arr = np.random.default_rng(0).integers(0, 255, (mb << 20,), np.uint8)

    # Untimed warm-up in EVERY mode: PJRT client init + first-transfer setup
    # cost seconds on the tunnel and must not land inside one mode's window.
    warm = jax.device_put(np.zeros((1024,), np.uint8))
    jax.block_until_ready(warm)

    def h2d_rate():
        t0 = time.perf_counter()
        devs = [jax.device_put(arr) for _ in range(iters)]
        jax.block_until_ready(devs)
        int(jnp.sum(devs[-1][:8].astype(jnp.int32)))  # dependent read: truth
        return (mb << 20) * iters / (time.perf_counter() - t0) / 1e6  # MB/s

    if mode == "after_d2h":
        d = jax.device_put(arr)
        np.asarray(d)          # one full D2H readback first
    print(json.dumps({"mbps": h2d_rate()}))
""")


def measure_h2d_mbps(mode: str = "virgin", timeout: float = 600.0,
                     cwd: str | None = None) -> dict:
    """Run the H2D probe in a fresh subprocess; mode 'virgin' | 'after_d2h'.

    Returns {"mbps": float} or {"error": str}.
    """
    proc = subprocess.run(
        [sys.executable, "-c", H2D_PROBE_SRC % mode],
        capture_output=True, text=True, timeout=timeout, cwd=cwd,
    )
    if proc.returncode != 0:
        return {"error": proc.stderr.strip()[-300:]}
    try:
        return json.loads(proc.stdout.strip().splitlines()[-1])
    except Exception as e:  # noqa: BLE001
        return {"error": f"unparseable probe output: {e}"}
