"""Roofline attribution + bench-variance helpers (ISSUE 6).

BENCH_r05 reported a serving "compute" phase of p50 465.6 ms/batch against a
raw compiled call of ~24.1 ms on the same backend — a ~19x gap that stayed a
mystery number for five PRs because nothing decomposed it. This module turns
that gap into named, graphed quantities:

- ``build_roofline`` assembles the bench JSON's ``roofline`` block from the
  phase histograms, the per-bucket raw-executable probes
  (``ModelRuntime.probe_raw_ms`` in-process; ``probes.measure_chip_img_s``
  in a fresh subprocess for the bench), and the measured link rate: per
  bucket the raw device ms and wire ms, per phase the observed p50 against
  its physical ceiling (``pct_of_ceiling``), the compute split into
  device-time vs host-wait, and the binding phase — so every future PR sees
  exactly which phase is the constraint before optimizing the wrong one.
- ``best_window`` / ``spread_pct`` / ``cv_pct`` implement the bench's
  variance discipline: r05's three measured passes spread 480/658/606
  (29%), so the headline was a coin flip. The bench now extends measured
  passes (capped) until the best *consecutive* window of three agrees
  within 15%, reports the window and its CV, and takes the headline median
  from that window only.

Pure functions over plain dicts/lists — no jax, no server imports — so the
units test on a bare interpreter and both bench.py and server /stats share
one definition of every roofline number.
"""

from __future__ import annotations

import math

# Phases with a measurable physical ceiling, and what prices it:
# body_read against the measured link rate at the actual request-body size
# (the ingest wire — with the framed format, bytes-per-item is
# frame.item_nbytes plus the amortized header/table), h2d against the
# measured link rate at the serving transfer size, compute against the
# bucket's raw-executable probe. parse/queue/preproc/postproc are
# host-side bookkeeping with no hardware floor — reported, not ratioed.
ROOFLINE_CEILINGS = {"body_read": "wire", "h2d": "wire", "compute": "device"}

# Attribution order (ingest phases first — ISSUE 11): body_read and parse
# are request-scoped (observed by the HTTP layer), the rest batch-scoped.
# With the framed wire carrying one device bucket per POST the two scales
# are directly comparable; with single-item POSTs a request is 1/bucket of
# a batch — read the per_bucket rows before comparing across the seam.
ROOFLINE_PHASES = ("body_read", "parse", "queue", "preproc", "h2d",
                   "compute", "postproc")


def best_window(values: list[float], k: int = 3) -> tuple[int, list[float]]:
    """The best (lowest relative spread) CONSECUTIVE window of ``k`` passes.

    Consecutive on purpose: cherry-picking the k closest passes from
    anywhere would let a bimodal run (fast half / slow half) fake
    convergence; adjacent passes share the same minute of tunnel weather,
    so their agreement is evidence the measurement settled."""
    if not values:
        return 0, []
    k = max(1, min(k, len(values)))
    best_i, best_s = 0, math.inf
    for i in range(len(values) - k + 1):
        w = values[i:i + k]
        s = spread_pct(w)
        if s < best_s:
            best_i, best_s = i, s
    return best_i, values[best_i:best_i + k]


def spread_pct(window: list[float]) -> float:
    """100 * (max - min) / max over a window; 0 for empty/degenerate."""
    if not window:
        return 0.0
    hi = max(window)
    return 100.0 * (hi - min(window)) / hi if hi > 0 else 0.0


def cv_pct(window: list[float]) -> float:
    """Coefficient of variation (population stddev / mean) in percent."""
    if not window:
        return 0.0
    mean = sum(window) / len(window)
    if mean <= 0:
        return 0.0
    var = sum((v - mean) ** 2 for v in window) / len(window)
    return 100.0 * math.sqrt(var) / mean


def phase_p50(latency_summary: dict, model: str, phase: str) -> float | None:
    """Observed p50 (ms) for one model phase from Metrics.summary()["latency"];
    None when the phase recorded nothing."""
    row = latency_summary.get(f"latency_ms{{model={model},phase={phase}}}")
    if not row or not row.get("n"):
        return None
    return float(row["p50_ms"])


def wire_ms_per_batch(bucket: int, img_bytes: int,
                      link_mbps: float) -> float | None:
    """Ideal transfer time for one padded batch at the measured link rate."""
    if not link_mbps or link_mbps <= 0:
        return None
    return bucket * img_bytes / (link_mbps * 1e6) * 1e3


def compute_split(observed_ms: float | None,
                  device_ms: float | None) -> dict | None:
    """Decompose the observed compute phase into device-time vs host-wait.

    ``device_ms`` is the raw-executable probe for the relevant bucket
    (inputs resident, dependent read); everything the serving path observes
    beyond it — transfer drain on buffered links, device queueing behind
    other batches, fetch-executor wait — is host-wait. This is the 465-vs-24
    gap as a named number."""
    if observed_ms is None or device_ms is None or device_ms <= 0:
        return None
    return {
        "observed_p50_ms": round(observed_ms, 3),
        "device_ms": round(device_ms, 3),
        "host_wait_ms": round(max(0.0, observed_ms - device_ms), 3),
        "pct_of_ceiling": round(100.0 * min(observed_ms, device_ms)
                                / observed_ms, 1) if observed_ms > 0 else None,
    }


def build_roofline(latency_summary: dict, model: str, buckets: list[int],
                   raw_ms_by_bucket: dict[int, float | None],
                   link_mbps: float, img_bytes: int,
                   chip_img_s: float | None,
                   value_img_s: float | None,
                   n_chips: int = 1,
                   req_bytes: int | None = None) -> dict:
    """The bench/``/stats`` ``roofline`` block for one model.

    ``raw_ms_by_bucket`` maps batch size -> raw-executable ms/batch (None
    where unprobed). Ceilings: the top bucket's wire time for h2d, its raw
    executable time for compute (the top bucket is what a saturated closed
    loop overwhelmingly serves; per-bucket numbers ship alongside so the
    reader can re-ratio for other fills).

    ``chip_img_s`` is the SINGLE-chip compute probe; with ``n_chips`` > 1
    the serving path has n_chips of those, so ``pct_of_chip_ceiling`` is
    taken against the aggregate (chip_img_s x n_chips) — an 8-chip run
    reporting 100% of one chip's ceiling is at 12.5% of the hardware it
    holds, and the block must say so (ISSUE 7).

    ``req_bytes`` (ISSUE 11) is the actual HTTP request-body size the load
    used — for the framed wire, ``frame.frame_nbytes(kind, edge, items)``
    — pricing the ``body_read`` ingest phase against the link the same way
    ``h2d`` is priced."""
    top = max(buckets) if buckets else None
    per_bucket: dict[str, dict] = {}
    for b in sorted(buckets):
        raw = raw_ms_by_bucket.get(b)
        wire = wire_ms_per_batch(b, img_bytes, link_mbps)
        per_bucket[str(b)] = {
            "raw_ms_per_batch": round(raw, 3) if raw else None,
            "raw_img_s": round(b / raw * 1e3, 1) if raw else None,
            "wire_ms_per_batch": round(wire, 3) if wire else None,
        }
    ceilings = {
        "body_read": (req_bytes / (link_mbps * 1e6) * 1e3
                      if req_bytes and link_mbps and link_mbps > 0 else None),
        "h2d": wire_ms_per_batch(top, img_bytes, link_mbps) if top else None,
        "compute": raw_ms_by_bucket.get(top) if top else None,
    }
    phases: dict[str, dict] = {}
    binding, binding_ms = None, -1.0
    for phase in ROOFLINE_PHASES:
        p50 = phase_p50(latency_summary, model, phase)
        row: dict = {"p50_ms": round(p50, 3) if p50 is not None else None}
        ceil = ceilings.get(phase)
        if ceil and p50:
            row["ceiling_ms"] = round(ceil, 3)
            row["ceiling_kind"] = ROOFLINE_CEILINGS[phase]
            row["pct_of_ceiling"] = round(100.0 * min(p50, ceil) / p50, 1)
        phases[phase] = row
        # Binding constraint among the pipelined per-batch stages (queue is
        # a symptom of the binding stage, not a stage itself).
        if phase != "queue" and p50 is not None and p50 > binding_ms:
            binding, binding_ms = phase, p50
    out = {
        "per_bucket": per_bucket,
        "phases": phases,
        "compute_split": compute_split(
            phase_p50(latency_summary, model, "compute"),
            ceilings.get("compute")),
        "binding_phase": binding,
    }
    if req_bytes:
        out["ingest_req_bytes"] = int(req_bytes)
    if chip_img_s and value_img_s is not None:
        n = max(1, n_chips)
        aggregate = chip_img_s * n
        out["chip_ceiling_img_s"] = round(chip_img_s, 1)
        out["aggregate_chip_ceiling_img_s"] = round(aggregate, 1)
        out["n_chips"] = n
        out["pct_of_chip_ceiling"] = round(100.0 * value_img_s / aggregate, 1)
    return out
