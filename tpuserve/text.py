"""Text preprocessing: a self-contained WordPiece tokenizer (SURVEY.md §2 C3,
§3d "tokenize on host").

The reference serves image models; the build's text configs (BERT-base,
BASELINE.json config 3) need BERT-style tokenization. No network means no
pretrained tokenizer downloads, so this implements the standard BERT scheme
from scratch:

- Basic tokenization: NFD accent stripping, optional lowercasing, punctuation
  splitting, CJK isolation, whitespace split.
- WordPiece: greedy longest-match-first against a vocab, "##" continuations,
  [UNK] fallback.

Vocabularies: ``WordPieceTokenizer.from_vocab_file`` loads a standard BERT
``vocab.txt`` (one token per line, id = line number). For no-artifact dev
serving, ``synthetic_vocab`` builds a deterministic vocab (special tokens,
printable ASCII pieces, common English subwords) so tokenization is stable
across processes without any file.

Tokenization runs on the host threadpool (pure Python, per-request); the
(ids, mask) arrays it emits are what crosses to the device.
"""

from __future__ import annotations

import unicodedata

import numpy as np

PAD, UNK, CLS, SEP, MASK = "[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"
SPECIALS = (PAD, UNK, CLS, SEP, MASK)


def _is_punct(ch: str) -> bool:
    cp = ord(ch)
    if (33 <= cp <= 47) or (58 <= cp <= 64) or (91 <= cp <= 96) or (123 <= cp <= 126):
        return True
    return unicodedata.category(ch).startswith("P")


def _is_cjk(cp: int) -> bool:
    return (
        0x4E00 <= cp <= 0x9FFF or 0x3400 <= cp <= 0x4DBF
        or 0x20000 <= cp <= 0x2A6DF or 0xF900 <= cp <= 0xFAFF
    )


def basic_tokenize(text: str, lower: bool = True) -> list[str]:
    """Whitespace/punctuation/CJK split with accent stripping."""
    if lower:
        text = text.lower()
    text = unicodedata.normalize("NFD", text)
    out: list[str] = []
    word: list[str] = []

    def flush() -> None:
        if word:
            out.append("".join(word))
            word.clear()

    for ch in text:
        if unicodedata.category(ch) == "Mn":  # combining accent
            continue
        if ch.isspace():
            flush()
        elif _is_punct(ch) or _is_cjk(ord(ch)):
            flush()
            out.append(ch)
        elif ch == "\x00" or unicodedata.category(ch) == "Cc":
            flush()
        else:
            word.append(ch)
    flush()
    return out


class WordPieceTokenizer:
    """BERT-scheme tokenizer: basic split + greedy WordPiece."""

    def __init__(self, vocab: dict[str, int], lower: bool = True,
                 max_word_chars: int = 100) -> None:
        self.vocab = vocab
        self.lower = lower
        self.max_word_chars = max_word_chars
        for tok in SPECIALS:
            if tok not in vocab:
                raise ValueError(f"vocab is missing special token {tok}")
        self.pad_id = vocab[PAD]
        self.unk_id = vocab[UNK]
        self.cls_id = vocab[CLS]
        self.sep_id = vocab[SEP]
        self.inv = {i: t for t, i in vocab.items()}

    @classmethod
    def from_vocab_file(cls, path: str, lower: bool = True) -> "WordPieceTokenizer":
        """Standard BERT vocab.txt: one token per line, id = line index."""
        vocab: dict[str, int] = {}
        with open(path, encoding="utf-8") as f:
            for i, line in enumerate(f):
                tok = line.rstrip("\n")
                if tok:
                    vocab[tok] = i
        return cls(vocab, lower=lower)

    def wordpiece(self, word: str) -> list[str]:
        """Greedy longest-match-first split of one basic token."""
        if len(word) > self.max_word_chars:
            return [UNK]
        pieces: list[str] = []
        start = 0
        while start < len(word):
            end = len(word)
            cur = None
            while start < end:
                piece = word[start:end]
                if start > 0:
                    piece = "##" + piece
                if piece in self.vocab:
                    cur = piece
                    break
                end -= 1
            if cur is None:
                return [UNK]
            pieces.append(cur)
            start = end
        return pieces

    def tokenize(self, text: str) -> list[str]:
        out: list[str] = []
        for word in basic_tokenize(text, self.lower):
            out.extend(self.wordpiece(word))
        return out

    def encode(self, text: str, max_len: int) -> tuple[np.ndarray, np.ndarray]:
        """Text -> ([CLS] pieces [SEP], mask), truncated+padded to max_len."""
        ids = [self.cls_id]
        ids += [self.vocab.get(t, self.unk_id) for t in self.tokenize(text)]
        ids = ids[: max_len - 1] + [self.sep_id]
        n = len(ids)
        arr = np.full((max_len,), self.pad_id, np.int32)
        arr[:n] = ids
        mask = np.zeros((max_len,), np.int32)
        mask[:n] = 1
        return arr, mask

    def n_tokens(self, text: str) -> int:
        """Sequence length encode() would need (incl. [CLS]/[SEP])."""
        return len(self.tokenize(text)) + 2


def synthetic_vocab(size: int = 8192, seed: int = 0) -> dict[str, int]:
    """Deterministic dev vocab: specials, ASCII chars (+## variants), common
    English subwords, then filler tokens up to `size`.

    Guarantees every ASCII string tokenizes without [UNK] (char fallback)."""
    toks: list[str] = list(SPECIALS)
    chars = [chr(c) for c in range(33, 127)] + list("0123456789")
    seen = set(toks)
    for c in [chr(c) for c in range(97, 123)] + [chr(c) for c in range(48, 58)] + chars:
        for t in (c, "##" + c):
            if t not in seen:
                seen.add(t)
                toks.append(t)
    common = (
        "the of and to in is was for on as with by at from it an be this that "
        "are or his her which not has had have but were they one all we can "
        "##s ##ed ##ing ##ly ##er ##est ##tion ##ment ##ness ##able ##ful "
        "time year day man world life hand part child eye woman place work "
        "week case point company number group problem fact model serve image "
        "text token batch size test run fast slow good new old high low"
    ).split()
    for t in common:
        if t not in seen:
            seen.add(t)
            toks.append(t)
    # The UNK-free guarantee needs every char+## piece above; never truncate
    # below them — clamp size up instead.
    size = max(size, len(toks))
    rng = np.random.default_rng(seed)
    letters = "abcdefghijklmnopqrstuvwxyz"
    while len(toks) < size:
        n = int(rng.integers(2, 6))
        t = "".join(letters[int(i)] for i in rng.integers(0, 26, n))
        if rng.random() < 0.5:
            t = "##" + t
        if t not in seen:
            seen.add(t)
            toks.append(t)
    return {t: i for i, t in enumerate(toks[:size])}


class CLIPBPETokenizer:
    """Byte-pair tokenizer for CLIP-family artifacts (SD 1.5 prompts).

    Real Stable Diffusion checkpoints pair the text encoder with OpenAI
    CLIP's byte-level BPE (vocab.json + merges.txt), not WordPiece. This
    wraps ``transformers.CLIPTokenizer`` (baked into the image; slow
    pure-python path, amortized by the decode threadpool) behind the same
    ``encode(text, max_len) -> (ids, mask)`` contract WordPiece exposes, so
    ``tpuserve.models.sd15`` swaps tokenizers by config alone.
    """

    def __init__(self, vocab_file: str, merges_file: str) -> None:
        from transformers import CLIPTokenizer

        self.tok = CLIPTokenizer(vocab_file=vocab_file, merges_file=merges_file)
        self.vocab: dict[str, int] = dict(self.tok.get_vocab())
        self.pad_id = int(self.tok.eos_token_id)  # CLIP pads with EOS
        self.bos_id = int(self.tok.bos_token_id)
        self.eos_id = int(self.tok.eos_token_id)

    def encode(self, text: str, max_len: int) -> tuple[np.ndarray, np.ndarray]:
        """Text -> (BOS ids EOS + EOS-padding, mask), fixed max_len."""
        out = self.tok(text, padding="max_length", truncation=True,
                       max_length=max_len)
        ids = np.asarray(out["input_ids"], np.int32)
        mask = np.asarray(out["attention_mask"], np.int32)
        return ids, mask

    def n_tokens(self, text: str) -> int:
        return len(self.tok(text)["input_ids"])
