"""Iteration-level generative serving (ISSUE 9; docs/PERFORMANCE.md "The
generation engine").

- :class:`~tpuserve.genserve.model.GenerativeModel` — the family contract:
  ``init_state`` / ``step`` / ``is_finished`` / ``finalize`` (+ ``extract``)
  decompose generation into slot-block device programs.
- :class:`~tpuserve.genserve.arena.SlotArena` — host-side slot ledger
  (never double-hands a slot).
- :class:`~tpuserve.genserve.engine.GenEngine` — the step loop: re-forms
  the active batch every model iteration, retires finished sequences
  immediately, folds queued requests into free slots, evicts past-deadline
  sequences with the fast-504 contract.
"""

from tpuserve.genserve.arena import SlotArena, SlotCorrupted, SlotInfo
from tpuserve.genserve.engine import GenEngine
from tpuserve.genserve.model import GenerativeModel

__all__ = ["GenEngine", "GenerativeModel", "SlotArena", "SlotCorrupted",
           "SlotInfo"]
