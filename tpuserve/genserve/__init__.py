"""Iteration-level generative serving (ISSUE 9; docs/PERFORMANCE.md "The
generation engine").

- :class:`~tpuserve.genserve.model.GenerativeModel` — the family contract:
  ``init_state`` / ``step`` / ``is_finished`` / ``finalize`` (+ ``extract``)
  decompose generation into slot-block device programs.
- :class:`~tpuserve.genserve.arena.SlotArena` — host-side slot ledger
  (never double-hands a slot).
- :class:`~tpuserve.genserve.engine.GenEngine` — the step loop: re-forms
  the active batch every model iteration, retires finished sequences
  immediately, folds queued requests into free slots, evicts past-deadline
  sequences with the fast-504 contract.
- :class:`~tpuserve.genserve.pages.PageLedger` — host-side KV page ledger
  for the paged cache (ISSUE 18; never double-hands a page), with
  :class:`~tpuserve.genserve.engine.KVPressure` as the page-exhaustion
  admission shed.
- :class:`~tpuserve.genserve.engine.GenEngineGroup` — replica-per-chip
  engines over a replica-mode runtime (ISSUE 20): one engine per mesh,
  least-loaded placement, the full engine surface aggregated.
"""

from tpuserve.genserve.arena import SlotArena, SlotCorrupted, SlotInfo
from tpuserve.genserve.engine import GenEngine, GenEngineGroup, KVPressure
from tpuserve.genserve.model import GenerativeModel
from tpuserve.genserve.pages import PageCorrupted, PageLedger

__all__ = ["GenEngine", "GenEngineGroup", "GenerativeModel", "KVPressure",
           "PageCorrupted", "PageLedger", "SlotArena", "SlotCorrupted",
           "SlotInfo"]
