"""Iteration-level continuous batching engine (ISSUE 9; Orca, PAPERS.md P4).

The static-bucket batcher (tpuserve.batcher) locks a batch for its whole
run: correct for one-shot ResNet/BERT, wrong for multi-step generative work
where a 2-token completion admitted behind a 200-token one waits for both.
This engine is the second dispatch path, scheduling at MODEL-ITERATION
granularity over a fixed block of generative slots:

- every iteration the active batch RE-FORMS: finished sequences retire
  immediately (``gen_early_exits_total``), queued requests fold into free
  slots mid-flight (``gen_fold_ins_total``), and past-deadline sequences
  evict with PR 2's fast-504 contract (``gen_evictions_total`` +
  ``deadline_exceeded_total``);
- the per-model state block (KV caches, latent slabs, token buffers) is ONE
  device-resident pytree with leading dim = slots, allocated at start and
  threaded through the compiled step — steady-state serving allocates
  nothing, and the host-side :class:`~tpuserve.genserve.arena.SlotArena`
  ledger guarantees no slot is ever double-handed;
- the three device programs (insert / step / extract) register in PR 6's
  VariantKey registry via ``ModelRuntime.register_program``, so
  ``runtime_compiles_total`` covers them and a delta of 0 across sustained
  admit/retire/``:reload`` churn is the zero-recompile proof
  (scripts/genserve_smoke.sh asserts it). Insert and extract take a TRACED
  slot index — one compile serves every slot.

The engine exposes the ModelBatcher surface (submit/start/stop/drain/
revive_group_loops/pipeline_stats), so the existing front door — deadlines,
breakers, result cache + coalescing, canaries, watchdog revival, graceful
drain, the router tier — holds for multi-step requests unchanged. Blocking
device work hops through the server's shared StageExecutors ("h2d" for
inserts, "fetch" for step/extract readback, "postproc" for finalize), so
generation shares the pipeline's stage-granularity scheduling and metrics.

All engine state is event-loop-only (the step loop owns every mutation);
there is deliberately no lock to witness.
"""

from __future__ import annotations

import asyncio
import collections
import logging
import time
from contextlib import nullcontext
from dataclasses import dataclass, field
from typing import Any

import jax
import numpy as np

from tpuserve.batcher import DeadlineExceeded, QueueFull
from tpuserve.config import GenserveConfig, PipelineConfig
from tpuserve.genserve.arena import SlotArena, SlotInfo
from tpuserve.genserve.model import GenerativeModel
from tpuserve.genserve.pages import PageLedger
from tpuserve.hostpipe import StageExecutors
from tpuserve.obs import GEN_STREAM_REASONS, PRIORITIES, Metrics
from tpuserve.utils.locks import new_lock
from tpuserve.utils.retrace import allow_transfers, host_fetch

log = logging.getLogger("tpuserve.genserve")


class KVPressure(QueueFull):
    """Paged-KV admission shed (ISSUE 18): the free-page ledger cannot
    cover this request's prompt + decode reservation on top of demand
    already queued. Subclasses QueueFull so every existing shed plumbing
    (result-cache passthrough, submit re-raise) carries it unchanged; the
    HTTP layer maps it to 503 with a clear-time Retry-After and shed
    reason "kv_pressure" — the same contract queue-full sheds follow."""

    def __init__(self, message: str,
                 retry_after_s: float | None = None) -> None:
        super().__init__(message)
        self.retry_after_s = retry_after_s


@dataclass
class _GenRequest:
    item: Any
    future: asyncio.Future = field(repr=False)
    enqueued_at: float = 0.0
    deadline_at: float | None = None
    # Paged mode: pages this request will reserve at fold-in (prompt +
    # decode budget); 0 when paging is off. Summed over the queue it is
    # the committed-demand term of the admission pressure check.
    pages_needed: int = 0
    # Priority class resolved at admission (obs.PRIORITIES); None when the
    # fleet scheduler is off.
    priority: str | None = None
    # Request trace context (obs.TraceContext, ISSUE 12); None untraced.
    ctx: Any = None
    # Emission channel for a streamed request (ISSUE 17); None for unary.
    stream: "GenStream | None" = None


def _retrieve_exception(fut: asyncio.Future) -> None:
    """Streamed requests surface failures as error terminal units on the
    stream; the future stays for cancellation + bookkeeping. Retrieve the
    exception so asyncio never logs 'exception was never retrieved'."""
    if not fut.cancelled():
        fut.exception()


class GenStream:
    """Consumer handle for one streamed generation (ISSUE 17): a bounded
    queue of unit dicts the engine produces and the HTTP layer drains.
    Exactly one terminal unit ("done" or "error") always arrives — every
    engine failure path enqueues one — so a client can always distinguish
    a complete stream from a torn transport. ``close()`` is the consumer's
    abandon signal (client disconnect): it stops further emission and
    unblocks a producer waiting on the full queue."""

    __slots__ = ("queue", "policy", "state", "first_unit_at", "terminated",
                 "dropped")

    def __init__(self, maxsize: int, policy: str) -> None:
        self.queue: asyncio.Queue = asyncio.Queue(maxsize=max(1, maxsize))
        self.policy = policy  # ModelConfig.stream_policy: "drop" | "block"
        self.state: dict = {}  # the model's incremental emission state
        self.first_unit_at: float | None = None
        # Terminal enqueued (or consumer gone): emission is over.
        self.terminated = False
        self.dropped = 0

    async def get(self) -> dict:
        return await self.queue.get()

    def close(self) -> None:
        """Consumer gone: stop emission and free any blocked producer."""
        self.terminated = True
        while True:
            try:
                self.queue.get_nowait()
            except asyncio.QueueEmpty:
                return


class GenEngine:
    """One iteration-level generation engine per served generative model."""

    def __init__(self, model: GenerativeModel, runtime: Any,
                 metrics: Metrics, gcfg: "GenserveConfig | None" = None,
                 breaker: "Any | None" = None,
                 injector: "Any | None" = None,
                 stages: "StageExecutors | None" = None,
                 pipeline_cfg: "PipelineConfig | None" = None,
                 replica: int = 0) -> None:
        self.model = model
        self.runtime = runtime
        self.metrics = metrics
        self.cfg = model.cfg
        self.gcfg = gcfg or GenserveConfig()
        self.breaker = breaker
        self.injector = injector
        # Replica identity (ISSUE 20): which runtime mesh this engine's
        # dispatches ride. 0 for single/sharded; a GenEngineGroup builds
        # one engine per replica mesh and sets ``peers`` so model-level
        # gauges publish group-wide sums instead of last-writer-wins.
        self.replica = int(replica)
        self.peers: "list[GenEngine] | None" = None
        # CPU-backend wedge guard (ISSUE 11, batcher device sections):
        # concurrent dispatches from several replica engines' stage threads
        # spin-wait against each other on forced-host-device meshes. The
        # group installs ONE shared lock on the cpu backend; real
        # accelerator backends (and single engines) keep this None — the
        # step loop stays lock-free there.
        self._dispatch_lock = None
        self.slots = self.gcfg.slots or max(self.cfg.batch_buckets)
        self.arena = SlotArena(self.slots)
        # Paged KV cache (ISSUE 18): only families that ship the paged
        # programs opt in — with kv_paging on, sd15 (no paged contract)
        # keeps the dense slab byte-for-byte.
        self.paging = bool(self.gcfg.kv_paging) \
            and bool(getattr(model, "supports_kv_paging", False))
        if self.gcfg.kv_paging and not self.paging:
            log.info("%s: [genserve] kv_paging is on but the family has no "
                     "paged programs — dense state slab kept",
                     model.cfg.name)
        self.pages: PageLedger | None = None
        self._pps = 0            # block-table width (pages per max-ctx slot)
        self._prefill_chunk = 0  # static chunk width of the prefill program
        if self.paging:
            pt = self.gcfg.kv_page_tokens
            self._pps = int(model.kv_pages_per_slot(pt))
            n_pages = self.gcfg.kv_pages or (self.slots * self._pps + 1)
            if n_pages < self._pps + 1:
                raise ValueError(
                    f"{model.cfg.name}: [genserve] kv_pages={n_pages} cannot "
                    f"cover one max-context request ({self._pps} pages + the "
                    "sentinel)")
            self.pages = PageLedger(n_pages, pt)
            self._prefill_chunk = int(
                model.kv_prefill_chunk(self.gcfg.prefill_chunk))
        # High-water active-slot mark (bench's max_concurrent_slots).
        self.peak_active = 0
        self._own_stages = stages is None
        self.stages = stages if stages is not None \
            else StageExecutors(pipeline_cfg or PipelineConfig(), metrics)
        name = model.cfg.name
        self.name = name
        # Hot-path metric handles, prebound once (the batcher discipline).
        self._c_iterations = metrics.counter(
            f"gen_iterations_total{{model={name}}}")
        self._c_admitted = metrics.counter(
            f"gen_admitted_total{{model={name}}}")
        self._c_fold_ins = metrics.counter(
            f"gen_fold_ins_total{{model={name}}}")
        self._c_early_exits = metrics.counter(
            f"gen_early_exits_total{{model={name}}}")
        self._c_evictions = metrics.counter(
            f"gen_evictions_total{{model={name}}}")
        self._c_deadline = metrics.counter(
            f"deadline_exceeded_total{{model={name}}}")
        self._c_items = metrics.counter(f"items_total{{model={name}}}")
        self._c_units = metrics.counter(f"gen_units_total{{model={name}}}")
        self._c_batch_errors = metrics.counter(
            f"batch_errors_total{{model={name}}}")
        self._c_shed = metrics.counter(f"shed_total{{model={name}}}")
        self._g_queue_depth = metrics.gauge(f"queue_depth{{model={name}}}")
        self._g_active = metrics.gauge(f"gen_active_slots{{model={name}}}")
        self._h_step = metrics.histogram(f"gen_step_ms{{model={name}}}")
        self._h_insert = metrics.histogram(f"gen_insert_ms{{model={name}}}")
        self._h_extract = metrics.histogram(f"gen_extract_ms{{model={name}}}")
        self._h_queue = metrics.histogram(
            f"latency_ms{{model={name},phase=queue}}")
        # Streaming (ISSUE 17): first-unit latency feeds the first-token
        # SLO; the terminated counter is per-reason (created on demand).
        self._h_first_unit = metrics.histogram(
            f"gen_first_unit_ms{{model={name}}}")
        self._c_streams = metrics.counter(f"gen_streams_total{{model={name}}}")
        self._c_disconnects = metrics.counter(
            f"gen_client_disconnects_total{{model={name}}}")
        self._c_stream_dropped = metrics.counter(
            f"gen_stream_dropped_total{{model={name}}}")
        # Paged-KV observability (ISSUE 18), prebound like everything else
        # so the telemetry sampler sees the rows from the first scrape.
        self._g_kv_pages_total = metrics.gauge(
            f"gen_kv_pages_total{{model={name}}}")
        self._g_kv_pages_free = metrics.gauge(
            f"gen_kv_pages_free{{model={name}}}")
        self._g_kv_util = metrics.gauge(
            f"gen_kv_page_utilization{{model={name}}}")
        self._c_prefill_chunks = metrics.counter(
            f"gen_prefill_chunks_total{{model={name}}}")
        self._c_kv_shed = metrics.sched_shed_counter(name, "kv_pressure")
        self._default_priority = getattr(model.cfg, "priority", "interactive")
        self._h_qwait = {p: metrics.queue_wait_histogram(name, p)
                         for p in PRIORITIES}
        # Fleet device-time ledger hook (tpuserve.scheduler): called with
        # each compiled step's seconds when a scheduler is attached.
        self.device_time_cb = None
        # Device-seconds ledger (ISSUE 14): step time lands on THIS
        # engine's replica row; the telemetry sampler derives
        # device_utilization{model=,replica=} from its rate.
        self._c_device_seconds = metrics.device_seconds_counter(
            name, self.replica)
        # Per-replica engine ledger (ISSUE 20): steps/units/occupancy rows
        # keyed {model=,replica=} — prebound so the telemetry sampler
        # captures them into /stats/history from the first scrape.
        self._c_replica_steps = metrics.gen_replica_steps_counter(
            name, self.replica)
        self._c_replica_units = metrics.gen_replica_units_counter(
            name, self.replica)
        self._g_replica_active = metrics.gen_replica_active_gauge(
            name, self.replica)
        self._g_replica_kv_free = metrics.gen_replica_kv_free_gauge(
            name, self.replica)
        self._pending: collections.deque[_GenRequest] = collections.deque()
        self._state: Any = None
        self._state_struct: Any = None
        self._loop_task: asyncio.Task | None = None
        self._work_event: asyncio.Event | None = None
        self._idle_event: asyncio.Event | None = None
        self._running = False
        # Serving-rate model for estimate_clear_s (429 Retry-After).
        self._ewma_step_ms: float | None = None
        self._ewma_iters: float | None = None
        # Pages-per-request EWMA (paged mode): the "typical admission" the
        # kv_clear_s pressure signal prices.
        self._ewma_pages: float | None = None
        # Runaway guard: a slot that somehow never reports done is failed
        # (and freed) past this bound instead of pinning its slot forever.
        self._max_steps_guard = 2 * max(1, model.gen_max_steps())
        # Drain's bounded stream budget: once set (perf_counter clock),
        # still-open streams past it terminate with the "drain" error
        # event instead of holding the drain hostage.
        self._stream_kill_at: float | None = None

    # -- compilation ----------------------------------------------------------
    def compile(self) -> None:
        """Register the insert/step/extract programs in the runtime's
        specialized-variant registry and execute each once (prewarm: PJRT
        program load off the first request's latency). Blocking; call from
        ServerState.build."""
        model, rt = self.model, self.runtime
        t0 = time.perf_counter()
        if self.paging:
            # Paged state block: global page pool + per-slot block table.
            # Page indices are TRACED (like slot indices), so this one
            # registration serves every page assignment the ledger ever
            # makes — the zero-recompile obligation extends to page churn.
            self._state_struct = model.kv_page_signature(
                self.slots, self.pages.pages, self.pages.page_tokens)
        else:
            self._state_struct = model.state_signature(self.slots)
        geometry = {"kv_paging": self.paging, "slots": self.slots,
                    "pages": self.pages.pages if self.paging else 0,
                    "page_tokens": self.pages.page_tokens
                    if self.paging else 0,
                    "prefill_chunk": self._prefill_chunk}
        if "step" in rt.gen_programs:
            # Programs already registered on this runtime (a second engine
            # over the same runtime — tests, restarts). Reuse requires the
            # same slot width AND the same paging geometry: the compiled
            # state block is shape-frozen.
            step_key = next(k for k in rt.variants
                            if k.bucket and k.bucket[0] == "step")
            if step_key.bucket[1] != self.slots:
                raise ValueError(
                    f"{self.name}: runtime programs were compiled for "
                    f"{step_key.bucket[1]} slots, engine wants {self.slots}")
            prior = getattr(rt, "gen_meta", None)
            if prior and prior != geometry:
                raise ValueError(
                    f"{self.name}: runtime programs were compiled for "
                    f"geometry {prior}, engine wants {geometry}")
            return
        item_struct = model.gen_item_signature()
        slot_struct = jax.ShapeDtypeStruct((), np.int32)
        # Sharded decode (ISSUE 20): on a sharded mesh the family may pin
        # state-block dims to mesh axes (textgen: KV heads on "model").
        # The SAME spec tree goes in as the state arg's sharding and out
        # as the state output's sharding — the state feeds back through
        # the AOT executable, and Compiled demands exact input shardings.
        from jax.sharding import PartitionSpec as P
        sspecs = None
        if getattr(rt, "mode", "single") == "sharded":
            sspecs = model.state_partition_specs(self._state_struct,
                                                 rt.meshes[0])

        def _specs(n_extra: int) -> dict:
            """register_program spec kwargs for (state, *n_extra args)."""
            if sspecs is None:
                return {}
            return {"arg_specs": (sspecs,) + (None,) * n_extra,
                    "out_specs": sspecs}

        if self.paging:
            start_struct = jax.ShapeDtypeStruct((), np.int32)
            pages_struct = jax.ShapeDtypeStruct((self._pps,), np.int32)
            chunk = self._prefill_chunk

            def prefill_fn(params, state, slot, item, start, pages):
                return model.prefill_chunk(params, state, slot, item,
                                           start, pages, chunk=chunk)

            rt.register_program("prefill", prefill_fn,
                                (self._state_struct, slot_struct,
                                 item_struct, start_struct, pages_struct),
                                width=self.slots, donate_argnums=(0,),
                                **_specs(4))
        else:
            def insert_fn(params, state, slot, item):
                fresh = model.init_state(params, item)
                return jax.tree_util.tree_map(
                    lambda s, u: jax.lax.dynamic_update_index_in_dim(
                        s, u.astype(s.dtype), slot, 0),
                    state, fresh)

            rt.register_program("insert", insert_fn,
                                (self._state_struct, slot_struct,
                                 item_struct),
                                width=self.slots, donate_argnums=(0,),
                                **_specs(2))
        step_specs = {} if sspecs is None else {
            "arg_specs": (sspecs,), "out_specs": (sspecs, P())}
        rt.register_program("step", model.step, (self._state_struct,),
                            width=self.slots, donate_argnums=(0,),
                            **step_specs)
        rt.register_program("extract", model.extract,
                            (self._state_struct, slot_struct),
                            width=self.slots,
                            **({} if sspecs is None
                               else {"arg_specs": (sspecs, None)}))
        rt.gen_meta = geometry
        # Prewarm: one full fold-in + step + extract on a zero state block,
        # with a dependent read per program (the only honest completion
        # signal). Paged mode walks every prefill chunk of the canary so
        # the chunked program loads too. EVERY replica mesh prewarms —
        # PJRT program load must come off replica k's first request too,
        # not just replica 0's.
        item = model.canary_item()
        for r in range(getattr(rt, "n_replicas", 1)):
            state = self._host_zeros(self._state_struct)
            with self._dispatch_guard():
                if self.paging:
                    row = np.arange(1, self._pps + 1, dtype=np.int32)
                    n_prompt = model.prompt_tokens(item)
                    start = 0
                    while True:
                        state = rt.run_program("prefill", state, np.int32(0),
                                               item, np.int32(start), row,
                                               replica=r)
                        start += self._prefill_chunk
                        if start >= n_prompt:
                            break
                else:
                    state = rt.run_program("insert", state, np.int32(0),
                                           item, replica=r)
                state, out = rt.run_program("step", state, replica=r)
                jax.tree_util.tree_map(np.asarray, out)
                jax.tree_util.tree_map(
                    np.asarray,
                    rt.run_program("extract", state, np.int32(0), replica=r))
        log.info("%s: generation engine compiled+prewarmed %d slots in %.1fs",
                 self.name, self.slots, time.perf_counter() - t0)

    @staticmethod
    def _host_zeros(struct: Any) -> Any:
        return jax.tree_util.tree_map(
            lambda s: np.zeros(tuple(s.shape), s.dtype), struct)

    # -- lifecycle ------------------------------------------------------------
    async def start(self) -> None:
        self._state = self._host_zeros(self._state_struct)
        if self.pages is not None:
            peers = [e for e in (self.peers or [self])
                     if e.pages is not None]
            self._g_kv_pages_total.set(
                float(sum(e.pages.usable for e in peers)))
            self._update_kv_gauges()
        self._work_event = asyncio.Event()
        self._idle_event = asyncio.Event()
        self._idle_event.set()
        self._running = True
        self._loop_task = asyncio.get_running_loop().create_task(
            self._step_loop())

    async def stop(self) -> None:
        """Cancel the step loop, fail queued and mid-flight requests."""
        self._running = False
        t = self._loop_task
        if t is not None:
            t.cancel()
            try:
                await t
            except asyncio.CancelledError:
                pass
            except Exception:
                log.exception("step loop for %s failed during stop", self.name)
            self._loop_task = None
        err = RuntimeError(f"server shutting down; {self.name} not served")
        while self._pending:
            req = self._pending.popleft()
            self._terminate_stream(req.stream, "shutdown", str(err))
            if not req.future.done():
                req.future.set_exception(err)
        for info in self.arena.release_all():
            self._terminate_stream(info.stream, "shutdown", str(err))
            if not info.future.done():
                info.future.set_exception(err)
        if self.pages is not None:
            self.pages.release_all()
            self._update_kv_gauges()
        self._publish_queue_depth()
        self._publish_active()
        self._maybe_idle()
        if self._own_stages:
            self.stages.shutdown()

    def revive_group_loops(self) -> int:
        """Watchdog hook (same name as the batcher's so server registration
        is uniform): restart the step loop if it died. Mid-flight slots are
        still in the arena, so a revived loop resumes stepping them."""
        if not self._running:
            return 0
        t = self._loop_task
        if t is not None and not t.done():
            return 0
        if t is not None and not t.cancelled() and t.exception() is not None:
            log.error("step loop for %s died: %r — restarting", self.name,
                      t.exception())
        self._loop_task = asyncio.get_running_loop().create_task(
            self._step_loop())
        return 1

    async def drain(self, deadline: float) -> bool:
        """Graceful drain: wait until every accepted request (queued or
        mid-generation) resolved, bounded by ``deadline`` (event-loop
        clock). Same idle-event discipline as the batcher. Streams get
        their own bounded budget inside the window (gcfg.stream_drain_s):
        past it the scheduling passes terminate stragglers with the
        "drain" error event — a well-formed torn-stream signal, never a
        silent truncation or an unbounded drain."""
        loop = asyncio.get_running_loop()
        self._stream_kill_at = time.perf_counter() + self.gcfg.stream_drain_s
        try:
            while self._pending or self.arena.n_active:
                timeout = deadline - loop.time()
                if timeout <= 0:
                    break
                self._idle_event.clear()
                if not self._pending and not self.arena.n_active:
                    break
                try:
                    await asyncio.wait_for(self._idle_event.wait(), timeout)
                except asyncio.TimeoutError:
                    break
        finally:
            self._stream_kill_at = None
        self._maybe_idle()
        return not self._pending and not self.arena.n_active

    # -- submission (event loop) ----------------------------------------------
    def submit(self, item: Any, group: Any = None,
               deadline_at: float | None = None,
               priority: str | None = None,
               ctx: Any = None) -> asyncio.Future:
        """Enqueue one decoded request; returns a Future of its result.
        ``group`` is accepted for batcher-API parity and ignored — the
        engine has one slot block, not per-group queues. ``priority``
        labels the queue-wait histogram (arbitration happened upstream).
        ``ctx`` (obs.TraceContext) collects the request's queue/fold-in/
        step/evict/retire spans, tagged with its slot (ISSUE 12)."""
        return self._enqueue(item, deadline_at, priority, ctx, None)

    def submit_stream(self, item: Any, deadline_at: float | None = None,
                      priority: str | None = None,
                      ctx: Any = None) -> "tuple[asyncio.Future, GenStream]":
        """Enqueue one streamed generation -> (future, stream). The HTTP
        layer consumes ONLY the stream (units ending in one terminal —
        every failure path pushes an error terminal, so the queue is the
        single channel); the future exists for disconnect cancellation.
        Raises QueueFull exactly like submit (a shed stream was never
        started — plain 429, no stream semantics involved)."""
        stream = GenStream(self.gcfg.stream_queue,
                           getattr(self.cfg, "stream_policy", "drop"))
        fut = self._enqueue(item, deadline_at, priority, ctx, stream)
        fut.add_done_callback(_retrieve_exception)
        self._c_streams.inc()
        return fut, stream

    def _enqueue(self, item: Any, deadline_at: float | None,
                 priority: str | None, ctx: Any,
                 stream: "GenStream | None") -> asyncio.Future:
        if not self._running or self._work_event is None:
            raise RuntimeError(f"engine for {self.name} not started")
        if len(self._pending) >= self.cfg.max_queue:
            self._c_shed.inc()
            raise QueueFull(self.name)
        need = 0
        if self.pages is not None:
            # Page-pressure admission (ISSUE 18; budgeted admission,
            # Clockwork P3).  An admitted request never hits mid-decode
            # page exhaustion (its FULL reservation — prompt + decode
            # budget — is taken at fold-in), so queued demand only costs
            # latency, not correctness.  We therefore allow one pool
            # turnover of backlog (pages recycle as sequences retire,
            # exactly like the dense queue draining) and shed with a
            # clear-time hint once projected demand exceeds that: at
            # that point the page pool, not compute, is the bottleneck.
            need = self.model.pages_needed(item, self.pages.page_tokens)
            projected = self.pages.n_reserved + self._queued_pages() + need
            if projected > 2 * self.pages.usable:
                self._c_shed.inc()
                self._c_kv_shed.inc()
                raise KVPressure(
                    f"{self.name}: kv page pool exhausted (need {need} "
                    f"pages, {self.pages.n_free} free, "
                    f"{self._queued_pages()} queued demand)",
                    retry_after_s=self.kv_clear_s())
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending.append(_GenRequest(
            item=item, future=fut, enqueued_at=time.perf_counter(),
            deadline_at=deadline_at, priority=priority, ctx=ctx,
            stream=stream, pages_needed=need))
        self._publish_queue_depth()
        self._idle_event.clear()
        self._work_event.set()
        return fut

    # -- stream emission (event loop; ISSUE 17) -------------------------------
    def _count_termination(self, reason: str) -> None:
        if reason not in GEN_STREAM_REASONS:
            # Off-vocabulary labels would fragment the metric and dodge
            # the docs/tests contract (TPS404): fail loudly in dev.
            raise ValueError(f"unknown stream-termination reason {reason!r} "
                             f"(add it to obs.GEN_STREAM_REASONS)")
        self.metrics.counter(
            f"gen_stream_terminated_total{{model={self.name},"
            f"reason={reason}}}").inc()

    def _terminate_stream(self, stream: "GenStream | None", reason: str,
                          message: str | None = None,
                          unit: dict | None = None) -> None:
        """Enqueue the terminal unit (sync-safe: callable from scheduling
        passes and stop()). The terminal is never dropped — on a full
        queue the oldest buffered unit makes room; the terminal outranks
        any backlog because the stream is ending either way."""
        if stream is None or stream.terminated:
            return
        stream.terminated = True
        if unit is None:
            unit = {"type": "error", "error": reason,
                    "message": message or reason}
        q = stream.queue
        while True:
            try:
                q.put_nowait(unit)
                break
            except asyncio.QueueFull:
                try:
                    q.get_nowait()
                except asyncio.QueueEmpty:
                    break
        self._count_termination(reason)

    async def _emit_unit(self, stream: "GenStream", unit: dict) -> None:
        """Policy-aware in-flight emission. A droppable unit under policy
        "drop" is discarded when the consumer lags (gen_stream_dropped_
        total); everything else blocks the step loop until the consumer
        drains — re-checking the terminated flag every 50 ms so an
        abandoned stream can never wedge the engine."""
        if stream.terminated:
            return
        if unit.get("droppable") and stream.policy == "drop":
            if stream.queue.full():
                stream.dropped += 1
                self._c_stream_dropped.inc()
                return
            stream.queue.put_nowait(unit)
            return
        while not stream.terminated:
            if not self._running:
                # stop() is tearing the engine down; it sends the
                # "shutdown" terminal itself once the loop exits.
                return
            kill_at = self._stream_kill_at
            if kill_at is not None and time.perf_counter() >= kill_at:
                # Draining and the stream budget is spent: a wedged
                # consumer must not hold the step loop (and the drain)
                # open — it gets the "drain" terminal instead.
                self._terminate_stream(stream, "drain",
                                       "server draining; stream budget spent")
                return
            try:
                await asyncio.wait_for(stream.queue.put(unit), 0.05)
                return
            except asyncio.TimeoutError:
                continue

    async def _emit_step_units(self, out: dict) -> None:
        """Flush each streaming slot's newly produced units for this
        iteration (the per-iteration flushing Orca's frame makes natural),
        plus the family's optional preview extract — which reuses the
        compiled extract program, so previews never add a compile."""
        model = self.model
        for slot in self.arena.active_slots():
            info = self.arena.peek(slot)
            stream = info.stream
            if stream is None or stream.terminated or info.future.done():
                continue
            try:
                units = model.stream_units(out, slot, stream.state)
            except Exception:  # noqa: BLE001 — emission must not kill a slot
                log.exception("stream_units failed for %s slot %d",
                              self.name, slot)
                continue
            if units and stream.first_unit_at is None:
                now = time.perf_counter()
                stream.first_unit_at = now
                ms = (now - info.enqueued_at) * 1e3
                tid = info.ctx.trace_id if info.ctx is not None else None
                self._h_first_unit.observe(ms, trace_id=tid)
                if info.ctx is not None:
                    wall = time.time()
                    info.ctx.span("first_unit", wall - ms / 1e3, wall,
                                  tid=self.name, slot=slot)
            for u in units:
                await self._emit_unit(stream, u)
            if model.stream_wants_preview(out, slot, stream.state):
                try:
                    extracted = await self.stages.run(
                        self.name, "fetch", self._extract_sync, slot)
                    u = model.stream_preview_unit(extracted, stream.state)
                except asyncio.CancelledError:
                    raise
                except Exception:  # noqa: BLE001 — a preview is best-effort
                    log.exception("preview extract failed for %s slot %d",
                                  self.name, slot)
                else:
                    await self._emit_unit(stream, u)

    def _maybe_idle(self) -> None:
        if self._idle_event is not None and not self._pending \
                and not self.arena.n_active:
            self._idle_event.set()

    # -- gauge publication (event loop) ---------------------------------------
    # Metrics are name-keyed singletons: every engine in a replica group
    # binds the SAME gen_active_slots{model=} handle, so model-level
    # gauges must publish the group-wide value (peers sum) — last-writer-
    # wins would make the gauge flap with whichever replica updated last.
    # Per-replica truth lives on the {model=,replica=} rows. All engines
    # of a group share one event loop, so the sums are consistent.
    def _publish_active(self) -> None:
        n = self.arena.n_active
        self._g_replica_active.set(float(n))
        peers = self.peers
        self._g_active.set(float(n) if peers is None
                           else float(sum(e.arena.n_active for e in peers)))

    def _publish_queue_depth(self) -> None:
        peers = self.peers
        n = (len(self._pending) if peers is None
             else sum(len(e._pending) for e in peers))
        self._g_queue_depth.set(float(n))

    # -- page ledger plumbing (event loop; ISSUE 18) --------------------------
    def _release_slot(self, slot: int) -> SlotInfo:
        """EVERY slot-release path funnels through here so the slot's KV
        pages return to the free list the same instant the slot frees —
        retire, evict, disconnect, runaway guard, insert failure alike.
        ``holds`` guards the page half: a slot can fail admission before
        its page-acquire lands (arena.release's SlotCorrupted tripwire
        still catches double-release through this funnel)."""
        if self.pages is not None and self.pages.holds(slot):
            self.pages.release(slot)
            self._update_kv_gauges()
        return self.arena.release(slot)

    def _update_kv_gauges(self) -> None:
        self._g_replica_kv_free.set(float(self.pages.n_free))
        peers = [e for e in (self.peers or [self]) if e.pages is not None]
        usable = sum(e.pages.usable for e in peers)
        self._g_kv_pages_free.set(float(sum(e.pages.n_free for e in peers)))
        self._g_kv_util.set(
            sum(e.pages.n_reserved for e in peers) / usable if usable
            else 0.0)

    def _queued_pages(self) -> int:
        """Pages the already-accepted queue will reserve once admitted
        (the committed-demand term of the admission pressure check)."""
        return sum(r.pages_needed for r in self._pending)

    def _pages_row(self, page_list: "list[int]") -> np.ndarray:
        """One slot's block-table row: its pages in position order, padded
        with the sentinel (page 0) past its reservation."""
        row = np.zeros((self._pps,), np.int32)
        row[:len(page_list)] = page_list
        return row

    def _observe_pages(self, need: int) -> None:
        prev = self._ewma_pages
        self._ewma_pages = (float(need) if prev is None
                            else prev + 0.2 * (need - prev))

    # -- step loop (event loop) -----------------------------------------------
    async def _step_loop(self) -> None:
        name = self.name
        # The loop condition (not just task cancellation) gates each
        # iteration: asyncio.wait_for can swallow a cancel that lands the
        # same tick its inner future completes, and a step loop that
        # survived its own cancellation would leave stop() awaiting it
        # forever. _running goes False before stop() cancels, so either
        # path exits.
        while self._running:
            if self.injector is not None:
                # Chaos: an escaped exception kills this task — exactly the
                # failure revive_group_loops exists to repair.
                self.injector.check("kill_group_loop", name)
            self._expire_pending()
            self._evict_expired()
            if not self.arena.n_active and not self._pending:
                self._maybe_idle()
                self._work_event.clear()
                if not self._pending and not self.arena.n_active:
                    await self._work_event.wait()
                continue
            await self._admit()
            await self._advance_prefills()
            if not self.arena.n_active:
                continue
            try:
                if self.injector is not None:
                    delay = self.injector.delay_s("slow_dispatch", name)
                    if delay > 0:
                        await asyncio.sleep(delay)
                    self.injector.check("batch_error", name)
                t0 = time.perf_counter()
                out = await self.stages.run(name, "fetch", self._step_sync)
                step_ms = (time.perf_counter() - t0) * 1e3
                # Step events per traced slot (ISSUE 12): every mid-flight
                # request's tree shows each iteration it rode, tagged with
                # its slot — bounded by the model's own step cap, and what
                # makes "why was THIS generation slow" answerable span by
                # span. The histogram exemplar samples one rider.
                wall = time.time()
                ex_tid = None
                for s in self.arena.active_slots():
                    info = self.arena.peek(s)
                    if info.ctx is not None:
                        if ex_tid is None:
                            ex_tid = info.ctx.trace_id
                        info.ctx.span("gen_step", wall - step_ms / 1e3,
                                      wall, tid=name, slot=s,
                                      iteration=info.iterations)
                self._h_step.observe(step_ms, trace_id=ex_tid)
                self._observe_step(step_ms)
                self._c_device_seconds.inc(step_ms / 1e3)
                if self.device_time_cb is not None:
                    self.device_time_cb(step_ms / 1e3)
                self._c_iterations.inc()
                self._c_replica_steps.inc()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — contained per batch
                await self._fail_active(e)
                continue
            await self._emit_step_units(out)
            await self._retire(out)

    def _dispatch_guard(self):
        """Context for one device-dispatch section: the group's shared
        CPU-backend lock when installed (see __init__), else a no-op.
        Sync-only sections — no await ever runs under it, so the lock
        witness has nothing to flag."""
        lock = self._dispatch_lock
        return lock if lock is not None else nullcontext()

    def _step_sync(self) -> dict:
        """One compiled iteration over the slot block + the small host
        fetch of the out pytree. Runs on the fetch stage executor."""
        with self._dispatch_guard():
            self._state, out = self.runtime.run_program(
                "step", self._state, replica=self.replica)
            return host_fetch(out)

    def _insert_sync(self, slot: int, item: Any) -> None:
        with self._dispatch_guard():
            self._state = self.runtime.run_program(
                "insert", self._state, np.int32(slot), item,
                replica=self.replica)

    def _prefill_sync(self, slot: int, item: Any, start: int,
                      pages_row: np.ndarray) -> None:
        with self._dispatch_guard():
            self._state = self.runtime.run_program(
                "prefill", self._state, np.int32(slot), item,
                np.int32(start), pages_row, replica=self.replica)

    async def _prefill_advance(self, slot: int, info: SlotInfo) -> None:
        """Fold ONE more prompt chunk for a prefilling slot (runs on the
        h2d stage like a dense insert). The compiled program arms the lane
        for decode on the final chunk; the host cursor here is what tells
        retire/step scheduling the slot is still mid-prefill."""
        start = info.meta["prefill_next"]
        await self.stages.run(self.name, "h2d", self._prefill_sync, slot,
                              info.item, start, info.meta["pages_row"])
        self._c_prefill_chunks.inc()
        nxt = start + self._prefill_chunk
        if nxt >= info.meta["prefill_n"]:
            del info.meta["prefill_next"]  # prefill complete: decode owns it
        else:
            info.meta["prefill_next"] = nxt

    async def _advance_prefills(self) -> None:
        """One chunk per prefilling slot per engine iteration, interleaved
        with decode steps (Orca's iteration-level scheduling applied to
        prefill) — in-flight decoders see a bounded per-iteration stall
        instead of a whole-prompt one."""
        if self.pages is None:
            return
        for slot in self.arena.active_slots():
            info = self.arena.peek(slot)
            if "prefill_next" not in info.meta or info.future.done():
                continue
            try:
                await self._prefill_advance(slot, info)
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — same blast radius as
                # an insert failure: the block may be half-written.
                self._release_slot(slot)
                self._terminate_stream(info.stream, "engine_error", str(e))
                if not info.future.done():
                    info.future.set_exception(e)
                await self._fail_active(e)
                return

    def _extract_sync(self, slot: int) -> Any:
        with self._dispatch_guard():
            return host_fetch(
                self.runtime.run_program("extract", self._state,
                                         np.int32(slot),
                                         replica=self.replica))

    # -- scheduling passes ----------------------------------------------------
    def _expire_pending(self) -> None:
        """Fail queued requests whose deadline passed and drop cancelled
        ones — rejected in microseconds, never admitted (fast-504)."""
        if not self._pending:
            return
        now = time.perf_counter()
        kill_at = self._stream_kill_at
        live: collections.deque[_GenRequest] = collections.deque()
        n_expired = 0
        for req in self._pending:
            if req.future.done():
                if req.stream is not None:
                    req.stream.close()  # consumer already gone
                continue
            if req.deadline_at is not None and now >= req.deadline_at:
                msg = ("deadline expired after "
                       f"{(now - req.enqueued_at) * 1e3:.0f} ms in queue")
                self._terminate_stream(req.stream, "deadline_exceeded", msg)
                req.future.set_exception(DeadlineExceeded(msg))
                n_expired += 1
                continue
            if req.stream is not None and kill_at is not None \
                    and now >= kill_at:
                # Drain's stream budget spent before this one ever started.
                self._terminate_stream(req.stream, "drain",
                                       "server draining; stream budget spent")
                req.future.set_exception(RuntimeError(
                    f"{self.name}: draining; stream budget spent"))
                continue
            live.append(req)
        if n_expired:
            self._c_deadline.inc(n_expired)
        if len(live) != len(self._pending):
            self._pending = live
            self._publish_queue_depth()

    def _evict_expired(self) -> None:
        """Mid-generation deadline eviction: a slot whose request deadline
        passed (or whose client went away) frees NOW — its remaining
        iterations are never computed for nobody (Clockwork P3). The
        freed slot's device lanes hold stale state until the next insert
        overwrites them; their own done-flag freezes them within the
        model's step bound, so the garbage compute is bounded and the
        ledger stays exact."""
        now = time.perf_counter()
        kill_at = self._stream_kill_at
        for slot in self.arena.active_slots():
            info = self.arena.peek(slot)
            if info.future.done():  # client disconnected mid-generation
                if info.stream is not None:
                    self._c_disconnects.inc()
                    self._count_termination("disconnect")
                    info.stream.close()
                self._release_slot(slot)
                continue
            if info.deadline_at is not None and now >= info.deadline_at:
                msg = (f"deadline expired after {info.iterations} "
                       "iteration(s) "
                       f"({(now - info.enqueued_at) * 1e3:.0f} ms total)")
                # Deadline-contract split (ISSUE 17): before the first unit
                # the HTTP layer still answers a plain fast 504; after it,
                # this terminal becomes the in-stream error event naming
                # deadline_exceeded — either way, never a silent cut.
                self._terminate_stream(info.stream, "deadline_exceeded", msg)
                info.future.set_exception(DeadlineExceeded(msg))
                self._c_deadline.inc()
                self._c_evictions.inc()
                if info.ctx is not None:
                    wall = time.time()
                    info.ctx.span("evict", wall, wall, tid=self.name,
                                  slot=slot, iterations=info.iterations)
                self._release_slot(slot)
                continue
            if info.stream is not None and kill_at is not None \
                    and now >= kill_at:
                self._terminate_stream(info.stream, "drain",
                                       "server draining; stream budget spent")
                info.future.set_exception(RuntimeError(
                    f"{self.name}: draining; stream terminated after "
                    f"{info.iterations} iteration(s)"))
                self._c_evictions.inc()
                if info.ctx is not None:
                    wall = time.time()
                    info.ctx.span("evict", wall, wall, tid=self.name,
                                  slot=slot, iterations=info.iterations,
                                  reason="drain")
                self._release_slot(slot)
        self._publish_active()

    async def _admit(self) -> None:
        """Fold queued requests into free slots — mid-flight when the block
        is already generating (the continuous-batching property)."""
        cap = self.gcfg.admit_per_step or self.slots
        admitted = 0
        while self.arena.n_free and self._pending and admitted < cap:
            req = self._pending.popleft()
            self._publish_queue_depth()
            if req.future.done():
                continue
            now = time.perf_counter()
            if req.deadline_at is not None and now >= req.deadline_at:
                msg = ("deadline expired after "
                       f"{(now - req.enqueued_at) * 1e3:.0f} ms in queue")
                self._terminate_stream(req.stream, "deadline_exceeded", msg)
                req.future.set_exception(DeadlineExceeded(msg))
                self._c_deadline.inc()
                continue
            if self.pages is not None \
                    and self.pages.n_free < req.pages_needed:
                # Head-of-line waits for pages to free (strict FIFO —
                # skipping ahead would starve long-context requests); the
                # admission-time pressure check bounds how long.
                self._pending.appendleft(req)
                self._publish_queue_depth()
                break
            fold = any(self.arena.peek(s).iterations > 0
                       for s in self.arena.active_slots())
            info = SlotInfo(item=req.item, future=req.future,
                            deadline_at=req.deadline_at,
                            enqueued_at=req.enqueued_at, admitted_at=now,
                            ctx=req.ctx, stream=req.stream)
            slot = self.arena.acquire(info)
            t0 = now
            try:
                # One protecting try covers the whole held window — page
                # acquire, host bookkeeping, and the compiled insert — so
                # no exception path can leak the slot or its pages
                # (TPS601: ledger escape analysis gates on this).
                if self.pages is not None:
                    page_list = self.pages.acquire(slot, req.pages_needed)
                    self._update_kv_gauges()
                    self._observe_pages(req.pages_needed)
                    n_prompt = self.model.prompt_tokens(req.item)
                    info.meta["pages_row"] = self._pages_row(page_list)
                    info.meta["prefill_n"] = n_prompt
                    info.meta["prefill_next"] = 0
                    info.meta["prefill_chunks"] = \
                        -(-n_prompt // self._prefill_chunk)
                if self.arena.n_active > self.peak_active:
                    self.peak_active = self.arena.n_active
                wait_ms = (now - req.enqueued_at) * 1e3
                trace_id = req.ctx.trace_id if req.ctx is not None else None
                self._h_queue.observe(wait_ms, trace_id=trace_id)
                self._h_qwait[req.priority or self._default_priority].observe(
                    wait_ms, trace_id=trace_id)
                if req.ctx is not None:
                    wall = time.time()
                    req.ctx.span("queue", wall - wait_ms / 1e3, wall,
                                 tid=self.name)
                t0 = time.perf_counter()
                if self.pages is not None:
                    # Paged fold-in is incremental: the FIRST prompt chunk
                    # lands now, later chunks interleave with decode steps
                    # (_advance_prefills) so a long prompt never stalls
                    # the block for one monolithic prefill.
                    await self._prefill_advance(slot, info)
                else:
                    await self.stages.run(self.name, "h2d",
                                          self._insert_sync, slot, req.item)
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001
                # The state block may be half-written (and donated buffers
                # consumed on TPU): hard-reset like a step failure. The
                # admitting request fails with the cause too.
                self._release_slot(slot)
                self._terminate_stream(req.stream, "engine_error", str(e))
                if not req.future.done():
                    req.future.set_exception(e)
                await self._fail_active(e)
                return
            insert_s = time.perf_counter() - t0
            self._h_insert.observe(insert_s * 1e3, trace_id=trace_id)
            if req.ctx is not None:
                # "fold_in" = admitted into an ALREADY-generating block
                # (the continuous-batching property); "admit" = joined a
                # fresh one. Span covers the compiled insert program.
                wall = time.time()
                req.ctx.span("fold_in" if fold else "admit",
                             wall - insert_s, wall, tid=self.name,
                             slot=slot)
            self._c_admitted.inc()
            admitted += 1
            if fold:
                self._c_fold_ins.inc()
        self._publish_active()

    async def _retire(self, out: dict) -> None:
        """Account the iteration and retire every finished slot
        immediately — a short sequence exits the instant its own work is
        done, regardless of what the rest of the block still owes."""
        for slot in self.arena.active_slots():
            self.arena.peek(slot).iterations += 1
        for slot in self.arena.active_slots():
            info = self.arena.peek(slot)
            if info.future.done():
                if info.stream is not None:
                    self._c_disconnects.inc()
                    self._count_termination("disconnect")
                    info.stream.close()
                self._release_slot(slot)
                continue
            # Prefill chunks ride the same iteration counter, so a paged
            # slot's guard stretches by its chunk count.
            guard = self._max_steps_guard + info.meta.get("prefill_chunks", 0)
            if info.iterations > guard:
                msg = (f"{self.name}: slot {slot} exceeded the "
                       f"{guard}-iteration guard without "
                       "reporting done")
                self._terminate_stream(info.stream, "engine_error", msg)
                info.future.set_exception(RuntimeError(msg))
                self._c_batch_errors.inc()
                self._release_slot(slot)
                continue
            if "prefill_next" in info.meta:
                # Mid-prefill: the lane's device done-flag is its FREEZE
                # (interleaved decode steps skip it), not completion.
                continue
            if not self.model.is_finished(out, slot):
                continue
            early = self.arena.n_active > 1 or bool(self._pending)
            trace_id = info.ctx.trace_id if info.ctx is not None else None
            t0 = time.perf_counter()
            try:
                extracted = await self.stages.run(
                    self.name, "fetch", self._extract_sync, slot)
                self._h_extract.observe((time.perf_counter() - t0) * 1e3,
                                        trace_id=trace_id)
                result = await self.stages.run(
                    self.name, "postproc", self.model.finalize, extracted,
                    info.item)
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — contained to this slot
                log.exception("retire failed for %s slot %d", self.name, slot)
                self._c_batch_errors.inc()
                if self.breaker is not None:
                    self.breaker.record_failure()
                self._terminate_stream(info.stream, "engine_error", str(e))
                if not info.future.done():
                    info.future.set_exception(e)
            else:
                if info.stream is not None and not info.stream.terminated:
                    # Terminal burst: the family's final units (sd15's
                    # image, then done with finish reason + usage). The
                    # done unit goes through _terminate_stream so its
                    # delivery is unconditional and the per-reason
                    # counter sees a "done".
                    finals = self.model.stream_final_units(extracted, result)
                    for u in finals[:-1]:
                        await self._emit_unit(info.stream, u)
                    self._terminate_stream(
                        info.stream, "done",
                        unit=finals[-1] if finals else {"type": "done"})
                if not info.future.done():
                    info.future.set_result(result)
                self._c_items.inc()
                units = self.model.result_units(result)
                self._c_units.inc(units)
                self._c_replica_units.inc(units)
                self._observe_retire(info.iterations)
                if early:
                    self._c_early_exits.inc()
                if self.breaker is not None:
                    self.breaker.record_success()
                wall1 = time.time()
                if info.ctx is not None:
                    # Retire event: extract + finalize for this slot, the
                    # tail of the request's step-span stack.
                    info.ctx.span("retire", wall1 - (time.perf_counter() - t0),
                                  wall1, tid=self.name, slot=slot,
                                  iterations=info.iterations)
                self.metrics.tracer.add(
                    f"gen[{info.iterations}it]",
                    wall1 - (time.perf_counter() - info.enqueued_at), wall1,
                    tid=self.name, trace_id=trace_id, slot=slot,
                    iterations=info.iterations)
            self._release_slot(slot)
        self._publish_active()
        self._maybe_idle()

    async def _fail_active(self, e: Exception) -> None:
        """A step/insert failure poisons the whole state block: fail every
        mid-flight request with the cause, free all slots, and reinitialize
        the block to zeros. The step loop and queued requests survive —
        failure is contained to the in-flight generation set."""
        log.exception("generation step failed for %s", self.name)
        self._c_batch_errors.inc()
        if self.breaker is not None:
            self.breaker.record_failure()
        wall = time.time()
        for info in self.arena.release_all():
            self._terminate_stream(info.stream, "engine_error", str(e))
            if not info.future.done():
                info.future.set_exception(e)
            if info.ctx is not None:
                info.ctx.span("engine_failure", wall, wall, tid=self.name,
                              iterations=info.iterations,
                              error=type(e).__name__)
        if self.pages is not None:
            self.pages.release_all()
            self._update_kv_gauges()
        self._state = self._host_zeros(self._state_struct)
        self._publish_active()
        self._maybe_idle()

    # -- staged canary (lifecycle hook; runs in an executor thread) -----------
    def staged_canary_sync(self, staged: list[Any]) -> None:
        """Run a SHORT generation end-to-end against a staged candidate
        tree (params_override) through the real compiled programs, on a
        scratch state block — the live block and the serving loop are
        untouched. Any non-finite output, empty result, or failure to
        finish within the model's step bound rejects the candidate
        (tpuserve.lifecycle wires this in place of the one-shot
        staged-canary path for engine-served models)."""
        model, rt = self.model, self.runtime
        r = self.replica
        item = model.canary_item()
        state = self._host_zeros(self._state_struct)
        with self._dispatch_guard():
            if self.paging:
                row = np.arange(1, self._pps + 1, dtype=np.int32)
                n_prompt = model.prompt_tokens(item)
                start = 0
                while True:
                    state = rt.run_program("prefill", state, np.int32(0),
                                           item, np.int32(start), row,
                                           params_override=staged, replica=r)
                    start += self._prefill_chunk
                    if start >= n_prompt:
                        break
            else:
                state = rt.run_program("insert", state, np.int32(0), item,
                                       params_override=staged, replica=r)
            for _ in range(self._max_steps_guard):
                state, out = rt.run_program("step", state,
                                            params_override=staged, replica=r)
                with allow_transfers():  # deliberate: canary progress read
                    done = bool(np.asarray(out["done"])[0])
                if done:
                    break
            else:
                raise ValueError(
                    f"staged canary did not finish a generation within "
                    f"{self._max_steps_guard} iterations")
            extracted = host_fetch(
                rt.run_program("extract", state, np.int32(0),
                               params_override=staged, replica=r))
        for path, leaf in jax.tree_util.tree_flatten_with_path(extracted)[0]:
            arr = np.asarray(leaf)
            if arr.dtype.kind == "f" and not np.isfinite(arr).all():
                raise ValueError(
                    "staged canary produced non-finite outputs in "
                    f"{jax.tree_util.keystr(path)}")
        if model.finalize(extracted, item) is None:
            raise ValueError("staged canary produced no result")

    # -- introspection --------------------------------------------------------
    def _observe_step(self, ms: float) -> None:
        prev = self._ewma_step_ms
        self._ewma_step_ms = ms if prev is None else prev + 0.2 * (ms - prev)

    def _observe_retire(self, iters: int) -> None:
        prev = self._ewma_iters
        self._ewma_iters = (float(iters) if prev is None
                            else prev + 0.2 * (iters - prev))

    @property
    def pending(self) -> int:
        """Requests accepted but not yet admitted into a slot (the fleet
        scheduler's demand signal)."""
        return len(self._pending)

    def predicted_service_s(self, n_items: int = 1) -> float | None:
        """Predicted seconds for one full generation once admitted:
        iterations-per-request EWMA priced at the step EWMA (the engine's
        counterpart of the batcher's per-bucket duration model). None
        before any retirement."""
        if not self._ewma_step_ms or not self._ewma_iters:
            return None
        return max(1, n_items) * self._ewma_iters * self._ewma_step_ms / 1e3

    def kv_clear_s(self) -> float | None:
        """Page-pressure term (paged mode only): estimated seconds until
        enough pages free for a typical admission — the Retry-After hint
        on a kv_pressure shed and a term FleetScheduler.predict_completion_s
        adds so deadline_unmeetable fires before enqueue. None when paging
        is off or the ledger already covers a typical request with nothing
        queued ahead. The soonest page return is the most-advanced active
        request finishing: one request's EWMA span over the active count
        (uniform-progress assumption, same modeling posture as
        estimate_clear_s)."""
        if self.pages is None:
            return None
        need = self._ewma_pages or 1.0
        if self.pages.n_free >= need and not self._pending:
            return None
        if not self._ewma_step_ms or not self._ewma_iters:
            return None
        per_req_s = self._ewma_iters * self._ewma_step_ms / 1e3
        return per_req_s / max(1, self.arena.n_active)

    def estimate_clear_s(self) -> float | None:
        """Queue-clear estimate (raw, unclamped — same split as the
        batcher's: ``clamp_retry_after_s`` owns the 429 Retry-After hint):
        pending requests
        times the observed iterations-per-request, priced at the step EWMA,
        amortized over the slot width, plus the page-pressure term when
        paging is on. None before any retirement."""
        if not self._pending:
            return None
        if not self._ewma_step_ms or not self._ewma_iters:
            return None
        per_req_s = self._ewma_iters * self._ewma_step_ms / 1e3
        base = len(self._pending) * per_req_s / max(1, self.slots)
        return base + (self.kv_clear_s() or 0.0)

    def pipeline_stats(self) -> dict:
        """The /stats "pipeline" block entry for this model (the engine's
        counterpart of the batcher's; mode "genserve" tells them apart)."""
        per_slot = [
            {"slot": s, "iterations": self.arena.peek(s).iterations}
            for s in self.arena.active_slots()]
        stats = {
            "mode": "genserve",
            "slots": self.slots,
            "active": self.arena.n_active,
            "free": self.arena.n_free,
            "peak_active": self.peak_active,
            "pending": len(self._pending),
            "admitted_total": self.arena.acquires_total,
            "iterations_total": self._c_iterations.value,
            "fold_ins_total": self._c_fold_ins.value,
            "early_exits_total": self._c_early_exits.value,
            "evictions_total": self._c_evictions.value,
            "step_ewma_ms": round(self._ewma_step_ms, 3)
            if self._ewma_step_ms else None,
            "iters_per_request_ewma": round(self._ewma_iters, 2)
            if self._ewma_iters else None,
            "per_slot": per_slot,
        }
        if self.pages is not None:
            stats["kv"] = {
                **self.pages.stats(),
                "prefill_chunk": self._prefill_chunk,
                "prefill_chunks_total": self._c_prefill_chunks.value,
                "queued_pages": self._queued_pages(),
                "kv_bytes": self.kv_cache_bytes(),
            }
        # Per-replica rows (ISSUE 20): one row for a single engine, one per
        # member for a GenEngineGroup (which overrides the aggregate keys
        # above and composes these) — uniform shape either way.
        stats["per_replica"] = [self.replica_row()]
        return stats

    def replica_row(self) -> dict:
        """One engine's row of the /stats genserve ``per_replica`` block:
        slots in use, steps, units, and page-pool occupancy."""
        row = {
            "replica": self.replica,
            "slots": self.slots,
            "active": self.arena.n_active,
            "free": self.arena.n_free,
            "pending": len(self._pending),
            "steps_total": self._c_replica_steps.value,
            "units_total": self._c_replica_units.value,
        }
        if self.pages is not None:
            row["kv"] = self.pages.snapshot()
        return row

    def kv_cache_bytes(self) -> int:
        """Device bytes the KV storage leaves occupy (dense slab k/v or the
        paged pool kp/vp) — the denominator of the bench's fixed-memory
        slot-count comparison."""
        total = 0
        if isinstance(self._state_struct, dict):
            for key in ("k", "v", "kp", "vp"):
                leaf = self._state_struct.get(key)
                if leaf is not None:
                    total += (int(np.prod(leaf.shape))
                              * np.dtype(leaf.dtype).itemsize)
        return total


class GenEngineGroup:
    """Replica-per-chip generation engines over one replica-mode runtime
    (ISSUE 20; AlpaServe P5's parallelism-as-serving-lever applied to the
    generation pillar).

    One :class:`GenEngine` per replica mesh, each owning its own slot
    arena, page ledger, and device state block on its own chip, all
    sharing the runtime's compiled program registry (register_program
    compiles each program once per replica mesh, so `runtime_compiles_
    total` counts chips x programs at startup and 0 forever after — the
    same zero-recompile obligation, now per chip). The group exposes the
    full engine surface (submit/submit_stream/start/stop/drain/
    revive_group_loops/pipeline_stats/staged_canary_sync/scheduler
    predictors), so every downstream consumer — HTTP layer, watchdog,
    lifecycle, fleet scheduler, /stats — composes unchanged.

    Placement is least-loaded: a request goes to the engine with the
    fewest committed items (active slots + queued), ties rotating, so a
    replica pinned by long generations never starves the others. Model-
    level counters are name-keyed singletons shared by every member;
    per-replica truth lives on the {model=,replica=} rows and the
    ``per_replica`` stats block."""

    def __init__(self, model: GenerativeModel, runtime: Any,
                 metrics: Metrics, gcfg: "GenserveConfig | None" = None,
                 breaker: "Any | None" = None,
                 injector: "Any | None" = None,
                 stages: "StageExecutors | None" = None,
                 pipeline_cfg: "PipelineConfig | None" = None) -> None:
        n = int(getattr(runtime, "n_replicas", 1))
        self.model = model
        self.runtime = runtime
        self.metrics = metrics
        self.cfg = model.cfg
        self.gcfg = gcfg or GenserveConfig()
        self.name = model.cfg.name
        self._own_stages = stages is None
        self.stages = stages if stages is not None \
            else StageExecutors(pipeline_cfg or PipelineConfig(), metrics)
        self.engines = [
            GenEngine(model, runtime, metrics, gcfg=self.gcfg,
                      breaker=breaker, injector=injector, stages=self.stages,
                      pipeline_cfg=pipeline_cfg, replica=i)
            for i in range(n)]
        for e in self.engines:
            e.peers = self.engines
        if n > 1 and jax.default_backend() == "cpu":
            # Shared dispatch lock: see GenEngine.__init__ (ISSUE 11's
            # forced-host-device wedge, the replica-engine form).
            lock = new_lock("genserve.cpu_dispatch")
            for e in self.engines:
                e._dispatch_lock = lock
        self._rr = 0

    # -- pass-through configuration (server wiring sets these post-build) -----
    @property
    def injector(self) -> Any:
        return self.engines[0].injector

    @injector.setter
    def injector(self, inj: Any) -> None:
        for e in self.engines:
            e.injector = inj

    @property
    def breaker(self) -> Any:
        return self.engines[0].breaker

    @breaker.setter
    def breaker(self, br: Any) -> None:
        for e in self.engines:
            e.breaker = br

    @property
    def device_time_cb(self) -> Any:
        return self.engines[0].device_time_cb

    @device_time_cb.setter
    def device_time_cb(self, cb: Any) -> None:
        # Every engine feeds the same fleet ledger: the model's device
        # seconds are the sum of its replicas' step time.
        for e in self.engines:
            e.device_time_cb = cb

    # -- aggregates -----------------------------------------------------------
    @property
    def slots(self) -> int:
        return sum(e.slots for e in self.engines)

    @property
    def peak_active(self) -> int:
        return sum(e.peak_active for e in self.engines)

    @property
    def pending(self) -> int:
        return sum(e.pending for e in self.engines)

    @property
    def paging(self) -> bool:
        return self.engines[0].paging

    def kv_cache_bytes(self) -> int:
        return sum(e.kv_cache_bytes() for e in self.engines)

    # -- lifecycle ------------------------------------------------------------
    def compile(self) -> None:
        """First engine registers the programs (compiled once per replica
        mesh) and prewarms every replica; the rest validate geometry
        against the registry and reuse."""
        for e in self.engines:
            e.compile()

    async def start(self) -> None:
        for e in self.engines:
            await e.start()

    async def stop(self) -> None:
        for e in self.engines:
            await e.stop()
        if self._own_stages:
            self.stages.shutdown()

    async def drain(self, deadline: float) -> bool:
        results = await asyncio.gather(
            *(e.drain(deadline) for e in self.engines))
        return all(results)

    def revive_group_loops(self) -> int:
        return sum(e.revive_group_loops() for e in self.engines)

    # -- submission (event loop) ----------------------------------------------
    def _pick(self) -> GenEngine:
        """Least-loaded engine by committed work (active + queued); ties
        rotate a cursor so idle replicas share cold traffic — the engine
        twin of ModelRuntime.pick_replica."""
        n = len(self.engines)
        best, best_load = self.engines[self._rr % n], None
        for k in range(n):
            e = self.engines[(self._rr + k) % n]
            load = e.arena.n_active + len(e._pending)
            if best_load is None or load < best_load:
                best, best_load = e, load
        self._rr = (self._rr + 1) % n
        return best

    def submit(self, item: Any, group: Any = None,
               deadline_at: float | None = None,
               priority: str | None = None,
               ctx: Any = None) -> asyncio.Future:
        return self._pick().submit(item, group=group, deadline_at=deadline_at,
                                   priority=priority, ctx=ctx)

    def submit_stream(self, item: Any, deadline_at: float | None = None,
                      priority: str | None = None,
                      ctx: Any = None) -> "tuple[asyncio.Future, GenStream]":
        return self._pick().submit_stream(item, deadline_at=deadline_at,
                                          priority=priority, ctx=ctx)

    # -- staged canary (lifecycle hook; executor thread) ----------------------
    def staged_canary_sync(self, staged: list[Any]) -> None:
        """Fan the staged canary to EVERY replica engine — each runs the
        short real generation against ITS mesh's staged tree, so a
        candidate that loads clean on replica 0 but broken on replica 3
        is rejected before publish. Failure names the replica (the
        lifecycle surfaces the message through /admin reload errors)."""
        for i, e in enumerate(self.engines):
            try:
                e.staged_canary_sync(staged)
            except Exception as err:
                raise ValueError(
                    f"staged canary failed on replica {i}: {err}") from err

    # -- scheduler surface ----------------------------------------------------
    def predicted_service_s(self, n_items: int = 1) -> float | None:
        vals = [v for e in self.engines
                if (v := e.predicted_service_s(n_items)) is not None]
        return (sum(vals) / len(vals)) if vals else None

    def kv_clear_s(self) -> float | None:
        vals = [v for e in self.engines
                if (v := e.kv_clear_s()) is not None]
        return max(vals) if vals else None

    def estimate_clear_s(self) -> float | None:
        # Replicas drain in parallel: the group clears when its slowest
        # member does.
        vals = [v for e in self.engines
                if (v := e.estimate_clear_s()) is not None]
        return max(vals) if vals else None

    # -- introspection --------------------------------------------------------
    def pipeline_stats(self) -> dict:
        e0 = self.engines[0]
        # Model-level counters are singletons — e0's handles already carry
        # group totals; only the occupancy fields need summing.
        stats = e0.pipeline_stats()
        stats.update(
            replicas=len(self.engines),
            slots=self.slots,
            active=sum(e.arena.n_active for e in self.engines),
            free=sum(e.arena.n_free for e in self.engines),
            peak_active=self.peak_active,
            pending=self.pending,
            admitted_total=sum(e.arena.acquires_total for e in self.engines),
        )
        ewmas = [e._ewma_step_ms for e in self.engines if e._ewma_step_ms]
        stats["step_ewma_ms"] = (round(sum(ewmas) / len(ewmas), 3)
                                 if ewmas else None)
        iters = [e._ewma_iters for e in self.engines if e._ewma_iters]
        stats["iters_per_request_ewma"] = (round(sum(iters) / len(iters), 2)
                                           if iters else None)
        stats["per_slot"] = [
            {"replica": e.replica, "slot": s,
             "iterations": e.arena.peek(s).iterations}
            for e in self.engines for s in e.arena.active_slots()]
        if e0.pages is not None:
            paged = [e for e in self.engines if e.pages is not None]
            usable = sum(e.pages.usable for e in paged)
            reserved = sum(e.pages.n_reserved for e in paged)
            stats["kv"] = {
                "pages": sum(e.pages.pages for e in paged),
                "usable": usable,
                "free": sum(e.pages.n_free for e in paged),
                "reserved": reserved,
                "page_tokens": e0.pages.page_tokens,
                "utilization": round(reserved / usable, 4) if usable else 0.0,
                "acquires_total": sum(e.pages.acquires_total for e in paged),
                "prefill_chunk": e0._prefill_chunk,
                "prefill_chunks_total": e0._c_prefill_chunks.value,
                "queued_pages": sum(e._queued_pages() for e in paged),
                "kv_bytes": self.kv_cache_bytes(),
            }
        stats["per_replica"] = [e.replica_row() for e in self.engines]
        return stats
