"""SlotArena: generative slot bookkeeping for the iteration-level engine.

The device-side state block (KV caches, latent slabs) is one fixed-capacity
pytree allocated at engine start; this arena is its host-side ledger — which
slot indices are free, which request owns each active slot, and how many
iterations it has taken. The invariant the engine (and
tests/test_genserve.py) lean on: a slot is never handed to two requests at
once, and never released by anything that doesn't hold it — a double-hand
would let one request's step output retire (or overwrite) another's state.
Violations raise instead of corrupting, the same posture as the hostpipe
AssemblyArena's free-list.

Event-loop-side only (the engine's step loop owns all mutation), so there is
deliberately no lock to witness.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any


class SlotCorrupted(RuntimeError):
    """The free-list and the active ledger disagree — a double acquire or a
    foreign release. Engine state can no longer be trusted for the slot."""


@dataclass
class SlotInfo:
    """One active slot's host-side request bookkeeping."""

    item: Any
    future: Any  # asyncio.Future of the final result
    deadline_at: float | None = None  # perf_counter clock (fast-504 contract)
    enqueued_at: float = 0.0
    admitted_at: float = 0.0
    iterations: int = 0
    # Request trace context (obs.TraceContext, ISSUE 12): the engine tags
    # this slot's fold-in/step/evict/retire events with its trace id.
    ctx: Any = None
    # Emission channel for a streamed request (engine.GenStream, ISSUE 17);
    # None for unary. Rides the ledger so every release path — retire,
    # evict, disconnect, engine failure — can push the terminal unit.
    stream: Any = None
    meta: dict = field(default_factory=dict)


class SlotArena:
    """Fixed set of generative slots [0, n) with an ownership ledger."""

    def __init__(self, slots: int) -> None:
        self.slots = max(1, int(slots))
        self._free: list[int] = list(range(self.slots - 1, -1, -1))
        self._active: dict[int, SlotInfo] = {}
        # Lifetime hand-out count (monotone; feeds /stats).
        self.acquires_total = 0

    @property
    def n_active(self) -> int:
        return len(self._active)

    @property
    def n_free(self) -> int:
        return len(self._free)

    def active_slots(self) -> list[int]:
        """Active slot indices in admission order (dicts preserve it)."""
        return list(self._active)

    def peek(self, slot: int) -> SlotInfo:
        return self._active[slot]

    def acquire(self, info: SlotInfo) -> int:
        """Hand out a free slot to ``info``; raises SlotCorrupted if the
        free-list offers a slot the ledger says is already owned (the
        double-hand this class exists to make impossible to miss), and
        IndexError when no slot is free (callers gate on n_free)."""
        slot = self._free.pop()
        if slot in self._active:
            self._free.append(slot)
            raise SlotCorrupted(
                f"slot {slot} is on the free-list AND active — double-hand")
        self._active[slot] = info
        self.acquires_total += 1
        return slot

    def release(self, slot: int) -> SlotInfo:
        """Return a slot; raises SlotCorrupted for a slot not held (foreign
        or double release)."""
        info = self._active.pop(slot, None)
        if info is None:
            raise SlotCorrupted(f"release of slot {slot} that is not active")
        self._free.append(slot)
        return info

    def release_all(self) -> list[SlotInfo]:
        """Error-path reset: return every active slot's info (the engine
        fails their futures and reinitializes the device state block)."""
        out = [self.release(s) for s in self.active_slots()]
        return out

    def stats(self) -> dict:
        return {
            "slots": self.slots,
            "active": self.n_active,
            "free": self.n_free,
            "acquires_total": self.acquires_total,
        }
