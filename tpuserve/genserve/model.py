"""GenerativeModel: the contract between generative families and the
iteration-level engine (ISSUE 9; Orca, PAPERS.md P4).

The one-shot ``ServingModel`` contract compiles ``forward`` per batch bucket
and runs each batch to completion — a locked batch. Multi-step generative
work (autoregressive text, diffusion denoising) breaks that shape: requests
need different iteration counts, so a locked batch runs every lane for the
LONGEST member. This contract decomposes generation into the three device
programs the engine (tpuserve.genserve.engine) schedules at iteration
granularity, all compiled ONCE over a fixed slot-capacity state block so
slot churn never recompiles:

- ``init_state(params, item)``  — one request's initial per-slot state
  (prompt prefill / text encode + latent init). The engine composes it with
  a traced dynamic-update into the slot dim, so one compiled "insert"
  program serves every slot index.
- ``step(params, state)``       — ONE model iteration over the whole slot
  block, returning the new state plus a small host-fetchable out pytree
  that must carry ``"done"`` per slot. Inactive/free slots hold benign
  zeros and are stepped along harmlessly (their lanes are ignored).
- ``extract(params, state, slot)`` — the finished slot's device outputs
  (token buffer, VAE-decoded image), fetched ONLY when that slot retires,
  so per-step readback stays small even when results are megabytes.

Host-side, ``is_finished`` reads the step out-block and ``finalize`` turns
one extracted result into the JSON-able / bytes response. Decoded request
items must be pytrees of fixed-shape np arrays carrying EVERY sampling
parameter (seed, temperature, max_new_tokens) — that is what makes
generative results content-addressable: the result cache digests the item,
so two prompts differing only in seed can never alias
(tests/test_genserve.py; ``ModelConfig.cacheable`` opts a family out).
"""

from __future__ import annotations

import abc
import json
from typing import Any

from tpuserve.models.base import ServingModel


class GenerativeModel(ServingModel):
    """A ServingModel that additionally serves through the iteration-level
    engine. Families keep their one-shot ``forward`` (the locked-batch
    path: still used by the static batcher when [genserve] is off, and as
    the bench's baseline), and add the decomposed programs below."""

    # Marker the server keys engine selection on (isinstance would also
    # work; the attribute makes duck-typed test doubles cheap).
    generative = True

    # -- device contract (jittable; compiled once via runtime.register_program)
    @abc.abstractmethod
    def state_signature(self, slots: int) -> Any:
        """Pytree of jax.ShapeDtypeStruct for the whole generative state
        block: every leaf has leading dim ``slots``. Allocated once at
        engine start (zeros) and threaded through step — KV caches, latent
        slabs, token buffers, per-slot counters and done flags all live
        here, so steady-state serving allocates nothing."""

    @abc.abstractmethod
    def gen_item_signature(self) -> Any:
        """Pytree of jax.ShapeDtypeStruct for ONE decoded request item as it
        crosses to the device (no slot dim). Fixed shapes are the contract:
        prompts pad to the prompt bucket, and every sampling parameter rides
        along as a scalar array."""

    @abc.abstractmethod
    def init_state(self, params: Any, item: Any) -> Any:
        """Jittable: one request's initial per-slot state — each leaf shaped
        like the state_signature leaf WITHOUT the slot dim. This is the
        expensive once-per-request work (prompt prefill through the stack,
        text encode, latent init from the seed)."""

    @abc.abstractmethod
    def step(self, params: Any, state: Any) -> tuple[Any, dict]:
        """Jittable: one iteration over all slots -> (new_state, out).
        ``out`` is the small per-step host fetch and must contain
        ``"done"``: (slots,) bool — True once a slot's sequence finished.
        Free slots hold zeros; the step must be NaN-safe on them."""

    @abc.abstractmethod
    def extract(self, params: Any, state: Any, slot: Any) -> Any:
        """Jittable with a TRACED slot index: the finished slot's final
        device outputs (one compile covers every slot). Runs once per
        retirement — put the heavy tail work here (e.g. the VAE decode)."""

    def state_partition_specs(self, struct: Any, mesh: Any) -> Any:
        """PartitionSpec tree (or None = replicate everything) for the
        engine's device state block on a SHARDED mesh (ISSUE 20). Families
        that can split decode across chips override — textgen puts KV
        heads on "model" beside its QKV column shards — and the engine
        threads the result through ``register_program``'s arg/out specs so
        the state block never materializes unsharded. Returning None keeps
        the replicated layout (correct for every family, the default)."""
        return None

    # -- host contract --------------------------------------------------------
    def gen_max_steps(self) -> int:
        """Upper bound on iterations any single request can take (the
        engine's runaway guard and the staged canary's loop bound)."""
        raise NotImplementedError

    def is_finished(self, step_out: dict, slot: int) -> bool:
        """Read one slot's finished flag from the fetched step out-block."""
        return bool(step_out["done"][slot])

    @abc.abstractmethod
    def finalize(self, extracted: Any, item: Any) -> Any:
        """Fetched extract() outputs (+ the original decoded item) -> the
        JSON-able / bytes response. Host-side, runs on the postproc stage."""

    def result_units(self, result: Any) -> float:
        """Headline output units one finished result carries — tokens for
        text, images for diffusion (default 1). Feeds the engine's
        ``gen_units_total`` counter, which is what bench.py's generative
        mode divides by wall time for its tokens/s / images-per-minute
        headline (counting requests would hide mixed output lengths)."""
        return 1.0

    # -- paged KV contract (ISSUE 18) -----------------------------------------
    # Families that answer supports_kv_paging = True swap the dense
    # per-slot state slab for a global pool of fixed-size KV pages plus a
    # per-slot block table, and swap init_state for an incremental
    # prefill_chunk program. The engine keeps the page ledger
    # (tpuserve.genserve.pages.PageLedger) host-side; EVERY page index the
    # compiled programs consume is traced, so one compiled step/prefill
    # serves every page assignment — the same zero-recompile obligation
    # slot indices already carry (runtime.register_program).

    # Opt-in marker; families without paged programs (sd15) keep the
    # dense slab even when [genserve] kv_paging is on.
    supports_kv_paging = False

    def kv_page_signature(self, slots: int, pages: int,
                          page_tokens: int) -> Any:
        """Pytree of jax.ShapeDtypeStruct for the PAGED state block: the
        global page pool (leading dim ``pages``), the per-slot block table
        of page indices, and the same per-slot scalar lanes the dense
        signature carries. Page 0 is the write-sink sentinel — free/done
        lanes scribble there, live lanes never attend through it."""
        raise NotImplementedError

    def kv_pages_per_slot(self, page_tokens: int) -> int:
        """Host-side: block-table width — pages covering one slot's
        worst-case context (ceil(max_ctx / page_tokens))."""
        raise NotImplementedError

    def pages_needed(self, item: Any, page_tokens: int) -> int:
        """Host-side: pages this request reserves at fold-in — its prompt
        PLUS its full decode budget, so an admitted sequence can never hit
        mid-decode page exhaustion (budgeted admission, Clockwork P3)."""
        raise NotImplementedError

    def prompt_tokens(self, item: Any) -> int:
        """Host-side: real (unpadded) prompt length of one decoded item —
        the engine's chunked-prefill cursor bound."""
        raise NotImplementedError

    def kv_prefill_chunk(self, requested: int) -> int:
        """Host-side: the static chunk width the compiled prefill program
        is built with, given the [genserve] prefill_chunk knob (0 = whole
        prompt in one chunk)."""
        raise NotImplementedError

    def prefill_chunk(self, params: Any, state: Any, slot: Any, item: Any,
                      start: Any, pages: Any, *, chunk: int) -> Any:
        """Jittable with TRACED slot/start/page indices, STATIC chunk
        width: fold tokens [start, start+chunk) of one prompt into the
        slot's pages and return the new state. The final chunk (start +
        chunk >= prompt length) also samples the first token and arms the
        lane for decode; earlier chunks leave the lane frozen
        (done=True) so interleaved decode steps skip it."""
        raise NotImplementedError

    # -- streaming contract (ISSUE 17) ----------------------------------------
    # The engine calls stream_units after EVERY fetched iteration for each
    # slot with an attached stream, and stream_final_units once at retire;
    # the HTTP layer encodes each unit with encode_stream_unit under
    # stream_content_type. Units are plain dicts with a "type" key; a unit
    # carrying "droppable": True may be discarded under the model's
    # stream_policy = "drop" when the client reads slowly (progress and
    # previews are droppable, tokens and terminals never are).

    def stream_units(self, step_out: dict, slot: int, stream: dict) -> list:
        """Newly produced stream units for one slot after one iteration.
        ``stream`` is a per-request mutable dict the model keeps its
        incremental emission state in (e.g. tokens already sent). The
        default streams nothing per iteration (the terminal burst from
        stream_final_units still makes the stream well-formed)."""
        return []

    def stream_wants_preview(self, step_out: dict, slot: int,
                             stream: dict) -> bool:
        """Side-effect-free: should the engine run the (already compiled)
        extract program for this slot NOW to build a mid-flight preview
        unit? Families that answer True pay one extract per preview but
        never a new compile — the program is the same one retirement uses
        (the zero-recompile obligation the stream drill gates on)."""
        return False

    def stream_preview_unit(self, extracted: Any, stream: dict) -> dict:
        """Fetched extract() outputs -> one droppable preview unit (and the
        model's chance to note in ``stream`` when it last previewed)."""
        return {"type": "preview", "droppable": True}

    def stream_final_units(self, extracted: Any, result: Any) -> list:
        """Terminal burst for one retired slot, ending in the ``done``
        event every complete stream MUST carry (clients distinguish
        complete from torn by the terminal alone)."""
        return [{"type": "done",
                 "finish_reason": self.stream_finish_reason(result),
                 "usage": self.stream_usage(result)}]

    def stream_finish_reason(self, result: Any) -> str:
        """Why generation ended: "stop" (natural EOS) or "length" (cap)."""
        return "stop"

    def stream_usage(self, result: Any) -> dict:
        """The usage block on the terminal ``done`` event."""
        return {"units": self.result_units(result)}

    def stream_content_type(self) -> str:
        """Wire format for streamed responses: SSE by default; binary
        families (sd15 previews) answer ``frame.CONTENT_TYPE`` instead."""
        return "text/event-stream"

    def encode_stream_unit(self, unit: dict) -> bytes:
        """One unit -> wire bytes under stream_content_type. The SSE
        default renders ``event: <type>`` + a JSON data line; every key
        except "type" (and the droppable marker) rides in the data."""
        data = {k: v for k, v in unit.items()
                if k not in ("type", "droppable")}
        return (f"event: {unit['type']}\n"
                f"data: {json.dumps(data)}\n\n").encode("utf-8")

    def stream_heartbeat(self) -> bytes:
        """Idle-gap keepalive bytes (an SSE comment by default); empty
        bytes disable heartbeats for the family."""
        return b": hb\n\n"
