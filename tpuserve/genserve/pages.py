"""PageLedger: host-side KV page bookkeeping for the paged generation
engine (ISSUE 18; PagedAttention / vLLM, PAPERS.md).

The paged device state holds one global pool of fixed-size KV pages —
``(pages, layers, page_tokens, heads, head_dim)`` — plus a per-slot block
table of page indices. This ledger is the pool's host-side truth: which
pages are free, which slot owns each handed-out page. Same posture as
SlotArena: a page is never double-handed, and a release by anything that
doesn't hold the page raises instead of corrupting — a double-hand would
let one request's decode writes land inside another request's context.

Page 0 is the SENTINEL and is never handed out. The compiled decode step
redirects writes for finished/free lanes to page 0 (their block-table rows
are zeros), so a retired slot can never scribble into pages the ledger has
already re-handed to a new request. The sentinel's contents are garbage by
design; no live lane ever attends through it.

Event-loop-side only (the engine's step loop owns all mutation), so there
is deliberately no lock to witness.
"""

from __future__ import annotations


class PageCorrupted(RuntimeError):
    """The free-list and the ownership ledger disagree — a double acquire
    or a foreign release. The paged KV pool can no longer be trusted."""


class PageLedger:
    """Fixed pool of KV pages [1, pages) with an ownership ledger.

    ``pages`` counts the sentinel: a ledger built with ``pages=N`` hands
    out at most ``N - 1`` (its ``usable``) real pages, indices 1..N-1.
    The engine reserves a request's FULL page need (prompt + decode
    budget) at fold-in, so a admitted sequence can never hit mid-decode
    page exhaustion — admission is where pressure is applied (Clockwork's
    budgeted-admission frame, PAPERS.md P3).
    """

    SENTINEL = 0

    def __init__(self, pages: int, page_tokens: int) -> None:
        if int(pages) < 2:
            raise ValueError("PageLedger needs >= 2 pages (sentinel + 1)")
        if int(page_tokens) < 1:
            raise ValueError("page_tokens must be >= 1")
        self.pages = int(pages)
        self.page_tokens = int(page_tokens)
        # LIFO free-list, popping from the low end first (1, 2, ...).
        self._free: list[int] = list(range(self.pages - 1, 0, -1))
        self._owned: dict[int, list[int]] = {}   # slot -> its pages
        self._owner: dict[int, int] = {}         # page -> owning slot
        # Lifetime hand-out count (monotone; feeds /stats).
        self.acquires_total = 0

    @property
    def usable(self) -> int:
        """Allocatable pages (total minus the sentinel)."""
        return self.pages - 1

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def n_reserved(self) -> int:
        return len(self._owner)

    def utilization(self) -> float:
        """Reserved fraction of the usable pool in [0, 1]."""
        return self.n_reserved / self.usable if self.usable else 0.0

    def pages_of(self, slot: int) -> list[int]:
        return list(self._owned.get(slot, ()))

    def holds(self, slot: int) -> bool:
        """Whether ``slot`` currently owns pages. The engine's release
        funnel checks this so a slot whose page-acquire itself failed
        mid-admit can still return to the arena without tripping the
        PageCorrupted double-release tripwire."""
        return slot in self._owned

    def acquire(self, slot: int, count: int) -> list[int]:
        """Hand ``count`` free pages to ``slot``; raises PageCorrupted if
        the free-list offers a page the ledger says is already owned, or
        if the slot already holds pages (one reservation per slot
        lifetime), and IndexError when the pool can't cover the count
        (callers gate on n_free)."""
        count = int(count)
        if count < 1:
            raise ValueError("acquire needs count >= 1")
        if slot in self._owned:
            raise PageCorrupted(
                f"slot {slot} already holds pages — double reservation")
        if count > len(self._free):
            raise IndexError(
                f"page pool exhausted: need {count}, free {len(self._free)}")
        out: list[int] = []
        for _ in range(count):
            page = self._free.pop()
            if page in self._owner or page == self.SENTINEL:
                self._free.append(page)
                raise PageCorrupted(
                    f"page {page} is on the free-list AND owned — double-hand")
            self._owner[page] = slot
            out.append(page)
        self._owned[slot] = out
        self.acquires_total += count
        return out

    def release(self, slot: int) -> list[int]:
        """Return ALL of a slot's pages to the free list; raises
        PageCorrupted for a slot holding nothing (foreign or double
        release) or for a page whose owner record disagrees."""
        pages = self._owned.pop(slot, None)
        if pages is None:
            raise PageCorrupted(
                f"release of slot {slot} that holds no pages")
        for page in pages:
            owner = self._owner.pop(page, None)
            if owner != slot:
                raise PageCorrupted(
                    f"page {page} owner ledger says {owner}, released by "
                    f"slot {slot}")
            self._free.append(page)
        return pages

    def release_all(self) -> int:
        """Error-path reset: free every reserved page (the engine
        reinitializes the device state block alongside)."""
        n = 0
        for slot in list(self._owned):
            n += len(self.release(slot))
        return n

    def snapshot(self) -> dict:
        """Compact live-occupancy row for the per-replica /stats block
        (ISSUE 20) — just the pool's current fill, not the full stats()
        geometry dump."""
        return {
            "free": self.n_free,
            "reserved": self.n_reserved,
            "usable": self.usable,
            "utilization": round(self.utilization(), 4),
        }

    def stats(self) -> dict:
        return {
            "pages": self.pages,
            "usable": self.usable,
            "free": self.n_free,
            "reserved": self.n_reserved,
            "page_tokens": self.page_tokens,
            "utilization": round(self.utilization(), 4),
            "acquires_total": self.acquires_total,
        }
