"""Model weight import/export (SURVEY.md §2 C6; §5 checkpoint/resume).

The reference persists models as TF SavedModels executed by TF-GPU. The
TPU-native build separates *weights* from *graphs*: graphs are always our own
Flax modules (tpuserve.models), and this module moves weights between three
formats:

- **orbax checkpoint dir** — the native format. Fast, sharding-aware,
  TF-free startup. Produced by ``python -m tpuserve import-model`` or
  ``save_orbax``.
- **TF SavedModel dir** (``saved_model.pb`` + ``variables/``) — read via
  ``tf.saved_model.load`` on CPU; variables are extracted to a flat
  ``name -> np.ndarray`` dict and handed to the model family's
  ``import_tf_variables`` for name/layout translation (NHWC vs NCHW, fused
  BN, etc.). TF import is lazy: serving from orbax never imports TF.
- **frozen GraphDef ``.pb``** — 2016-era repos ship these; constants are
  extracted from the graph nodes into the same flat dict.

Detection is by directory shape, so ``ModelConfig.weights`` is just a path.
Golden-output parity between the TF graph and our Flax path is asserted in
tests (SURVEY.md §4-4), not here.
"""

from __future__ import annotations

import logging
import os
from typing import Any

import jax
import numpy as np

log = logging.getLogger("tpuserve.savedmodel")


# -- format detection --------------------------------------------------------

def detect_format(path: str) -> str:
    """'orbax' | 'saved_model' | 'graphdef'."""
    if os.path.isdir(path):
        if os.path.exists(os.path.join(path, "saved_model.pb")):
            return "saved_model"
        return "orbax"
    if path.endswith(".pb"):
        return "graphdef"
    raise ValueError(f"cannot identify weight format of {path!r}")


def load_params_for(model) -> Any:
    """Entry point used by ServingModel.load_params when cfg.weights is set."""
    path = model.cfg.weights
    fmt = detect_format(path)
    log.info("loading %s weights for %s from %s", fmt, model.name, path)
    if fmt == "orbax":
        return load_orbax(path, model)
    flat = (
        extract_saved_model_variables(path)
        if fmt == "saved_model"
        else extract_graphdef_constants(path)
    )
    return model.import_tf_variables(flat)


# -- orbax native checkpoints ------------------------------------------------

def save_orbax(path: str, params: Any) -> None:
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(os.path.abspath(path), jax.device_get(params))
        ckptr.wait_until_finished()


def load_orbax(path: str, model) -> Any:
    """Restore with the model's own param structure as the abstract target."""
    import orbax.checkpoint as ocp

    target = jax.eval_shape(model.init_params, jax.random.key(0))
    # Restore as host numpy; the runtime device_puts with shardings itself.
    target = jax.tree_util.tree_map(
        lambda s: ocp.utils.to_shape_dtype_struct(s) if hasattr(ocp, "utils") else s, target
    )
    with ocp.StandardCheckpointer() as ckptr:
        return ckptr.restore(os.path.abspath(path), target)


# -- TF weight extraction (lazy TF import) -----------------------------------

_CKPT_SUFFIX = "/.ATTRIBUTES/VARIABLE_VALUE"


def extract_saved_model_variables(path: str) -> dict[str, np.ndarray]:
    """Flat {name: np.ndarray} from a TF2 SavedModel.

    Prefers the loaded object's ``variables`` collection, whose names are the
    semantic layer paths (``conv1_conv/kernel``) that family
    ``import_tf_variables`` mappings are written against; the ``:0`` tensor
    suffix is stripped. Falls back to reading the ``variables/`` checkpoint
    shards directly (object-graph paths like ``layer_with_weights-0/kernel``)
    for SavedModels whose root object exposes no variables.
    """
    import tensorflow as tf  # lazy: only on import paths

    out: dict[str, np.ndarray] = {}
    try:
        loaded = tf.saved_model.load(path)
        variables = list(getattr(loaded, "variables", None) or ())
        semantic: dict[str, np.ndarray] = {}
        for v in variables:
            semantic[v.name.split(":")[0]] = np.asarray(v.numpy())
        # Commit only a complete AND collision-free read: a mid-loop failure
        # or duplicate names (legal in TF for subclassed models) must not
        # hand a truncated dict to import_tf_variables when the checkpoint
        # reader below could produce the full set.
        if len(semantic) == len(variables):
            out = semantic
        elif variables:
            log.warning(
                "SavedModel %s has %d variables but only %d unique names; "
                "using checkpoint reader", path, len(variables), len(semantic))
    except Exception:  # noqa: BLE001 — fall through to the checkpoint reader
        log.warning("tf.saved_model.load failed for %s; using checkpoint reader", path)
    if out:
        return out

    reader = tf.train.load_checkpoint(os.path.join(path, "variables", "variables"))
    for key in reader.get_variable_to_shape_map():
        name = key[: -len(_CKPT_SUFFIX)] if key.endswith(_CKPT_SUFFIX) else key
        if name.startswith("_CHECKPOINTABLE_OBJECT_GRAPH") or "OBJECT_CONFIG" in name:
            continue
        out[name] = reader.get_tensor(key)
    if not out:
        raise ValueError(f"SavedModel at {path!r} exposes no variables")
    return out


def extract_graphdef_constants(path: str) -> dict[str, np.ndarray]:
    """Flat {node_name: np.ndarray} of Const nodes from a frozen GraphDef."""
    import tensorflow as tf

    gd = tf.compat.v1.GraphDef()
    with open(path, "rb") as f:
        gd.ParseFromString(f.read())
    out: dict[str, np.ndarray] = {}
    for node in gd.node:
        if node.op == "Const":
            t = node.attr["value"].tensor
            out[node.name] = np.array(tf.make_ndarray(t))
    if not out:
        raise ValueError(f"GraphDef at {path!r} has no Const nodes")
    return out


# -- CLI ---------------------------------------------------------------------

def convert_cli(saved_model_path: str, family: str, out_path: str,
                options: dict | None = None) -> None:
    """SavedModel/GraphDef -> orbax, so serving startup never needs TF.

    ``options`` configures the family for the import — keys naming
    ModelConfig fields (e.g. num_classes, dtype, seq_buckets) set those
    fields; everything else lands in ModelConfig.options (e.g. BERT's
    vocab_file / layer sizes). The import must match the artifact."""
    import dataclasses

    from tpuserve.config import ModelConfig
    from tpuserve import models as modelzoo

    opts = dict(options or {})
    reserved = {"name", "family", "weights", "options"}
    bad = reserved & set(opts)
    if bad:
        raise ValueError(f"--opt cannot set {sorted(bad)}; use the dedicated "
                         "CLI flags instead")
    settable = {f.name for f in dataclasses.fields(ModelConfig)} - reserved
    fields = {k: opts.pop(k) for k in list(opts) if k in settable}
    cfg = ModelConfig(name=family, family=family, weights=saved_model_path,
                      options=opts, **fields)
    model = modelzoo.build(cfg)
    params = load_params_for(model)
    save_orbax(out_path, params)
    log.info("wrote orbax checkpoint to %s", out_path)
    print(f"converted {saved_model_path} -> {out_path}")
