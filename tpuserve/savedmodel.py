"""Model weight import/export (SURVEY.md §2 C6; §5 checkpoint/resume).

The reference persists models as TF SavedModels executed by TF-GPU. The
TPU-native build separates *weights* from *graphs*: graphs are always our own
Flax modules (tpuserve.models), and this module moves weights between three
formats:

- **orbax checkpoint dir** — the native format. Fast, sharding-aware,
  TF-free startup. Produced by ``python -m tpuserve import-model`` or
  ``save_orbax``.
- **TF SavedModel dir** (``saved_model.pb`` + ``variables/``) — read via
  ``tf.saved_model.load`` on CPU; variables are extracted to a flat
  ``name -> np.ndarray`` dict and handed to the model family's
  ``import_tf_variables`` for name/layout translation (NHWC vs NCHW, fused
  BN, etc.). TF import is lazy: serving from orbax never imports TF.
- **frozen GraphDef ``.pb``** — 2016-era repos ship these; constants are
  extracted from the graph nodes into the same flat dict.
- **torch checkpoints** (``.safetensors`` / ``.ckpt`` / ``.pt`` / ``.pth`` /
  ``.bin``) — how SD 1.5-class artifacts actually ship (VERDICT r3 missing
  1). Read on CPU (safetensors directly; pickle checkpoints via
  ``torch.load(weights_only=True)`` so untrusted files cannot execute code)
  into the same flat ``name -> np.ndarray`` dict, then handed to the
  family's ``import_torch_variables``.

Detection is by directory shape, so ``ModelConfig.weights`` is just a path.
Golden-output parity between the TF graph and our Flax path is asserted in
tests (SURVEY.md §4-4), not here.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
from typing import Any

import jax
import numpy as np

log = logging.getLogger("tpuserve.savedmodel")


class IntegrityError(ValueError):
    """A checkpoint failed its sidecar checksum manifest (tpuserve.lifecycle:
    the reload path rejects the candidate and the old version keeps serving)."""


# -- format detection --------------------------------------------------------

def detect_format(path: str) -> str:
    """'orbax' | 'saved_model' | 'graphdef' | 'torch'."""
    if os.path.isdir(path):
        if os.path.exists(os.path.join(path, "saved_model.pb")):
            return "saved_model"
        return "orbax"
    if path.endswith(".pb"):
        return "graphdef"
    if path.endswith((".safetensors", ".ckpt", ".pt", ".pth", ".bin")):
        return "torch"
    raise ValueError(f"cannot identify weight format of {path!r}")


def load_params_for(model) -> Any:
    """Entry point used by ServingModel.load_params when cfg.weights is set."""
    path = model.cfg.weights
    fmt = detect_format(path)
    log.info("loading %s weights for %s from %s", fmt, model.name, path)
    if fmt == "orbax":
        return load_orbax(path, model)
    if fmt == "torch":
        try:
            state = extract_torch_state_dict(path)
        except Exception as e:
            if path.endswith(".bin"):
                # '.bin' is only *assumed* torch (pytorch_model.bin is the
                # common case); a GGML/raw-blob .bin fails torch parsing —
                # give the unidentified-format guidance instead of a bare
                # unpickling trace (ADVICE r4).
                raise ValueError(
                    f"cannot identify weight format of {path!r}: tried the "
                    "torch loader for the '.bin' suffix but it failed "
                    f"({type(e).__name__}: {e}); supported formats are orbax "
                    "dirs, TF SavedModel dirs, GraphDef .pb, and torch "
                    ".safetensors/.ckpt/.pt/.pth/.bin"
                ) from e
            raise
        return model.import_torch_variables(state)
    flat = (
        extract_saved_model_variables(path)
        if fmt == "saved_model"
        else extract_graphdef_constants(path)
    )
    return model.import_tf_variables(flat)


# -- sidecar checksum manifest (tpuserve.lifecycle integrity gate) -----------
#
# Written NEXT TO the orbax dir (<path>.manifest.json), never inside it, so
# orbax's own directory layout is untouched. Per-leaf sha256 over
# dtype/shape/raw bytes of the saved host tree; a reload recomputes the
# digests over the restored tree and any mismatch (bit rot, truncated copy,
# a writer racing the reload) rejects the candidate before it can serve.

MANIFEST_ALGO = "sha256"


def manifest_path(ckpt_path: str) -> str:
    return os.path.abspath(ckpt_path).rstrip("/") + ".manifest.json"


def tree_digests(params: Any) -> dict[str, str]:
    """{tree path: sha256 hex} over dtype + shape + raw bytes per leaf."""
    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    out: dict[str, str] = {}
    for path, leaf in flat:
        a = np.asarray(jax.device_get(leaf))
        h = hashlib.sha256()
        h.update(str(a.dtype).encode())
        h.update(repr(tuple(a.shape)).encode())
        h.update(np.ascontiguousarray(a).tobytes())
        out[jax.tree_util.keystr(path)] = h.hexdigest()
    return out


def write_manifest(ckpt_path: str, params: Any) -> str:
    mpath = manifest_path(ckpt_path)
    doc = {"algo": MANIFEST_ALGO, "leaves": tree_digests(params)}
    tmp = mpath + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    os.replace(tmp, mpath)  # atomic: a racing reader never sees a torn file
    return mpath


def verify_manifest_if_present(ckpt_path: str, params: Any,
                               require: bool = False) -> bool:
    """Check ``params`` against the sidecar manifest; raises IntegrityError on
    any mismatch. Returns False when no manifest exists (skipped) — unless
    ``require`` is set, which makes a missing manifest itself a rejection."""
    mpath = manifest_path(ckpt_path)
    if not os.path.exists(mpath):
        if require:
            raise IntegrityError(
                f"no checksum manifest at {mpath!r} and lifecycle."
                "require_manifest is set; re-export the checkpoint with "
                "save_orbax / import-model")
        log.debug("no manifest for %s; integrity check skipped", ckpt_path)
        return False
    with open(mpath, encoding="utf-8") as f:
        doc = json.load(f)
    if doc.get("algo") != MANIFEST_ALGO:
        raise IntegrityError(
            f"manifest {mpath!r} uses unknown algo {doc.get('algo')!r}")
    want: dict[str, str] = doc.get("leaves", {})
    got = tree_digests(params)
    if got != want:
        missing = sorted(set(want) - set(got))
        extra = sorted(set(got) - set(want))
        changed = sorted(k for k in set(want) & set(got) if want[k] != got[k])
        detail = "; ".join(
            f"{label} {paths[:3]}" for label, paths in
            (("missing", missing), ("unexpected", extra), ("corrupt", changed))
            if paths)
        raise IntegrityError(
            f"checkpoint at {ckpt_path!r} fails its checksum manifest "
            f"({detail}); candidate rejected")
    return True


# -- orbax native checkpoints ------------------------------------------------

def save_orbax(path: str, params: Any) -> None:
    import orbax.checkpoint as ocp

    host_params = jax.device_get(params)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(os.path.abspath(path), host_params)
        ckptr.wait_until_finished()
    # Sidecar integrity manifest: the lifecycle reload gate verifies the
    # restored tree against these digests before staging.
    write_manifest(path, host_params)


def load_orbax(path: str, model) -> Any:
    """Restore an orbax checkpoint, raw or int8-quantized.

    The restore target comes from the checkpoint's own metadata (shapes +
    dtypes of the saved tree), so a checkpoint written by ``import-model
    --quantize int8`` — whose eligible leaves are {"q8", "q8_scale"}
    sub-trees — restores exactly as saved with no agreement needed on
    quantization settings. After restore, the tree is validated against the
    model's structure (quantized sub-trees collapse to their weight's
    shape) and a quantized checkpoint without quantize = "int8" set
    produces guidance, not a downstream crash.
    """
    import orbax.checkpoint as ocp

    from tpuserve import quantize as qz

    apath = os.path.abspath(path)
    with ocp.StandardCheckpointer() as ckptr:
        meta = ckptr.metadata(apath)
        # orbax >= 0.9 wraps the tree in .item_metadata; 0.7 returns it raw.
        saved = getattr(meta, "item_metadata", meta)
        target = jax.tree_util.tree_map(
            lambda m: jax.ShapeDtypeStruct(tuple(m.shape), np.dtype(str(m.dtype))),
            saved)
        # Restore as host numpy; the runtime device_puts with shardings.
        restored = ckptr.restore(apath, target)

    if qz.has_quantized_leaves(restored) \
            and getattr(model.cfg, "quantize", None) not in ("int8", "int8c"):
        raise ValueError(
            f"checkpoint at {path!r} holds int8-quantized weights; set "
            "quantize = \"int8\" (weight-only) or \"int8c\" (int8 compute) "
            "on the model to serve it")

    raw = jax.eval_shape(model.init_params, jax.random.key(0))
    shape_of = lambda x: (tuple(x[qz.QKEY].shape) if qz.is_quantized(x)  # noqa: E731
                          else tuple(x.shape))

    def dtype_ok(g, w) -> bool:
        # Exact dtype equality is too strict (bf16 vs f32 checkpoints are
        # both fine — the runtime casts to compute dtype), but a float-slot
        # leaf restored as int (or vice versa) must fail HERE with guidance,
        # not later as a cast surprise or compile error (ADVICE r3).
        # Quantized sub-trees carry their own {q8:int8, q8_scale:float}
        # dtypes by design.
        if qz.is_quantized(g):
            return True
        # jnp.issubdtype, not np: numpy classifies bfloat16 (kind 'V') as
        # non-floating, which would reject legitimate bf16 checkpoints.
        import jax.numpy as jnp

        return (jnp.issubdtype(np.dtype(g.dtype), jnp.floating)
                == jnp.issubdtype(np.dtype(w.dtype), jnp.floating))

    got, got_def = jax.tree_util.tree_flatten_with_path(
        restored, is_leaf=qz.is_quantized)
    want, want_def = jax.tree_util.tree_flatten_with_path(raw)
    if len(got) != len(want) or any(
            gp != wp or shape_of(g) != tuple(w.shape) or not dtype_ok(g, w)
            for (gp, g), (wp, w) in zip(got, want)):
        raise ValueError(
            f"checkpoint at {path!r} does not match {model.name}'s param "
            "structure (tree paths, shapes, or dtype classes differ); pair "
            "the checkpoint with the family/options it was converted with")
    return restored


# -- torch checkpoint extraction (lazy torch import) -------------------------

def extract_torch_state_dict(path: str) -> dict[str, np.ndarray]:
    """Flat {name: np.ndarray} from a torch-ecosystem checkpoint file.

    - ``.safetensors``: read via safetensors (zero pickle exposure).
    - pickle checkpoints (``.ckpt``/``.pt``/``.pth``/``.bin``): read with
      ``torch.load(weights_only=True)`` — tensor data only, no arbitrary
      code execution from untrusted files. LDM-style wrappers that nest the
      weights under a ``state_dict`` key are unwrapped.

    bf16/f16 tensors are widened to f32 on the host (numpy has no bf16);
    the runtime casts to the serving compute dtype at device_put anyway.
    """
    import torch  # lazy: only on torch-import paths

    if path.endswith(".safetensors"):
        from safetensors.torch import load_file

        sd = load_file(path, device="cpu")
    else:
        obj = torch.load(path, map_location="cpu", weights_only=True)
        sd = obj.get("state_dict", obj) if isinstance(obj, dict) else obj
    out: dict[str, np.ndarray] = {}
    for k, v in sd.items():
        if not isinstance(v, torch.Tensor):
            continue  # e.g. LDM checkpoints carry step counters
        if v.dtype in (torch.bfloat16, torch.float16):
            v = v.float()
        out[k] = v.numpy()
    if not out:
        raise ValueError(f"torch checkpoint at {path!r} holds no tensors")
    return out


# -- TF weight extraction (lazy TF import) -----------------------------------

_CKPT_SUFFIX = "/.ATTRIBUTES/VARIABLE_VALUE"


def extract_saved_model_variables(path: str) -> dict[str, np.ndarray]:
    """Flat {name: np.ndarray} from a TF2 SavedModel.

    Prefers the loaded object's ``variables`` collection, whose names are the
    semantic layer paths (``conv1_conv/kernel``) that family
    ``import_tf_variables`` mappings are written against; the ``:0`` tensor
    suffix is stripped. Falls back to reading the ``variables/`` checkpoint
    shards directly (object-graph paths like ``layer_with_weights-0/kernel``)
    for SavedModels whose root object exposes no variables.
    """
    import tensorflow as tf  # lazy: only on import paths

    out: dict[str, np.ndarray] = {}
    try:
        loaded = tf.saved_model.load(path)
        variables = list(getattr(loaded, "variables", None) or ())
        semantic: dict[str, np.ndarray] = {}
        for v in variables:
            semantic[v.name.split(":")[0]] = np.asarray(v.numpy())
        # Commit only a complete AND collision-free read: a mid-loop failure
        # or duplicate names (legal in TF for subclassed models) must not
        # hand a truncated dict to import_tf_variables when the checkpoint
        # reader below could produce the full set.
        if len(semantic) == len(variables):
            out = semantic
        elif variables:
            log.warning(
                "SavedModel %s has %d variables but only %d unique names; "
                "using checkpoint reader", path, len(variables), len(semantic))
    except Exception:  # noqa: BLE001 — fall through to the checkpoint reader
        log.warning("tf.saved_model.load failed for %s; using checkpoint reader", path)
    if out:
        return out

    reader = tf.train.load_checkpoint(os.path.join(path, "variables", "variables"))
    for key in reader.get_variable_to_shape_map():
        name = key[: -len(_CKPT_SUFFIX)] if key.endswith(_CKPT_SUFFIX) else key
        if name.startswith("_CHECKPOINTABLE_OBJECT_GRAPH") or "OBJECT_CONFIG" in name:
            continue
        out[name] = reader.get_tensor(key)
    if not out:
        raise ValueError(f"SavedModel at {path!r} exposes no variables")
    return out


def extract_graphdef_constants(path: str) -> dict[str, np.ndarray]:
    """Flat {node_name: np.ndarray} of Const nodes from a frozen GraphDef."""
    import tensorflow as tf

    gd = tf.compat.v1.GraphDef()
    with open(path, "rb") as f:
        gd.ParseFromString(f.read())
    out: dict[str, np.ndarray] = {}
    for node in gd.node:
        if node.op == "Const":
            t = node.attr["value"].tensor
            out[node.name] = np.array(tf.make_ndarray(t))
    if not out:
        raise ValueError(f"GraphDef at {path!r} has no Const nodes")
    return out


# -- CLI ---------------------------------------------------------------------

def convert_cli(saved_model_path: str, family: str, out_path: str,
                options: dict | None = None, quantize: str | None = None) -> None:
    """SavedModel/GraphDef -> orbax, so serving startup never needs TF.

    ``options`` configures the family for the import — keys naming
    ModelConfig fields (e.g. num_classes, dtype, seq_buckets) set those
    fields; everything else lands in ModelConfig.options (e.g. BERT's
    vocab_file / layer sizes). The import must match the artifact.

    ``quantize="int8"`` writes the weight-only-quantized tree (half the
    checkpoint bytes and startup upload); serve it with quantize = "int8".
    The loader reads the saved structure from checkpoint metadata, so no
    other settings need to agree."""
    import dataclasses

    from tpuserve.config import ModelConfig
    from tpuserve import models as modelzoo

    opts = dict(options or {})
    reserved = {"name", "family", "weights", "options"}
    bad = reserved & set(opts)
    if bad:
        raise ValueError(f"--opt cannot set {sorted(bad)}; use the dedicated "
                         "CLI flags instead")
    settable = {f.name for f in dataclasses.fields(ModelConfig)} - reserved
    fields = {k: opts.pop(k) for k in list(opts) if k in settable}
    cfg = ModelConfig(name=family, family=family, weights=saved_model_path,
                      options=opts, **fields)
    if quantize not in (None, "int8"):
        raise ValueError(f"unknown --quantize mode {quantize!r}")
    model = modelzoo.build(cfg)
    params = load_params_for(model)
    if quantize == "int8":
        from tpuserve import quantize as qz

        params = qz.quantize_tree(jax.device_get(params), cfg.quantize_min_size)
    save_orbax(out_path, params)
    log.info("wrote orbax checkpoint to %s", out_path)
    print(f"converted {saved_model_path} -> {out_path}"
          + (f" ({quantize}-quantized)" if quantize else ""))
