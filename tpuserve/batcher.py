"""Static-shape request batching engine (SURVEY.md §2 C2, §3c).

The reference accumulates requests into dynamic batches; XLA wants static
shapes, so this batcher assembles **padded, bucketed** batches:

- Requests are routed to a *group* (model-defined: e.g. seq-len bucket for
  text; vision models have one group). Each group has its own accumulation
  task and queue.
- A group flushes when the largest batch bucket fills, or when the oldest
  request has waited ``deadline_ms`` (flush-on-deadline), whichever is first.
- The flush picks the smallest configured batch bucket >= the ready count and
  zero-pads up to it; ``host_postprocess`` only reads the valid rows, and
  padded lanes are tested to never perturb real lanes
  (tests/test_runtime.py::test_padding_lanes_do_not_affect_real_lanes).
- Dispatch is pipelined: up to ``max_inflight`` batches are in flight on the
  device at once (assembly, H2D and the blocking D2H fetch run in a
  threadpool; the event loop never blocks), hiding H2D under compute.

Failure containment (SURVEY.md §5): an executable failure fails only that
batch's futures; the group task and server keep serving. Client disconnects
cancel futures, which are dropped at flush time.
"""

from __future__ import annotations

import asyncio
import concurrent.futures as cf
import logging
import time
from dataclasses import dataclass, field
from typing import Any, Hashable

from tpuserve.models.base import ServingModel
from tpuserve.obs import Metrics
from tpuserve.runtime import ModelRuntime

log = logging.getLogger("tpuserve.batcher")


class QueueFull(Exception):
    """Raised by submit() when the model queue is at capacity (-> HTTP 429)."""


@dataclass
class _Request:
    item: Any  # decoded input (np arrays), model-specific
    group: Hashable
    future: asyncio.Future = field(repr=False)
    enqueued_at: float = 0.0  # time.perf_counter()


class ModelBatcher:
    """One batching engine per served model."""

    def __init__(
        self,
        model: ServingModel,
        runtime: "ModelRuntime | Any",
        metrics: Metrics,
        pool: cf.ThreadPoolExecutor,
    ) -> None:
        self.model = model
        self.runtime = runtime
        # Deferred-readback pool (tpuserve.deferred.DeferredPool) instead of
        # an in-process runtime: dispatch awaits epoch readback.
        self.deferred = hasattr(runtime, "run_deferred")
        self.metrics = metrics
        self.pool = pool
        self.cfg = model.cfg
        self._queues: dict[Hashable, asyncio.Queue[_Request]] = {}
        self._tasks: list[asyncio.Task] = []
        self._dispatch_tasks: set[asyncio.Task] = set()
        self._inflight: asyncio.Semaphore | None = None
        self._pending = 0
        self._running = False
        # test-only fault injection hook: callable raised inside dispatch
        self.fault_hook = None

    # -- lifecycle ----------------------------------------------------------
    async def start(self) -> None:
        self._running = True
        self._inflight = asyncio.Semaphore(max(1, self.cfg.max_inflight))

    async def stop(self) -> None:
        """Cancel accumulation, fail queued requests, drain in-flight batches."""
        self._running = False
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except asyncio.CancelledError:
                pass
        self._tasks.clear()
        # Requests still queued (never dispatched) must not hang their
        # clients: fail them explicitly (ADVICE r1: stop() cleared queues
        # without resolving futures).
        err = RuntimeError(f"server shutting down; {self.model.name} not served")
        for q in self._queues.values():
            while not q.empty():
                req = q.get_nowait()
                self._pending -= 1
                if not req.future.done():
                    req.future.set_exception(err)
        self._queues.clear()
        if self._dispatch_tasks:
            await asyncio.gather(*self._dispatch_tasks, return_exceptions=True)

    # -- submission (event loop) --------------------------------------------
    def submit(self, item: Any, group: Hashable = None) -> asyncio.Future:
        """Enqueue one decoded request; returns a Future of its result."""
        if not self._running or self._inflight is None:
            raise RuntimeError(f"batcher for {self.model.name} not started")
        if self._pending >= self.cfg.max_queue:
            self.metrics.counter(f"shed_total{{model={self.model.name}}}").inc()
            raise QueueFull(self.model.name)
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        req = _Request(item=item, group=group, future=fut, enqueued_at=time.perf_counter())
        q = self._queues.get(group)
        if q is None:
            q = self._queues[group] = asyncio.Queue()
            self._tasks.append(loop.create_task(self._group_loop(group, q)))
        q.put_nowait(req)
        self._pending += 1
        self.metrics.gauge(f"queue_depth{{model={self.model.name}}}").set(self._pending)
        return fut

    # -- accumulation (event loop) ------------------------------------------
    async def _group_loop(self, group: Hashable, q: asyncio.Queue) -> None:
        max_bucket = max(self.cfg.batch_buckets)
        deadline_s = self.cfg.deadline_ms / 1e3
        while True:
            req = await q.get()
            batch = [req]
            try:
                flush_at = req.enqueued_at + deadline_s
                while len(batch) < max_bucket:
                    timeout = flush_at - time.perf_counter()
                    if timeout <= 0:
                        break
                    try:
                        batch.append(await asyncio.wait_for(q.get(), timeout))
                    except asyncio.TimeoutError:
                        break
                # Backpressure: the semaphore bounds in-flight device batches;
                # the group task itself waits here, which pipelines dispatch.
                await self._inflight.acquire()
            except asyncio.CancelledError:
                # stop() cancelled us mid-accumulation: requests already
                # pulled off the queue must fail, not hang their clients.
                err = RuntimeError(
                    f"server shutting down; {self.model.name} not served")
                self._pending -= len(batch)
                for r in batch:
                    if not r.future.done():
                        r.future.set_exception(err)
                raise
            # Adaptive drain: anything that queued while we waited (deadline or
            # a free slot) would only wait longer — fold it into this batch up
            # to the largest bucket. This makes batch size track device speed
            # instead of deadline x arrival-rate (SURVEY.md §7 hard-part 2).
            while len(batch) < max_bucket and not q.empty():
                batch.append(q.get_nowait())
            self._pending -= len(batch)
            self.metrics.gauge(f"queue_depth{{model={self.model.name}}}").set(self._pending)
            live = [r for r in batch if not r.future.cancelled()]
            if not live:
                self._inflight.release()
                continue
            now = time.perf_counter()
            for r in live:
                self.metrics.observe_phase(self.model.name, "queue", (now - r.enqueued_at) * 1e3)
            task = asyncio.get_running_loop().create_task(self._dispatch(live, group))
            self._dispatch_tasks.add(task)
            task.add_done_callback(self._dispatch_tasks.discard)

    # -- dispatch (threadpool does the blocking work) ------------------------
    async def _dispatch(self, reqs: list[_Request], group: Hashable) -> None:
        loop = asyncio.get_running_loop()
        name = self.model.name
        sem_released = False
        try:
            bucket = self.model.bucket_for(len(reqs), group=group)
            fill = len(reqs) / bucket[0]
            self.metrics.gauge(f"batch_fill_ratio{{model={name}}}").set(fill)
            self.metrics.counter(f"batches_total{{model={name}}}").inc()

            t0 = time.perf_counter()
            items = [r.item for r in reqs]
            host_batch = await loop.run_in_executor(
                self.pool, self.model.assemble, items, bucket
            )
            t1 = time.perf_counter()
            self.metrics.observe_phase(name, "preproc", (t1 - t0) * 1e3)

            if self.fault_hook is not None:
                self.fault_hook()

            if self.deferred:
                # Deferred mode: enqueue is cheap (shm write + slot wait = the
                # backpressure), so the inflight semaphore is released as soon
                # as the batch is on its worker; the await then spans the rest
                # of the owning worker's epoch + bulk readback, which is what
                # "compute" measures in this mode by design.
                out_fut = await self.runtime.enqueue(bucket, host_batch)
                t2 = time.perf_counter()
                self.metrics.observe_phase(name, "h2d", (t2 - t1) * 1e3)
                self._inflight.release()
                sem_released = True
                np_out = await out_fut
                t3 = time.perf_counter()
                self.metrics.observe_phase(name, "compute", (t3 - t2) * 1e3)
            else:
                outputs = await loop.run_in_executor(self.pool, self.runtime.run, bucket, host_batch)
                t2 = time.perf_counter()
                self.metrics.observe_phase(name, "h2d", (t2 - t1) * 1e3)

                # "compute" = dispatch-to-ready wall time. With pipelined
                # dispatch that includes waiting behind the other in-flight
                # batches' transfers, so on a transfer-bound link this phase
                # absorbs the wire wait (BASELINE.md "Link physics"), not
                # just MXU time.
                np_out = await loop.run_in_executor(self.pool, self.runtime.fetch, outputs)
                t3 = time.perf_counter()
                self.metrics.observe_phase(name, "compute", (t3 - t2) * 1e3)

            results = self.model.host_postprocess(np_out, len(reqs))
            t4 = time.perf_counter()
            self.metrics.observe_phase(name, "postproc", (t4 - t3) * 1e3)
            self.metrics.counter(f"items_total{{model={name}}}").inc(len(reqs))
            self.metrics.tracer.add(
                f"batch[{bucket}]", time.time() - (t4 - t0), time.time(),
                tid=name, n=len(reqs), fill=fill,
            )
            for r, res in zip(reqs, results):
                if not r.future.done():
                    r.future.set_result(res)
        except Exception as e:  # contain: fail only this batch
            log.exception("batch dispatch failed for %s", name)
            self.metrics.counter(f"batch_errors_total{{model={name}}}").inc()
            for r in reqs:
                if not r.future.done():
                    r.future.set_exception(e)
        finally:
            if not sem_released:
                self._inflight.release()
