"""Static-shape request batching engine (SURVEY.md §2 C2, §3c).

The reference accumulates requests into dynamic batches; XLA wants static
shapes, so this batcher assembles **padded, bucketed** batches:

- Requests are routed to a *group* (model-defined: e.g. seq-len bucket for
  text; vision models have one group). Each group has its own accumulation
  task and queue.
- A group flushes when the largest batch bucket fills, or when the oldest
  request has waited ``deadline_ms`` (flush-on-deadline), whichever is first.
- The flush picks the smallest configured batch bucket >= the ready count and
  zero-pads up to it; ``host_postprocess`` only reads the valid rows, and
  padded lanes are tested to never perturb real lanes
  (tests/test_runtime.py::test_padding_lanes_do_not_affect_real_lanes).

Dispatch is a **staged pipeline** (ISSUE 3; docs/PERFORMANCE.md): instead of
one shared threadpool running assemble -> device_put -> blocking fetch
sequentially per batch, each stage has its own executor
(tpuserve.hostpipe.StageExecutors) so consecutive batches occupy different
stages concurrently — batch N+1 assembles and transfers while batch N
computes. Assembly writes into preallocated per-bucket arena buffers
(AssemblyArena) recycled through a free-list instead of np.stack-allocating
per batch, and a depth-k staging-slot pool per replica (SlotPool) bounds how
many batches occupy the device section [h2d..fetch] at once. Admission into
the pipeline (depth x replicas + assemble_ahead batches) replaces the old
single semaphore acquired before assembly even started.

Flush scheduling is **SLO-aware and adaptive** (ISSUE 5; docs/PERFORMANCE.md
"Adaptive batching"): instead of always accumulating toward the largest
bucket under a fixed max-wait timer, each group keeps an AIMD-adjusted
*target batch size* (Clipper, PAPERS.md P1) — a batch that fills to target
with work still queued grows it additively, a timer-driven partial flush
shrinks it multiplicatively — so light load converges to target 1 (flush immediately,
no deadline_ms wait) while sustained load converges to the bucket
(throughput). A per-bucket EWMA of observed batch duration (Clockwork, P3:
inference duration is predictable) bounds the wait further: a batch whose
earliest member deadline leaves less than EWMA + slack of headroom flushes
NOW rather than discovering the deadline at dispatch. ``deadline_ms``
remains the max-wait backstop, and ``[adaptive] enabled = false`` restores
the fixed-timer behavior exactly.

Failure containment (SURVEY.md §5, docs/ROBUSTNESS.md): a failed dispatch
first re-assembles and re-runs the batch once (``batch_retry``); if the
retry also fails the batch recursively bisects (``retry_split``) so a single
poison item fails only its own future while the other lanes succeed. Only
then do futures carry the error. Dispatch outcomes feed the per-model
circuit breaker, an optional FaultInjector supplies deterministic chaos at
the dispatch call sites, and dead group tasks are revived by the server
watchdog (``revive_group_loops``). Client disconnects cancel futures, which
are dropped at flush time. Requests carrying a per-request deadline
(``timeout_ms``) that expires while queued fail fast with DeadlineExceeded
at flush time or while waiting for admission/staging capacity — rejected in
microseconds, not computed for nobody (P3).
"""

from __future__ import annotations

import asyncio
import concurrent.futures as cf
import logging
import math
import time
from dataclasses import dataclass, field
from typing import Any, Hashable

from tpuserve.config import AdaptiveConfig, PipelineConfig
from tpuserve.hostpipe import AssemblyArena, SlotPool, StageExecutors
from tpuserve.models.base import ServingModel
from tpuserve.obs import PHASES, PRIORITIES, Counter, Metrics
from tpuserve.runtime import ModelRuntime

log = logging.getLogger("tpuserve.batcher")


class QueueFull(Exception):
    """Raised by submit() when the model queue is at capacity (-> HTTP 429)."""


def clamp_retry_after_s(est: "float | None") -> "int | None":
    """The [1, 30] s Retry-After hint derived from a raw queue-clear
    estimate. Deliberately split from ``estimate_clear_s`` (ISSUE 10
    satellite): the clamp is a client-facing hint policy, not a property of
    the estimate — the fleet scheduler's admission math needs the RAW
    number (clamping a 90 s backlog to 30 s would admit work that provably
    cannot meet a 45 s deadline)."""
    if est is None:
        return None
    return max(1, min(30, math.ceil(est)))


class DeadlineExceeded(Exception):
    """A request's absolute deadline expired while it was still queued
    (-> fast HTTP 504). Clockwork discipline (PAPERS.md P3): work nobody is
    waiting for is rejected before dispatch, not computed and discarded."""


@dataclass
class _Request:
    item: Any  # decoded input (np arrays), model-specific
    group: Hashable
    future: asyncio.Future = field(repr=False)
    enqueued_at: float = 0.0  # time.perf_counter()
    # Absolute per-request deadline (perf_counter clock), stamped at
    # admission from the client's timeout_ms; None = model default only.
    deadline_at: float | None = None
    # Priority class ("interactive"/"batch"; obs.PRIORITIES) resolved at
    # admission from X-Priority or the model default; None = unscheduled.
    priority: str | None = None
    # Request trace context (obs.TraceContext, ISSUE 12): the batcher
    # appends per-request queue + phase spans (tagged with the batch id)
    # to it; None when the caller doesn't trace (tests, embedding).
    ctx: Any = None


class ModelBatcher:
    """One batching engine per served model."""

    def __init__(
        self,
        model: ServingModel,
        runtime: "ModelRuntime | Any",
        metrics: Metrics,
        pool: cf.ThreadPoolExecutor,
        breaker: "Any | None" = None,
        injector: "Any | None" = None,
        stages: "StageExecutors | None" = None,
        pipeline_cfg: "PipelineConfig | None" = None,
        adaptive_cfg: "AdaptiveConfig | None" = None,
    ) -> None:
        self.model = model
        self.runtime = runtime
        # Deferred-readback pool (tpuserve.deferred.DeferredPool) instead of
        # an in-process runtime: dispatch awaits epoch readback.
        self.deferred = hasattr(runtime, "run_deferred")
        self.metrics = metrics
        # Legacy shared pool (the server's decode pool). The hot path no
        # longer runs on it — stage executors own assemble/h2d/fetch/postproc
        # — but the argument stays for API stability with callers/tests.
        self.pool = pool
        self.cfg = model.cfg
        self.pipeline_cfg = pipeline_cfg or PipelineConfig()
        self.adaptive_cfg = adaptive_cfg or AdaptiveConfig()
        # Adaptive scheduler state (event loop only): AIMD target batch size
        # per group, batch-duration EWMA per bucket key.
        self._targets: dict[Hashable, float] = {}
        self._ewma_ms: dict[tuple, float] = {}
        # Hot-path metric handles, prebound once (ISSUE 5 satellite: the
        # per-request/per-flush f-string format + registry lookup was pure
        # overhead on every submit).
        name = model.cfg.name
        self._g_queue_depth = metrics.gauge(f"queue_depth{{model={name}}}")
        self._g_fill = metrics.gauge(f"batch_fill_ratio{{model={name}}}")
        self._g_inflight = metrics.gauge(f"pipeline_inflight{{model={name}}}")
        self._g_target = metrics.gauge(f"adaptive_target_batch{{model={name}}}")
        self._g_ewma = metrics.gauge(f"batch_duration_ewma_ms{{model={name}}}")
        self._c_shed = metrics.counter(f"shed_total{{model={name}}}")
        self._c_deadline = metrics.counter(
            f"deadline_exceeded_total{{model={name}}}")
        self._c_batches = metrics.counter(f"batches_total{{model={name}}}")
        self._c_items = metrics.counter(f"items_total{{model={name}}}")
        self._c_batch_errors = metrics.counter(
            f"batch_errors_total{{model={name}}}")
        self._c_retries = metrics.counter(f"batch_retries_total{{model={name}}}")
        self._c_retry_failures = metrics.counter(
            f"batch_retry_failures_total{{model={name}}}")
        self._c_poison = metrics.counter(f"poison_items_total{{model={name}}}")
        self._h_phase = {
            p: metrics.histogram(f"latency_ms{{model={name},phase={p}}}")
            for p in PHASES}
        # Per-priority queue-wait split (tpuserve.scheduler): requests
        # without a resolved priority land under the model's default class.
        self._default_priority = getattr(model.cfg, "priority", "interactive")
        self._h_qwait = {p: metrics.queue_wait_histogram(name, p)
                         for p in PRIORITIES}
        # Fleet-scheduler device-time ledger hook: called with each batch's
        # device-section seconds (compute phase) when a scheduler is
        # attached; None otherwise. Event-loop-only, like the ledger.
        self.device_time_cb = None
        # Per-replica device-seconds counters (ISSUE 14): ticked with every
        # batch's device section regardless of scheduler presence — the
        # telemetry sampler derives device_utilization{model=,replica=}
        # from their rates. Sized to the replica count at start().
        self._c_device_seconds: list[Counter] = []
        # Stage executors are normally server-owned and shared across models
        # (stage-granularity scheduling); a batcher built without one (tests,
        # embedding) creates and later shuts down its own.
        self._own_stages = stages is None
        self.stages = stages if stages is not None \
            else StageExecutors(self.pipeline_cfg, metrics)
        self._queues: dict[Hashable, asyncio.Queue[_Request]] = {}
        self._tasks: dict[Hashable, asyncio.Task] = {}
        self._dispatch_tasks: set[asyncio.Task] = set()
        self._inflight: asyncio.Semaphore | None = None
        self._staging: list[SlotPool] = []
        self._g_replica_inflight: list[Any] = []
        self.arena: AssemblyArena | None = None
        self.depth = 0
        self._admission_cap = 0
        self._inflight_now = 0
        self._inflight_peak = 0
        self._idle_event: asyncio.Event | None = None
        self._pending = 0
        self._running = False
        self._loop: asyncio.AbstractEventLoop | None = None
        # Arena assembly requires assemble_into to produce exactly what
        # assemble would: provable only when assemble is the base
        # implementation, or the family overrode assemble_into alongside its
        # custom assemble. Wrappers that monkey with assemble (tests) fall
        # back to the allocating path automatically.
        t = type(model)
        a = getattr(t, "assemble", None)
        ai = getattr(t, "assemble_into", None)
        self._use_arena = (a is ServingModel.assemble
                           or (ai is not None
                               and ai is not ServingModel.assemble_into))
        # Per-model circuit breaker (tpuserve.faults.CircuitBreaker): fed
        # dispatch outcomes here, consulted by the HTTP layer.
        self.breaker = breaker
        # Deterministic chaos (tpuserve.faults.FaultInjector); None in prod.
        self.injector = injector

    # -- lifecycle ----------------------------------------------------------
    async def start(self) -> None:
        self._running = True
        # The loop that owns every queue/future/counter below; captured so
        # submit_threadsafe (the parallel-ingest entry) can hop onto it.
        self._loop = asyncio.get_running_loop()
        pcfg = self.pipeline_cfg
        if self.deferred:
            # Deferred mode: enqueue's shm-slot wait is the device
            # backpressure; the semaphore bounds batches between assembly
            # and enqueue exactly as before.
            self._admission_cap = max(1, self.cfg.max_inflight)
            self._staging = []
            self.arena = None
            self.depth = 0
            # Deferred pools own devices out-of-process: all device time
            # lands on one "replica 0" ledger row.
            self._c_device_seconds = [
                self.metrics.device_seconds_counter(self.cfg.name, 0)]
        else:
            n_rep = max(1, int(getattr(self.runtime, "n_replicas", 1)))
            if hasattr(self.runtime, "h2d_sync"):
                # Transfer-completion gate ([pipeline] h2d_sync): the h2d
                # stage owns the wire wait, so the "compute" phase measures
                # dispatch-to-ready only (roofline attribution).
                self.runtime.h2d_sync = pcfg.h2d_sync
            self.depth = max(1, pcfg.depth or self.cfg.max_inflight)
            if n_rep == 1 and getattr(self.runtime, "n_chips", 1) > 1:
                import jax

                if jax.default_backend() == "cpu":
                    # Forced-host-device meshes (CPU CI/smokes/bench): the
                    # fake devices share the host's cores, and CONCURRENT
                    # multi-device program dispatches spin-wait against
                    # each other — observed wedging every request past a
                    # 60 s deadline at depth 4 (ISSUE 11). Serialize the
                    # device section; depth > 1 buys nothing on a shared
                    # core anyway. Real accelerator backends keep the
                    # configured depth (per-device execution streams
                    # serialize safely there).
                    self.depth = 1
            self._staging = [SlotPool(self.depth) for _ in range(n_rep)]
            # Replica-aware admission: depth-k batches per DEVICE section
            # plus the assembly ramp — with 8 replicas the pipeline admits
            # 8x the single-chip batch count, which is what keeps every
            # chip's staging slots full instead of one chip's (ISSUE 7).
            self._admission_cap = self.depth * n_rep + pcfg.assemble_ahead
            # Per-chip occupancy gauges (docs/PERFORMANCE.md "Serving on
            # the mesh"), prebound once per replica.
            self._g_replica_inflight = [
                self.metrics.replica_inflight_gauge(self.cfg.name, i)
                for i in range(n_rep)]
            # Per-replica device-seconds ledger (ISSUE 14): the telemetry
            # sampler turns these rates into device_utilization gauges.
            self._c_device_seconds = [
                self.metrics.device_seconds_counter(self.cfg.name, i)
                for i in range(n_rep)]
            arena_slots = pcfg.arena_slots or (self.depth + pcfg.assemble_ahead)
            self.arena = (AssemblyArena(self.model, arena_slots, self.metrics)
                          if self._use_arena else None)
        self._inflight = asyncio.Semaphore(self._admission_cap)
        self._idle_event = asyncio.Event()
        self._idle_event.set()

    async def stop(self) -> None:
        """Cancel accumulation, fail queued requests, drain in-flight batches."""
        self._running = False
        for t in self._tasks.values():
            t.cancel()
        for group, t in self._tasks.items():
            try:
                await t
            except asyncio.CancelledError:
                pass  # the cancellation we just requested — expected
            except Exception:
                # A loop that already died must not abort stop(), but its
                # death is a real failure, not shutdown noise — surface it
                # instead of swallowing it with the cancellation.
                log.exception("group loop %r for %s failed during stop",
                              group, self.model.name)
        self._tasks.clear()
        # Requests still queued (never dispatched) must not hang their
        # clients: fail them explicitly (ADVICE r1: stop() cleared queues
        # without resolving futures).
        err = RuntimeError(f"server shutting down; {self.model.name} not served")
        for q in self._queues.values():
            while not q.empty():
                req = q.get_nowait()
                self._pending -= 1
                if not req.future.done():
                    req.future.set_exception(err)
        self._queues.clear()
        if self._dispatch_tasks:
            await asyncio.gather(*self._dispatch_tasks, return_exceptions=True)
        self._maybe_idle()
        if self._own_stages:
            self.stages.shutdown()

    # -- submission (event loop) --------------------------------------------
    def submit(self, item: Any, group: Hashable = None,
               deadline_at: float | None = None,
               priority: str | None = None,
               ctx: Any = None) -> asyncio.Future:
        """Enqueue one decoded request; returns a Future of its result.

        ``deadline_at`` (perf_counter clock) is the request's absolute
        deadline: if it expires while the request is still queued, the
        future fails with DeadlineExceeded instead of dispatching.
        ``priority`` labels the request's queue-wait histogram (the fleet
        scheduler's arbitration happened BEFORE submit — by here the
        request is admitted either way). ``ctx`` (obs.TraceContext)
        collects the request's queue/phase spans when the HTTP layer is
        tracing it."""
        if not self._running or self._inflight is None:
            raise RuntimeError(f"batcher for {self.model.name} not started")
        if self._pending >= self.cfg.max_queue:
            self._c_shed.inc()
            raise QueueFull(self.model.name)
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        req = _Request(item=item, group=group, future=fut,
                       enqueued_at=time.perf_counter(), deadline_at=deadline_at,
                       priority=priority, ctx=ctx)
        q = self._queues.get(group)
        if q is None:
            q = self._queues[group] = asyncio.Queue()
            self._tasks[group] = loop.create_task(self._group_loop(group, q))
        q.put_nowait(req)
        self._pending += 1
        self._idle_event.clear()
        self._g_queue_depth.set(self._pending)
        return fut

    def submit_threadsafe(self, item: Any, group: Hashable = None,
                          deadline_at: float | None = None,
                          priority: str | None = None,
                          ctx: Any = None) -> cf.Future:
        """Loop-safe submit for callers OFF the batcher's event loop — the
        parallel ingest loops (ISSUE 11; ``[server] ingest_loops``) and any
        embedding thread. Schedules the real ``submit`` on the owning loop
        (captured at ``start``) and returns a ``concurrent.futures.Future``
        of the result; submit-time errors (QueueFull, RuntimeError) arrive
        through the returned future instead of raising here. Cancelling the
        returned future does NOT cancel the queued request (cancel
        propagation across loops would race the flush; the request's own
        deadline bounds it instead). On the owning loop, call ``submit``
        directly — the hop would deadlock a caller that blocks on the
        result."""
        loop = self._loop
        if not self._running or loop is None:
            raise RuntimeError(f"batcher for {self.model.name} not started")
        out: cf.Future = cf.Future()

        def _do() -> None:
            try:
                fut = self.submit(item, group=group, deadline_at=deadline_at,
                                  priority=priority, ctx=ctx)
            except Exception as e:  # QueueFull / stopped: through the future
                out.set_exception(e)
                return

            def _done(f: asyncio.Future) -> None:
                if out.cancelled():
                    return
                if f.cancelled():
                    out.cancel()
                elif f.exception() is not None:
                    out.set_exception(f.exception())
                else:
                    out.set_result(f.result())

            fut.add_done_callback(_done)

        loop.call_soon_threadsafe(_do)
        return out

    def revive_group_loops(self) -> int:
        """Watchdog hook: restart group-accumulation tasks that died.

        A group loop only exits on stop() (cancelled while not running); any
        other completion — an escaped exception, an injected kill — orphans
        its queue and hangs every future routed to that group. The watchdog
        calls this on its sweep; requests the dead loop had already pulled
        into its local batch are lost (their futures resolve at the server's
        request timeout), but everything still queued is served by the
        revived task."""
        if not self._running:
            return 0
        revived = 0
        loop = asyncio.get_running_loop()
        for group, q in self._queues.items():
            t = self._tasks.get(group)
            if t is not None and not t.done():
                continue
            if t is not None and not t.cancelled() and t.exception() is not None:
                log.error("group loop %r for %s died: %r — restarting",
                          group, self.model.name, t.exception())
            self._tasks[group] = loop.create_task(self._group_loop(group, q))
            revived += 1
        return revived

    def _maybe_idle(self) -> None:
        """Signal drain() waiters when no accepted work remains. Spurious
        sets are fine — drain re-checks under its clear/recheck discipline."""
        if self._idle_event is not None and self._pending == 0 \
                and not self._dispatch_tasks:
            self._idle_event.set()

    async def drain(self, deadline: float) -> bool:
        """Graceful drain: wait until every accepted request (queued or in
        flight) has resolved, bounded by ``deadline`` (event-loop time).
        The caller stops admitting new work first (server.draining).

        Wakes on the idle event set by the last completion instead of
        polling on an interval (the old 20 ms sleep loop added avoidable
        shutdown latency and jitter at high batch rates)."""
        loop = asyncio.get_running_loop()
        while self._pending > 0 or self._dispatch_tasks:
            timeout = deadline - loop.time()
            if timeout <= 0:
                break
            # clear-then-recheck: the loop is single-threaded, so a
            # completion between the recheck and wait() is impossible and
            # no wakeup can be missed.
            self._idle_event.clear()
            if self._pending == 0 and not self._dispatch_tasks:
                break
            try:
                await asyncio.wait_for(self._idle_event.wait(), timeout)
            except asyncio.TimeoutError:
                break
        self._maybe_idle()  # leave the event consistent for the next drain
        return self._pending == 0 and not self._dispatch_tasks

    def _expire_dead(self, reqs: list[_Request],
                     adjust_pending: bool) -> list[_Request]:
        """Fail requests whose per-request deadline has passed (-> fast 504,
        ``deadline_exceeded_total``) and drop already-done futures; returns
        the still-live rest. ``adjust_pending`` settles the queue-depth
        accounting for dropped requests when the batch-wide decrement has
        not run yet (the admission-wait call sites)."""
        now = time.perf_counter()
        live: list[_Request] = []
        n_expired = 0
        for r in reqs:
            if r.future.done():  # cancelled while queued (client gone)
                if adjust_pending:
                    self._pending -= 1
                continue
            if r.deadline_at is not None and now >= r.deadline_at:
                r.future.set_exception(DeadlineExceeded(
                    "deadline expired after "
                    f"{(now - r.enqueued_at) * 1e3:.0f} ms in queue"))
                n_expired += 1
                if adjust_pending:
                    self._pending -= 1
                continue
            live.append(r)
        if n_expired:
            self._c_deadline.inc(n_expired)
        if adjust_pending and len(live) != len(reqs):
            self._g_queue_depth.set(self._pending)
            self._maybe_idle()
        return live

    # -- adaptive flush scheduling (event loop) ------------------------------
    def _flush_headroom(self, batch: list[_Request]) -> float:
        """Earliest-deadline flush bound (perf_counter clock): the batch must
        dispatch while ~EWMA(batch duration) + slack still fits before the
        earliest per-request deadline (Clockwork P3 — duration is
        predictable, so schedule against it instead of discovering the
        deadline at dispatch). +inf when no member carries a deadline."""
        earliest = min((r.deadline_at for r in batch
                        if r.deadline_at is not None), default=None)
        if earliest is None:
            return float("inf")
        bucket = self.model.bucket_for(len(batch), group=batch[0].group)
        est_ms = self._ewma_ms.get(bucket, 0.0)
        return earliest - (est_ms + self.adaptive_cfg.slack_ms) / 1e3

    def _aimd_update(self, group: Hashable, tgt: float, n: int,
                     target_n: int, timer_flush: bool,
                     pressure: bool) -> None:
        """AIMD (Clipper P1): a batch that filled to target WITH more work
        still queued (``pressure``) grows the target additively; a
        timer-driven partial flush shrinks it multiplicatively toward
        min_target. A batch that fills with an empty queue is equilibrium —
        growing on it would make lone sequential requests at target 1 flap
        between immediate and full-timer flushes. Light load therefore
        converges to immediate single-request flushes, saturation to full
        buckets."""
        acfg = self.adaptive_cfg
        if n >= target_n and pressure:
            tgt = min(float(max(self.cfg.batch_buckets)), tgt + acfg.increase)
        elif timer_flush and n < target_n:
            tgt = max(float(acfg.min_target), tgt * acfg.decrease)
        self._targets[group] = tgt
        self._g_target.set(tgt)

    def _observe_batch_duration(self, bucket: tuple, dur_ms: float) -> None:
        prev = self._ewma_ms.get(bucket)
        alpha = self.adaptive_cfg.ewma_alpha
        ewma = dur_ms if prev is None else prev + alpha * (dur_ms - prev)
        self._ewma_ms[bucket] = ewma
        self._g_ewma.set(ewma)

    # -- accumulation (event loop) ------------------------------------------
    async def _group_loop(self, group: Hashable, q: asyncio.Queue) -> None:
        max_bucket = max(self.cfg.batch_buckets)
        deadline_s = self.cfg.deadline_ms / 1e3
        acfg = self.adaptive_cfg
        adaptive = acfg.enabled
        init_target = float(acfg.initial_target or max_bucket)
        while True:
            if self.injector is not None:
                # Chaos: an escaped exception kills this task, exactly the
                # failure revive_group_loops exists to repair.
                self.injector.check("kill_group_loop", self.model.name)
            req = await q.get()
            batch = [req]
            tgt = self._targets.get(group, init_target)
            target_n = (min(max_bucket, max(acfg.min_target, math.ceil(tgt)))
                        if adaptive else max_bucket)
            timer_flush = False
            try:
                # Max-wait backstop: adaptive mode additionally bounds the
                # wait by the deadline headroom, and stops accumulating at
                # the AIMD target instead of the largest bucket.
                flush_at = req.enqueued_at + deadline_s
                while len(batch) < target_n:
                    limit = flush_at
                    if adaptive:
                        limit = min(limit, self._flush_headroom(batch))
                    timeout = limit - time.perf_counter()
                    if timeout <= 0:
                        timer_flush = True
                        break
                    try:
                        batch.append(await asyncio.wait_for(q.get(), timeout))
                    except asyncio.TimeoutError:
                        timer_flush = True
                        break
                if adaptive:
                    self._aimd_update(group, tgt, len(batch), target_n,
                                      timer_flush, pressure=not q.empty())
                # Backpressure: admission bounds batches inside the pipeline
                # (depth x replicas in the device section + assemble_ahead
                # ramping through assembly); the group task itself waits
                # here. The wait is bounded by the earliest per-request
                # deadline in the batch (P3): a request that dies behind
                # slow in-flight work fails fast AT its deadline, instead of
                # being discovered dead only when capacity finally frees.
                batch = self._expire_dead(batch, adjust_pending=True)
                while batch:
                    earliest = min((r.deadline_at for r in batch
                                    if r.deadline_at is not None),
                                   default=None)
                    if earliest is None:
                        await self._inflight.acquire()
                        break
                    slot_wait = earliest - time.perf_counter()
                    if slot_wait > 0:
                        try:
                            await asyncio.wait_for(self._inflight.acquire(),
                                                   slot_wait)
                            break
                        except asyncio.TimeoutError:
                            pass
                    batch = self._expire_dead(batch, adjust_pending=True)
                if not batch:
                    continue  # everything expired; no admission was taken
            except asyncio.CancelledError:
                # stop() cancelled us mid-accumulation: requests already
                # pulled off the queue must fail, not hang their clients.
                err = RuntimeError(
                    f"server shutting down; {self.model.name} not served")
                self._pending -= len(batch)
                for r in batch:
                    if not r.future.done():
                        r.future.set_exception(err)
                self._maybe_idle()
                raise
            # Adaptive drain: anything that queued while we waited (deadline or
            # admission) would only wait longer — fold it into this batch up
            # to the largest bucket. This makes batch size track device speed
            # instead of deadline x arrival-rate (SURVEY.md §7 hard-part 2).
            while len(batch) < max_bucket and not q.empty():
                batch.append(q.get_nowait())
            self._pending -= len(batch)
            self._g_queue_depth.set(self._pending)
            live = [r for r in batch if not r.future.cancelled()]
            # Last deadline check at flush: requests drained from the queue
            # above may have expired too. Their pending count was already
            # settled in the batch-wide decrement.
            live = self._expire_dead(live, adjust_pending=False)
            if not live:
                self._inflight.release()
                self._maybe_idle()
                continue
            now = time.perf_counter()
            now_wall = time.time()
            for r in live:
                wait_ms = (now - r.enqueued_at) * 1e3
                tid = r.ctx.trace_id if r.ctx is not None else None
                self._h_phase["queue"].observe(wait_ms, trace_id=tid)
                self._h_qwait[r.priority or self._default_priority].observe(
                    wait_ms, trace_id=tid)
                if r.ctx is not None:
                    r.ctx.span("queue", now_wall - wait_ms / 1e3, now_wall,
                               tid=self.model.name)
            task = asyncio.get_running_loop().create_task(self._dispatch(live, group))
            self._dispatch_tasks.add(task)
            task.add_done_callback(self._dispatch_tasks.discard)
            task.add_done_callback(lambda _t: self._maybe_idle())

    # -- dispatch (stage executors do the blocking work) ---------------------
    async def _dispatch(self, reqs: list[_Request], group: Hashable) -> None:
        """Run one batch through the pipeline; on failure, retry/split per
        config before failing futures. Failure is contained to this batch
        either way: the group task and server keep serving."""
        name = self.model.name
        released = [False]  # deferred mode releases admission mid-flight
        self._inflight_now += 1
        self._inflight_peak = max(self._inflight_peak, self._inflight_now)
        self._g_inflight.set(self._inflight_now)
        try:
            try:
                await self._execute(reqs, group, released)
            except Exception as e:
                log.exception("batch dispatch failed for %s", name)
                self._c_batch_errors.inc()
                if self.breaker is not None:
                    self.breaker.record_failure()
                live = [r for r in reqs if not r.future.done()]
                if self.cfg.batch_retry and live:
                    try:
                        await self._retry(live, group, released)
                    except Exception as retry_err:
                        # The retry machinery itself must never leave
                        # futures unresolved (clients would hang to 504).
                        log.exception("batch retry machinery failed for %s", name)
                        for r in live:
                            if not r.future.done():
                                r.future.set_exception(retry_err)
                else:
                    for r in live:
                        r.future.set_exception(e)
        finally:
            self._inflight_now -= 1
            self._g_inflight.set(self._inflight_now)
            if not released[0]:
                self._inflight.release()

    async def _acquire_staging(self, reqs: list[_Request]) -> tuple[int | None, int | None]:
        """Pick a replica and take one of its depth-k staging slots, bounded
        by the earliest per-request deadline. The first choice is the
        runtime's least-loaded pick (fed each pool's live occupancy); when
        that pool is exhausted the fallback scans the REMAINING pools in
        ascending-occupancy order — the old fixed index-order scan
        systematically filled low-index replicas first and starved
        high-index chips under bursty load (ISSUE 7 satellite). Returns
        (replica, slot), or (None, None) when every request expired while
        waiting — their futures already carry DeadlineExceeded (fast
        504)."""
        live = [r for r in reqs if not r.future.done()]
        n = len(self._staging)
        while True:
            loads = [p.in_use for p in self._staging]
            first = self.runtime.pick_replica(loads) if n > 1 else 0
            slot = self._staging[first].try_acquire()
            if slot is not None:
                return self._staged(first), slot
            for i in sorted((j for j in range(n) if j != first),
                            key=lambda j: (loads[j], (j - first) % n)):
                slot = self._staging[i].try_acquire()
                if slot is not None:
                    return self._staged(i), slot
            live = self._expire_dead(live, adjust_pending=False)
            if not live:
                return None, None
            earliest = min((r.deadline_at for r in live
                            if r.deadline_at is not None), default=None)
            timeout = (None if earliest is None
                       else max(0.0, earliest - time.perf_counter()))
            try:
                slot = await self._staging[first].acquire(timeout)
                return self._staged(first), slot
            except asyncio.TimeoutError:
                continue

    def _staged(self, replica: int) -> int:
        """Record a staging acquire on the replica's occupancy gauge."""
        if self._g_replica_inflight:
            self._g_replica_inflight[replica].set(
                self._staging[replica].in_use)
        return replica

    def _release_staging(self, replica: int, slot: int) -> None:
        self._staging[replica].release(slot)
        if self._g_replica_inflight:
            self._g_replica_inflight[replica].set(
                self._staging[replica].in_use)

    async def _execute(self, reqs: list[_Request], group: Hashable,
                       released: list[bool]) -> None:
        """Assemble + run + postprocess one batch through the stage
        pipeline, resolving futures on success. Raises on failure WITHOUT
        failing futures — the caller owns the retry policy."""
        name = self.model.name
        bucket = self.model.bucket_for(len(reqs), group=group)
        fill = len(reqs) / bucket[0]
        self._g_fill.set(fill)
        self._c_batches.inc()
        # Batch identity for trace correlation (ISSUE 12): the lifetime
        # batch counter read right after its tick — unique per model (all
        # increments happen on the owning loop). The ring's batch span
        # carries its member trace ids; each member's per-phase spans carry
        # this id back, so a request tree and the batch timeline join both
        # ways. Retries/splits re-enter here and get their own batch id —
        # a retried request's tree visibly contains BOTH attempts.
        bid = int(self._c_batches.value)
        ctxs = [r.ctx for r in reqs if r.ctx is not None]
        ex_tid = ctxs[0].trace_id if ctxs else None

        wall0 = time.time()
        t0 = time.perf_counter()

        def mark(phase: str, t_a: float, t_b: float) -> None:
            """Observe one batch phase (exemplar = a member trace id) and
            append the span to every traced member, batch-tagged."""
            self._h_phase[phase].observe((t_b - t_a) * 1e3, trace_id=ex_tid)
            for c in ctxs:
                c.span(phase, wall0 + (t_a - t0), wall0 + (t_b - t0),
                       tid=name, batch=bid)

        items = [r.item for r in reqs]
        # Assemble stage: into a recycled arena buffer when provably
        # equivalent, else the model's allocating assemble.
        lease = self.arena.acquire(bucket) if self.arena is not None else None
        try:
            if lease is not None:
                host_batch = await self.stages.run(
                    name, "assemble", self.model.assemble_into,
                    items, bucket, lease.buf)
            else:
                host_batch = await self.stages.run(
                    name, "assemble", self.model.assemble, items, bucket)
            t1 = time.perf_counter()
            mark("preproc", t0, t1)

            if self.deferred:
                # Deferred mode: enqueue is cheap (shm write + slot wait =
                # the backpressure), so admission is released as soon as the
                # batch is on its worker; the await then spans the rest of
                # the owning worker's epoch + bulk readback, which is what
                # "compute" measures in this mode by design.
                if self.injector is not None:
                    delay = self.injector.delay_s("slow_dispatch", name)
                    if delay > 0:
                        await asyncio.sleep(delay)
                    self.injector.check("batch_error", name)
                out_fut = await self.runtime.enqueue(bucket, host_batch)
                t2 = time.perf_counter()
                mark("h2d", t1, t2)
                if not released[0]:
                    self._inflight.release()
                    released[0] = True
                np_out = await out_fut
                t3 = time.perf_counter()
                mark("compute", t2, t3)
                if self._c_device_seconds:
                    self._c_device_seconds[0].inc(t3 - t2)
                if self.device_time_cb is not None:
                    self.device_time_cb(t3 - t2)
            else:
                # Device section: a staging slot bounds batches inside
                # [h2d..fetch] to depth-k per replica; the wait is
                # deadline-bounded (fast 504 for work nobody awaits).
                replica, slot = await self._acquire_staging(reqs)
                if replica is None:
                    return  # every request expired; nothing to run
                try:
                    if self.injector is not None:
                        delay = self.injector.delay_s("slow_dispatch", name)
                        if delay > 0:
                            await asyncio.sleep(delay)
                        self.injector.check("batch_error", name)
                    # h2d stage: batched device_put of the whole pytree +
                    # async dispatch of the compiled call.
                    outputs = await self.stages.run(
                        name, "h2d", self.runtime.run, bucket, host_batch,
                        replica)
                    t2 = time.perf_counter()
                    mark("h2d", t1, t2)

                    # fetch stage: "compute" = dispatch-to-ready wall time.
                    # With per-stage executors this is the device's own
                    # queue + MXU time; it no longer absorbs other batches'
                    # transfer waits the way the shared-pool path did
                    # (docs/PERFORMANCE.md "Phase semantics").
                    np_out = await self.stages.run(
                        name, "fetch", self.runtime.fetch, outputs)
                    t3 = time.perf_counter()
                    mark("compute", t2, t3)
                    if replica < len(self._c_device_seconds):
                        self._c_device_seconds[replica].inc(t3 - t2)
                    if self.device_time_cb is not None:
                        # Fleet device-time ledger: the device section
                        # (dispatch-to-ready) is what models compete for.
                        self.device_time_cb(t3 - t2)
                finally:
                    self._release_staging(replica, slot)
        finally:
            if lease is not None:
                # Safe only now: the fetch completing proves the device is
                # done reading the batch (CPU-backend device_put may alias
                # this buffer).
                self.arena.release(lease)

        results = await self.stages.run(
            name, "postproc", self.model.host_postprocess, np_out, len(reqs))
        t4 = time.perf_counter()
        mark("postproc", t3, t4)
        self._c_items.inc(len(reqs))
        # Feed the adaptive scheduler's per-bucket duration model (tracked
        # even with adaptive off: the gauge is useful on its own).
        self._observe_batch_duration(bucket, (t4 - t0) * 1e3)
        # Span start/duration from the same wall-clock capture: mixing a
        # perf_counter delta into a fresh time.time() read skewed span
        # starts by the time spent between the two calls.
        self.metrics.tracer.add(
            f"batch[{bucket}]", wall0, wall0 + (t4 - t0),
            tid=name, trace_id=ex_tid, n=len(reqs), fill=fill, batch=bid,
            # Member trace ids, capped: joins the ring's batch timeline to
            # the flight recorder's per-request trees without letting a
            # 64-wide bucket bloat every ring event.
            trace_ids=[c.trace_id for c in ctxs[:8]],
        )
        if self.breaker is not None:
            self.breaker.record_success()
        for r, res in zip(reqs, results):
            if not r.future.done():
                r.future.set_result(res)

    async def _retry(self, reqs: list[_Request], group: Hashable,
                     released: list[bool]) -> None:
        """One-shot batch retry with poison isolation.

        The whole batch re-assembles and re-runs once (absorbing transient
        faults); if that fails and ``retry_split`` is on, the batch bisects
        recursively — each sub-batch runs once — so a single poison item
        fails only its own future while every other lane succeeds. Worst
        case a lane re-runs O(log batch) times; every path ends with all
        futures resolved."""
        name = self.model.name
        self._c_retries.inc()

        async def run_split(rs: list[_Request]) -> None:
            live = [r for r in rs if not r.future.done()]
            if not live:
                return
            try:
                await self._execute(live, group, released)
            except Exception as e:
                self._c_retry_failures.inc()
                if len(live) == 1 or not self.cfg.retry_split:
                    if len(live) == 1 and self.cfg.retry_split:
                        self._c_poison.inc()
                    for r in live:
                        if not r.future.done():
                            r.future.set_exception(e)
                else:
                    mid = (len(live) + 1) // 2
                    await run_split(live[:mid])
                    await run_split(live[mid:])

        await run_split(reqs)

    # -- introspection -------------------------------------------------------
    @property
    def pending(self) -> int:
        """Requests accepted but not yet flushed into a batch (the
        scheduler's demand signal and the idle-demotion guard)."""
        return self._pending

    def predicted_service_s(self, n_items: int = 1) -> float | None:
        """Predicted seconds of service time for a request of ``n_items``
        once it reaches the front of the queue: the batch-duration EWMA of
        the smallest bucket that covers it (Clockwork P3 — duration is
        predictable per (model, bucket)). Falls back to the largest
        observed bucket when nothing that small has run; None before any
        batch has completed."""
        if not self._ewma_ms:
            return None
        covering = [(b, ms) for b, ms in self._ewma_ms.items()
                    if ms > 0 and b[0] >= n_items]
        if covering:
            _, ms = min(covering, key=lambda kv: kv[0][0])
        else:
            _, ms = max(self._ewma_ms.items(), key=lambda kv: kv[0][0])
            if ms <= 0:
                return None
        return ms / 1e3

    def estimate_clear_s(self) -> float | None:
        """Estimated seconds for the current queue to clear at the observed
        serving rate. Deliberately UNCLAMPED (ISSUE 10 satellite): the
        fleet scheduler's admission math consumes this raw number;
        ``clamp_retry_after_s`` derives the [1, 30] s client-facing
        Retry-After hint for queue-full 429s from it (docs/ROBUSTNESS.md).
        Rate = the best items/s any bucket has
        demonstrated (its size over its batch-duration EWMA), so the hint
        tracks what the device is actually doing instead of a constant.
        None before any batch has completed (no EWMA yet) or with an empty
        queue."""
        if self._pending <= 0:
            return None
        rate = max((b[0] / (ms / 1e3)
                    for b, ms in self._ewma_ms.items() if ms > 0),
                   default=0.0)
        if rate <= 0:
            return None
        return self._pending / rate

    def pipeline_stats(self) -> dict:
        """The /stats "pipeline" block entry for this model
        (docs/PERFORMANCE.md "Reading the metrics")."""
        out = {
            "mode": "deferred" if self.deferred else "direct",
            "admission": self._admission_cap,
            "inflight": self._inflight_now,
            "inflight_peak": self._inflight_peak,
            "adaptive": {
                "enabled": self.adaptive_cfg.enabled,
                "targets": {repr(g): round(t, 2)
                            for g, t in self._targets.items()},
                "batch_ewma_ms": {repr(b): round(v, 2)
                                  for b, v in self._ewma_ms.items()},
            },
        }
        if not self.deferred:
            out["depth"] = self.depth
            out["replicas"] = len(self._staging)
            out["staging_in_use"] = [p.in_use for p in self._staging]
            out["arena"] = (self.arena.stats()
                            if self.arena is not None else None)
            # Per-chip serving attribution (ISSUE 7): dispatch count and
            # live device-section occupancy per replica, so an operator
            # (or the multichip smoke) sees a starved chip as a row of
            # zeros instead of a vaguely-low aggregate.
            batches = (self.runtime.replica_batches()
                       if hasattr(self.runtime, "replica_batches")
                       else [None] * len(self._staging))
            out["per_replica"] = [
                {"replica": i,
                 "batches_total": batches[i],
                 "staging_in_use": p.in_use,
                 "occupancy": round(p.in_use / self.depth, 3)
                 if self.depth else 0.0}
                for i, p in enumerate(self._staging)]
        return out
