"""Content-addressed result cache + single-flight coalescing (ISSUE 5).

BENCH_r05 put the chip-side ceiling at ~10,628 img/s with the HTTP path
delivering 606 img/s — the request path, not the executable, is the
bottleneck. Clipper (PAPERS.md P1) closed the same gap with a prediction
cache in front of the model containers; this module is that layer for
tpuserve, sitting between ``handle_predict`` and ``ModelBatcher``:

- **Content addressing** — key = (live model version, digest of the
  *preprocessed* item). Two byte-identical uploads hash to the same key no
  matter which connection carried them; the value is the *postprocessed*
  JSON-able result, so a hit skips decode-to-result entirely.
- **Version binding** — the live model version is baked into every key, so
  a lifecycle publish or rollback (tpuserve.lifecycle) atomically
  invalidates every older entry with no sweep and no lock: lookups under
  the new version simply never construct an old key. A flight that
  completes *after* a mid-flight version change is dropped instead of
  cached (``cache_stale_drops_total``) — its waiters still get the result
  (exactly what they'd have gotten uncached), but no future request can
  observe it.
- **Single-flight coalescing** — N concurrent identical misses occupy ONE
  batch slot: the first becomes the leader and submits to the batcher,
  the rest get waiter futures resolved from the leader's completion
  (``cache_coalesced_total``). A failed leader (including poison-split
  retries, PR 1) fans the error out and populates nothing.
- **Honest accounting** — hits, misses, and coalesced waiters are disjoint
  counters so cache traffic can never masquerade as model throughput in a
  bench (bench.py reports ``cache_hit_rate`` separately).

Threading: every method runs on the server's single asyncio event loop
(handle_predict and future done-callbacks); there is deliberately no lock
to witness. Digesting a wire-sized image costs ~10 µs (blake2b).
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import time
from dataclasses import dataclass
from typing import Any, Callable, Iterable

import numpy as np

from tpuserve.config import CacheConfig
from tpuserve.obs import CACHE_EVENTS, Metrics


def item_digest(item: Any) -> str:
    """Stable content digest of one decoded request item (np arrays, tuples
    of planes, text dicts, scalars). Dtype and shape are part of the digest
    so a (64,) uint8 never collides with an (8, 8) uint8 of the same bytes."""
    h = hashlib.blake2b(digest_size=16)
    _feed(h, item)
    return h.hexdigest()


def _feed(h: "hashlib._Hash", obj: Any) -> None:
    if isinstance(obj, np.ndarray):
        h.update(b"a")
        h.update(obj.dtype.str.encode())
        h.update(repr(obj.shape).encode())
        h.update(obj.tobytes())  # C-order copy when non-contiguous
    elif isinstance(obj, (tuple, list)):
        h.update(b"t" if isinstance(obj, tuple) else b"l")
        h.update(str(len(obj)).encode())
        for el in obj:
            _feed(h, el)
    elif isinstance(obj, dict):
        h.update(b"d")
        for k in sorted(obj, key=repr):
            h.update(repr(k).encode())
            _feed(h, obj[k])
    elif isinstance(obj, bytes):
        h.update(b"b")
        h.update(obj)
    else:  # str / int / float / bool / None / np scalars
        h.update(b"s")
        h.update(repr(obj).encode())


@dataclass
class CacheEntry:
    """One cached result. ``body`` is the pre-serialized JSON response for
    the single-item hit fast path (None for non-JSON or oversized values)."""

    value: Any
    body: bytes | None
    at: float  # time.monotonic() at population
    # Tenant that populated the entry (ISSUE 16 partitioned capacity);
    # None outside multi-tenant serving.
    tenant: str | None = None


@dataclass
class _Flight:
    """One in-flight miss: the leader's submission plus everyone waiting."""

    key: str
    version: int
    waiters: list[asyncio.Future]
    # The leader request's trace id (ISSUE 12): a coalesced waiter records
    # a link span naming it, so a waiter's span tree explains WHERE its
    # result was actually computed (the leader's trace has the batch
    # phases; the waiter's has only the coalesced link + the wait).
    leader_trace: "str | None" = None
    # The leader's tenant: the completed flight populates into that
    # tenant's cache partition.
    tenant: "str | None" = None


class ModelCache:
    """Per-model result cache + single-flight front of the batcher."""

    def __init__(self, name: str, cfg: CacheConfig, metrics: Metrics,
                 version_fn: Callable[[], int]) -> None:
        self.name = name
        self.cfg = cfg
        # Live weight-tree version (ModelRuntime.version); recycle-mode
        # pools have no in-process version and pin 0.
        self._version_fn = version_fn
        self._entries: dict[str, CacheEntry] = {}  # dicts iterate in LRU order
        self._flights: dict[str, _Flight] = {}
        c = {ev: metrics.cache_counter(name, ev) for ev in CACHE_EVENTS}
        self._c_hits = c["hits"]
        self._c_misses = c["misses"]
        self._c_coalesced = c["coalesced"]
        self._c_evictions = c["evictions"]
        self._c_stale = c["stale_drops"]
        self._g_entries = metrics.gauge(f"cache_entries{{model={name}}}")
        # Tenant partitioning (ISSUE 16): entry-count shares derived from
        # tenant weights. Empty = unpartitioned (the single-tenant path).
        self._tenant_shares: dict[str, int] = {}
        self._tenant_counts: dict[str, int] = {}

    def set_tenant_weights(self, weights: dict[str, float]) -> None:
        """Partition capacity by tenant weight: each tenant's entries are
        capped at ``max(1, floor(capacity * weight/total))`` so one
        tenant's churn evicts its OWN oldest entries, never a neighbor's
        hits. Hits stay content-addressed across tenants (identical bytes
        are identical results — serving them is not a leak, the result
        was computable from the request)."""
        self._tenant_shares = {}
        total = sum(weights.values())
        if total <= 0:
            return
        for name, w in weights.items():
            self._tenant_shares[name] = max(
                1, int(self.cfg.capacity * w / total))

    # -- lookup ---------------------------------------------------------------
    def key_for(self, item: Any) -> str:
        return f"{self._version_fn()}:{item_digest(item)}"

    def _pop(self, key: str) -> CacheEntry | None:
        e = self._entries.pop(key, None)
        if e is not None and e.tenant is not None:
            n = self._tenant_counts.get(e.tenant, 0) - 1
            if n > 0:
                self._tenant_counts[e.tenant] = n
            else:
                self._tenant_counts.pop(e.tenant, None)
        return e

    def get(self, key: str) -> CacheEntry | None:
        """Return the live entry for ``key`` (counting a hit) or None."""
        e = self._entries.get(key)
        if e is None:
            return None
        if self.cfg.ttl_s > 0 and time.monotonic() - e.at > self.cfg.ttl_s:
            self._pop(key)
            self._g_entries.set(len(self._entries))
            return None
        # LRU touch: move to the end of the dict's insertion order.
        del self._entries[key]
        self._entries[key] = e
        self._c_hits.inc()
        return e

    def _evict_one(self, tenant: str | None = None) -> None:
        """Evict the oldest entry — of ``tenant`` when given, else of any
        over-share tenant, else globally."""
        victim = None
        if tenant is not None:
            victim = next((k for k, e in self._entries.items()
                           if e.tenant == tenant), None)
        else:
            for k, e in self._entries.items():
                share = (self._tenant_shares.get(e.tenant)
                         if e.tenant is not None else None)
                if share is not None \
                        and self._tenant_counts.get(e.tenant, 0) > share:
                    victim = k
                    break
        if victim is None:
            victim = next(iter(self._entries))
        self._pop(victim)
        self._c_evictions.inc()

    def put(self, key: str, value: Any, tenant: str | None = None) -> None:
        body = None
        if isinstance(value, (dict, list)):
            try:
                raw = json.dumps(value).encode()
                if len(raw) <= self.cfg.max_body_bytes:
                    body = raw
            except (TypeError, ValueError):
                body = None  # non-JSON-able results cache by value only
        self._pop(key)
        self._entries[key] = CacheEntry(value, body, time.monotonic(), tenant)
        if tenant is not None:
            self._tenant_counts[tenant] = \
                self._tenant_counts.get(tenant, 0) + 1
            share = self._tenant_shares.get(tenant)
            while share is not None \
                    and self._tenant_counts.get(tenant, 0) > share:
                self._evict_one(tenant)
        while len(self._entries) > self.cfg.capacity:
            self._evict_one()
        self._g_entries.set(len(self._entries))

    # -- single-flight --------------------------------------------------------
    def submit_through(self, key: str,
                       submit: Callable[[], asyncio.Future],
                       ctx: Any = None,
                       tenant: str | None = None) -> asyncio.Future:
        """Miss path: join the in-flight computation for ``key`` or lead a
        new one by calling ``submit()`` (which may raise, e.g. QueueFull —
        propagated to the caller with nothing registered).

        Returns a per-caller waiter future. Cancelling a waiter (client
        disconnect, HTTP timeout) never cancels the underlying batch slot or
        the other waiters; the flight still completes and populates.
        ``ctx`` (obs.TraceContext) makes coalescing traceable: the leader's
        trace id is stored on the flight, and every joining waiter records
        a ``coalesced`` link span naming it (ISSUE 12)."""
        loop = asyncio.get_running_loop()
        if self.cfg.coalesce:
            fl = self._flights.get(key)
            if fl is not None:
                w = loop.create_future()
                fl.waiters.append(w)
                self._c_coalesced.inc()
                if ctx is not None:
                    now = time.time()
                    ctx.span("coalesced", now, now, tid=self.name,
                             linked_trace=fl.leader_trace)
                return w
        base = submit()
        self._c_misses.inc()
        fl = _Flight(key=key, version=self._version_fn(), waiters=[],
                     leader_trace=ctx.trace_id if ctx is not None else None,
                     tenant=tenant)
        if self.cfg.coalesce:
            self._flights[key] = fl
        w = loop.create_future()
        fl.waiters.append(w)
        base.add_done_callback(lambda f: self._settle(fl, f))
        return w

    def _settle(self, fl: _Flight, base: asyncio.Future) -> None:
        if self._flights.get(fl.key) is fl:
            del self._flights[fl.key]
        if base.cancelled():
            for w in fl.waiters:
                if not w.done():
                    w.cancel()
            return
        exc = base.exception()
        if exc is not None:
            # Failed batches (incl. poison-split leftovers) populate NOTHING.
            for w in fl.waiters:
                if not w.done():
                    w.set_exception(exc)
            return
        val = base.result()
        if self._version_fn() == fl.version:
            self.put(fl.key, val, tenant=fl.tenant)
        else:
            # Publish/rollback mid-flight: the result was admitted under a
            # version that is no longer live. Waiters still get it (same as
            # an uncached request spanning the publish), but it must never
            # answer a future lookup.
            self._c_stale.inc()
        for w in fl.waiters:
            if not w.done():
                w.set_result(val)

    # -- introspection --------------------------------------------------------
    def stats(self) -> dict:
        """The /stats "cache" block entry for this model."""
        out = {
            "entries": len(self._entries),
            "capacity": self.cfg.capacity,
            "inflight": len(self._flights),
            "hits": self._c_hits.value,
            "misses": self._c_misses.value,
            "coalesced": self._c_coalesced.value,
            "evictions": self._c_evictions.value,
            "stale_drops": self._c_stale.value,
        }
        if self._tenant_shares:
            out["tenants"] = {
                t: {"entries": self._tenant_counts.get(t, 0),
                    "share": share}
                for t, share in sorted(self._tenant_shares.items())}
        return out

    def clear(self) -> None:
        self._entries.clear()
        self._tenant_counts.clear()
        self._g_entries.set(0)


def hit_rate(counters: dict[str, float]) -> float | None:
    """hits / (hits + misses + coalesced) from a counter snapshot or delta;
    None when no cacheable traffic was seen. Shared by bench.py and the
    cache smoke so the reported rate has one definition."""
    total = sum(counters.get(k, 0.0) for k in ("hits", "misses", "coalesced"))
    if total <= 0:
        return None
    return counters.get("hits", 0.0) / total


def counter_snapshot(metrics: Metrics, model: str,
                     events: Iterable[str] = ("hits", "misses",
                                              "coalesced")) -> dict[str, float]:
    """Current cache counter values for ``model`` (bench/smoke helper)."""
    return {ev: metrics.cache_counter(model, ev).value for ev in events}
