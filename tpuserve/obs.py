"""Observability: metrics, phase timing, request tracing (SURVEY.md §2 C8, §5).

The reference's observability is unknowable (empty mount); BASELINE.json's
``metric`` field defines what must be observable: throughput (img/s) and
p50/p99 latency. The build records:

- counters (requests, errors, images served),
- fixed-bucket latency histograms split by phase
  (queue / preproc / h2d / compute / total), with per-bucket trace-id
  exemplars ([trace] exemplars; docs/OBSERVABILITY.md),
- gauges (queue depth, batch fill ratio, pipeline occupancy
  ``pipeline_inflight{model=}``, per-stage executor queue depth
  ``pipeline_stage_depth{model=,stage=}``),
- a bounded ring buffer of span events, dumpable as Chrome
  ``chrome://tracing`` JSON,
- request-scoped distributed tracing (ISSUE 12): a ``TraceContext``
  minted per HTTP request (128-bit trace id, returned as ``X-Trace-Id``
  on every response) collects completed spans across every layer and
  process the request crosses, and a ``FlightRecorder`` retains the
  complete span trees of the slowest-N requests per model plus every
  errored/shed request for ``/debug/slow`` and ``/debug/trace``.

Everything is in-process and designed for a single asyncio event loop plus a
decode threadpool: histogram/counter updates take a short lock (contention is
negligible at the update rates involved; the scrape path merges under the same
lock).
"""

from __future__ import annotations

import bisect
import heapq
import json
import math
import os
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Iterable

from tpuserve.utils.locks import new_lock


def _default_latency_buckets() -> list[float]:
    # Log-linear (HDR-style): 9 linear sub-buckets per decade, 0.1 ms .. 100 s.
    # Power-of-two buckets made quantile() return upper bounds up to 2x off
    # (VERDICT r3 weak 4: a 105 s "p99" from the +Inf-adjacent bucket); with
    # 9/decade the worst-case relative error is ~11% even before the in-bucket
    # interpolation below.
    return [m * (10.0**d) for d in range(-1, 5) for m in range(1, 10)] + [1e5]


class Histogram:
    """Fixed-bucket histogram (milliseconds by default).

    ``exemplars=True`` keeps, per bucket, the LAST (trace_id, value,
    timestamp) observed there (ISSUE 12): a dashboard's p99 bucket then
    names a concrete recorded trace to click through to
    (docs/OBSERVABILITY.md "Exemplars"). The slot is overwritten on every
    traced observation, so memory is bounded at one tuple per bucket."""

    def __init__(self, name: str, buckets: list[float] | None = None,
                 exemplars: bool = False) -> None:
        self.name = name
        self.bounds = buckets or _default_latency_buckets()
        self.counts = [0] * (len(self.bounds) + 1)
        self.total = 0.0
        self.n = 0
        # bucket index -> (trace_id, observed value, unix ts); None when
        # exemplars are disabled so the hot path pays a single None check.
        self._exemplars: dict[int, tuple[str, float, float]] | None = (
            {} if exemplars else None)
        self._lock = new_lock("obs.Histogram")

    def observe(self, value: float, trace_id: str | None = None) -> None:
        # bisect_left returns the first bound >= value — identical bucket
        # assignment to the old linear scan (first bound with value <= b,
        # overflow past the last), in O(log 55) instead of O(55) on every
        # hot-path observation (ISSUE 12 satellite; equivalence pinned by
        # tests/test_obs.py::test_observe_bisect_matches_linear_scan).
        i = bisect.bisect_left(self.bounds, value)
        with self._lock:
            self.counts[i] += 1
            self.total += value
            self.n += 1
            if trace_id is not None and self._exemplars is not None:
                self._exemplars[i] = (trace_id, value, time.time())

    def quantile(self, q: float) -> float:
        """Approximate quantile, linearly interpolated inside the bucket that
        contains the rank (the Prometheus ``histogram_quantile`` rule) —
        returning the raw upper bound overstated tail percentiles by up to the
        bucket width (VERDICT r3 weak 4)."""
        with self._lock:
            n = self.n
            if n == 0:
                return 0.0
            rank = math.ceil(q * n)
            acc = 0
            for i, c in enumerate(self.counts):
                prev_acc = acc
                acc += c
                if acc >= rank and c > 0:
                    if i == len(self.bounds):
                        # Rank lands in the +Inf overflow bucket: report inf
                        # rather than clamping to bounds[-1], so a tail of
                        # hung >100 s requests is visible as saturation in
                        # /metrics instead of masquerading as a real 100 s
                        # p99 (ADVICE r4).
                        return float("inf")
                    lo = self.bounds[i - 1] if i > 0 else 0.0
                    return lo + (self.bounds[i] - lo) * (rank - prev_acc) / c
        return self.bounds[-1]

    def snapshot(self) -> dict:
        with self._lock:
            out = {"n": self.n, "total": self.total,
                   "counts": list(self.counts)}
            if self._exemplars:
                out["exemplars"] = dict(self._exemplars)
            return out


class Counter:
    def __init__(self, name: str) -> None:
        self.name = name
        self.value = 0.0
        self._lock = new_lock("obs.Counter")

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount


class Gauge:
    def __init__(self, name: str) -> None:
        self.name = name
        self.value = 0.0

    def set(self, value: float) -> None:
        self.value = value


@dataclass
class SpanEvent:
    """One completed span: request-scoped phase timing."""

    name: str
    ts_us: float  # start, microseconds since epoch
    dur_us: float
    tid: str = "main"  # logical track: model name or "http"
    args: dict = field(default_factory=dict)
    # Trace identity (ISSUE 12): the request trace this span belongs to,
    # when the emitting layer knows one (batch spans carry a sample member;
    # engine retire spans the retiring slot's). None for anonymous spans.
    trace_id: str | None = None
    # Process lane in a stitched Chrome trace: 0 = router / single-process
    # server, worker id + 1 behind the router tier.
    pid: int = 0


class Tracer:
    """Bounded ring buffer of spans; dumps Chrome trace JSON.

    The ring keeps the NEWEST ``capacity`` spans (deque maxlen semantics:
    overflow drops the oldest) — a post-incident pull always sees the most
    recent window, never a frozen prefix."""

    def __init__(self, capacity: int = 65536) -> None:
        self._events: deque[SpanEvent] = deque(maxlen=capacity)
        self._lock = new_lock("obs.Tracer")

    def add(self, name: str, start_s: float, end_s: float, tid: str = "main",
            trace_id: str | None = None, pid: int = 0, **args) -> None:
        ev = SpanEvent(name, start_s * 1e6, (end_s - start_s) * 1e6, tid,
                       args, trace_id, pid)
        with self._lock:
            self._events.append(ev)

    def chrome_trace(self, limit: int | None = None,
                     since_us: float | None = None) -> str:
        """Chrome ``chrome://tracing`` JSON of the ring. ``limit`` caps the
        dump to the NEWEST that many events and ``since_us`` (epoch
        microseconds) drops older spans — a trace pull on a loaded server
        must not build a multi-hundred-MB body from a 65536-event ring on
        the event loop (ISSUE 12 satellite; the HTTP layer defaults
        limit=5000)."""
        with self._lock:
            events = list(self._events)
        if since_us is not None:
            events = [e for e in events if e.ts_us >= since_us]
        if limit is not None and limit >= 0:
            # NOT events[-limit:]: -0 slices the WHOLE list.
            events = events[len(events) - limit:] if limit else []
        out = []
        for e in events:
            args = dict(e.args)
            if e.trace_id is not None:
                args["trace_id"] = e.trace_id
            out.append({
                "name": e.name,
                "ph": "X",
                "ts": e.ts_us,
                "dur": e.dur_us,
                "pid": e.pid,
                "tid": e.tid,
                "args": args,
            })
        return json.dumps({"traceEvents": out})


# -- request-scoped tracing (ISSUE 12) ----------------------------------------

_TRACE_ID_HEX = 32  # 128-bit trace id
_SPAN_ID_HEX = 16   # 64-bit span id


def _hex_id(nbytes: int) -> str:
    return os.urandom(nbytes).hex()


def valid_trace_id(value) -> bool:
    """True for a well-formed 128-bit lowercase-hex trace id (the wire
    format of X-Trace-Id). Malformed ids from clients are replaced with a
    fresh mint, never echoed."""
    if not isinstance(value, str) or len(value) != _TRACE_ID_HEX:
        return False
    return all(c in "0123456789abcdef" for c in value)


def _valid_span_id(value) -> bool:
    if not isinstance(value, str) or len(value) != _SPAN_ID_HEX:
        return False
    return all(c in "0123456789abcdef" for c in value)


class TraceContext:
    """One request's trace identity plus its collected spans.

    Minted at ingest (one per HTTP request, adopted from ``X-Trace-Id``
    when an upstream tier — the router — already stamped one); every layer
    the request crosses appends COMPLETED spans. There is deliberately no
    "current span" stack: spans are recorded after the fact with explicit
    wall-clock bounds, so recording is safe from any thread or event loop
    (``list.append`` is atomic) and costs one small dict per span.

    The span tree is reconstructed from ``parent_id``: the root span is
    the HTTP request itself (``span_id == root_id``; ``parent_id`` points
    at the upstream attempt span when the router relayed us), and every
    ``span()`` call without an explicit parent hangs off the root. ``pid``
    labels the process lane in a stitched Chrome trace (0 = router or
    single-process server, worker id + 1 behind the router tier), which is
    what makes the cross-process hop visible as a gap between lanes.

    Span dict fields (the flight-recorder/chrome contract, pinned by
    tests/test_trace.py): name, trace_id, span_id, parent_id, ts_us,
    dur_us, tid, pid, args.
    """

    __slots__ = ("trace_id", "root_id", "parent_id", "pid", "spans")

    def __init__(self, trace_id: str | None = None,
                 parent_id: str | None = None, pid: int = 0) -> None:
        self.trace_id = trace_id if valid_trace_id(trace_id) \
            else _hex_id(_TRACE_ID_HEX // 2)
        self.parent_id = parent_id if _valid_span_id(parent_id) else None
        self.root_id = _hex_id(_SPAN_ID_HEX // 2)
        self.pid = pid
        self.spans: list[dict] = []

    @classmethod
    def from_headers(cls, headers, pid: int = 0) -> "TraceContext":
        """Adopt the upstream trace identity (X-Trace-Id / X-Parent-Span)
        or mint a fresh one. Invalid ids mint rather than propagate."""
        return cls(trace_id=headers.get("X-Trace-Id"),
                   parent_id=headers.get("X-Parent-Span"), pid=pid)

    def new_span_id(self) -> str:
        """Preallocate a span id (the router allocates one per relay
        attempt BEFORE dispatch so the worker can parent under it)."""
        return _hex_id(_SPAN_ID_HEX // 2)

    def span(self, name: str, start_s: float, end_s: float, *,
             span_id: str | None = None, parent_id: str | None = None,
             tid: str = "req", **args) -> str:
        """Record one completed span (wall-clock seconds); returns its
        span id. Default parent is the request's root span."""
        sid = span_id or _hex_id(_SPAN_ID_HEX // 2)
        self.spans.append({
            "name": name,
            "trace_id": self.trace_id,
            "span_id": sid,
            "parent_id": self.root_id if parent_id is None else parent_id,
            "ts_us": start_s * 1e6,
            "dur_us": max(0.0, end_s - start_s) * 1e6,
            "tid": tid,
            "pid": self.pid,
            "args": args,
        })
        return sid

    def root_span(self, name: str, start_s: float, end_s: float,
                  tid: str = "req", **args) -> str:
        """Record the request's root span (span_id = root_id, parented
        under the upstream attempt span when one was relayed)."""
        self.spans.append({
            "name": name,
            "trace_id": self.trace_id,
            "span_id": self.root_id,
            "parent_id": self.parent_id,
            "ts_us": start_s * 1e6,
            "dur_us": max(0.0, end_s - start_s) * 1e6,
            "tid": tid,
            "pid": self.pid,
            "args": args,
        })
        return self.root_id


def spans_to_chrome(spans: Iterable[dict],
                    events: Iterable[dict] = ()) -> str:
    """Render recorded span dicts (the TraceContext format) as Chrome
    ``chrome://tracing`` JSON. Each event carries the documented fields —
    name / ph="X" / ts / dur / pid / tid / args — with the trace identity
    (trace_id, span_id, parent_id) folded into args; ``pid`` separates
    process lanes so a router→worker hop reads as a gap between lanes.

    ``events`` (ISSUE 15) interleaves structured event records from the
    event plane as instant events (``ph: "i"``,
    tpuserve.telemetry.events.events_to_chrome) on the same timeline, so
    one artifact shows what the process was SAYING while the spans ran."""
    out = []
    for s in spans:
        args = dict(s.get("args") or {})
        args["trace_id"] = s.get("trace_id")
        args["span_id"] = s.get("span_id")
        args["parent_id"] = s.get("parent_id")
        out.append({
            "name": s.get("name", ""),
            "ph": "X",
            "ts": float(s.get("ts_us", 0.0)),
            "dur": float(s.get("dur_us", 0.0)),
            "pid": int(s.get("pid", 0)),
            "tid": s.get("tid", "req"),
            "args": args,
        })
    if events:
        from tpuserve.telemetry.events import events_to_chrome

        out.extend(events_to_chrome(list(events)))
    out.sort(key=lambda e: e["ts"])
    return json.dumps({"traceEvents": out})


class FlightRecorder:
    """Tail-latency flight recorder (ISSUE 12): a bounded reservoir of
    COMPLETE span trees for the requests worth keeping —

    - the slowest ``slow_n`` requests per model (a min-heap keyed by
      duration: a new request bumps the FASTEST retained entry, so under
      churn the reservoir converges on the true tail), and
    - every errored/shed request (HTTP status >= 400) in FIFO order up to
      ``error_capacity``, retained even when fast — a shed 503 or fast 504
      is exactly the request an operator gets paged about.

    Dumped at ``GET /debug/slow`` (summaries + span trees) and
    ``GET /debug/trace?trace_id=...`` (one tree, Chrome format); behind
    the router tier the router's version stitches worker spans in.
    Thread-safe: finish() is called from every ingest accept loop."""

    def __init__(self, slow_n: int = 16, error_capacity: int = 256,
                 always_record_errors: bool = True,
                 metrics: "Metrics | None" = None) -> None:
        self.slow_n = max(0, int(slow_n))
        self.error_capacity = max(0, int(error_capacity))
        self.always_record_errors = always_record_errors
        self._metrics = metrics
        self._rec_counters: dict[tuple[str, str], Counter] = {}
        # model -> min-heap of (duration_ms, seq, record); heap[0] is the
        # FASTEST retained record, evicted first when the heap is full.
        self._slow: dict[str, list] = {}
        self._errors: deque = deque()
        self._by_id: dict[str, dict] = {}
        self._seq = 0
        self._lock = new_lock("obs.FlightRecorder")

    def _counter(self, model: str, kind: str) -> "Counter | None":
        if self._metrics is None:
            return None
        c = self._rec_counters.get((model, kind))
        if c is None:
            c = self._rec_counters[(model, kind)] = self._metrics.counter(
                f"trace_recorded_total{{model={model},kind={kind}}}")
        return c

    @staticmethod
    def _make_record(ctx: TraceContext, model: str, status: int,
                     duration_ms: float) -> dict:
        return {
            "trace_id": ctx.trace_id,
            "model": model,
            "status": int(status),
            "duration_ms": round(duration_ms, 3),
            "ts": time.time(),
            "spans": list(ctx.spans),
            "_slow": False,
            "_err": False,
        }

    def _maybe_drop(self, record: dict) -> None:
        """Forget a record no reservoir retains anymore."""
        if not record["_slow"] and not record["_err"]:
            self._by_id.pop(record["trace_id"], None)

    def finish(self, ctx: TraceContext, model: str, status: int,
               duration_ms: float) -> list[str]:
        """Offer one completed request to the reservoirs; returns the
        kinds that retained it (subset of ``["error", "slow"]``, empty =
        not retained — still truthy-compatible with the old bool). Called
        once per HTTP request, errors included. The HTTP layer feeds
        retained-as-slow requests into the event plane so
        ``/debug/trace?trace_id=`` has events to interleave (ISSUE 15)."""
        kinds: list[str] = []
        with self._lock:
            record: dict | None = None
            if status >= 400 and self.always_record_errors \
                    and self.error_capacity > 0:
                record = self._make_record(ctx, model, status, duration_ms)
                record["_err"] = True
                self._errors.append(record)
                if len(self._errors) > self.error_capacity:
                    old = self._errors.popleft()
                    old["_err"] = False
                    self._maybe_drop(old)
                kinds.append("error")
            if self.slow_n > 0:
                heap = self._slow.setdefault(model, [])
                if len(heap) < self.slow_n or duration_ms > heap[0][0]:
                    if record is None:
                        record = self._make_record(ctx, model, status,
                                                   duration_ms)
                    record["_slow"] = True
                    self._seq += 1
                    heapq.heappush(heap, (duration_ms, self._seq, record))
                    if len(heap) > self.slow_n:
                        _, _, old = heapq.heappop(heap)
                        old["_slow"] = False
                        self._maybe_drop(old)
                    kinds.append("slow")
            if record is not None:
                self._by_id[record["trace_id"]] = record
        for kind in kinds:
            c = self._counter(model, kind)
            if c is not None:
                c.inc()
        return kinds

    @staticmethod
    def _public(record: dict) -> dict:
        return {k: v for k, v in record.items() if not k.startswith("_")}

    def get(self, trace_id: str) -> dict | None:
        """The retained record for one trace id (full span tree), or None
        once both reservoirs have let it go."""
        with self._lock:
            rec = self._by_id.get(trace_id)
            return self._public(rec) if rec is not None else None

    def dump(self, model: str | None = None) -> dict:
        """The /debug/slow body: per-model slowest-first records plus the
        errored-request FIFO (newest first), complete span trees included
        (the reservoirs are small by construction)."""
        with self._lock:
            slow = {
                m: [self._public(r)
                    for _, _, r in sorted(heap, key=lambda t: -t[0])]
                for m, heap in self._slow.items()
                if model is None or m == model
            }
            errors = [self._public(r) for r in reversed(self._errors)
                      if model is None or r["model"] == model]
        return {"slow": slow, "errors": errors,
                "slow_n": self.slow_n, "error_capacity": self.error_capacity}

    def stats(self) -> dict:
        """The /stats "trace" block: reservoir occupancy only."""
        with self._lock:
            return {
                "slow_n": self.slow_n,
                "slow": {m: len(h) for m, h in self._slow.items()},
                "errors": len(self._errors),
                "error_capacity": self.error_capacity,
                "records": len(self._by_id),
            }


# Per-request/per-batch phase labels on latency_ms{model=,phase=}. The
# ingest phases (ISSUE 11) are request-scoped and observed by the HTTP
# layer — "body_read" is the time to read the request body off the socket
# (the HTTP ingress wire), "parse" the host decode/frame-parse time; the
# rest are batch-scoped and observed by the batcher. Together with the
# roofline ceilings they attribute where an ingest-bound config loses time
# (docs/PERFORMANCE.md "The ingest fast path").
PHASES = ("body_read", "parse", "queue", "preproc", "h2d", "compute",
          "postproc", "total")

# Host-pipeline stage executors (tpuserve.hostpipe, docs/PERFORMANCE.md):
# the stage label on pipeline_stage_depth{model=,stage=} and the keys of the
# /stats "pipeline" block. One dedicated thread pool per stage; phase
# histograms keep their own (overlapping) names above — "preproc" measures
# the assemble stage, "compute" the fetch stage's dispatch-to-ready wait.
PIPELINE_STAGES = ("assemble", "h2d", "fetch", "postproc")

# Circuit-breaker states as gauge values (breaker_state{model=...}), chosen
# so "bigger = less healthy" reads naturally on a dashboard.
BREAKER_STATES = {"closed": 0.0, "half_open": 1.0, "open": 2.0}

# Demand-shaping cache events (tpuserve.cache): the ``cache_<event>_total``
# per-model counters. "hits" answer from the cache, "misses" lead a real
# batch submission, "coalesced" join an identical in-flight miss
# (single-flight), "evictions" are LRU drops, and "stale_drops" are flights
# that completed after a mid-flight version change (served to their waiters
# but never cached). hits/misses/coalesced are disjoint per request item, so
# cache traffic can never inflate miss-path throughput numbers.
CACHE_EVENTS = ("hits", "misses", "coalesced", "evictions", "stale_drops")

# Lifecycle reload gates, in pipeline order (tpuserve.lifecycle): the stage
# label on reload_rejected_total{model=,stage=}. "post_canary" is the only
# one that implies a rollback happened (the candidate had published).
RELOAD_STAGES = ("integrity", "nan_scan", "structure", "load",
                 "staged_canary", "post_canary")

# Reasons on rollbacks_total{model=,reason=}: the explicit admin endpoint,
# a failed post-publish canary, and the two soak-window triggers.
ROLLBACK_REASONS = ("manual", "post_publish_canary", "soak_breaker",
                    "soak_canary")

# Priority classes (tpuserve.scheduler; the X-Priority request header and
# the per-model `priority` default): the label on
# queue_wait_ms{model=,priority=}. Under fleet overload, "batch" sheds
# first; "interactive" is protected by the [scheduler] min_share floor.
PRIORITIES = ("interactive", "batch")

# Fleet-scheduler model states as gauge values (model_state{model=...}),
# the warm/cold weight-paging state machine (tpuserve.scheduler): cold =
# no device params resident (HBM free), warming = staging through the
# lifecycle path, warm = serving.
MODEL_STATES = {"cold": 0.0, "warming": 1.0, "warm": 2.0}

# SLO alert states as gauge values (slo_alert_state{model=...}), chosen —
# like BREAKER_STATES — so "bigger = less healthy" reads naturally on a
# dashboard (tpuserve.telemetry.slo; the /alerts endpoint carries the
# same vocabulary as strings).
SLO_ALERT_STATES = {"ok": 0.0, "pending": 1.0, "firing": 2.0}

# Reasons on sched_sheds_total{model=,reason=} (tpuserve.scheduler):
# "deadline_unmeetable" — the stamped deadline provably cannot be met at
# admission (fast 504, Clockwork P3); "priority_shed" — batch-class work
# shed under fleet saturation; "share_exceeded" — an over-allowance model
# shed while another model's interactive traffic was starved below
# min_share; "model_warming" — shed during a cold model's warming window;
# "kv_pressure" — the paged generation engine's free-page ledger cannot
# cover the request's prompt + decode reservation (ISSUE 18; 503 with a
# clear-time Retry-After, same contract as queue-full).
SCHED_SHED_REASONS = ("deadline_unmeetable", "priority_shed",
                      "share_exceeded", "model_warming", "burn_shed",
                      "kv_pressure", "chip_budget")

# Tenant admission rejections (tpuserve.scheduler.tenants), by cause.
TENANT_SHED_REASONS = ("tenant_unknown", "tenant_rate_exceeded",
                       "tenant_quota_exceeded", "tenant_share_exceeded")

# Reasons on gen_stream_terminated_total{model=,reason=} — how a
# generation stream ended (tpuserve.genserve.engine._terminate_stream):
# "done" is the only success; everything else names which machinery cut
# the stream. The engine guards emission against this tuple so a new
# call site cannot mint an off-vocabulary label (TPS404 holds each value
# to a docs/REFERENCE.md row and at least one test).
GEN_STREAM_REASONS = ("done", "disconnect", "deadline_exceeded",
                      "engine_error", "drain", "shutdown")

# Reasons on router_stream_terminated_total{model=,reason=} — the
# worker-router's stream proxy (tpuserve.workerproc.router): same
# contract as GEN_STREAM_REASONS, seen from the proxy side ("done" the
# only success; "upstream_error" folds any worker-side failure).
ROUTER_STREAM_REASONS = ("done", "client_disconnect", "deadline_exceeded",
                         "idle_timeout", "upstream_error", "drain")


class Metrics:
    """Registry of all server metrics. One instance per server process."""

    def __init__(self, trace_capacity: int = 65536,
                 exemplars: bool = True) -> None:
        self._lock = new_lock("obs.Metrics")
        self._histograms: dict[str, Histogram] = {}
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, Gauge] = {}
        # [trace] exemplars: histograms record per-bucket (trace_id, value,
        # ts) exemplars, rendered in OpenMetrics exemplar syntax on
        # /metrics (docs/OBSERVABILITY.md "Exemplars").
        self.exemplars = exemplars
        self.tracer = Tracer(trace_capacity)
        self.started_at = time.time()

    # -- registry -----------------------------------------------------------
    def histogram(self, name: str) -> Histogram:
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram(
                    name, exemplars=self.exemplars)
            return h

    def counter(self, name: str) -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter(name)
            return c

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge(name)
            return g

    def counter_values(self) -> dict[str, float]:
        """Plain name -> value snapshot of every counter (the black-box
        checkpointer's cheap alternative to summary(), which also prices
        every histogram's quantiles)."""
        with self._lock:
            counters = list(self._counters.items())
        return {name: c.value for name, c in counters}

    # -- convenience --------------------------------------------------------
    def observe_phase(self, model: str, phase: str, ms: float) -> None:
        self.histogram(f"latency_ms{{model={model},phase={phase}}}").observe(ms)

    def cache_counter(self, model: str, event: str) -> Counter:
        """cache_<event>_total{model=}: one of CACHE_EVENTS
        (tpuserve.cache). Prebound by ModelCache at construction — never
        call this per request."""
        return self.counter(f"cache_{event}_total{{model={model}}}")

    def replica_batches_counter(self, model: str, replica: int) -> Counter:
        """replica_batches_total{model=,replica=}: batches dispatched on one
        runtime replica (tpuserve.runtime.dispatch). Per-chip attribution
        for multi-chip serving (docs/PERFORMANCE.md "Serving on the
        mesh"): every replica nonzero under load is the proof the batcher
        keeps the whole mesh busy; a flat-zero replica is a starved chip.
        Prebound at runtime construction — never call per batch."""
        return self.counter(
            f"replica_batches_total{{model={model},replica={replica}}}")

    def replica_inflight_gauge(self, model: str, replica: int) -> Gauge:
        """replica_inflight{model=,replica=}: batches currently occupying
        one replica's depth-k device-section staging slots
        (tpuserve.batcher). Occupancy at depth on every replica = the mesh
        is compute-bound; occupancy pinned at 0 on some replicas = the
        load (or the replica pick) is starving chips. Prebound at batcher
        start — never call per batch."""
        return self.gauge(
            f"replica_inflight{{model={model},replica={replica}}}")

    def ingest_requests_counter(self, loop_index: int) -> Counter:
        """ingest_requests_total{loop=}: predict requests served by one
        ingest accept loop (loop 0 = the main serving loop; 1..N-1 the
        dedicated SO_REUSEPORT ingest threads, tpuserve.server). Roughly
        equal values across loops under load = the kernel is spreading
        connections and no single accept loop is the choke point; one hot
        loop = clients are reusing few connections (ISSUE 11). Prebound
        per app at construction — never call per request."""
        return self.counter(f"ingest_requests_total{{loop={loop_index}}}")

    def ingest_bytes_counter(self, loop_index: int) -> Counter:
        """ingest_bytes_total{loop=}: request-body bytes read by one ingest
        accept loop — the ingress-wire balance twin of
        ingest_requests_total (big framed bodies make byte balance the
        honest signal). Prebound per app at construction."""
        return self.counter(f"ingest_bytes_total{{loop={loop_index}}}")

    def worker_up_gauge(self, worker: int) -> Gauge:
        """worker_up{worker=}: 1 while the supervised worker process is
        alive and passing health probes, 0 while dead/respawning/unhealthy
        (tpuserve.workerproc.supervisor). The fleet's availability at a
        glance: sum(worker_up) is the live serving capacity. Prebound at
        supervisor construction — never call per probe."""
        return self.gauge(f"worker_up{{worker={worker}}}")

    def worker_respawns_counter(self, worker: int) -> Counter:
        """worker_respawns_total{worker=}: times the supervisor respawned
        this worker slot after its process died (SIGKILL, native crash,
        OOM). A climbing counter on one slot with worker_up stuck at 0 is
        a crash loop — the respawn backoff (worker_backoff_s) shows how
        hard the supervisor is backing off."""
        return self.counter(f"worker_respawns_total{{worker={worker}}}")

    def worker_backoff_gauge(self, worker: int) -> Gauge:
        """worker_backoff_s{worker=}: the exponential respawn delay the
        supervisor applied to this slot's most recent respawn (0 once it
        is back up and healthy)."""
        return self.gauge(f"worker_backoff_s{{worker={worker}}}")

    def worker_inflight_gauge(self, worker: int) -> Gauge:
        """worker_inflight{worker=}: relayed requests currently in flight
        on one worker (tpuserve.workerproc.router feeds the least-loaded
        pick from it)."""
        return self.gauge(f"worker_inflight{{worker={worker}}}")

    def host_up_gauge(self, host: int) -> Gauge:
        """host_up{host=}: 1 while the host agent process (one whole
        failure domain: agent + its worker fleet) is alive
        (tpuserve.workerproc.hosts). sum(host_up) is the live failure-
        domain count; one at 0 with the rest at 1 is graceful degradation
        working. Prebound at supervisor construction."""
        return self.gauge(f"host_up{{host={host}}}")

    def host_respawns_counter(self, host: int) -> Counter:
        """host_respawns_total{host=}: times the router respawned this
        entire host (agent + workers) after the agent process died —
        the machine-level twin of worker_respawns_total."""
        return self.counter(f"host_respawns_total{{host={host}}}")

    def host_backoff_gauge(self, host: int) -> Gauge:
        """host_backoff_s{host=}: exponential respawn delay applied to the
        host slot's latest respawn (0 once the domain is back up)."""
        return self.gauge(f"host_backoff_s{{host={host}}}")

    def host_breaker_gauge(self, host: int) -> Gauge:
        """host_breaker_open{host=}: 1 while consecutive relay transport
        failures have tripped the host breaker and picks shed around the
        whole domain (tpuserve.workerproc.hosts); 0 when closed."""
        return self.gauge(f"host_breaker_open{{host={host}}}")

    def router_up_gauge(self, router: int) -> Gauge:
        """router_up{router=}: 1 while the supervised peer router process
        is alive and in the consistent-hash ring
        (tpuserve.workerproc.peers). Emitted by the PRIMARY router."""
        return self.gauge(f"router_up{{router={router}}}")

    def router_respawns_counter(self, router: int) -> Counter:
        """router_respawns_total{router=}: times the primary respawned a
        dead peer router process (its cache shard rejoins the ring on
        boot)."""
        return self.counter(f"router_respawns_total{{router={router}}}")

    def queue_wait_histogram(self, model: str, priority: str) -> Histogram:
        """queue_wait_ms{model=,priority=}: time a request spent queued
        before its batch flushed (or its generation slot admitted), split
        by priority class (tpuserve.scheduler). Batch-class p99 growing
        while interactive stays flat is the priority arbitration working;
        both growing is genuine undercapacity. Prebound at batcher/engine
        start — never call per request."""
        return self.histogram(
            f"queue_wait_ms{{model={model},priority={priority}}}")

    def sched_shed_counter(self, model: str, reason: str) -> Counter:
        """sched_sheds_total{model=,reason=}: requests the fleet scheduler
        refused at admission, by reason (one of SCHED_SHED_REASONS).
        Prebound by the scheduler at registration — never call per
        request."""
        return self.counter(
            f"sched_sheds_total{{model={model},reason={reason}}}")

    def sched_device_seconds_counter(self, model: str) -> Counter:
        """sched_device_seconds_total{model=}: cumulative device-section
        seconds this model's dispatches consumed (fed by batch compute /
        generation step timings) — the fleet scheduler's cross-model
        device-time ledger in monotonic form."""
        return self.counter(f"sched_device_seconds_total{{model={model}}}")

    def device_seconds_counter(self, model: str, replica: int) -> Counter:
        """device_seconds_total{model=,replica=}: cumulative device-section
        seconds (dispatch-to-ready) one runtime replica spent serving this
        model — the per-chip form of the device-time ledger. The telemetry
        sampler divides its windowed rate by wall time to derive
        device_utilization{model=,replica=} (docs/OBSERVABILITY.md "The
        telemetry plane"). Prebound at batcher/engine start — never call
        per batch."""
        return self.counter(
            f"device_seconds_total{{model={model},replica={replica}}}")

    def gen_replica_steps_counter(self, model: str, replica: int) -> Counter:
        """gen_replica_steps_total{model=,replica=}: decode iterations one
        replica's generation engine executed (tpuserve.genserve.engine).
        The generation twin of replica_batches_total: every replica
        nonzero under sustained load is the proof least-loaded placement
        keeps the whole mesh generating; a flat-zero replica is a starved
        chip (docs/PERFORMANCE.md "Generation on the mesh"). Prebound at
        engine construction — never call per step."""
        return self.counter(
            f"gen_replica_steps_total{{model={model},replica={replica}}}")

    def gen_replica_units_counter(self, model: str, replica: int) -> Counter:
        """gen_replica_units_total{model=,replica=}: output units (tokens,
        images) retired by one replica's generation engine — the per-chip
        decomposition of gen_units_total. Skew between replicas under a
        mixed-length workload is expected (long generations pin a chip);
        a replica whose units flatline while its steps climb is spinning
        on never-finishing lanes. Prebound at engine construction."""
        return self.counter(
            f"gen_replica_units_total{{model={model},replica={replica}}}")

    def gen_replica_active_gauge(self, model: str, replica: int) -> Gauge:
        """gen_replica_active_slots{model=,replica=}: slots currently
        generating on one replica's engine. The model-level
        gen_active_slots{model=} gauge publishes the group SUM (metrics
        are name-keyed singletons — N engines binding the model row share
        one gauge); this row is the per-chip truth the placement balance
        test reads. Sampled into /stats/history like every gauge."""
        return self.gauge(
            f"gen_replica_active_slots{{model={model},replica={replica}}}")

    def gen_replica_kv_free_gauge(self, model: str, replica: int) -> Gauge:
        """gen_replica_kv_pages_free{model=,replica=}: free KV pages in one
        replica engine's page pool (paged mode only; ISSUE 18 ledger).
        Each replica owns an independent pool, so the model-level
        gen_kv_pages_free is the sum and THIS row is where pressure
        actually binds — admission stalls on the replica whose pool runs
        dry, not on the aggregate."""
        return self.gauge(
            f"gen_replica_kv_pages_free{{model={model},replica={replica}}}")

    def device_utilization_gauge(self, model: str, replica: int) -> Gauge:
        """device_utilization{model=,replica=}: fraction of wall time one
        chip spent in this model's device sections over the
        [telemetry] utilization window (0.0 idle .. ~1.0 saturated;
        derived by the sampler from device_seconds_total). Summed across
        models per replica it is that chip's total occupancy — the number
        the roofline's ceiling math needs to be honest about."""
        return self.gauge(
            f"device_utilization{{model={model},replica={replica}}}")

    def slo_burn_gauge(self, model: str, window_s: float,
                       label: str = "model") -> Gauge:
        """slo_burn_rate{model=,window=}: the model's error-budget burn
        rate over one [telemetry] burn window (bad fraction / budget;
        1.0 = spending the budget exactly at the sustainable pace).
        Updated every sampler tick (tpuserve.telemetry.slo). ``label``
        swaps the subject dimension — the tenant SLO engine burns
        slo_burn_rate{tenant=,window=} through the same machinery."""
        return self.gauge(
            f"slo_burn_rate{{{label}={model},window={window_s:g}s}}")

    def set_slo_alert_state(self, model: str, state: str,
                            label: str = "model") -> None:
        """slo_alert_state{model=}: the /alerts state as a gauge
        (SLO_ALERT_STATES: ok 0 / pending 1 / firing 2). ``label`` as in
        slo_burn_gauge (tenant alerts are slo_alert_state{tenant=})."""
        self.gauge(f"slo_alert_state{{{label}={model}}}").set(
            SLO_ALERT_STATES[state])

    def tenant_requests_counter(self, tenant: str) -> Counter:
        """tenant_requests_total{tenant=}: predict requests admitted for
        one tenant. Prebound by the tenant ledger — never call per
        request."""
        return self.counter(f"tenant_requests_total{{tenant={tenant}}}")

    def tenant_shed_counter(self, tenant: str, reason: str) -> Counter:
        """tenant_sheds_total{tenant=,reason=}: requests refused at the
        tenant front door, by reason (one of TENANT_SHED_REASONS)."""
        return self.counter(
            f"tenant_sheds_total{{tenant={tenant},reason={reason}}}")

    def tenant_device_seconds_counter(self, tenant: str) -> Counter:
        """tenant_device_seconds_total{tenant=}: cumulative device-time
        proxy one tenant consumed — the windowed form drives quota and
        fair-share admission (tpuserve.scheduler.tenants)."""
        return self.counter(
            f"tenant_device_seconds_total{{tenant={tenant}}}")

    def tenant_latency_histogram(self, tenant: str) -> Histogram:
        """tenant_latency_ms{tenant=}: end-to-end predict latency per
        tenant (the substrate the per-tenant SLO burn engine reads)."""
        return self.histogram(f"tenant_latency_ms{{tenant={tenant}}}")

    def autopilot_action_counter(self, kind: str, outcome: str) -> Counter:
        """autopilot_actions_total{kind=,outcome=}: fleet-controller
        decisions by action kind (scale_up/scale_down/shed_on/shed_off/
        warm/demote) and outcome (ok/error/rollback)."""
        return self.counter(
            f"autopilot_actions_total{{kind={kind},outcome={outcome}}}")

    def set_model_state(self, model: str, state: str) -> None:
        """model_state{model=}: the warm/cold paging state as a gauge
        (MODEL_STATES: cold 0 / warming 1 / warm 2)."""
        self.gauge(f"model_state{{model={model}}}").set(MODEL_STATES[state])

    def set_model_version(self, model: str, version: int) -> None:
        """model_version{model=}: the live weight-tree version number
        (tpuserve.lifecycle). A sawtooth on a dashboard = publish followed
        by rollback."""
        self.gauge(f"model_version{{model={model}}}").set(float(version))

    # -- export -------------------------------------------------------------
    def render_prometheus(self) -> str:
        """Prometheus text exposition format."""
        lines: list[str] = []
        with self._lock:
            counters = list(self._counters.values())
            gauges = list(self._gauges.values())
            hists = list(self._histograms.values())
        typed: set[str] = set()

        def emit(name: str, kind: str, value: float) -> None:
            base, labels = _split(name)
            if base not in typed:
                typed.add(base)
                lines.append(f"# TYPE {base} {kind}")
            label_str = "{" + labels.rstrip(",") + "}" if labels else ""
            lines.append(f"{base}{label_str} {value}")

        for c in counters:
            emit(c.name, "counter", c.value)
        for g in gauges:
            emit(g.name, "gauge", g.value)
        for h in hists:
            base, labels = _split(h.name)
            if base not in typed:
                typed.add(base)
                lines.append(f"# TYPE {base} histogram")
            snap = h.snapshot()
            # OpenMetrics exemplar syntax on bucket lines ([trace]
            # exemplars): `... <count> # {trace_id="..."} <value> <ts>` —
            # the last trace id observed in that bucket, so a dashboard's
            # p99 bucket names a recorded trace to pull from /debug/trace.
            exemplars = snap.get("exemplars") or {}

            def _ex(i: int) -> str:
                e = exemplars.get(i)
                if e is None:
                    return ""
                tid, val, ts = e
                return f' # {{trace_id="{tid}"}} {val:g} {ts:.3f}'

            acc = 0
            for i, (bound, count) in enumerate(zip(h.bounds, snap["counts"])):
                acc += count
                lines.append(
                    f'{base}_bucket{{{labels}le="{bound:g}"}} {acc}{_ex(i)}')
            lines.append(f'{base}_bucket{{{labels}le="+Inf"}} {snap["n"]}'
                         f'{_ex(len(h.bounds))}')
            lines.append(f"{base}_sum{{{labels.rstrip(',')}}} {snap['total']}")
            lines.append(f"{base}_count{{{labels.rstrip(',')}}} {snap['n']}")
        # OpenMetrics terminator (ISSUE 14 satellite): a scraper that
        # understands OpenMetrics treats a missing `# EOF` as a truncated
        # (torn) scrape; plain Prometheus parsers read it as a comment, so
        # it is emitted unconditionally.
        lines.append("# EOF")
        return "\n".join(lines) + "\n"

    def summary(self) -> dict:
        """JSON-friendly summary used by /stats and the bench harness."""
        out: dict = {"uptime_s": time.time() - self.started_at, "counters": {}, "gauges": {}, "latency": {}}
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hists = dict(self._histograms)
        for name, c in counters.items():
            out["counters"][name] = c.value
        for name, g in gauges.items():
            out["gauges"][name] = g.value
        for name, h in hists.items():
            p50, p99 = h.quantile(0.5), h.quantile(0.99)
            # quantile() returns inf when the rank lands in the +Inf overflow
            # bucket; json.dumps would emit the invalid-JSON token `Infinity`
            # and break every strict /stats consumer. Cap to the top bound
            # and say so explicitly instead.
            sat = not (math.isfinite(p50) and math.isfinite(p99))
            row = {
                "n": h.n,
                "mean_ms": (h.total / h.n) if h.n else 0.0,
                "p50_ms": min(p50, h.bounds[-1]),
                "p99_ms": min(p99, h.bounds[-1]),
            }
            if sat:
                row["saturated"] = True
            out["latency"][name] = row
        return out


# Exposition content types for /metrics content negotiation (ISSUE 14
# satellite): the OpenMetrics type is served when the client's Accept
# header asks for it (Prometheus ≥ 2.5 does), the classic text type
# otherwise. The BODY is identical either way — the exposition this
# registry renders (`name_total` counters, `# TYPE` metadata, exemplar
# syntax, `# EOF`) is valid under both parsers.
OPENMETRICS_CONTENT_TYPE = \
    "application/openmetrics-text; version=1.0.0; charset=utf-8"
PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def exposition_content_type(accept: str | None) -> str:
    """Negotiate the /metrics Content-Type from the request's Accept
    header: OpenMetrics when explicitly acceptable, classic text format
    otherwise (including no/wildcard Accept — maximum compatibility)."""
    if accept and "application/openmetrics-text" in accept:
        return OPENMETRICS_CONTENT_TYPE
    return PROMETHEUS_CONTENT_TYPE


def _escape_label(value: str) -> str:
    """Prometheus label-value escaping: backslash, double-quote, newline."""
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _split(name: str) -> tuple[str, str]:
    """'lat{model=x,phase=y}' -> ('lat', 'model="x",phase="y",')."""
    if "{" not in name:
        return name, ""
    base, _, rest = name.partition("{")
    rest = rest.rstrip("}")
    pairs = [p.split("=", 1) for p in rest.split(",") if p]
    labels = ",".join(f'{k}="{_escape_label(v)}"' for k, v in pairs)
    return base, labels + "," if labels else ""


class phase_timer:
    """Context manager: time a phase into Metrics (+ optional trace span)."""

    def __init__(self, metrics: Metrics, model: str, phase: str, trace: bool = False) -> None:
        self.metrics = metrics
        self.model = model
        self.phase = phase
        self.trace = trace

    def __enter__(self) -> "phase_timer":
        self.t0 = time.perf_counter()
        self.wall0 = time.time()
        return self

    def __exit__(self, *exc) -> None:
        t1 = time.perf_counter()
        ms = (t1 - self.t0) * 1e3
        self.metrics.observe_phase(self.model, self.phase, ms)
        if self.trace:
            self.metrics.tracer.add(self.phase, self.wall0, self.wall0 + (t1 - self.t0), tid=self.model)


def percentile(values: Iterable[float], q: float) -> float:
    """Exact percentile of a finite sample (bench-side helper)."""
    vs = sorted(values)
    if not vs:
        return 0.0
    idx = min(len(vs) - 1, max(0, math.ceil(q * len(vs)) - 1))
    return vs[idx]
