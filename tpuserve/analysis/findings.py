"""Finding records + the checked-in baseline (burndown) workflow.

A finding's identity is ``(rule, file, symbol, message)`` — deliberately NOT
the line number, so unrelated edits above a known violation do not churn the
baseline. Messages therefore never embed line numbers; ``line`` rides along
for display only.

Baseline semantics (docs/ANALYSIS.md): findings present in
``tpuserve/analysis/baseline.json`` are known debt and do not fail the run;
anything new fails; baseline entries that no longer reproduce are reported as
stale so the file is burned down explicitly with ``--update-baseline``, never
silently.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from pathlib import Path


@dataclass(frozen=True)
class Finding:
    rule: str  # e.g. "TPS101"
    file: str  # repo-relative posix path
    symbol: str  # dotted symbol the finding anchors to
    message: str  # deterministic, line-number-free
    line: int = 0  # display only; not part of identity

    @property
    def key(self) -> str:
        return f"{self.rule} {self.file} {self.symbol} :: {self.message}"

    def render(self) -> str:
        return f"{self.file}:{self.line}: {self.rule} [{self.symbol}] {self.message}"


def load_baseline(path: Path) -> set[str]:
    if not path.exists():
        return set()
    data = json.loads(path.read_text())
    return {entry["key"] for entry in data.get("findings", [])}


def save_baseline(path: Path, findings: list[Finding]) -> None:
    data = {
        "comment": (
            "Known findings burned down explicitly (docs/ANALYSIS.md). "
            "Regenerate with: python -m tpuserve lint --update-baseline"
        ),
        "findings": [
            {"key": f.key, "rule": f.rule, "file": f.file, "symbol": f.symbol}
            for f in sorted(findings, key=lambda f: f.key)
        ],
    }
    path.write_text(json.dumps(data, indent=2) + "\n")


def compare(findings: list[Finding], baseline: set[str]) -> tuple[list[Finding], set[str]]:
    """(new findings not in baseline, stale baseline keys no longer seen)."""
    seen = {f.key for f in findings}
    new = [f for f in findings if f.key not in baseline]
    stale = baseline - seen
    return new, stale
