"""Drift rules: keep config, docs, examples, and tests honest (TPS4xx).

These rules exist because the artifacts around the code rot silently: a knob
added to ``config.py`` that no example or doc mentions is a knob operators
cannot find; a metric emitted but undocumented is a dashboard nobody builds;
a chaos fault kind no test references is recovery machinery nobody proves.

- **TPS401** — every dataclass field in ``tpuserve/config.py`` appears (as a
  whole token) in ``examples/serve_all.toml`` AND in the docs corpus
  (README.md + docs/*.md). docs/REFERENCE.md is the canonical fix location.
- **TPS402** — every metric name emitted anywhere in ``tpuserve/`` (the
  ``counter(f"name{...}")`` / ``gauge`` / ``histogram`` / ``observe_phase``
  call sites) appears in the docs corpus.
- **TPS403** — every fault kind in ``config.FAULT_KINDS`` is referenced by
  at least one file under ``tests/``.
- **TPS404** — every shed/terminal reason string in the closed label
  vocabularies (``SCHED_SHED_REASONS``, ``TENANT_SHED_REASONS``,
  ``GEN_STREAM_REASONS``, ``ROUTER_STREAM_REASONS``, ``ROLLBACK_REASONS``
  in ``tpuserve/obs.py``) appears in docs/REFERENCE.md AND is referenced
  by at least one file under ``tests/`` — the same contract TPS403 gives
  fault kinds. A reason an operator can see on a dashboard must be a
  reason the docs explain and a test exercises.

Everything is pure text/AST scanning — no tpuserve imports — so the lint CI
job runs on a bare Python install.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path

from tpuserve.analysis.findings import Finding


def _token_in(name: str, text: str) -> bool:
    return re.search(rf"(?<![A-Za-z0-9_]){re.escape(name)}(?![A-Za-z0-9_])", text) is not None


def _read_all(paths: list[Path]) -> str:
    return "\n".join(p.read_text() for p in paths if p.exists())


def config_fields(config_py: Path) -> list[tuple[str, str]]:
    """(dataclass name, field name) for every annotated field in config.py."""
    tree = ast.parse(config_py.read_text())
    out = []
    for stmt in tree.body:
        if not isinstance(stmt, ast.ClassDef):
            continue
        is_dataclass = any(
            (isinstance(d, ast.Name) and d.id == "dataclass")
            or (isinstance(d, ast.Attribute) and d.attr == "dataclass")
            for d in stmt.decorator_list
        )
        if not is_dataclass:
            continue
        for item in stmt.body:
            if isinstance(item, ast.AnnAssign) and isinstance(item.target, ast.Name):
                out.append((stmt.name, item.target.id))
    return out


def fault_kinds(config_py: Path) -> list[str]:
    tree = ast.parse(config_py.read_text())
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
            t = stmt.targets[0]
            if isinstance(t, ast.Name) and t.id == "FAULT_KINDS":
                return [
                    el.value
                    for el in stmt.value.elts
                    if isinstance(el, ast.Constant) and isinstance(el.value, str)
                ]
    return []


# The closed reason vocabularies (module-level tuples in tpuserve/obs.py)
# TPS404 holds to the docs+tests contract.
REASON_VOCABULARIES = ("SCHED_SHED_REASONS", "TENANT_SHED_REASONS",
                       "GEN_STREAM_REASONS", "ROUTER_STREAM_REASONS",
                       "ROLLBACK_REASONS")


def reason_vocabularies(obs_py: Path) -> list[tuple[str, str]]:
    """(vocabulary tuple name, reason string) for every entry of the
    REASON_VOCABULARIES tuples in obs.py."""
    tree = ast.parse(obs_py.read_text())
    out: list[tuple[str, str]] = []
    for stmt in tree.body:
        if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1:
            continue
        t = stmt.targets[0]
        if not isinstance(t, ast.Name) or t.id not in REASON_VOCABULARIES:
            continue
        for el in getattr(stmt.value, "elts", ()):
            if isinstance(el, ast.Constant) and isinstance(el.value, str):
                out.append((t.id, el.value))
    return out


_METRIC_RE = re.compile(
    r"""\.(?:counter|gauge|histogram)\(\s*f?["']([a-z][a-z0-9_]*)"""
)


def metric_names(package_dir: Path) -> dict[str, Path]:
    """Metric base name -> first file that emits it."""
    out: dict[str, Path] = {}
    for path in sorted(package_dir.rglob("*.py")):
        text = path.read_text()
        for m in _METRIC_RE.finditer(text):
            out.setdefault(m.group(1), path)
        if "observe_phase(" in text:
            out.setdefault("latency_ms", path)
    return out


def run(root: Path) -> list[Finding]:
    findings: list[Finding] = []
    config_py = root / "tpuserve" / "config.py"
    if not config_py.exists():
        return findings
    docs = _read_all([root / "README.md", *sorted((root / "docs").glob("*.md"))])
    example = _read_all([root / "examples" / "serve_all.toml"])
    tests = _read_all(sorted((root / "tests").rglob("*.py")))

    for cls, name in config_fields(config_py):
        missing = []
        if not _token_in(name, example):
            missing.append("examples/serve_all.toml")
        if not _token_in(name, docs):
            missing.append("docs (README.md + docs/*.md)")
        if missing:
            findings.append(
                Finding(
                    rule="TPS401",
                    file="tpuserve/config.py",
                    symbol=f"{cls}.{name}",
                    message=f"config knob not mentioned in: {', '.join(missing)}",
                )
            )

    for name, path in sorted(metric_names(root / "tpuserve").items()):
        if not _token_in(name, docs):
            findings.append(
                Finding(
                    rule="TPS402",
                    file=path.relative_to(root).as_posix(),
                    symbol=f"metric.{name}",
                    message="metric emitted but undocumented (README.md + docs/*.md)",
                )
            )

    for kind in fault_kinds(config_py):
        if not _token_in(kind, tests):
            findings.append(
                Finding(
                    rule="TPS403",
                    file="tpuserve/config.py",
                    symbol=f"fault.{kind}",
                    message="fault kind has no test referencing it under tests/",
                )
            )

    obs_py = root / "tpuserve" / "obs.py"
    reference = _read_all([root / "docs" / "REFERENCE.md"])
    if obs_py.exists():
        for vocab, reason in reason_vocabularies(obs_py):
            missing = []
            if not _token_in(reason, reference):
                missing.append("docs/REFERENCE.md")
            if not _token_in(reason, tests):
                missing.append("tests/")
            if missing:
                findings.append(
                    Finding(
                        rule="TPS404",
                        file="tpuserve/obs.py",
                        symbol=f"reason.{vocab}.{reason}",
                        message=("shed/terminal reason not covered by: "
                                 + ", ".join(missing)),
                    )
                )
    return findings
