"""Trace-discipline lint (TPS5xx) — static retrace/transfer hazards.

The repo's central invariant is "steady-state compile delta 0": every smoke
and drill gates on ``runtime_compiles_total`` staying flat after warmup.
These rules prove the obligation *ahead* of runtime by walking the
jit-reachability set — every function whose body JAX traces — and flagging
the hazard classes that silently reintroduce retrace churn or forced
host transfers:

- **TPS501 — per-call compile-cache entries.** ``jax.jit`` applied to a
  lambda or a function defined in the enclosing call (a fresh function
  object per invocation → a fresh cache entry per invocation), unless the
  result is AOT-consumed (``.lower(...).compile()`` — the repo's own
  bucket-compile idiom, which never relies on the dispatch cache). Also a
  call of a jitted function passing a fresh dict/list/set literal or a
  lambda in a ``static_argnums``/``static_argnames`` position —
  non-hashable statics raise, fresh hashables mint an entry per call.

- **TPS502 — host-forcing ops on traced values.** ``.item()`` /
  ``.tolist()``, ``float()`` / ``int()`` / ``bool()``, and ``np.*`` calls
  on tracer-typed names inside a traced body force a device sync +
  transfer at trace or dispatch time; bare ``print`` in a traced body
  fires at trace time only (use ``jax.debug.print``).

- **TPS503 — Python control flow on traced values.** ``if``/``while`` on a
  tracer-derived expression inside a traced body raises
  ``TracerBoolConversionError`` at best and bakes a trace-time constant at
  worst. ``x is None`` checks and kwonly-arg branches are exempt (both are
  static by construction — kwonly args of traced functions are the repo's
  convention for compile-time parameters, e.g. ``prefill_chunk``'s
  ``chunk``).

- **TPS504 / TPS505 — retrace-by-closure.** In a *host-side* function, a
  nested function handed to ``jax.jit`` / ``register_program`` that
  captures (TPS504) an array freshly built per call from the enclosing
  function's arguments (``jnp.arange(n)`` and friends) or (TPS505) an
  enclosing-function argument directly — the captured value is baked into
  the trace as a constant, so every distinct value recompiles. Pass it as
  a traced argument instead. Traced enclosing functions are exempt
  (capturing a tracer into a ``fori_loop`` body is the normal idiom).

Jit-reachability = functions decorated with / passed to ``jax.jit``, the
second argument of ``register_program(tag, fn, ...)`` calls, the
conventional GenerativeModel/ServingModel entry points (``forward``,
``step``, ``extract``, ``init_state``, ``prefill_chunk``), plus a bounded
same-module call-graph walk through their helpers (nested defs included —
``fori_loop``/``scan`` bodies are checked as part of their enclosing
traced body).

Deliberate host reads carry an inline sanction::

    if "kp" in state:  # tps-ok[TPS503]: pytree structure check at trace time

The annotation names the rule and MUST give a reason; it suppresses that
rule on that statement only (docs/ANALYSIS.md "Sanctioned patterns").

Honest limits: resolution is name-based within a module/class (no type
inference), so cross-object helpers (``self.unet.apply``) are not
descended into, and a model whose bucket set varies per call defeats the
static view — that residue is exactly what the runtime retrace witness
(``TPUSERVE_RETRACE_WITNESS=1``) covers.

Pure AST + text — no jax import — so the bare-Python CI lint job runs it.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path

from tpuserve.analysis.astlint import (
    MAX_CALL_DEPTH,
    FuncInfo,
    ModuleInfo,
    _parse_module,
    _self_attr,
    dotted,
)
from tpuserve.analysis.findings import Finding

# Conventional traced entry points on serving/generative model classes.
TRACED_METHOD_NAMES = {"forward", "step", "extract", "init_state",
                       "prefill_chunk"}
TRACED_BASE_NAMES = {"GenerativeModel", "ServingModel"}

# Attribute reads that yield static (trace-time Python) values even on a
# tracer: branching on a shape is free, branching on data is not.
UNTAINT_ATTRS = {"shape", "ndim", "dtype", "size"}

# Builtins that force a concrete host value out of a tracer.
HOST_FORCERS = {"float", "int", "bool", "complex"}
HOST_FORCER_ATTRS = {"item", "tolist"}

# Untainting builtins: static under trace (len of a tracer is its static
# leading dim; isinstance/type are structural).
STATIC_BUILTINS = {"len", "isinstance", "type", "range", "enumerate"}

# Array constructors whose per-call result, captured into a traced body,
# bakes a fresh constant (TPS504).
ARRAY_BUILDERS = {"arange", "zeros", "ones", "full", "asarray", "array",
                  "linspace", "eye", "tri"}
ARRAY_NAMESPACES = {"jnp", "np", "numpy", "jax.numpy"}

_SANCTION_RE = re.compile(
    r"#\s*tps-ok\[(?P<rules>TPS\d{3}(?:\s*,\s*TPS\d{3})*)\]:\s*\S")


def sanctioned_rules(line_text: str) -> set[str]:
    """Rule ids sanctioned by an inline ``# tps-ok[TPSnnn]: reason``
    annotation on this source line (empty set when absent or when the
    required reason text is missing)."""
    m = _SANCTION_RE.search(line_text)
    if m is None:
        return set()
    return {r.strip() for r in m.group("rules").split(",")}


def filter_sanctioned(findings: list[Finding],
                      sources: dict[str, list[str]]) -> list[Finding]:
    """Drop findings whose source line carries a matching sanction."""
    out = []
    for f in findings:
        lines = sources.get(f.file)
        if lines and 1 <= f.line <= len(lines) \
                and f.rule in sanctioned_rules(lines[f.line - 1]):
            continue
        out.append(f)
    return out


def _is_jit_name(name: str | None) -> bool:
    return name is not None and (name == "jit" or name.endswith(".jit"))


def _jit_decorator(dec: ast.AST) -> ast.Call | None:
    """The decorator as a pseudo jit Call when it is ``@jax.jit`` /
    ``@jit`` / ``@functools.partial(jax.jit, ...)``, else None."""
    if _is_jit_name(dotted(dec)):
        return ast.Call(func=dec, args=[], keywords=[])
    if isinstance(dec, ast.Call):
        if _is_jit_name(dotted(dec.func)):
            return dec
        if dotted(dec.func) in ("functools.partial", "partial") and dec.args \
                and _is_jit_name(dotted(dec.args[0])):
            return ast.Call(func=dec.args[0], args=[], keywords=dec.keywords)
    return None


def _static_names(jit_call: ast.Call) -> tuple[set[str], set[int]]:
    """(static_argnames, static_argnums) literal values on a jit call."""
    names: set[str] = set()
    nums: set[int] = set()
    for kw in jit_call.keywords:
        if kw.arg == "static_argnames":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, str):
                    names.add(n.value)
        elif kw.arg == "static_argnums":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, int):
                    nums.add(n.value)
    return names, nums


def _fresh_literal(node: ast.AST) -> str | None:
    """A per-call-fresh / non-hashable literal in a static position."""
    if isinstance(node, (ast.Dict, ast.DictComp)):
        return "dict literal"
    if isinstance(node, (ast.List, ast.ListComp)):
        return "list literal"
    if isinstance(node, (ast.Set, ast.SetComp)):
        return "set literal"
    if isinstance(node, ast.Lambda):
        return "lambda"
    return None


def _positional_params(fn: ast.AST) -> list[str]:
    """Positional parameter names, minus self/cls. Kwonly args are NOT
    included: a kwonly arg of a traced function is this repo's convention
    for a compile-time-static parameter (closed over at register time)."""
    a = fn.args
    names = [p.arg for p in [*a.posonlyargs, *a.args]]
    return [n for n in names if n not in ("self", "cls")]


def _static_param_names(fn: ast.AST) -> set[str]:
    """Params that are host-static by declaration — annotated with a host
    scalar type (``b: int``), or listed in a ``custom_vjp``/``custom_jvp``
    ``nondiff_argnums`` (JAX hands those to the function as Python
    values, not tracers)."""
    static: set[str] = set()
    pos = [*fn.args.posonlyargs, *fn.args.args]
    for p in pos:
        ann = p.annotation
        if isinstance(ann, ast.Name) and ann.id in ("int", "bool", "str"):
            static.add(p.arg)
    for dec in getattr(fn, "decorator_list", ()):
        if not isinstance(dec, ast.Call):
            continue
        name = dotted(dec.func) or ""
        is_custom = name.split(".")[-1] in ("custom_vjp", "custom_jvp")
        if not is_custom and name in ("functools.partial", "partial") \
                and dec.args:
            inner = (dotted(dec.args[0]) or "").split(".")[-1]
            is_custom = inner in ("custom_vjp", "custom_jvp")
        if not is_custom:
            continue
        for kw in dec.keywords:
            if kw.arg != "nondiff_argnums":
                continue
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, int) \
                        and 0 <= n.value < len(pos):
                    static.add(pos[n.value].arg)
    return static


def _bound_names(fn: ast.AST) -> set[str]:
    """Every name bound inside ``fn`` (params, assignments, defs, loop and
    comprehension targets, imports) — for free-variable computation."""
    a = fn.args
    bound = {p.arg for p in [*a.posonlyargs, *a.args, *a.kwonlyargs]}
    if a.vararg:
        bound.add(a.vararg.arg)
    if a.kwarg:
        bound.add(a.kwarg.arg)
    for n in ast.walk(fn):
        if isinstance(n, ast.Name) and isinstance(n.ctx, (ast.Store, ast.Del)):
            bound.add(n.id)
        elif isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            bound.add(n.name)
        elif isinstance(n, ast.alias):
            bound.add((n.asname or n.name).split(".")[0])
        elif isinstance(n, ast.ExceptHandler) and n.name:
            bound.add(n.name)
    return bound


def _free_names(fn: ast.AST) -> set[str]:
    """Names ``fn`` reads but does not bind (its closure candidates)."""
    bound = _bound_names(fn)
    free = set()
    for n in ast.walk(fn):
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) \
                and n.id not in bound:
            free.add(n.id)
    return free


class _Taint:
    """Flow-through taint for tracer-typed names inside one traced body.

    Positional params seed the set; values computed from tainted names or
    from ``jnp.*``/``jax.*`` calls propagate; ``.shape``/``.dtype``-style
    reads, ``len()``, and ``x is None`` checks untaint (static at trace
    time)."""

    def __init__(self, seed: set[str]) -> None:
        self.names = set(seed)

    def expr(self, e: ast.AST) -> bool:
        if isinstance(e, ast.Name):
            return e.id in self.names
        if isinstance(e, ast.Attribute):
            if e.attr in UNTAINT_ATTRS:
                return False
            return self.expr(e.value)
        if isinstance(e, ast.Subscript):
            return self.expr(e.value)
        if isinstance(e, ast.Call):
            name = dotted(e.func) or ""
            if isinstance(e.func, ast.Name) and e.func.id in STATIC_BUILTINS:
                return False
            if isinstance(e.func, ast.Name) and e.func.id in HOST_FORCERS:
                return False  # result is a host scalar (and flagged)
            if name.split(".")[-1] == "typeof":
                return False  # avals are static trace-time metadata
            if name.split(".")[0] in ("jnp", "jax"):
                return True
            if isinstance(e.func, ast.Attribute):
                if e.func.attr in HOST_FORCER_ATTRS:
                    return False  # result is a host value (and flagged)
                if self.expr(e.func.value):
                    return True  # method on a tracer (x.mean(), x.sum())
            return any(self.expr(a) for a in e.args) or any(
                self.expr(kw.value) for kw in e.keywords)
        if isinstance(e, ast.BinOp):
            return self.expr(e.left) or self.expr(e.right)
        if isinstance(e, ast.UnaryOp):
            return self.expr(e.operand)
        if isinstance(e, ast.BoolOp):
            return any(self.expr(v) for v in e.values)
        if isinstance(e, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in e.ops) \
                    and any(isinstance(c, ast.Constant) and c.value is None
                            for c in e.comparators):
                return False  # `x is None`: structural, static under trace
            return self.expr(e.left) or any(self.expr(c)
                                            for c in e.comparators)
        if isinstance(e, (ast.Tuple, ast.List, ast.Set)):
            return any(self.expr(v) for v in e.elts)
        if isinstance(e, (ast.GeneratorExp, ast.ListComp, ast.SetComp)):
            return self.expr(e.elt)
        if isinstance(e, ast.IfExp):
            return self.expr(e.body) or self.expr(e.test) or self.expr(e.orelse)
        if isinstance(e, ast.Starred):
            return self.expr(e.value)
        if isinstance(e, ast.Await):
            return self.expr(e.value)
        return False

    def assign(self, target: ast.AST, value_tainted: bool) -> None:
        if isinstance(target, ast.Name):
            if value_tainted:
                self.names.add(target.id)
            else:
                self.names.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for t in target.elts:
                self.assign(t, value_tainted)
        elif isinstance(target, ast.Starred):
            self.assign(target.value, value_tainted)


class TraceAnalyzer:
    """TPS5xx driver over a parsed module set."""

    def __init__(self, modules: list[ModuleInfo]) -> None:
        self.modules = modules
        self.findings: list[Finding] = []
        self.traced: set[tuple[str, str]] = set()  # (modname, qualname)
        # Method names handed to register_program through an object we
        # cannot type (``rt.register_program("step", model.step)``) — any
        # conventional model class defining them is treated as traced.
        self._traced_attr_names: set[str] = set(TRACED_METHOD_NAMES)

    # -- reachability ---------------------------------------------------------

    def _conventional_classes(self, mi: ModuleInfo) -> set[str]:
        out = set()
        for stmt in mi.tree.body:
            if not isinstance(stmt, ast.ClassDef):
                continue
            for base in stmt.bases:
                name = dotted(base) or ""
                if name.split(".")[-1] in TRACED_BASE_NAMES:
                    out.add(stmt.name)
        return out

    def _mark(self, mi: ModuleInfo, fi: FuncInfo) -> None:
        self.traced.add((mi.modname, fi.qualname))

    def _seed_roots(self) -> None:
        for mi in self.modules:
            conv = self._conventional_classes(mi)
            for fi in mi.functions.values():
                node = fi.node
                bare = fi.name.split(".")[-1]
                if fi.cls in conv and bare in TRACED_METHOD_NAMES \
                        and "<locals>" not in fi.name:
                    self._mark(mi, fi)
                for dec in getattr(node, "decorator_list", ()):
                    if _jit_decorator(dec) is not None:
                        self._mark(mi, fi)
            # Functions passed (by reference) to jit / register_program.
            for fi in list(mi.functions.values()):
                for n in ast.walk(fi.node):
                    if not isinstance(n, ast.Call):
                        continue
                    fn_arg = None
                    if _is_jit_name(dotted(n.func)) and n.args:
                        fn_arg = n.args[0]
                    elif isinstance(n.func, ast.Attribute) \
                            and n.func.attr == "register_program" \
                            and len(n.args) >= 2:
                        fn_arg = n.args[1]
                    if fn_arg is None:
                        continue
                    self._mark_reference(mi, fi, fn_arg)

    def _mark_reference(self, mi: ModuleInfo, scope: FuncInfo,
                        ref: ast.AST) -> None:
        if isinstance(ref, ast.Name):
            # A local def of the enclosing function (registered under a
            # ``<locals>`` qualname by astlint), else a module-level def.
            suffix = f".<locals>.{ref.id}"
            local = next(
                (f for q, f in mi.functions.items()
                 if q.endswith(suffix) and q.startswith(
                     scope.qualname.split(".<locals>.")[0])),
                None)
            target = local or mi.functions.get(ref.id)
            if target is not None:
                self._mark(mi, target)
            return
        attr = _self_attr(ref)
        if attr is not None and scope.cls is not None:
            target = mi.functions.get(f"{scope.cls}.{attr}")
            if target is not None:
                self._mark(mi, target)
            return
        if isinstance(ref, ast.Attribute):
            # ``model.step``-style: type unknown; remember the method name
            # and mark it on every conventional model class.
            self._traced_attr_names.add(ref.attr)

    def _walk_reachability(self) -> None:
        # Conventional-class methods named like recorded attr references.
        for mi in self.modules:
            conv = self._conventional_classes(mi)
            for fi in mi.functions.values():
                if fi.cls in conv and fi.name in self._traced_attr_names \
                        and "<locals>" not in fi.name:
                    self._mark(mi, fi)
        # Bounded same-module call-graph closure.
        work = [(mi, fi, 1) for mi in self.modules
                for fi in list(mi.functions.values())
                if (mi.modname, fi.qualname) in self.traced]
        while work:
            mi, fi, depth = work.pop()
            if depth > MAX_CALL_DEPTH:
                continue
            for n in ast.walk(fi.node):
                if not isinstance(n, ast.Call):
                    continue
                callee = None
                attr = _self_attr(n.func)
                if attr is not None and fi.cls is not None:
                    callee = mi.functions.get(f"{fi.cls}.{attr}")
                elif isinstance(n.func, ast.Name):
                    callee = mi.functions.get(n.func.id)
                if callee is None:
                    continue
                key = (mi.modname, callee.qualname)
                if key in self.traced:
                    continue
                self.traced.add(key)
                work.append((mi, callee, depth + 1))

    # -- rules ----------------------------------------------------------------

    def run(self) -> list[Finding]:
        self._seed_roots()
        self._walk_reachability()
        for mi in self.modules:
            top = [fi for fi in mi.functions.values()
                   if "<locals>" not in fi.name]
            for fi in top:
                if (mi.modname, fi.qualname) in self.traced:
                    self._check_traced_body(mi, fi)
                else:
                    self._check_closure_capture(mi, fi)
                self._check_jit_sites(mi, fi)
        self.findings.sort(key=lambda f: (f.file, f.line, f.rule, f.symbol))
        return self.findings

    # TPS502 / TPS503 over one traced body (nested defs included: a
    # fori_loop/scan body is part of the trace).
    def _check_traced_body(self, mi: ModuleInfo, fi: FuncInfo) -> None:
        taint = _Taint(set(_positional_params(fi.node))
                       - _static_param_names(fi.node))

        def visit_stmt(stmt: ast.AST) -> None:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for p in set(_positional_params(stmt)) \
                        - _static_param_names(stmt):
                    taint.names.add(p)
                for s in stmt.body:
                    visit_stmt(s)
                return
            if isinstance(stmt, ast.Assign):
                t = taint.expr(stmt.value)
                self._check_exprs(mi, fi, taint, stmt)
                for target in stmt.targets:
                    taint.assign(target, t)
                return
            if isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
                value = stmt.value
                t = taint.expr(value) if value is not None else False
                if isinstance(stmt, ast.AugAssign):
                    t = t or taint.expr(stmt.target)
                self._check_exprs(mi, fi, taint, stmt)
                taint.assign(stmt.target, t)
                return
            if isinstance(stmt, (ast.If, ast.While)):
                if taint.expr(stmt.test):
                    kind = "if" if isinstance(stmt, ast.If) else "while"
                    self._add(
                        "TPS503", mi, fi,
                        f"Python `{kind}` on traced value "
                        f"{ast.unparse(stmt.test)} (trace-time branch; use "
                        "jnp.where / lax.cond)", stmt.lineno)
                self._check_exprs(mi, fi, taint, stmt.test)
                for s in [*stmt.body, *stmt.orelse]:
                    visit_stmt(s)
                return
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._check_exprs(mi, fi, taint, stmt.iter)
                taint.assign(stmt.target, taint.expr(stmt.iter))
                for s in [*stmt.body, *stmt.orelse]:
                    visit_stmt(s)
                return
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                for s in stmt.body:
                    visit_stmt(s)
                return
            if isinstance(stmt, ast.Try):
                for s in [*stmt.body, *stmt.orelse, *stmt.finalbody]:
                    visit_stmt(s)
                for h in stmt.handlers:
                    for s in h.body:
                        visit_stmt(s)
                return
            self._check_exprs(mi, fi, taint, stmt)

        for s in fi.node.body:
            visit_stmt(s)

    def _check_exprs(self, mi: ModuleInfo, fi: FuncInfo, taint: _Taint,
                     node: ast.AST) -> None:
        for n in ast.walk(node):
            if not isinstance(n, ast.Call):
                continue
            name = dotted(n.func) or ""
            if isinstance(n.func, ast.Name):
                if n.func.id in HOST_FORCERS and len(n.args) == 1 \
                        and taint.expr(n.args[0]):
                    self._add(
                        "TPS502", mi, fi,
                        f"host-forcing {n.func.id}() on traced value "
                        f"{ast.unparse(n.args[0])}", n.lineno)
                elif n.func.id == "print":
                    self._add(
                        "TPS502", mi, fi,
                        "print() in traced body fires at trace time only "
                        "(use jax.debug.print)", n.lineno)
            if isinstance(n.func, ast.Attribute) \
                    and n.func.attr in HOST_FORCER_ATTRS \
                    and not n.args and taint.expr(n.func.value):
                self._add(
                    "TPS502", mi, fi,
                    f"host-forcing .{n.func.attr}() on traced value "
                    f"{ast.unparse(n.func.value)}", n.lineno)
            if name.split(".")[0] in ("np", "numpy") and (
                    any(taint.expr(a) for a in n.args)
                    or any(taint.expr(kw.value) for kw in n.keywords)):
                self._add(
                    "TPS502", mi, fi,
                    f"{name}() on traced value forces a host transfer "
                    "(use jnp)", n.lineno)

    # TPS501 over one function's jit sites.
    def _check_jit_sites(self, mi: ModuleInfo, fi: FuncInfo) -> None:
        node = fi.node
        # jitted-name -> its static argnames/argnums, to vet call sites.
        statics: dict[str, tuple[set[str], set[int]]] = {}
        for dec in getattr(node, "decorator_list", ()):
            jc = _jit_decorator(dec)
            if jc is not None:
                statics[node.name.split(".")[-1]] = _static_names(jc)
        aot_names = set()
        jit_assigns: list[tuple[str, ast.Call]] = []
        local_defs = {n.name for n in ast.walk(node)
                      if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                      and n is not node}
        parents: dict[int, ast.AST] = {}
        for p in ast.walk(node):
            for c in ast.iter_child_nodes(p):
                parents[id(c)] = p
        for n in ast.walk(node):
            if isinstance(n, ast.Attribute) and n.attr == "lower" \
                    and isinstance(n.value, ast.Name):
                aot_names.add(n.value.id)
            if not isinstance(n, ast.Call) or not _is_jit_name(dotted(n.func)):
                continue
            par = parents.get(id(n))
            if isinstance(par, ast.Attribute) and par.attr == "lower":
                continue  # jax.jit(...).lower(...): AOT, no dispatch cache
            assigned = None
            if isinstance(par, ast.Assign) and len(par.targets) == 1 \
                    and isinstance(par.targets[0], ast.Name):
                assigned = par.targets[0].id
            if n.args and fi.name.split(".")[-1] != "__init__":
                arg0 = n.args[0]
                if isinstance(arg0, ast.Lambda) or (
                        isinstance(arg0, ast.Name) and arg0.id in local_defs):
                    # Verdict deferred: aot_names fills as the walk runs.
                    jit_assigns.append((assigned or "", n))
            if assigned is not None:
                statics[assigned] = _static_names(n)
        # Re-check fresh-callable jit sites now that aot_names is complete.
        for assigned, call in jit_assigns:
            if assigned and assigned in aot_names:
                continue
            arg0 = call.args[0]
            what = ("a lambda" if isinstance(arg0, ast.Lambda)
                    else f"locally-defined {ast.unparse(arg0)}")
            self._add(
                "TPS501", mi, fi,
                f"jax.jit({what}) mints a fresh compile-cache entry per "
                "call (hoist the function, or AOT-compile via "
                ".lower().compile())", call.lineno)
        # Call sites of jitted names: fresh/non-hashable statics.
        for n in ast.walk(node):
            if not isinstance(n, ast.Call):
                continue
            base = dotted(n.func)
            if base is None or base.split(".")[-1] not in statics:
                continue
            names, nums = statics[base.split(".")[-1]]
            for i, a in enumerate(n.args):
                lit = _fresh_literal(a)
                if lit and i in nums:
                    self._add(
                        "TPS501", mi, fi,
                        f"{lit} passed in static_argnums position {i} of "
                        f"{base}() (non-hashable / fresh per call)",
                        n.lineno)
            for kw in n.keywords:
                lit = _fresh_literal(kw.value) if kw.arg else None
                if lit and kw.arg in names:
                    self._add(
                        "TPS501", mi, fi,
                        f"{lit} passed as static_argnames {kw.arg!r} of "
                        f"{base}() (non-hashable / fresh per call)",
                        n.lineno)

    # TPS504/TPS505 over one HOST-side function.
    def _check_closure_capture(self, mi: ModuleInfo, fi: FuncInfo) -> None:
        node = fi.node
        params = set(_positional_params(node))
        if not params:
            return
        # Locals built per call from params via array constructors.
        fresh_arrays: dict[str, int] = {}
        for n in ast.walk(node):
            if not isinstance(n, ast.Assign) or len(n.targets) != 1 \
                    or not isinstance(n.targets[0], ast.Name):
                continue
            v = n.value
            if not isinstance(v, ast.Call):
                continue
            name = dotted(v.func) or ""
            ns, _, last = name.rpartition(".")
            if last in ARRAY_BUILDERS and ns in ARRAY_NAMESPACES:
                uses_param = any(
                    isinstance(sub, ast.Name) and sub.id in params
                    for a in [*v.args, *[kw.value for kw in v.keywords]]
                    for sub in ast.walk(a))
                if uses_param:
                    fresh_arrays[n.targets[0].id] = n.lineno
        local_fns = {n.name: n for n in ast.walk(node)
                     if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                     and n is not node}
        for n in ast.walk(node):
            if not isinstance(n, ast.Call):
                continue
            fn_arg = None
            if _is_jit_name(dotted(n.func)) and n.args:
                fn_arg = n.args[0]
            elif isinstance(n.func, ast.Attribute) \
                    and n.func.attr == "register_program" and len(n.args) >= 2:
                fn_arg = n.args[1]
            if fn_arg is None:
                continue
            target = None
            if isinstance(fn_arg, ast.Lambda):
                target = fn_arg
            elif isinstance(fn_arg, ast.Name) and fn_arg.id in local_fns:
                target = local_fns[fn_arg.id]
            if target is None:
                continue
            free = _free_names(target)
            label = (getattr(target, "name", None) or "lambda")
            for name in sorted(free & params):
                self._add(
                    "TPS505", mi, fi,
                    f"traced {label} captures enclosing argument {name!r} "
                    "by closure — baked as a constant, retraces per "
                    "distinct value (pass it as a traced argument)",
                    n.lineno)
            for name in sorted(free & set(fresh_arrays)):
                self._add(
                    "TPS504", mi, fi,
                    f"traced {label} captures {name!r}, an array built "
                    "per call from enclosing arguments (line "
                    f"{fresh_arrays[name]}) — baked as a constant, "
                    "retraces per call (pass it as a traced argument)",
                    n.lineno)

    # -- plumbing --------------------------------------------------------------

    def _add(self, rule: str, mi: ModuleInfo, fi: FuncInfo, message: str,
             line: int) -> None:
        f = Finding(rule=rule, file=mi.relpath, symbol=fi.qualname,
                    message=message, line=line)
        if f not in self.findings:
            self.findings.append(f)


def run_paths(files: list[Path], root: Path) -> list[Finding]:
    """Parse ``files``, run the TPS5xx rules, and honor inline sanctions."""
    modules = []
    sources: dict[str, list[str]] = {}
    for path in sorted(files):
        mi = _parse_module(path, root)
        if mi is not None:
            modules.append(mi)
            try:
                sources[mi.relpath] = path.read_text().splitlines()
            except OSError:
                pass
    findings = TraceAnalyzer(modules).run()
    return filter_sanctioned(findings, sources)
