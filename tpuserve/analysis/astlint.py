"""AST concurrency lint over the serving path (docs/ANALYSIS.md).

Three rule families, tuned to this repo's architecture — one asyncio event
loop fronting per-stage thread pools, worker processes, and 15+ named locks:

- **TPS101 / TPS102 — blocking on the event loop.** TPS101 flags blocking
  primitives (``time.sleep``, sync file/socket/subprocess IO) called in an
  ``async def`` body or in a sync function the async body calls *directly*
  (a bounded call-graph walk: work handed to ``run_in_executor`` /
  ``StageExecutors.run`` passes a reference, not a call, so it never creates
  an edge). It also flags loop-side ``.result()`` / ``.join()`` and blocking
  ``acquire()``/``wait()`` on a known threading lock inside async bodies.
  TPS102 flags a threading lock held across an ``await`` (a ``with`` over a
  thread-family lock whose body awaits) — the static twin of the runtime
  witness's LockHeldAcrossAwait.

- **TPS201 — lock-order cycles.** Lock attributes are typed from their
  creation sites (``threading.Lock()`` / ``utils.locks.new_lock`` vs
  ``asyncio.Lock()`` / ``new_async_lock``); nested ``with lock:`` scopes
  (plus locks acquired by functions called while a lock is held, one level
  deep) build a global acquisition graph, and any cycle — the classic AB/BA
  inversion — is reported with both acquisition sites.

- **TPS301 — unguarded cross-context writes.** Per class, every method is
  placed in an execution context: event loop (``async def``, or referenced
  by ``call_soon*``/``call_later``/``add_done_callback``) or executor thread
  (referenced by ``run_in_executor``/``submit``/``map``/``Thread(target=)``),
  with contexts and held locks propagated through intra-class calls to a
  fixpoint. An instance attribute mutated from both contexts with no common
  threading lock on every path is flagged.

Honest limits: resolution is name-based within a module/class (no type
inference across objects), so cross-object mutation (``w.rows_used += 1``)
and dynamically-dispatched calls are invisible — that residue is exactly
what the runtime witness covers. Findings must be read with the baseline
workflow in mind: ``tpuserve/analysis/baseline.json`` holds accepted debt.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from pathlib import Path

from tpuserve.analysis.findings import Finding

THREAD_LOCK_FACTORIES = {
    "threading.Lock",
    "threading.RLock",
    "new_lock",
    "locks.new_lock",
}
THREAD_COND_FACTORIES = {
    "threading.Condition",
    "threading.Event",
    "threading.Semaphore",
    "threading.BoundedSemaphore",
}
ASYNC_LOCK_FACTORIES = {
    "asyncio.Lock",
    "asyncio.Condition",
    "asyncio.Semaphore",
    "asyncio.Event",
    "new_async_lock",
    "locks.new_async_lock",
}

# Blocking in ANY loop-executed code: flagged in async bodies and propagated
# through directly-called sync helpers.
BLOCKING_CALLS = {
    "time.sleep",
    "os.system",
    "os.waitpid",
    "subprocess.run",
    "subprocess.call",
    "subprocess.check_call",
    "subprocess.check_output",
    "socket.create_connection",
    "socket.getaddrinfo",
    "urllib.request.urlopen",
    "requests.get",
    "requests.post",
    "requests.request",
}
BLOCKING_BUILTINS = {"open", "input"}

# Blocking only worth flagging when written directly in an async body (sync
# helpers use these legitimately on executor threads).
ASYNC_ONLY_ATTRS = {"result", "join"}

MUTATOR_ATTRS = {
    "append",
    "appendleft",
    "extend",
    "insert",
    "remove",
    "pop",
    "popleft",
    "popitem",
    "clear",
    "update",
    "setdefault",
    "add",
    "discard",
}

THREAD_SCHEDULERS = {"run_in_executor", "submit", "map"}
LOOP_SCHEDULERS = {
    "call_soon",
    "call_soon_threadsafe",
    "call_later",
    "call_at",
    "add_done_callback",
}

MAX_CALL_DEPTH = 4


def dotted(node: ast.AST) -> str | None:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _self_attr(node: ast.AST) -> str | None:
    """'X' when node is ``self.X``, else None."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _lock_family(call: ast.AST) -> str | None:
    """'thread' / 'async' when ``call`` constructs a known lock, else None."""
    if not isinstance(call, ast.Call):
        return None
    name = dotted(call.func)
    if name is None:
        return None
    if name in THREAD_LOCK_FACTORIES or name in THREAD_COND_FACTORIES:
        return "thread"
    if name in ASYNC_LOCK_FACTORIES:
        return "async"
    # The named constructors also match when imported qualified
    # (tpuserve.utils.locks.new_lock) or called through an alias ending in
    # the bare helper name.
    short = name.split(".")[-1]
    if short == "new_lock":
        return "thread"
    if short == "new_async_lock":
        return "async"
    return None


@dataclass
class FuncInfo:
    module: str
    cls: str | None
    name: str
    node: ast.AST
    is_async: bool

    @property
    def qualname(self) -> str:
        return f"{self.cls}.{self.name}" if self.cls else self.name


@dataclass
class ModuleInfo:
    relpath: str
    modname: str
    tree: ast.Module
    class_locks: dict[str, dict[str, str]] = field(default_factory=dict)
    module_locks: dict[str, str] = field(default_factory=dict)
    functions: dict[str, FuncInfo] = field(default_factory=dict)  # qualname ->


def _walk_skipping_defs(node: ast.AST):
    """Yield nodes of ``node``'s body without descending into nested defs."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(n))


def _collect_nested(mi: ModuleInfo, owner: FuncInfo) -> None:
    """Register defs nested inside ``owner`` under ``<locals>`` qualnames.

    Async generators defined inside handler functions (PR 17's streaming
    bodies) run ON the event loop when iterated, but used to be invisible:
    only top-level and class-level defs were collected, so the blocking-call
    rules never saw them. The ``<locals>`` qualname keeps them out of the
    bare-name resolution map (``_resolve_call`` looks up ``f`` or
    ``Cls.f``), so they are checked directly without becoming accidental
    call-graph targets."""
    for sub in ast.walk(owner.node):
        if sub is owner.node:
            continue
        if not isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        fi = FuncInfo(
            mi.modname,
            owner.cls,
            f"{owner.name}.<locals>.{sub.name}",
            sub,
            isinstance(sub, ast.AsyncFunctionDef),
        )
        mi.functions.setdefault(fi.qualname, fi)


def _parse_module(path: Path, root: Path) -> ModuleInfo | None:
    try:
        src = path.read_text()
        tree = ast.parse(src, filename=str(path))
    except (OSError, SyntaxError):
        return None
    rel = path.relative_to(root).as_posix() if path.is_relative_to(root) else path.name
    modname = rel[:-3].replace("/", ".") if rel.endswith(".py") else rel
    mi = ModuleInfo(relpath=rel, modname=modname, tree=tree)

    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
            fam = _lock_family(stmt.value)
            if fam and isinstance(stmt.targets[0], ast.Name):
                mi.module_locks[stmt.targets[0].id] = fam
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fi = FuncInfo(modname, None, stmt.name, stmt, isinstance(stmt, ast.AsyncFunctionDef))
            mi.functions[fi.qualname] = fi
        elif isinstance(stmt, ast.ClassDef):
            locks: dict[str, str] = {}
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                    attr = _self_attr(sub.targets[0])
                    fam = _lock_family(sub.value)
                    if attr and fam:
                        locks[attr] = fam
            mi.class_locks[stmt.name] = locks
            for item in stmt.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    fi = FuncInfo(
                        modname,
                        stmt.name,
                        item.name,
                        item,
                        isinstance(item, ast.AsyncFunctionDef),
                    )
                    mi.functions[fi.qualname] = fi
    for fi in list(mi.functions.values()):
        _collect_nested(mi, fi)
    return mi


class Analyzer:
    """Cross-module rule driver over a set of parsed modules."""

    def __init__(self, modules: list[ModuleInfo]) -> None:
        self.modules = modules
        self.findings: list[Finding] = []

    def run(self) -> list[Finding]:
        edges: list[tuple[str, str, str, str, int]] = []  # (a, b, file, symbol, line)
        for mi in self.modules:
            for fi in mi.functions.values():
                if fi.is_async:
                    self._check_async_body(mi, fi)
                    self._check_lock_across_await(mi, fi)
            edges.extend(self._lock_edges(mi))
        self._check_lock_cycles(edges)
        for mi in self.modules:
            for stmt in mi.tree.body:
                if isinstance(stmt, ast.ClassDef):
                    self._check_shared_state(mi, stmt)
        self.findings.sort(key=lambda f: (f.file, f.line, f.rule, f.symbol))
        return self.findings

    # -- shared resolution helpers -------------------------------------------

    def _lock_id(self, mi: ModuleInfo, cls: str | None, node: ast.AST) -> tuple[str, str] | None:
        """(lock id, family) for a lock-valued expression, else None."""
        attr = _self_attr(node)
        if attr is not None and cls is not None:
            fam = mi.class_locks.get(cls, {}).get(attr)
            if fam:
                return f"{mi.modname}.{cls}.{attr}", fam
        if isinstance(node, ast.Name) and node.id in mi.module_locks:
            return f"{mi.modname}.{node.id}", mi.module_locks[node.id]
        return None

    def _resolve_call(self, mi: ModuleInfo, cls: str | None, call: ast.Call) -> FuncInfo | None:
        """Resolve a direct call to a same-module function / same-class
        method. Cross-object calls resolve to None on purpose (no type
        inference — see module docstring)."""
        func = call.func
        attr = _self_attr(func)
        if attr is not None and cls is not None:
            return mi.functions.get(f"{cls}.{attr}")
        if isinstance(func, ast.Name):
            return mi.functions.get(func.id)
        return None

    # -- TPS101: blocking reachable from async -------------------------------

    def _direct_blocking(self, node: ast.AST, awaited: set[int]) -> list[tuple[str, int]]:
        out = []
        for n in _walk_skipping_defs(node):
            if isinstance(n, ast.Await):
                awaited.add(id(n.value))
            if not isinstance(n, ast.Call) or id(n) in awaited:
                continue
            name = dotted(n.func)
            if name in BLOCKING_CALLS:
                out.append((name, n.lineno))
            elif isinstance(n.func, ast.Name) and n.func.id in BLOCKING_BUILTINS:
                out.append((f"{n.func.id}()", n.lineno))
        return out

    def _check_async_body(self, mi: ModuleInfo, fi: FuncInfo) -> None:
        awaited: set[int] = set()
        for n in _walk_skipping_defs(fi.node):
            if isinstance(n, ast.Await):
                awaited.add(id(n.value))
        parents: dict[int, ast.AST] = {}
        for p in ast.walk(fi.node):
            for c in ast.iter_child_nodes(p):
                parents[id(c)] = p
        # Direct blocking primitives + loop-only smells in the async body.
        for desc, line in self._direct_blocking(fi.node, awaited):
            self._add("TPS101", mi, fi.qualname, f"blocking call {desc} in async def", line)
        for n in _walk_skipping_defs(fi.node):
            if not isinstance(n, ast.Call) or id(n) in awaited:
                continue
            if isinstance(n.func, ast.Attribute):
                if n.func.attr in ASYNC_ONLY_ATTRS and not n.args and not n.keywords:
                    if self._done_guarded(parents, n):
                        continue  # t.result() under `if t.done():` — no wait
                    self._add(
                        "TPS101",
                        mi,
                        fi.qualname,
                        f"blocking .{n.func.attr}() in async def",
                        n.lineno,
                    )
                elif n.func.attr in ("acquire", "wait"):
                    lock = self._lock_id(mi, fi.cls, n.func.value)
                    if lock and lock[1] == "thread":
                        self._add(
                            "TPS101",
                            mi,
                            fi.qualname,
                            f"blocking {lock[0]}.{n.func.attr}() in async def",
                            n.lineno,
                        )
        # Propagate through directly-called sync helpers (bounded DFS).
        self._reach_blocking(mi, fi, fi.node, awaited, [fi.qualname], set())

    @staticmethod
    def _done_guarded(parents: dict[int, ast.AST], call: ast.Call) -> bool:
        """True for ``t.result()`` inside the body of ``if t.done():`` — the
        task already completed, so the read cannot block the loop."""
        if call.func.attr != "result":
            return False
        recv = dotted(call.func.value)
        if recv is None:
            return False
        child: ast.AST = call
        n: ast.AST = call
        while id(n) in parents:
            child, n = n, parents[id(n)]
            if not isinstance(n, ast.If) or child not in n.body:
                continue
            for sub in ast.walk(n.test):
                if isinstance(sub, ast.Call) \
                        and isinstance(sub.func, ast.Attribute) \
                        and sub.func.attr == "done" \
                        and dotted(sub.func.value) == recv:
                    return True
        return False

    def _reach_blocking(self, mi, fi, node, awaited, path, seen) -> None:
        if len(path) > MAX_CALL_DEPTH:
            return
        for n in _walk_skipping_defs(node):
            if not isinstance(n, ast.Call):
                continue
            callee = self._resolve_call(mi, fi.cls, n)
            if callee is None or callee.is_async or callee.qualname in seen:
                continue
            seen.add(callee.qualname)
            sub_awaited: set[int] = set()
            for hit, _line in self._direct_blocking(callee.node, sub_awaited):
                self._add(
                    "TPS101",
                    mi,
                    path[0],
                    f"blocking call {hit} reachable from async def via "
                    + " -> ".join([*path[1:], callee.qualname]),
                    n.lineno,
                )
            self._reach_blocking(mi, callee, callee.node, sub_awaited, [*path, callee.qualname], seen)

    # -- TPS102: threading lock held across await ----------------------------

    def _check_lock_across_await(self, mi: ModuleInfo, fi: FuncInfo) -> None:
        for n in _walk_skipping_defs(fi.node):
            if not isinstance(n, ast.With):
                continue
            for item in n.items:
                lock = self._lock_id(mi, fi.cls, item.context_expr)
                if lock is None or lock[1] != "thread":
                    continue
                body_awaits = any(
                    isinstance(sub, ast.Await)
                    for stmt in n.body
                    for sub in [stmt, *_walk_skipping_defs(stmt)]
                )
                if body_awaits:
                    self._add(
                        "TPS102",
                        mi,
                        fi.qualname,
                        f"threading lock {lock[0]} held across await",
                        n.lineno,
                    )

    # -- TPS201: lock-order graph --------------------------------------------

    def _locks_acquired_in(self, mi: ModuleInfo, fi: FuncInfo) -> list[tuple[str, int]]:
        out = []
        for n in _walk_skipping_defs(fi.node):
            if isinstance(n, ast.With):
                for item in n.items:
                    lock = self._lock_id(mi, fi.cls, item.context_expr)
                    if lock:
                        out.append((lock[0], n.lineno))
        return out

    def _lock_edges(self, mi: ModuleInfo) -> list[tuple[str, str, str, str, int]]:
        edges = []

        def visit(fi: FuncInfo, node: ast.AST, held: list[str]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                    continue
                if isinstance(child, ast.With):
                    acquired = []
                    for item in child.items:
                        lock = self._lock_id(mi, fi.cls, item.context_expr)
                        if lock:
                            for h in held:
                                if h != lock[0]:
                                    edges.append((h, lock[0], mi.relpath, fi.qualname, child.lineno))
                            acquired.append(lock[0])
                    visit(fi, child, held + acquired)
                    continue
                if isinstance(child, ast.Call) and held:
                    callee = self._resolve_call(mi, fi.cls, child)
                    if callee is not None:
                        for lock_id, line in self._locks_acquired_in(mi, callee):
                            for h in held:
                                if h != lock_id:
                                    edges.append((h, lock_id, mi.relpath, fi.qualname, child.lineno))
                visit(fi, child, held)

        for fi in mi.functions.values():
            visit(fi, fi.node, [])
        return edges

    def _check_lock_cycles(self, edges: list[tuple[str, str, str, str, int]]) -> None:
        succ: dict[str, set[str]] = {}
        first_site: dict[tuple[str, str], tuple[str, str, int]] = {}
        for a, b, f, sym, line in edges:
            succ.setdefault(a, set()).add(b)
            first_site.setdefault((a, b), (f, sym, line))

        def path(start: str, goal: str) -> list[str] | None:
            stack, seen = [(start, [start])], {start}
            while stack:
                node, p = stack.pop()
                if node == goal:
                    return p
                for nxt in sorted(succ.get(node, ())):
                    if nxt not in seen:
                        seen.add(nxt)
                        stack.append((nxt, [*p, nxt]))
            return None

        reported: set[frozenset] = set()
        for (a, b), (f, sym, line) in sorted(first_site.items()):
            back = path(b, a)
            if back is None:
                continue
            cycle = [a, *back]
            key = frozenset(cycle)
            if key in reported:
                continue
            reported.add(key)
            sites = []
            for x, y in zip(cycle, cycle[1:]):
                sf, ssym, _sl = first_site[(x, y)]
                sites.append(f"{x}->{y} in {ssym} ({sf})")
            self._add_raw(
                Finding(
                    rule="TPS201",
                    file=f,
                    symbol=" -> ".join(cycle),
                    message="lock-order cycle: " + "; ".join(sites),
                    line=line,
                )
            )

    # -- TPS301: unguarded cross-context writes ------------------------------

    def _check_shared_state(self, mi: ModuleInfo, cls: ast.ClassDef) -> None:
        lock_attrs = {a for a, fam in mi.class_locks.get(cls.name, {}).items() if fam == "thread"}
        methods = {
            fi.name: fi
            for fi in mi.functions.values()
            if fi.cls == cls.name and fi.name not in ("__init__", "__post_init__")
        }
        # Per method: writes/calls with the lexical thread-lock guards active.
        writes: dict[str, list[tuple[str, frozenset, int]]] = {m: [] for m in methods}
        calls: dict[str, list[tuple[str, frozenset]]] = {m: [] for m in methods}
        seeds: set[tuple[str, str]] = set()  # (method, ctx)

        def written_attr(n: ast.AST) -> str | None:
            if isinstance(n, (ast.Assign, ast.AugAssign)):
                targets = n.targets if isinstance(n, ast.Assign) else [n.target]
                for t in targets:
                    attr = _self_attr(t)
                    if attr is not None:
                        return attr
                    if isinstance(t, ast.Subscript):
                        attr = _self_attr(t.value)
                        if attr is not None:
                            return attr
            if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute):
                if n.func.attr in MUTATOR_ATTRS:
                    attr = _self_attr(n.func.value)
                    if attr is not None:
                        return attr
            return None

        def scan(mname: str, fi: FuncInfo, node: ast.AST, guards: frozenset) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                    continue
                if isinstance(child, ast.With):
                    acquired = set()
                    for item in child.items:
                        attr = _self_attr(item.context_expr)
                        if attr in lock_attrs:
                            acquired.add(attr)
                    scan(mname, fi, child, guards | acquired)
                    continue
                attr = written_attr(child)
                if attr is not None and attr not in lock_attrs:
                    writes[mname].append((attr, guards, child.lineno))
                if isinstance(child, ast.Call):
                    callee = self._resolve_call(mi, cls.name, child)
                    if callee is not None and callee.name in methods:
                        calls[mname].append((callee.name, guards))
                    self._scan_scheduling(child, methods, seeds)
                scan(mname, fi, child, guards)

        for mname, fi in methods.items():
            if fi.is_async:
                seeds.add((mname, "loop"))
            scan(mname, fi, fi.node, frozenset())

        # Propagate (ctx, held-at-entry) through intra-class calls. Entry
        # state per (method, ctx) is the INTERSECTION over paths: a write is
        # guarded only if the lock is held however the method was reached.
        entry: dict[tuple[str, str], frozenset] = {}
        work = [(m, ctx, frozenset()) for m, ctx in seeds]
        while work:
            mname, ctx, held = work.pop()
            key = (mname, ctx)
            merged = held if key not in entry else entry[key] & held
            if key in entry and merged == entry[key]:
                continue
            entry[key] = merged
            for callee, site_guards in calls.get(mname, ()):
                work.append((callee, ctx, merged | site_guards))

        # An attribute written unguarded from both contexts (no common lock
        # between the thread-side and loop-side writes) is a race.
        per_attr: dict[str, dict[str, list[tuple[frozenset, str]]]] = {}
        for (mname, ctx), held in entry.items():
            for attr, guards, _line in writes.get(mname, ()):
                per_attr.setdefault(attr, {}).setdefault(ctx, []).append(
                    (guards | held, mname)
                )
        for attr, by_ctx in sorted(per_attr.items()):
            for tguards, tmeth in by_ctx.get("thread", ()):
                for lguards, lmeth in by_ctx.get("loop", ()):
                    if tguards & lguards:
                        continue
                    self._add_raw(
                        Finding(
                            rule="TPS301",
                            file=mi.relpath,
                            symbol=f"{cls.name}.{attr}",
                            message=(
                                f"written from executor-thread context ({tmeth}) and "
                                f"event-loop context ({lmeth}) with no common lock"
                            ),
                            line=cls.lineno,
                        )
                    )
                    break
                else:
                    continue
                break

    def _scan_scheduling(self, call: ast.Call, methods: dict, seeds: set) -> None:
        """Record methods handed to executors/threads vs loop callbacks."""
        ctx = None
        func = call.func
        if isinstance(func, ast.Attribute):
            if func.attr in THREAD_SCHEDULERS:
                ctx = "thread"
            elif func.attr in LOOP_SCHEDULERS:
                ctx = "loop"
        name = dotted(func) or ""
        values = list(call.args) + [kw.value for kw in call.keywords]
        if name.split(".")[-1] == "Thread":
            ctx = "thread"
        if ctx is None:
            return
        for v in values:
            attr = _self_attr(v)
            if attr in methods:
                seeds.add((attr, ctx))

    # -- plumbing ------------------------------------------------------------

    def _add(self, rule: str, mi: ModuleInfo, symbol: str, message: str, line: int) -> None:
        self._add_raw(Finding(rule=rule, file=mi.relpath, symbol=symbol, message=message, line=line))

    def _add_raw(self, finding: Finding) -> None:
        if finding not in self.findings:
            self.findings.append(finding)


def run_paths(files: list[Path], root: Path) -> list[Finding]:
    """Parse ``files`` and run every AST rule family; returns findings."""
    modules = []
    for path in sorted(files):
        mi = _parse_module(path, root)
        if mi is not None:
            modules.append(mi)
    return Analyzer(modules).run()


def collect_files(paths: list[Path]) -> list[Path]:
    files: list[Path] = []
    for p in paths:
        if p.is_dir():
            files.extend(sorted(p.rglob("*.py")))
        elif p.suffix == ".py":
            files.append(p)
    return files
