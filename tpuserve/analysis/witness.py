"""Runtime lock-order witness: a Python-level mini-TSan for the serving path.

The static pass (tpuserve.analysis.astlint) sees lock *sites*; this module
watches lock *instances* live. When ``TPUSERVE_LOCK_WITNESS=1`` (the chaos
drill and the smoke scripts set it in CI), every lock built through
``tpuserve.utils.locks.new_lock`` / ``new_async_lock`` becomes a witness
wrapper that:

- records, per thread (and per asyncio task for async locks), the stack of
  currently-held witnessed locks;
- maintains one global lock-order graph keyed by lock *name* (the creation
  site, e.g. ``deferred.spawn``), adding an edge H -> L whenever L is
  acquired while H is held, and **raising LockOrderViolation** the moment a
  new edge closes a cycle — an AB/BA inversion is reported at acquisition
  time, deterministically, instead of as a once-a-month production deadlock;
- via an asyncio task factory (``install``), checks at **every coroutine
  suspension** that the event-loop thread holds no witnessed ``threading``
  lock, raising LockHeldAcrossAwait with the acquisition stack when one is
  held across an ``await`` (asyncio locks are exempt: holding those across
  awaits is their job).

Violations raise because silent logging defeats the point in CI: the chaos
drill asserts availability, and a raised violation fails the run visibly.
``snapshot()`` exposes the observed graph (surfaced in ``/stats`` under
``robustness.lock_witness`` when the witness is installed).

Scope and honesty: only locks created through the named constructors are
witnessed — third-party and stdlib-internal locks are invisible, and a lock
acquired and released inside one bytecode run of a C extension cannot be
seen at all. That is the right trade: the serving path's own 15+ locks are
the ones whose ordering this repo controls. See docs/ANALYSIS.md.

A second, independent witness lives here too: the **retrace witness**
(``TPUSERVE_RETRACE_WITNESS=1``). The static pass (tracelint, TPS5xx)
proves trace discipline over what it can see; the residue — a model whose
bucket set varies per call, a shape leaking into a program identity — only
shows up as ``runtime_compiles_total`` ticking under load. The server
declares a *warmup barrier* once startup compilation is done
(``declare_warmup_complete``); after it, every compile the runtime reports
through ``note_compile(tag, variant)`` raises **RetraceViolation naming
the (tag, variant)** unless it happens inside a ``sanctioned_compiles()``
window (the lifecycle's cold-boot ``ensure_compiled`` is the one such
window: demand-compiling a cold model is the feature, not a retrace). The
jax half — arming ``jax_transfer_guard`` at the barrier and the blessed
``host_fetch`` escape — lives in ``tpuserve.utils.retrace`` so this module
stays importable on bare Python (the CI lint job). Smokes export the env
var exactly like ``TPUSERVE_LOCK_WITNESS``, so every drill doubles as a
retrace-detection pass.
"""

from __future__ import annotations

import asyncio
import os
import threading
import traceback

_ENV = "TPUSERVE_LOCK_WITNESS"
_TRUE = ("1", "true", "yes", "on")

# Bound kept state: violations and per-edge stacks are capped so a pathological
# run cannot grow memory without bound.
_MAX_VIOLATIONS = 64
_STACK_FRAMES = 8


class WitnessViolation(RuntimeError):
    """Base class for witness findings (raised, not logged: see module doc)."""


class LockOrderViolation(WitnessViolation):
    """A lock acquisition closed a cycle in the global lock-order graph."""


class LockHeldAcrossAwait(WitnessViolation):
    """A threading lock was held by the event-loop thread at a coroutine
    suspension point — the await parks the loop while the lock stays taken."""


_forced: bool | None = None


def enabled() -> bool:
    """Witness on? Env-driven (TPUSERVE_LOCK_WITNESS=1) unless force()d."""
    if _forced is not None:
        return _forced
    return os.environ.get(_ENV, "").strip().lower() in _TRUE


def force(value: bool | None) -> None:
    """Test hook: override the env check (None restores env behavior)."""
    global _forced
    _forced = value


def _site_stack() -> str:
    frames = [f for f in traceback.extract_stack() if not f.filename.endswith("witness.py")]
    keep = [f for f in frames if "tpuserve" in f.filename] or frames
    return " <- ".join(
        f"{os.path.basename(f.filename)}:{f.lineno}({f.name})" for f in keep[-_STACK_FRAMES:]
    )


class _Registry:
    """Global witness state: held-lock stacks and the lock-order graph.

    Internal synchronization uses a RAW threading.Lock (never a WitnessLock:
    the registry must not witness itself). The graph is name-keyed, so two
    instances from one creation site share a node — an AB/BA inversion
    between *roles* is caught even across distinct instances.
    """

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._tls = threading.local()
        self.edges: dict[tuple[str, str], dict] = {}
        self.succ: dict[str, set[str]] = {}
        self.locks_seen: set[str] = set()
        self.acquisitions = 0
        self.violations: list[dict] = []
        # Held asyncio-lock names per task id (tasks are not weakly held long:
        # entries are removed on release, and a task dying mid-hold leaks one
        # small list at most until the same id is reused).
        self._task_held: dict[int, list[tuple[str, str]]] = {}

    # -- held-state ----------------------------------------------------------
    def _thread_held(self) -> list[tuple[str, str]]:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def _current_task_id(self) -> int | None:
        try:
            task = asyncio.current_task()
        except RuntimeError:
            return None
        return None if task is None else id(task)

    def register(self, name: str) -> None:
        with self._mu:
            self.locks_seen.add(name)

    # -- threading-lock protocol --------------------------------------------
    def intent(self, name: str) -> None:
        """About to acquire ``name`` on this thread: record order edges from
        every lock already held here; raise if one closes a cycle."""
        self._note_edges(name, self._thread_held())

    def push(self, name: str) -> None:
        self._thread_held().append((name, _site_stack()))

    def pop(self, name: str) -> None:
        held = self._thread_held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] == name:
                del held[i]
                return
        # Released on a different thread than it was acquired on (legal for
        # bare Lock, and happens when a violation unwound the holder): no-op.

    # -- asyncio-lock protocol ----------------------------------------------
    def async_intent(self, name: str) -> None:
        """Order edges for an async acquire: predecessors are the current
        task's held async locks plus this thread's held threading locks."""
        held = list(self._thread_held())
        tid = self._current_task_id()
        if tid is not None:
            held += self._task_held.get(tid, [])
        self._note_edges(name, held)

    def push_async(self, name: str) -> None:
        tid = self._current_task_id()
        if tid is not None:
            self._task_held.setdefault(tid, []).append((name, _site_stack()))

    def pop_async(self, name: str) -> None:
        tid = self._current_task_id()
        held = self._task_held.get(tid)
        if not held:
            return
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] == name:
                del held[i]
                break
        if not held:
            self._task_held.pop(tid, None)

    # -- graph ---------------------------------------------------------------
    def _note_edges(self, name: str, held: list[tuple[str, str]]) -> None:
        if not held:
            with self._mu:
                self.acquisitions += 1
            return
        stack = _site_stack()
        cycle_msg = None
        with self._mu:
            self.acquisitions += 1
            for prev, _ in held:
                if prev == name:
                    continue  # same-site reentry across instances: not an order
                key = (prev, name)
                if key in self.edges:
                    self.edges[key]["count"] += 1
                    continue
                path = self._find_path(name, prev)
                self.edges[key] = {"stack": stack, "count": 1}
                self.succ.setdefault(prev, set()).add(name)
                if path is not None:
                    cycle = [prev, name, *path[1:]]
                    cycle_msg = self._record_violation(
                        "lock_order",
                        "lock-order cycle: " + " -> ".join(cycle),
                        stack,
                    )
        if cycle_msg is not None:
            raise LockOrderViolation(cycle_msg)

    def _find_path(self, start: str, goal: str) -> list[str] | None:
        """Path start ->* goal over recorded edges (callers hold self._mu)."""
        stack = [(start, [start])]
        seen = {start}
        while stack:
            node, path = stack.pop()
            if node == goal:
                return path
            for nxt in self.succ.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, [*path, nxt]))
        return None

    def _record_violation(self, kind: str, message: str, stack: str) -> str:
        if len(self.violations) < _MAX_VIOLATIONS:
            self.violations.append({"kind": kind, "message": message, "stack": stack})
        return f"{message} [at {stack}]"

    # -- suspension check (task driver) --------------------------------------
    def check_suspension(self) -> None:
        held = self._thread_held()
        if not held:
            return
        detail = "; ".join(f"{name} (acquired at {stack})" for name, stack in held)
        with self._mu:
            msg = self._record_violation(
                "held_across_await",
                f"threading lock(s) held across an await: {detail}",
                _site_stack(),
            )
        raise LockHeldAcrossAwait(msg)

    # -- admin ---------------------------------------------------------------
    def snapshot(self) -> dict:
        with self._mu:
            return {
                "locks": sorted(self.locks_seen),
                "acquisitions": self.acquisitions,
                "edges": sorted(
                    [a, b, info["count"]] for (a, b), info in self.edges.items()
                ),
                "violations": list(self.violations),
            }

    def reset(self) -> None:
        with self._mu:
            self.edges.clear()
            self.succ.clear()
            self.locks_seen.clear()
            self.acquisitions = 0
            self.violations.clear()
            self._task_held.clear()
        self._tls.held = []


_REG = _Registry()


def snapshot() -> dict:
    """Observed lock graph + violations (the /stats lock_witness block)."""
    return _REG.snapshot()


def reset() -> None:
    """Test hook: drop all recorded graph/held state."""
    _REG.reset()


class WitnessLock:
    """Drop-in threading.Lock wrapper feeding the witness registry."""

    __slots__ = ("name", "_lock")

    def __init__(self, name: str) -> None:
        self.name = name
        self._lock = threading.Lock()
        _REG.register(name)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        _REG.intent(self.name)  # may raise LockOrderViolation, before blocking
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            _REG.push(self.name)
        return ok

    def release(self) -> None:
        self._lock.release()
        _REG.pop(self.name)

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> "WitnessLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<WitnessLock {self.name} locked={self._lock.locked()}>"


class WitnessAsyncLock:
    """Drop-in asyncio.Lock wrapper feeding the witness registry.

    Holding one across an await is legal (that is what asyncio locks are
    for); it still participates in the order graph so an AB/BA inversion
    between two async locks — or an async lock nested against a threading
    lock on the loop thread — is caught."""

    __slots__ = ("name", "_lock")

    def __init__(self, name: str) -> None:
        self.name = name
        self._lock = asyncio.Lock()
        _REG.register(name)

    async def acquire(self) -> bool:
        _REG.async_intent(self.name)  # may raise LockOrderViolation
        await self._lock.acquire()
        _REG.push_async(self.name)
        return True

    def release(self) -> None:
        self._lock.release()
        _REG.pop_async(self.name)

    def locked(self) -> bool:
        return self._lock.locked()

    async def __aenter__(self) -> None:
        await self.acquire()

    async def __aexit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<WitnessAsyncLock {self.name} locked={self._lock.locked()}>"


# ---------------------------------------------------------------------------
# Suspension instrumentation: a task factory whose tasks run coroutines
# through a driver that re-yields every suspension, checking held locks at
# each one. This is the piece that turns "lock held across await" from a
# code-review judgement into a deterministic runtime error.
# ---------------------------------------------------------------------------


class _YieldThrough:
    """Awaitable forwarding one raw yield (a Future or None) to the Task."""

    __slots__ = ("value",)

    def __init__(self, value) -> None:
        self.value = value

    def __await__(self):
        result = yield self.value
        return result


async def _driver(coro):
    """Step ``coro`` manually, checking witness state at every suspension."""
    send_value = None
    exc: BaseException | None = None
    while True:
        try:
            if exc is None:
                yielded = coro.send(send_value)
            else:
                pending, exc = exc, None
                yielded = coro.throw(pending)
        except StopIteration as stop:
            return stop.value
        try:
            _REG.check_suspension()
        except WitnessViolation:
            # Unwind the inner coroutine NOW so its with/finally blocks run
            # and release the offending lock; otherwise release would happen
            # nondeterministically at GC and poison this thread's held list.
            coro.close()
            raise
        try:
            send_value = await _YieldThrough(yielded)
        except BaseException as e:  # noqa: BLE001 — forwarded into coro
            send_value = None
            exc = e


def _task_factory(loop, coro, **kwargs):
    if asyncio.iscoroutine(coro):
        coro = _driver(coro)
    return asyncio.Task(coro, loop=loop, **kwargs)


def install(loop: asyncio.AbstractEventLoop | None = None) -> None:
    """Instrument task creation on ``loop`` (default: the running loop)."""
    if loop is None:
        loop = asyncio.get_running_loop()
    loop.set_task_factory(_task_factory)


def maybe_install(loop: asyncio.AbstractEventLoop | None = None) -> bool:
    """install() when the witness is enabled; returns whether it is."""
    if enabled():
        install(loop)
        return True
    return False


# ---------------------------------------------------------------------------
# Retrace witness: compile-stability assertions after the warmup barrier.
# Pure Python (no jax import) — tpuserve.utils.retrace holds the jax half.
# ---------------------------------------------------------------------------

_RETRACE_ENV = "TPUSERVE_RETRACE_WITNESS"
_retrace_forced: bool | None = None


class RetraceViolation(WitnessViolation):
    """The runtime compiled a new executable after the warmup barrier —
    the steady-state compile-delta-0 invariant broke, and the message
    names the (tag, variant) that minted the compile."""


def retrace_enabled() -> bool:
    """Retrace witness on? Env-driven unless force_retrace()d."""
    if _retrace_forced is not None:
        return _retrace_forced
    return os.environ.get(_RETRACE_ENV, "").strip().lower() in _TRUE


def force_retrace(value: bool | None) -> None:
    """Test hook: override the env check (None restores env behavior)."""
    global _retrace_forced
    _retrace_forced = value


class _RetraceRegistry:
    """Per-process compile ledger around one declared warmup barrier.

    Compiles before the barrier are warmup (counted, silent). A
    ``sanctioned()`` window marks deliberate post-barrier compilation —
    the lifecycle's cold-boot ``ensure_compiled`` — process-wide on
    purpose: the compile may run on an executor thread, not the thread
    that opened the window. Everything else after the barrier raises."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self.barrier: str | None = None  # declaring site, None = not yet
        self.warmup_compiles = 0
        self.sanction_depth = 0
        self.sanctioned_compiles = 0
        self.violations: list[dict] = []

    def note_compile(self, tag: str, variant: str) -> None:
        if not retrace_enabled():
            return
        stack = _site_stack()
        with self._mu:
            if self.barrier is None:
                self.warmup_compiles += 1
                return
            if self.sanction_depth > 0:
                self.sanctioned_compiles += 1
                return
            msg = (f"compile after warmup barrier: tag={tag} "
                   f"variant={variant} (barrier declared at {self.barrier})")
            if len(self.violations) < _MAX_VIOLATIONS:
                self.violations.append(
                    {"kind": "retrace", "tag": tag, "variant": variant,
                     "message": msg, "stack": stack})
        raise RetraceViolation(f"{msg} [at {stack}]")

    def declare_barrier(self) -> None:
        with self._mu:
            self.barrier = _site_stack()

    def sanction_enter(self) -> None:
        with self._mu:
            self.sanction_depth += 1

    def sanction_exit(self) -> None:
        with self._mu:
            self.sanction_depth = max(0, self.sanction_depth - 1)

    def snapshot(self) -> dict:
        with self._mu:
            return {
                "enabled": retrace_enabled(),
                "barrier_declared": self.barrier is not None,
                "warmup_compiles": self.warmup_compiles,
                "sanctioned_compiles": self.sanctioned_compiles,
                "violations": list(self.violations),
            }

    def reset(self) -> None:
        with self._mu:
            self.barrier = None
            self.warmup_compiles = 0
            self.sanction_depth = 0
            self.sanctioned_compiles = 0
            self.violations.clear()


_RETRACE = _RetraceRegistry()


def note_compile(tag: str, variant: str) -> None:
    """Runtime compile-site hook (``_compile_bucket``/``register_program``
    call this at every ``runtime_compiles_total`` tick). Raises
    RetraceViolation after the barrier outside a sanctioned window."""
    _RETRACE.note_compile(tag, variant)


def declare_warmup_complete() -> None:
    """The server finished startup compilation: from here on, any
    unsanctioned compile is a retrace violation. Recorded with the
    declaring site so the violation message can name it."""
    _RETRACE.declare_barrier()


class sanctioned_compiles:
    """Context manager blessing deliberate post-barrier compilation
    (cold-boot ``ensure_compiled``). Process-wide while open."""

    def __enter__(self) -> "sanctioned_compiles":
        _RETRACE.sanction_enter()
        return self

    def __exit__(self, *exc) -> None:
        _RETRACE.sanction_exit()


def retrace_snapshot() -> dict:
    """Barrier/compile-ledger state (the /stats retrace_witness block)."""
    return _RETRACE.snapshot()


def reset_retrace() -> None:
    """Drop barrier + ledger (each ServerState.build starts fresh)."""
    _RETRACE.reset()
