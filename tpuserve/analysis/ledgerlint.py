"""Ledger escape analysis (TPS6xx) — acquire/release balance along the AST.

The serving path owns four acquire/release ledgers — ``SlotArena`` /
``PageLedger`` (genserve slot blocks + paged KV), ``AssemblyArena``
(recycled host batch buffers), ``SlotPool`` (staging / shm-slot
admission) — and each already carries a runtime tripwire
(``SlotCorrupted`` / ``PageCorrupted``) for double-release. This rule
catches the *other* direction ahead of runtime: an acquisition that
dominates an exception-capable region without a release on every path
leaks the entry forever (slots vanish from the pool, pages never return
to the free list).

- **TPS601** — after ``x = ledger.acquire(...)``, an await / call / raise
  executes while the entry is held, with no ``try`` whose ``finally`` or
  handler releases it.

Receivers are typed from their creation sites (``self.arena =
SlotArena(...)``, ``SlotPool(depth)``, lists of pools), exactly like
astlint types locks — no inference across objects beyond the attribute
name. The protection patterns honored:

- the acquire sits inside a ``try`` whose ``finally`` or any handler
  releases the receiver — directly, or through a same-class method whose
  body releases it (``self._release_slot``-style funnels, one level);
- a subsequent ``try`` with such a handler/finally starts before any
  risky statement (the acquire-then-guard idiom);
- a guard ``if`` whose body releases the receiver (release-and-bail);
- ``return`` transfers ownership to the caller (long-lived entries — a
  genserve slot lives across iterations by design — are not findings:
  the rule is about exception windows, not held-at-exit);
- tracking stops at the enclosing loop boundary (an entry that survives
  a loop iteration is long-lived by design).

``try_acquire`` (returns ``None`` instead of blocking) is not tracked:
its callers branch on the result, which a linear scan cannot follow.
Inline sanctions use the same annotation tracelint honors::

    slot = pool.acquire()  # tps-ok[TPS601]: released by the reaper task

Pure AST — no tpuserve/jax imports — so the bare-Python CI lint job
runs it (docs/ANALYSIS.md "Ledger escape analysis").
"""

from __future__ import annotations

import ast
from pathlib import Path

from tpuserve.analysis.astlint import (
    FuncInfo,
    ModuleInfo,
    _parse_module,
    _self_attr,
    dotted,
)
from tpuserve.analysis.findings import Finding
from tpuserve.analysis.tracelint import filter_sanctioned

LEDGER_CLASSES = {"SlotArena", "PageLedger", "AssemblyArena", "SlotPool"}


def _ledger_ctor(value: ast.AST) -> str | None:
    """Ledger class name when ``value`` constructs one (directly or as a
    list/comprehension of them), else None."""
    if isinstance(value, ast.Call):
        name = (dotted(value.func) or "").split(".")[-1]
        if name in LEDGER_CLASSES:
            return name
    if isinstance(value, ast.ListComp):
        return _ledger_ctor(value.elt)
    if isinstance(value, (ast.List, ast.Tuple)) and value.elts:
        return _ledger_ctor(value.elts[0])
    return None


def _receiver_name(node: ast.AST) -> str | None:
    """The identifying attribute/variable name of an acquire/release
    receiver: ``self.arena`` -> 'arena', ``w.slots`` -> 'slots',
    ``self._staging[i]`` -> '_staging', ``pool`` -> 'pool'."""
    if isinstance(node, ast.Subscript):
        return _receiver_name(node.value)
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _collect_ledger_names(modules: list[ModuleInfo]) -> dict[str, str]:
    """attr/var name -> ledger class, from every creation site in the
    module set (cross-module on purpose: the engine's ``self.pages`` is a
    ``PageLedger`` no matter which file reads it)."""
    out: dict[str, str] = {}
    for mi in modules:
        for n in ast.walk(mi.tree):
            if isinstance(n, ast.Assign) and len(n.targets) == 1:
                cls = _ledger_ctor(n.value)
                name = _receiver_name(n.targets[0])
                if cls and name:
                    out[name] = cls
            elif isinstance(n, ast.AnnAssign) and n.value is not None:
                cls = _ledger_ctor(n.value)
                name = _receiver_name(n.target)
                if cls and name:
                    out[name] = cls
    return out


def _is_release(node: ast.AST, recv: str) -> bool:
    """True when ``node`` releases receiver ``recv`` (release/release_all/
    close on the same-named receiver)."""
    for n in ast.walk(node):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr in ("release", "release_all", "close") \
                and _receiver_name(n.func.value) == recv:
            return True
    return False


def _shallow_nodes(stmt: ast.stmt):
    """The statement's own expression nodes — no descent into nested
    statement blocks (those are scanned as their own blocks) or defs."""
    stack = [stmt]
    while stack:
        n = stack.pop()
        yield n
        for c in ast.iter_child_nodes(n):
            if isinstance(c, (ast.stmt, ast.FunctionDef,
                              ast.AsyncFunctionDef, ast.Lambda,
                              ast.ExceptHandler)):
                continue
            stack.append(c)


def _walk_no_defs(node: ast.AST):
    """ast.walk without descending into nested function bodies."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for c in ast.iter_child_nodes(n):
            if isinstance(c, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue
            stack.append(c)


class LedgerAnalyzer:
    def __init__(self, modules: list[ModuleInfo]) -> None:
        self.modules = modules
        self.ledgers = _collect_ledger_names(modules)
        self.findings: list[Finding] = []

    def run(self) -> list[Finding]:
        for mi in self.modules:
            for fi in mi.functions.values():
                if "<locals>" in fi.name:
                    continue  # subtree of its owner; scanned there
                self._check_function(mi, fi)
        self.findings.sort(key=lambda f: (f.file, f.line, f.rule, f.symbol))
        return self.findings

    # -- release resolution ---------------------------------------------------

    def _releases(self, mi: ModuleInfo, cls: str | None, node: ast.AST,
                  recv: str) -> bool:
        """``node`` releases ``recv`` directly, or calls a same-class /
        same-module funnel whose body does (one level deep)."""
        if _is_release(node, recv):
            return True
        for n in ast.walk(node):
            if not isinstance(n, ast.Call):
                continue
            callee = None
            attr = _self_attr(n.func)
            if attr is not None and cls is not None:
                callee = mi.functions.get(f"{cls}.{attr}")
            elif isinstance(n.func, ast.Name):
                callee = mi.functions.get(n.func.id)
            if callee is not None and _is_release(callee.node, recv):
                return True
        return False

    # -- the scan -------------------------------------------------------------

    def _check_function(self, mi: ModuleInfo, fi: FuncInfo) -> None:
        # Parent chain for enclosing-try protection checks.
        parents: dict[int, ast.AST] = {}
        for p in ast.walk(fi.node):
            for c in ast.iter_child_nodes(p):
                parents[id(c)] = p

        def enclosing_protected(stmt: ast.AST, recv: str) -> bool:
            n = stmt
            while id(n) in parents:
                n = parents[id(n)]
                if isinstance(n, ast.Try):
                    handlers = [*(h for h in n.handlers), ]
                    if any(self._releases(mi, fi.cls, h, recv)
                           for h in handlers) \
                            or self._releases(
                                mi, fi.cls,
                                ast.Module(body=n.finalbody,
                                           type_ignores=[]), recv):
                        return True
                if n is fi.node:
                    break
            return False

        # Find acquire statements: any statement whose OWN expressions
        # contain ``<typed receiver>.acquire(...)`` (awaited/assigned ok).
        for block, idx, recv, cls_name, line in self._acquires(fi):
            if enclosing_protected(block[idx], recv):
                continue
            hazard = self._scan_after(mi, fi, parents, block, idx, recv)
            if hazard is not None:
                kind, hline = hazard
                # Anchored at the ACQUIRE site — that is where the inline
                # ``# tps-ok[TPS601]: reason`` sanction goes.
                self._add(
                    "TPS601", mi, fi,
                    f"{cls_name} '{recv}' acquired here is held across an "
                    f"exception-capable {kind} (line {hline}) with no "
                    "try/finally or except-path release", line)

    def _acquires(self, fi: FuncInfo):
        """(directly enclosing block, index, receiver, class, line) for
        each typed-ledger ``.acquire(...)`` statement in ``fi``."""
        out = []

        def visit_block(body: list[ast.stmt]) -> None:
            for i, stmt in enumerate(body):
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    visit_block(stmt.body)
                    continue
                for n in _shallow_nodes(stmt):
                    if isinstance(n, ast.Call) \
                            and isinstance(n.func, ast.Attribute) \
                            and n.func.attr == "acquire":
                        recv = _receiver_name(n.func.value)
                        cls = self.ledgers.get(recv or "")
                        if cls:
                            out.append((body, i, recv, cls, n.lineno))
                for name in ("body", "orelse", "finalbody"):
                    sub = getattr(stmt, name, None)
                    if isinstance(sub, list) and sub:
                        visit_block(sub)
                for h in getattr(stmt, "handlers", ()):
                    visit_block(h.body)

        visit_block(fi.node.body)
        return out

    def _scan_after(self, mi: ModuleInfo, fi: FuncInfo,
                    parents: dict[int, ast.AST], block: list[ast.stmt],
                    idx: int, recv: str):
        """Walk statements after the acquire; return (kind, line) for the
        first unprotected exception-capable statement, None when the
        window closes safely (release / protecting try / return / guard /
        loop boundary / end of function)."""
        # Owner map: block list -> the compound statement (or function)
        # holding it, so block exhaustion can unwind outward.
        owner: dict[int, ast.AST] = {id(fi.node.body): fi.node}
        for n in ast.walk(fi.node):
            for name in ("body", "orelse", "finalbody"):
                blk = getattr(n, name, None)
                if isinstance(blk, list):
                    owner.setdefault(id(blk), n)
            for h in getattr(n, "handlers", ()):
                owner.setdefault(id(h.body), n)

        body, i = block, idx + 1
        while True:
            while i < len(body):
                stmt = body[i]
                i += 1
                verdict = self._classify(mi, fi, stmt, recv)
                if verdict in ("released", "protected-closed"):
                    return None
                if verdict == "safe":
                    continue
                return verdict  # (kind, line) hazard tuple
            comp = owner.get(id(body))
            if comp is None or comp is fi.node \
                    or isinstance(comp, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                return None  # end of function: held-at-exit is by design
            if isinstance(comp, (ast.For, ast.AsyncFor, ast.While)):
                return None  # loop boundary: long-lived by design
            parent_body = None
            grand = parents.get(id(comp))
            if grand is not None:
                for name in ("body", "orelse", "finalbody"):
                    blk = getattr(grand, name, None)
                    if isinstance(blk, list) and comp in blk:
                        parent_body = blk
                for h in getattr(grand, "handlers", ()):
                    if comp in h.body:
                        parent_body = h.body
            if parent_body is None:
                return None
            body, i = parent_body, parent_body.index(comp) + 1

    def _classify(self, mi: ModuleInfo, fi: FuncInfo, stmt: ast.stmt,
                  recv: str):
        """'released' | 'protected-closed' | 'safe' | (kind, line)."""
        if isinstance(stmt, ast.Try):
            protects = any(self._releases(mi, fi.cls, h, recv)
                           for h in stmt.handlers) \
                or self._releases(mi, fi.cls,
                                  ast.Module(body=stmt.finalbody,
                                             type_ignores=[]), recv)
            if protects:
                # finally-release closes the window entirely; handler-only
                # release leaves the success path holding (by design —
                # ownership passed to runtime machinery). Either way the
                # escape window is closed.
                return "protected-closed"
            # An unprotecting try is only as safe as its contents.
            hazard = self._first_hazard(stmt, recv)
            return hazard if hazard is not None else "safe"
        if isinstance(stmt, ast.If):
            # Guard-release idiom: a branch that releases and bails is part
            # of the release protocol; the statement as a whole is safe iff
            # neither branch contains an unguarded hazard. The held path
            # continues to be scanned after the if.
            for branch in (stmt.body, stmt.orelse):
                branch_mod = ast.Module(body=branch, type_ignores=[])
                if self._releases(mi, fi.cls, branch_mod, recv):
                    continue
                hazard = self._first_hazard(branch_mod, recv)
                if hazard is not None:
                    return hazard
            return "safe"
        if self._releases(mi, fi.cls, stmt, recv):
            # Direct release (or a call into a same-class release funnel).
            return "released"
        if isinstance(stmt, (ast.Return, ast.Break, ast.Continue)):
            return "released"  # ownership transfer / loop boundary
        hazard = self._first_hazard(stmt, recv)
        return hazard if hazard is not None else "safe"

    def _first_hazard(self, node: ast.AST, recv: str):
        """(kind, line) for the first await/call/raise in ``node`` that is
        not an operation on the receiver itself, else None."""
        for n in _walk_no_defs(node):
            if isinstance(n, ast.Raise):
                return ("raise", n.lineno)
            if isinstance(n, ast.Await):
                return ("await", n.lineno)
            if isinstance(n, ast.Call):
                if isinstance(n.func, ast.Attribute) \
                        and _receiver_name(n.func.value) == recv:
                    continue  # ops on the ledger itself
                return ("call", n.lineno)
        return None

    def _add(self, rule: str, mi: ModuleInfo, fi: FuncInfo, message: str,
             line: int) -> None:
        f = Finding(rule=rule, file=mi.relpath, symbol=fi.qualname,
                    message=message, line=line)
        if f not in self.findings:
            self.findings.append(f)


def run_paths(files: list[Path], root: Path) -> list[Finding]:
    """Parse ``files``, run the TPS6xx rules, and honor inline sanctions."""
    modules = []
    sources: dict[str, list[str]] = {}
    for path in sorted(files):
        mi = _parse_module(path, root)
        if mi is not None:
            modules.append(mi)
            try:
                sources[mi.relpath] = path.read_text().splitlines()
            except OSError:
                pass
    findings = LedgerAnalyzer(modules).run()
    return filter_sanctioned(findings, sources)
