"""Concurrency static analysis + runtime lock-order witness (ISSUE 4).

- ``tpuserve.analysis.astlint`` — AST rule families over the serving path
  (blocking-in-async, lock-order cycles, unguarded cross-thread writes).
- ``tpuserve.analysis.tracelint`` — TPS5xx trace discipline over the
  jit-reachability set (retrace/recompile/host-transfer hazards).
- ``tpuserve.analysis.ledgerlint`` — TPS6xx acquire/release escape
  analysis over the four resource ledgers.
- ``tpuserve.analysis.drift`` — docs/config/test drift rules.
- ``tpuserve.analysis.witness`` — TPUSERVE_LOCK_WITNESS=1 lock-order and
  TPUSERVE_RETRACE_WITNESS=1 compile-stability runtime witnesses.
- ``tpuserve.analysis.cli`` — ``python -m tpuserve lint`` entry point, with
  the checked-in baseline at ``tpuserve/analysis/baseline.json``.

Kept import-light on purpose: ``python -m tpuserve lint`` must run on a bare
Python (CI lint job) with none of the serving dependencies installed.
"""
