"""``python -m tpuserve lint``: run the analysis pass against the baseline.

Exit codes: 0 = clean vs baseline (stale baseline entries are warnings),
1 = new findings (CI fails), 2 = usage error. ``--update-baseline`` rewrites
``tpuserve/analysis/baseline.json`` from the current findings — the explicit
burndown step (docs/ANALYSIS.md).
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from tpuserve.analysis import astlint, drift, ledgerlint, tracelint
from tpuserve.analysis.findings import compare, load_baseline, save_baseline

DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"


def repo_root() -> Path:
    return Path(__file__).resolve().parents[2]


def add_lint_args(p: argparse.ArgumentParser) -> None:
    p.add_argument("paths", nargs="*", help="files/dirs to lint (default: tpuserve/)")
    p.add_argument("--baseline", default=str(DEFAULT_BASELINE), help="baseline JSON path")
    p.add_argument("--no-baseline", action="store_true", help="report every finding, ignore baseline")
    p.add_argument("--update-baseline", action="store_true", help="rewrite the baseline from current findings")
    p.add_argument("--no-drift", action="store_true", help="skip the TPS4xx docs/config/test drift rules")
    p.add_argument("--json", action="store_true", help="emit findings as JSON instead of text")


def run_lint(args: argparse.Namespace) -> int:
    root = repo_root()
    paths = [Path(p).resolve() for p in args.paths] if args.paths else [root / "tpuserve"]
    for p in paths:
        if not p.exists():
            print(f"lint: no such path: {p}", file=sys.stderr)
            return 2
    files = astlint.collect_files(paths)
    findings = astlint.run_paths(files, root)
    findings += tracelint.run_paths(files, root)
    findings += ledgerlint.run_paths(files, root)
    if not args.no_drift:
        findings += drift.run(root)

    if args.update_baseline:
        save_baseline(Path(args.baseline), findings)
        print(f"lint: baseline rewritten with {len(findings)} finding(s) -> {args.baseline}")
        return 0

    baseline = set() if args.no_baseline else load_baseline(Path(args.baseline))
    new, stale = compare(findings, baseline)

    if args.json:
        print(
            json.dumps(
                {
                    "findings": [f.__dict__ for f in findings],
                    "new": [f.key for f in new],
                    "stale_baseline": sorted(stale),
                },
                indent=2,
            )
        )
    else:
        for f in new:
            print(f.render())
        for key in sorted(stale):
            print(f"stale baseline entry (fixed? run --update-baseline): {key}", file=sys.stderr)
        known = len(findings) - len(new)
        print(
            f"lint: {len(findings)} finding(s): {len(new)} new, "
            f"{known} baselined, {len(stale)} stale baseline entr(y/ies)",
            file=sys.stderr,
        )
    return 1 if new else 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="tpuserve lint")
    add_lint_args(parser)
    return run_lint(parser.parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
