"""Structured event plane + crash-forensics black box + admin audit trail
(ISSUE 15; docs/OBSERVABILITY.md "The third pillar").

Metrics say THAT something happened, traces say WHERE a request spent its
time; this module records WHAT the process was saying — and keeps saying it
after the process dies. Three pieces, one schema:

- **EventLog** — a bounded per-process ring of structured event records
  (``ts_us`` / ``level`` / ``subsystem`` / ``event`` / ``model`` /
  ``trace_id``+``span_id`` when the emitter is in request context /
  free-form ``fields``), fed two ways: explicit ``emit()`` calls at the
  moments that matter (sheds, publishes, rollbacks, state transitions,
  supervision events), and an ``EventLogBridge`` stdlib ``logging.Handler``
  over the existing ``tpuserve.*`` loggers so every ``log.info(...)`` call
  site in the tree flows in without rewriting. Optional JSONL file sink.
  Queried at ``GET /debug/events`` with the same junk-param-400 hardening
  as ``/debug/trace``.
- **PostmortemLog + BlackBoxWriter** — the black box. Every worker (and
  host agent / peer router) gets its stderr redirected to a per-slot
  capture file at spawn, and a ``BlackBoxWriter`` thread periodically
  checkpoints a small postmortem snapshot (last-N events, flight-recorder
  summaries, key counters) to a per-slot file. When the supervisor reaps a
  dead process it folds exit code/signal + the stderr tail + the snapshot
  into a postmortem record (``postmortems_total{component=,signal=}``;
  ``GET /debug/postmortems``) — a SIGKILLed worker leaves evidence naming
  the signal, its last requests, and its final words on stderr.
- **AuditLog** — every admin verb (``:reload``, ``:rollback``, ``:warm``,
  ``/debug/profile``, drain) records verb / target / outcome / duration
  plus verb-specific fields (version, generation, per-host fan-out
  results), FIFO-bounded, mirrored into the event ring, queryable at
  ``GET /debug/audit`` (serialized through the primary router, like the
  reload fan-out itself).

Correlation: events carry the request trace id when the emitter knows one,
so ``/debug/trace?trace_id=`` interleaves matching events into the record
(and into the Chrome output as instant events via
``obs.spans_to_chrome(..., events=)``) — one artifact shows what the
process was *saying* while the spans ran.

Thread/loop ownership: every structure here is locked (``utils.locks``) —
events are emitted from handlers on any accept loop, from the logging
bridge on any thread, and from the black-box thread. File reads for
postmortem capture are blocking and deliberately live in
``capture_blocking`` / ``read_tail`` / ``read_snapshot`` (``os.open`` /
``os.read``), which supervisors call on executor threads, never on the
event loop.
"""

from __future__ import annotations

import json
import logging
import os
import signal as _signal
import sys
import tempfile
import threading
import time
from collections import deque

from tpuserve.utils.locks import new_lock

# Event severity vocabulary — the `level` label on
# events_logged_total{level=,subsystem=} and the /debug/events?level=
# filter (junk values 400).
EVENT_LEVELS = ("debug", "info", "warning", "error")

_LOGGING_TO_LEVEL = {
    logging.DEBUG: "debug",
    logging.INFO: "info",
    logging.WARNING: "warning",
    logging.ERROR: "error",
    logging.CRITICAL: "error",
}


def signal_name(exitcode: int | None) -> str | None:
    """The signal that killed a process, from its multiprocessing/waitpid
    exit code (negative = killed by that signal). None for clean exits and
    unknown codes — the postmortem then carries the raw exit code only."""
    if exitcode is None or exitcode >= 0:
        return None
    try:
        return _signal.Signals(-exitcode).name
    except ValueError:
        return None


def read_tail(path: str | None, nbytes: int) -> str | None:
    """Last ``nbytes`` of a capture file, decoded leniently. None when the
    path is unset/unreadable (a worker that never wrote stderr is data,
    not an error). os-level IO: callers run this on executor threads or in
    plain processes, never on an event loop."""
    if not path or nbytes <= 0:
        return None
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return None
    try:
        size = os.fstat(fd).st_size
        os.lseek(fd, max(0, size - nbytes), os.SEEK_SET)
        data = os.read(fd, nbytes)
    except OSError:
        return None
    finally:
        os.close(fd)
    return data.decode("utf-8", errors="replace")


def read_snapshot(path: str | None) -> dict | None:
    """Parse a black-box snapshot file; None when absent/corrupt (a
    process killed mid-write must still get a postmortem — the atomic
    tmp+rename in BlackBoxWriter makes corruption rare, not impossible)."""
    if not path:
        return None
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return None
    try:
        chunks = []
        while True:
            b = os.read(fd, 65536)
            if not b:
                break
            chunks.append(b)
    except OSError:
        return None
    finally:
        os.close(fd)
    try:
        out = json.loads(b"".join(chunks))
    except ValueError:
        return None
    return out if isinstance(out, dict) else None


def resolve_blackbox_dir(events_cfg) -> str:
    """The black-box directory (stderr captures + snapshots), created.
    ``[events] dir`` when set; otherwise a per-deployment default keyed by
    THIS process's pid — the supervisor resolves it once and bakes the
    result into every derived worker config, so respawns reuse the same
    files across the whole deployment's lifetime."""
    d = events_cfg.dir or os.path.join(
        tempfile.gettempdir(), f"tpuserve-blackbox-{os.getpid()}")
    os.makedirs(d, exist_ok=True)
    return d


def redirect_stderr(path: str | None, banner: str) -> bool:
    """Redirect THIS process's fd 2 to an append-mode capture file (call
    first thing in a spawned child, before any backend import can write).
    Append + a boot banner per spawn, so a respawned slot's file keeps the
    previous incarnation's last words for the postmortem reader. Returns
    False (and leaves stderr alone) when the path is unset or the open
    fails — stderr capture is forensics, never a boot blocker."""
    if not path:
        return False
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        os.write(fd, f"--- {banner} ---\n".encode())
        sys.stderr.flush()
        os.dup2(fd, 2)
        os.close(fd)
        return True
    except OSError:
        return False


class EventLog:
    """Bounded per-process ring of structured event records.

    Records keep the NEWEST ``capacity`` events (deque maxlen). ``pid`` is
    the process lane, same vocabulary as span pids (0 = router /
    single-process server, worker id + 1 behind the router tier) — it is
    mutable because a worker learns its id after construction. Emissions
    tick ``events_logged_total{level=,subsystem=}`` (counters prebound
    lazily per pair — the label space is small and stable)."""

    def __init__(self, metrics, capacity: int = 4096, pid: int = 0,
                 jsonl_path: str = "") -> None:
        self.metrics = metrics
        self.capacity = max(1, int(capacity))
        self.pid = pid
        self._ring: deque[dict] = deque(maxlen=self.capacity)
        self._counters: dict[tuple[str, str], object] = {}
        self._lock = new_lock("events.EventLog")
        self._sink_fd: int | None = None
        self._sink_failed = False
        if jsonl_path:
            try:
                os.makedirs(os.path.dirname(jsonl_path) or ".",
                            exist_ok=True)
                self._sink_fd = os.open(
                    jsonl_path, os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                    0o644)
            except OSError:
                self._sink_failed = True

    def emit(self, level: str, subsystem: str, event: str, *,
             model: str | None = None, trace_id: str | None = None,
             span_id: str | None = None, msg: str | None = None,
             **fields) -> dict:
        """Record one structured event; returns the record. Safe from any
        thread or event loop; never raises (the event plane must not take
        the serving path down)."""
        if level not in EVENT_LEVELS:
            level = "info"
        rec: dict = {
            "ts_us": time.time() * 1e6,
            "level": level,
            "subsystem": subsystem,
            "event": event,
            "pid": self.pid,
        }
        if model is not None:
            rec["model"] = model
        if trace_id is not None:
            rec["trace_id"] = trace_id
        if span_id is not None:
            rec["span_id"] = span_id
        if msg is not None:
            rec["msg"] = msg
        if fields:
            rec["fields"] = fields
        with self._lock:
            self._ring.append(rec)
            c = self._counters.get((level, subsystem))
            if c is None:
                c = self._counters[(level, subsystem)] = self.metrics.counter(
                    f"events_logged_total{{level={level},"
                    f"subsystem={subsystem}}}")
            if self._sink_fd is not None and not self._sink_failed:
                try:
                    os.write(self._sink_fd,
                             (json.dumps(rec, ensure_ascii=False,
                                         default=str) + "\n").encode())
                except OSError:
                    # One-shot disable, no logging: a dead sink must not
                    # recurse through the bridge back into emit().
                    self._sink_failed = True
        c.inc()
        return rec

    def query(self, since_us: float | None = None, level: str | None = None,
              subsystem: str | None = None, trace_id: str | None = None,
              limit: int = 1000) -> list[dict]:
        """Filtered view of the ring, oldest-first, capped to the NEWEST
        ``limit`` matching records (a post-incident pull sees the most
        recent window — same contract as the span ring)."""
        with self._lock:
            events = list(self._ring)
        out = [e for e in events
               if (since_us is None or e["ts_us"] >= since_us)
               and (level is None or e["level"] == level)
               and (subsystem is None or e["subsystem"] == subsystem)
               and (trace_id is None or e.get("trace_id") == trace_id)]
        if limit >= 0:
            # NOT out[-limit:]: -0 slices the WHOLE list (the /debug/trace
            # lesson, pinned again in tests/test_events.py).
            out = out[len(out) - limit:] if limit else []
        return out

    def tail(self, n: int) -> list[dict]:
        """The newest ``n`` records, oldest-first (black-box snapshots)."""
        with self._lock:
            events = list(self._ring)
        return events[max(0, len(events) - n):]

    def stats(self) -> dict:
        with self._lock:
            size = len(self._ring)
            logged = {f"{lv}/{sub}": c.value
                      for (lv, sub), c in self._counters.items()}
        return {"capacity": self.capacity, "size": size,
                "logged_total": logged,
                "jsonl_sink": ("failed" if self._sink_failed
                               else "on" if self._sink_fd is not None
                               else "off")}

    def close(self) -> None:
        with self._lock:
            if self._sink_fd is not None:
                try:
                    os.close(self._sink_fd)
                except OSError:
                    pass
                self._sink_fd = None


class EventLogBridge(logging.Handler):
    """stdlib-logging → event-ring bridge: a handler on the ``tpuserve``
    root logger, so every existing ``log = logging.getLogger("tpuserve.*")``
    call site flows into the structured ring without rewriting. Subsystem =
    the logger-name suffix after ``tpuserve.`` (bare ``tpuserve`` maps to
    ``server``). Never raises — a logging handler that throws turns every
    log line into an incident."""

    def __init__(self, event_log: EventLog) -> None:
        super().__init__()
        self.event_log = event_log

    def emit(self, record: logging.LogRecord) -> None:  # noqa: A003
        try:
            name = record.name
            subsystem = (name.split(".", 1)[1] if "." in name
                         else "server")
            level = _LOGGING_TO_LEVEL.get(record.levelno)
            if level is None:
                level = "error" if record.levelno >= logging.ERROR else \
                    "warning" if record.levelno >= logging.WARNING else \
                    "info" if record.levelno >= logging.INFO else "debug"
            self.event_log.emit(level, subsystem, "log",
                                msg=record.getMessage())
        except Exception:  # noqa: BLE001 — see docstring
            pass


_BRIDGE: EventLogBridge | None = None
_ACTIVE: EventLog | None = None


def install_bridge(event_log: EventLog, level: str = "INFO") -> EventLogBridge:
    """Install (or replace) THE process's logging bridge on the
    ``tpuserve`` root logger. One bridge per process: a test constructing
    a second ServerState swaps the bridge rather than double-recording."""
    global _BRIDGE
    root = logging.getLogger("tpuserve")
    if _BRIDGE is not None:
        root.removeHandler(_BRIDGE)
    _BRIDGE = EventLogBridge(event_log)
    lvl = getattr(logging, level.upper(), logging.INFO)
    _BRIDGE.setLevel(lvl)
    # A record is gated by its LOGGER's effective level before any handler
    # sees it; with an unconfigured root (WARNING) the bridge would
    # silently miss every INFO line. The server always configures INFO
    # logging, so lowering the tpuserve subtree to the bridge level
    # changes nothing in production and makes the bridge honest elsewhere.
    if root.getEffectiveLevel() > lvl:
        root.setLevel(lvl)
    root.addHandler(_BRIDGE)
    return _BRIDGE


def set_active(event_log: EventLog | None) -> None:
    """Register the process's event log for module-level ``emit()`` — the
    light-weight entry used by layers (lifecycle, scheduler) that predate
    the event plane and should not grow a constructor parameter for it."""
    global _ACTIVE
    _ACTIVE = event_log


def emit(level: str, subsystem: str, event: str, **kw) -> None:
    """Emit onto the process's active event log; silent no-op before
    ``set_active`` (unit tests driving a bare lifecycle emit nowhere)."""
    log = _ACTIVE
    if log is not None:
        log.emit(level, subsystem, event, **kw)


def reject_unknown_query(query, known) -> None:
    """The shared half of introspection-endpoint query hardening (the
    /debug/trace discipline: junk is a 400, never a 500 or a silent
    default). Every read-only debug/admin view — /debug/events,
    /debug/autopilot, /tenants — runs its params through this one check
    so a typo'd filter fails identically everywhere. Raises ValueError
    with a client-facing message."""
    unknown = set(query) - set(known)
    if unknown:
        raise ValueError(f"unknown query param(s): {sorted(unknown)} "
                         f"(known: {sorted(known)})")


def query_limit(query, default: int = 1000) -> int:
    """Parse the conventional ``limit`` param (int, >= 0)."""
    try:
        limit = int(query.get("limit", str(default)))
    except (TypeError, ValueError):
        raise ValueError("limit must be an integer") from None
    if limit < 0:
        raise ValueError(f"limit must be >= 0, got {limit}")
    return limit


def parse_events_query(query) -> dict:
    """Validate /debug/events query params. Raises ValueError with a
    client-facing message."""
    out: dict = {}
    reject_unknown_query(
        query, {"since_us", "level", "subsystem", "trace_id", "limit"})
    if "since_us" in query:
        try:
            out["since_us"] = float(query["since_us"])
        except (TypeError, ValueError):
            raise ValueError("since_us must be a number (epoch "
                             "microseconds)") from None
    level = query.get("level")
    if level is not None:
        if level not in EVENT_LEVELS:
            raise ValueError(f"level must be one of {list(EVENT_LEVELS)}, "
                             f"got {level!r}")
        out["level"] = level
    if query.get("subsystem"):
        out["subsystem"] = query["subsystem"]
    if query.get("trace_id"):
        out["trace_id"] = query["trace_id"]
    out["limit"] = query_limit(query)
    return out


class AuditLog:
    """Bounded FIFO of admin-action records: who-did-what for every verb
    that mutates serving state (`:reload`, `:rollback`, `:warm`,
    `/debug/profile`, drain). Each record lands in the event ring too
    (subsystem ``audit``) so the flight data interleaves, and ticks
    ``audit_events_total{verb=,outcome=}``."""

    def __init__(self, metrics, capacity: int = 256,
                 events: EventLog | None = None) -> None:
        self.metrics = metrics
        self.capacity = max(1, int(capacity))
        self.events = events
        self._records: deque[dict] = deque(maxlen=self.capacity)
        self._counters: dict[tuple[str, str], object] = {}
        self._lock = new_lock("events.AuditLog")

    def record(self, verb: str, target: str, outcome: str,
               duration_ms: float | None = None, **fields) -> dict:
        rec: dict = {
            "ts": round(time.time(), 3),
            "verb": verb,
            "target": target,
            "outcome": outcome,
        }
        if duration_ms is not None:
            rec["duration_ms"] = round(duration_ms, 3)
        rec.update(fields)
        with self._lock:
            self._records.append(rec)
            c = self._counters.get((verb, outcome))
            if c is None:
                c = self._counters[(verb, outcome)] = self.metrics.counter(
                    f"audit_events_total{{verb={verb},outcome={outcome}}}")
        c.inc()
        if self.events is not None:
            self.events.emit(
                "info" if outcome == "ok" else "warning", "audit", verb,
                model=None if target == "server" else target,
                outcome=outcome, **({"duration_ms": rec["duration_ms"]}
                                    if duration_ms is not None else {}))
        return rec

    def dump(self) -> list[dict]:
        """Newest-first records (the /debug/audit body)."""
        with self._lock:
            return list(reversed(self._records))

    def stats(self) -> dict:
        with self._lock:
            return {"capacity": self.capacity, "size": len(self._records)}


class PostmortemLog:
    """Bounded FIFO of process-death forensics records.

    ``add()`` is pure bookkeeping (safe on the event loop — host agents
    ship the tail/snapshot over the control pipe); ``capture_blocking()``
    additionally reads the dead slot's stderr capture + snapshot files and
    belongs on an executor thread. Every record ticks
    ``postmortems_total{component=,signal=}`` (signal = the killing signal
    name, or ``none`` for clean/unknown exits)."""

    def __init__(self, metrics, capacity: int = 64,
                 tail_bytes: int = 4096,
                 events: EventLog | None = None) -> None:
        self.metrics = metrics
        self.capacity = max(1, int(capacity))
        self.tail_bytes = max(0, int(tail_bytes))
        self.events = events
        self._records: deque[dict] = deque(maxlen=self.capacity)
        self._counters: dict[tuple[str, str], object] = {}
        self._lock = new_lock("events.PostmortemLog")

    def add(self, component: str, ident: str, pid: int | None,
            exitcode: int | None, stderr_tail: str | None = None,
            snapshot: dict | None = None, **fields) -> dict:
        sig = signal_name(exitcode)
        rec: dict = {
            "ts": round(time.time(), 3),
            "component": component,
            "id": ident,
            "pid": pid,
            "exitcode": exitcode,
            "signal": sig,
            "stderr_tail": stderr_tail,
            "snapshot": snapshot,
        }
        rec.update(fields)
        with self._lock:
            self._records.append(rec)
            key = (component, sig or "none")
            c = self._counters.get(key)
            if c is None:
                c = self._counters[key] = self.metrics.counter(
                    f"postmortems_total{{component={component},"
                    f"signal={sig or 'none'}}}")
        c.inc()
        if self.events is not None:
            self.events.emit("error", "supervision", "postmortem",
                             component=component, id=ident, pid=pid,
                             exitcode=exitcode, signal=sig)
        return rec

    def capture_blocking(self, component: str, ident: str, pid: int | None,
                         exitcode: int | None, stderr_path: str | None = None,
                         snapshot_path: str | None = None, **fields) -> dict:
        """Read the dead slot's black-box files and fold a record.
        Blocking file IO — executor threads only (supervisors schedule it
        off the loop at reap time)."""
        return self.add(
            component, ident, pid, exitcode,
            stderr_tail=read_tail(stderr_path, self.tail_bytes),
            snapshot=read_snapshot(snapshot_path), **fields)

    def dump(self) -> list[dict]:
        """Newest-first records (the /debug/postmortems body)."""
        with self._lock:
            return list(reversed(self._records))

    def stats(self) -> dict:
        with self._lock:
            return {"capacity": self.capacity, "size": len(self._records)}


class BlackBoxWriter(threading.Thread):
    """The per-process postmortem checkpointer: every ``interval_s`` (and
    once immediately at start, so even a freshly booted worker leaves
    evidence) writes ``collect()`` to the slot's snapshot file atomically
    (tmp + rename — a SIGKILL mid-write leaves the previous snapshot, not
    a torn one). Daemon + event-signalled stop, the MetricSampler
    discipline: drains join it cleanly, a wedged write can't hang exit."""

    def __init__(self, path: str, interval_s: float, collect) -> None:
        super().__init__(name="tpuserve-blackbox", daemon=True)
        self.path = path
        self.interval_s = max(0.05, float(interval_s))
        self.collect = collect
        self._stop_ev = threading.Event()
        self.writes = 0

    def run(self) -> None:
        self.write_once()
        while not self._stop_ev.wait(self.interval_s):
            self.write_once()

    def write_once(self) -> None:
        """One snapshot (callable directly from tests). Never raises."""
        try:
            data = json.dumps(self.collect(), ensure_ascii=False,
                              default=str).encode()
        except Exception:  # noqa: BLE001 — a bad collect skips one tick
            return
        tmp = f"{self.path}.tmp"
        try:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644)
            try:
                os.write(fd, data)
            finally:
                os.close(fd)
            os.replace(tmp, self.path)
            self.writes += 1
        except OSError:
            pass

    def stop(self, timeout: float = 5.0) -> None:
        """Signal and join (idempotent; called from drain AND stop)."""
        self._stop_ev.set()
        if self.is_alive():
            self.join(timeout)


def events_to_chrome(events: list[dict]) -> list[dict]:
    """Render event records as Chrome instant events (``ph: "i"``) for
    interleaving with span trees — ``obs.spans_to_chrome`` merges them so
    the trace shows what the process was saying while the spans ran."""
    out = []
    for e in events:
        args = dict(e.get("fields") or {})
        for k in ("level", "model", "trace_id", "msg"):
            if e.get(k) is not None:
                args[k] = e[k]
        out.append({
            "name": f"{e.get('subsystem', '?')}:{e.get('event', '?')}",
            "ph": "i",
            "ts": float(e.get("ts_us", 0.0)),
            "pid": int(e.get("pid", 0)),
            "tid": e.get("subsystem", "events"),
            "s": "p",  # process-scoped instant marker
            "args": args,
        })
    return out
