"""On-demand deep profiling (ISSUE 14 tentpole part 5).

``POST /debug/profile?duration_ms=`` arms a ``jax.profiler`` device trace
for the window, then merges whatever the profiler produced (the perfetto
trace JSON when the backend emits one) with the span ring's events from
the same window into ONE Chrome-trace artifact. The workflow this closes:
``/debug/slow`` names a slow request → its span tree says *which phase*
(queue/h2d/compute) — but not which kernel; arming a capture during a
repro answers at device-op granularity, device lanes and serving-path
spans on one timeline.

Degradation contract: profiling is best-effort by construction — a
backend that emits only an xplane (no perfetto JSON), or a profiler that
refuses to start, still yields the span-ring half with
``device_trace: "unavailable"`` in the metadata, and never a 5xx for the
capture having less to say than hoped. One capture at a time (409 while
armed): the profiler is process-global state.

Blocking profiler calls run in an executor; the duration wait is an
``asyncio.sleep`` — nothing here may stall the serving loop.
"""

from __future__ import annotations

import asyncio
import glob
import gzip
import json
import logging
import os
import shutil
import tempfile
import time

from tpuserve.obs import Metrics

log = logging.getLogger("tpuserve.telemetry")


class CaptureBusy(Exception):
    """A capture is already armed (-> 409): jax.profiler is one-at-a-time
    process-global state."""


def _find_device_events(log_dir: str) -> "list | None":
    """Pull Chrome/perfetto trace events out of a finished profiler dir.

    jax writes ``plugins/profile/<run>/*.trace.json.gz`` (and, when asked,
    ``perfetto_trace.json.gz``); both are Chrome-trace JSON. None when the
    backend emitted nothing parseable (xplane-only captures)."""
    patterns = [
        os.path.join(log_dir, "**", "*.trace.json.gz"),
        os.path.join(log_dir, "**", "*trace.json"),
    ]
    for pattern in patterns:
        for path in sorted(glob.glob(pattern, recursive=True)):
            try:
                if path.endswith(".gz"):
                    with gzip.open(path, "rt", encoding="utf-8") as f:
                        data = json.load(f)
                else:
                    with open(path, encoding="utf-8") as f:
                        data = json.load(f)
            except (OSError, ValueError):
                continue
            events = data.get("traceEvents")
            if isinstance(events, list) and events:
                return events
    return None


class ProfileCapture:
    """One process's profiling endpoint state."""

    # Device lanes are re-based onto pids >= this so they never collide
    # with the serving tiers' span lanes (0 router, worker id + 1 workers).
    DEVICE_PID_BASE = 1000

    def __init__(self, metrics: Metrics) -> None:
        self.metrics = metrics
        self._armed = False
        self.captures = metrics.counter("profile_captures_total")
        self.last_capture: dict | None = None

    @property
    def armed(self) -> bool:
        return self._armed

    async def capture(self, duration_ms: float) -> dict:
        """Run one capture; returns the merged Chrome-trace dict. Raises
        CaptureBusy when one is already in flight."""
        if self._armed:
            raise CaptureBusy()
        self._armed = True
        loop = asyncio.get_running_loop()
        tmpdir = tempfile.mkdtemp(prefix="tpuserve_profile_")
        t0_us = time.time() * 1e6
        device_note = "ok"
        device_events: "list | None" = None
        try:
            started = await loop.run_in_executor(
                None, self._start_trace, tmpdir)
            await asyncio.sleep(duration_ms / 1e3)
            if started:
                await loop.run_in_executor(None, self._stop_trace)
                device_events = await loop.run_in_executor(
                    None, _find_device_events, tmpdir)
                if device_events is None:
                    device_note = ("unavailable: profiler emitted no "
                                   "parseable trace JSON (xplane-only "
                                   "backend output)")
            else:
                device_note = "unavailable: jax.profiler failed to start"
        finally:
            self._armed = False
            shutil.rmtree(tmpdir, ignore_errors=True)

        # The span ring's slice of the SAME window: serving-path batch /
        # generation spans beside the device lanes.
        ring = json.loads(self.metrics.tracer.chrome_trace(
            limit=None, since_us=t0_us))["traceEvents"]
        merged = list(ring)
        if device_events:
            for ev in device_events:
                ev = dict(ev)
                if isinstance(ev.get("pid"), int):
                    ev["pid"] = self.DEVICE_PID_BASE + ev["pid"]
                else:
                    ev["pid"] = self.DEVICE_PID_BASE
                merged.append(ev)
        self.captures.inc()
        meta = {
            "duration_ms": duration_ms,
            "device_trace": device_note,
            "ring_events": len(ring),
            "device_events": len(device_events or []),
            "captured_at": round(t0_us / 1e6, 3),
        }
        self.last_capture = meta
        return {"traceEvents": merged, "tpuserve_profile": meta}

    @staticmethod
    def _start_trace(log_dir: str) -> bool:
        try:
            import jax

            try:
                jax.profiler.start_trace(log_dir,
                                         create_perfetto_trace=True)
            except TypeError:  # older jax: no perfetto kwarg
                jax.profiler.start_trace(log_dir)
            return True
        except Exception:  # noqa: BLE001 — best-effort by contract
            log.exception("jax.profiler.start_trace failed")
            return False

    @staticmethod
    def _stop_trace() -> None:
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception:  # noqa: BLE001
            log.exception("jax.profiler.stop_trace failed")

    def stats(self) -> dict:
        return {"armed": self._armed,
                "captures_total": int(self.captures.value),
                "last_capture": self.last_capture}
