"""SLO burn-rate engine + device-utilization derivation (ISSUE 14
tentpole parts 3 & 4; docs/OBSERVABILITY.md "The telemetry plane").

**Burn rates** (the Google-SRE multi-window form). Each ``[model.slo]``
block names a latency objective (ms) and an availability target; the
error budget is ``1 - availability``. Per ``[telemetry] burn_windows_s``
window the engine takes the window DELTA of the model's latency histogram
from the time-series store, computes the bad fraction (requests over the
objective — interpolated inside the objective's bucket, so an objective
mid-bucket doesn't round a whole bucket the wrong way), and divides by
the budget:

    burn = bad_fraction / (1 - availability)

Burn 1.0 spends the budget exactly at the sustainable pace; burn N spends
it N× too fast. The alert rule is deliberately two-window (fast to fire,
fast to clear, hard to flap): **firing** when burn exceeds the model's
``burn_alert`` over BOTH the short and the mid window, **pending** on the
short window alone, **ok** otherwise. Exported as
``slo_burn_rate{model=,window=}`` + ``slo_alert_state{model=}`` gauges and
the ``/alerts`` endpoint; the fleet scheduler holds a reference as its
shed-on-burn seam (FleetScheduler.slo — future PRs shed batch-class work
while a model is burning instead of waiting for saturation).

**Utilization**. The per-replica device-seconds counters (ticked by the
batcher's device section and the generation engine's step loop) divided
by wall time over ``utilization_window_s`` are each chip's busy fraction:
``device_utilization{model=,replica=}``. This is the instrument the
ROADMAP's stale-bench item needs — a bench round now records what the
chips were actually doing, not just what came out the other end.
"""

from __future__ import annotations

import re
import time

from tpuserve.obs import SLO_ALERT_STATES, Metrics
from tpuserve.telemetry.store import TimeSeriesStore
from tpuserve.utils.locks import new_lock

OK, PENDING, FIRING = "ok", "pending", "firing"
assert set((OK, PENDING, FIRING)) == set(SLO_ALERT_STATES)


def good_fraction(bounds: list[float], counts: list[float],
                  objective_ms: float) -> float | None:
    """Fraction of a window delta's requests at or under the objective,
    linearly interpolated inside the bucket containing it. None on an
    empty window (no evidence — the alert machine holds its state)."""
    n = sum(counts)
    if n <= 0:
        return None
    good = 0.0
    lo = 0.0
    for i, b in enumerate(bounds):
        c = counts[i]
        if objective_ms >= b:
            good += c
        else:
            if b > lo:
                good += c * max(0.0, (objective_ms - lo) / (b - lo))
            break
        lo = b
    return min(1.0, good / n)


class _ModelSlo:
    """One model's objective + live evaluation state."""

    __slots__ = ("name", "slo", "metric", "burn_gauges", "state", "since")

    def __init__(self, name: str, slo, metric: str, metrics: Metrics,
                 windows: list[float], label: str = "model") -> None:
        self.name = name
        self.slo = slo
        self.metric = metric
        self.burn_gauges = {w: metrics.slo_burn_gauge(name, w, label=label)
                            for w in windows}
        self.state = OK
        self.since = time.time()


class SloEngine:
    """Multi-window burn-rate evaluation over the time-series store.

    One instance per process; the worker/single-process server evaluates
    over ``latency_ms{model=,phase=total}`` (what the model served), the
    router over ``router_latency_ms{model=}`` (what the client saw —
    retries, hedges, and queue time included). ``tick()`` runs on the
    sampler thread; ``alerts()`` on HTTP handlers — state is behind one
    short witnessed lock."""

    def __init__(self, metrics: Metrics, store: TimeSeriesStore,
                 windows: list[float],
                 metric_fmt: str = "latency_ms{{model={name},phase=total}}",
                 label: str = "model") -> None:
        self.metrics = metrics
        self.store = store
        self.windows = list(windows)
        self.metric_fmt = metric_fmt
        # Subject dimension of the exported gauges: "model" for the
        # serving engines, "tenant" for the per-tenant burn engine (same
        # state machine over tenant_latency_ms{tenant=}).
        self.label = label
        self._models: dict[str, _ModelSlo] = {}
        self._lock = new_lock("telemetry.SloEngine")

    def register(self, name: str, slo, metric: str | None = None) -> bool:
        """Track one model's [model.slo] block; False when it is disabled
        (latency_ms = 0). ``metric`` overrides the engine's metric_fmt for
        subjects evaluated over a different histogram than the default —
        the first-token objective (ISSUE 17) registers "<model>:first_unit"
        over ``gen_first_unit_ms{model=}`` this way, reusing the whole
        burn-window/alert state machine unchanged."""
        if slo is None or slo.latency_ms <= 0:
            return False
        m = _ModelSlo(name, slo,
                      metric or self.metric_fmt.format(name=name),
                      self.metrics, self.windows, label=self.label)
        with self._lock:
            self._models[name] = m
        self.metrics.set_slo_alert_state(name, OK, label=self.label)
        return True

    # -- evaluation (sampler thread) -----------------------------------------
    def burn_rates(self, name: str) -> dict[float, float | None]:
        """Current burn per window for one registered model (None = no
        evidence in that window)."""
        with self._lock:
            m = self._models[name]
        budget = 1.0 - m.slo.availability
        out: dict[float, float | None] = {}
        for w in self.windows:
            delta = self.store.histogram_delta(m.metric, w)
            if delta is None:
                out[w] = None
                continue
            good = good_fraction(self.store._bounds or [], delta["counts"],
                                 m.slo.latency_ms)
            out[w] = None if good is None else (1.0 - good) / budget
        return out

    def tick(self) -> None:
        """One evaluation pass (a sampler hook): refresh every model's
        burn gauges and step its alert state machine."""
        with self._lock:
            names = list(self._models)
        for name in names:
            burns = self.burn_rates(name)
            with self._lock:
                m = self._models[name]
                for w, b in burns.items():
                    m.burn_gauges[w].set(b if b is not None else 0.0)
                short, mid = self.windows[0], self.windows[1]
                over_short = (burns[short] or 0.0) > m.slo.burn_alert
                over_mid = (burns[mid] or 0.0) > m.slo.burn_alert
                new_state = (FIRING if over_short and over_mid
                             else PENDING if over_short else OK)
                if new_state != m.state:
                    m.state = new_state
                    m.since = time.time()
            self.metrics.set_slo_alert_state(name, new_state,
                                             label=self.label)

    # -- reads (HTTP / scheduler) --------------------------------------------
    def state_of(self, name: str) -> str:
        """The model's alert state — the fleet scheduler's shed-on-burn
        seam (OK when the model has no SLO registered)."""
        with self._lock:
            m = self._models.get(name)
            return m.state if m is not None else OK

    def alerts(self) -> dict:
        """The /alerts body: per-model state + live burn per window."""
        with self._lock:
            models = list(self._models.items())
        rows = {}
        worst = OK
        order = [OK, PENDING, FIRING]
        for name, m in models:
            burns = self.burn_rates(name)
            with self._lock:
                state, since = m.state, m.since
            if order.index(state) > order.index(worst):
                worst = state
            rows[name] = {
                "state": state,
                "since": round(since, 3),
                "objective_latency_ms": m.slo.latency_ms,
                "availability": m.slo.availability,
                "error_budget": round(1.0 - m.slo.availability, 6),
                "burn_alert": m.slo.burn_alert,
                "burn": {f"{w:g}s": (round(b, 3) if b is not None else None)
                         for w, b in burns.items()},
                "metric": m.metric,
            }
        return {"status": worst, "windows_s": self.windows, "models": rows}


# -- device utilization -------------------------------------------------------

_DEVSEC_RE = re.compile(
    r"^device_seconds_total\{model=([^,}]+),replica=(\d+)\}$")


class UtilizationDeriver:
    """Sampler hook turning ``device_seconds_total{model=,replica=}``
    counter rates into ``device_utilization{model=,replica=}`` gauges:
    seconds of device time per second of wall time on one chip IS that
    chip's busy fraction for the model. Gauges are created as the
    counters appear (replica sets are static after start)."""

    def __init__(self, metrics: Metrics, store: TimeSeriesStore,
                 window_s: float) -> None:
        self.metrics = metrics
        self.store = store
        self.window_s = window_s
        self._gauges: dict[tuple[str, int], object] = {}

    def tick(self) -> None:
        for name in self.store.metric_names():
            match = _DEVSEC_RE.match(name)
            if match is None:
                continue
            model, replica = match.group(1), int(match.group(2))
            h = self.store.history(name, self.window_s)
            if h is None or "window_rate_per_s" not in h:
                continue
            g = self._gauges.get((model, replica))
            if g is None:
                g = self._gauges[(model, replica)] = \
                    self.metrics.device_utilization_gauge(model, replica)
            # rate of a seconds-counter is dimensionless busy fraction;
            # clamp: sampling jitter can push a saturated chip past 1.0.
            g.set(min(1.0, max(0.0, h["window_rate_per_s"])))

    def stats(self) -> dict:
        """The /stats ``utilization`` block: per model, per-replica busy
        fractions plus the lifetime device-seconds ledger."""
        out: dict[str, dict] = {}
        for (model, replica), g in sorted(self._gauges.items()):
            row = out.setdefault(model, {"per_replica": {},
                                         "device_seconds_total": 0.0})
            row["per_replica"][str(replica)] = round(g.value, 4)
        for name in self.store.metric_names():
            match = _DEVSEC_RE.match(name)
            if match is None:
                continue
            hist = self.store.history(name)
            if hist is None or not hist.get("v"):
                continue
            row = out.get(match.group(1))
            if row is not None:
                row["device_seconds_total"] = round(
                    row["device_seconds_total"] + hist["v"][-1], 4)
        for row in out.values():
            vals = list(row["per_replica"].values())
            row["mean_utilization"] = round(sum(vals) / len(vals), 4) \
                if vals else 0.0
        return out
