"""Fleet telemetry plane (ISSUE 14; docs/OBSERVABILITY.md "The telemetry
plane").

Four pieces, all fed by the one metric registry every process already
owns (tpuserve.obs.Metrics):

- ``store``   — bounded per-metric time-series rings + the background
  sampler thread that fills them (``GET /stats/history``);
- ``slo``     — the multi-window burn-rate engine over ``[model.slo]``
  objectives (``slo_burn_rate`` gauges, ``GET /alerts``), plus the
  device-utilization derivation;
- ``fleet``   — exposition parse/merge for the router's fleet scrape
  (``GET /metrics/fleet`` / ``/stats/fleet``);
- ``profile`` — on-demand jax.profiler device-trace capture merged with
  the span ring (``POST /debug/profile``);
- ``events``  — the structured event plane, crash-forensics black box,
  and admin audit trail (``GET /debug/events`` / ``/debug/postmortems`` /
  ``/debug/audit``; docs/OBSERVABILITY.md "The third pillar").
"""

from tpuserve.telemetry.events import (AuditLog, BlackBoxWriter, EventLog,
                                       PostmortemLog)
from tpuserve.telemetry.fleet import merge_expositions, parse_exposition
from tpuserve.telemetry.profile import ProfileCapture
from tpuserve.telemetry.slo import SloEngine, UtilizationDeriver
from tpuserve.telemetry.store import MetricSampler, TimeSeriesStore

__all__ = [
    "AuditLog",
    "BlackBoxWriter",
    "EventLog",
    "MetricSampler",
    "PostmortemLog",
    "ProfileCapture",
    "SloEngine",
    "TimeSeriesStore",
    "UtilizationDeriver",
    "merge_expositions",
    "parse_exposition",
]
