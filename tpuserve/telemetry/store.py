"""Time-series store + background sampler (ISSUE 14 tentpole part 1).

Every counter, gauge, and histogram in the process's ``Metrics`` registry
is snapshotted into a bounded per-metric ring at ``[telemetry]
sample_interval_s``. The rings are what turn the instantaneous ``/metrics``
view into *history*: ``GET /stats/history?metric=&window_s=`` serves the
raw samples plus derived counter **rates** and histogram **window-delta
quantiles** (the p50/p99 of exactly the requests that landed inside the
window, not the lifetime aggregate), and the SLO engine (tpuserve.
telemetry.slo) reads the same rings for its burn-rate math.

Counter-reset handling: a sampled value *below* its predecessor means the
emitting process restarted (worker respawn — PR 8/13 make that an ordinary
event). The increase over such a step is the new value itself (the counter
restarted from 0), never a negative rate; the same rule applies per
histogram bucket. Pinned by tests/test_telemetry.py.

Threading: the sampler is a daemon thread (it must tick while the event
loop is busy serving); the store takes one short witnessed lock per
sample/read, and metric snapshots are collected BEFORE the store lock is
taken so the obs-registry locks and the store lock never nest.
"""

from __future__ import annotations

import logging
import math
import threading
import time
from collections import deque

from tpuserve.obs import Metrics, _split
from tpuserve.utils.locks import new_lock

log = logging.getLogger("tpuserve.telemetry")

# Hard cap on ring capacity per metric: history_s / sample_interval_s can
# be misconfigured into the millions; 4096 samples is > an hour at 1 s.
MAX_RING = 4096


class _Series:
    """One metric's bounded ring of (t, value) samples.

    ``kind`` is "counter" / "gauge" / "histogram". Counter and gauge
    samples are floats; histogram samples are ``(n, total, counts)`` with
    ``counts`` the cumulative-per-bucket tuple from ``Histogram.snapshot``
    (bucket bounds are process-wide constants, so only counts are kept).
    """

    __slots__ = ("kind", "samples")

    def __init__(self, kind: str, capacity: int) -> None:
        self.kind = kind
        self.samples: deque = deque(maxlen=capacity)


def _increase(prev: float, cur: float) -> float:
    """Monotonic increase across one sample step, reset-aware: a drop
    means the source process restarted and the counter began again at 0,
    so the increase is the new value — never negative."""
    if cur >= prev:
        return cur - prev
    return cur


def quantile_from_counts(bounds: list[float], counts: list[float],
                         q: float) -> float | None:
    """Interpolated quantile over one window's per-bucket DELTA counts
    (the histogram_quantile rule, same math as obs.Histogram.quantile but
    over a delta instead of the lifetime counts). None on an empty window;
    inf when the rank lands in the overflow bucket."""
    n = sum(counts)
    if n <= 0:
        return None
    rank = math.ceil(q * n)
    acc = 0.0
    for i, c in enumerate(counts):
        prev_acc = acc
        acc += c
        if acc >= rank and c > 0:
            if i == len(bounds):
                return float("inf")
            lo = bounds[i - 1] if i > 0 else 0.0
            return lo + (bounds[i] - lo) * (rank - prev_acc) / c
    return bounds[-1]


class TimeSeriesStore:
    """Bounded per-metric history over one ``Metrics`` registry."""

    def __init__(self, metrics: Metrics, capacity: int = 600) -> None:
        self.metrics = metrics
        self.capacity = max(2, min(MAX_RING, int(capacity)))
        self._series: dict[str, _Series] = {}
        self._lock = new_lock("telemetry.TimeSeriesStore")
        self.samples_total = 0
        self.last_sample_at: float | None = None
        # Histogram bucket bounds are shared process-wide (obs module
        # default); captured from the first histogram seen.
        self._bounds: list[float] | None = None

    # -- sampling ------------------------------------------------------------
    def sample(self, now: float | None = None) -> None:
        """Snapshot every registered metric into its ring (one tick).

        Registry + per-histogram locks are taken during collection, the
        store lock only afterwards — no nesting between the two families.
        """
        now = time.time() if now is None else now
        with self.metrics._lock:
            counters = list(self.metrics._counters.values())
            gauges = list(self.metrics._gauges.values())
            hists = list(self.metrics._histograms.values())
        rows: list[tuple[str, str, object]] = []
        rows.extend(("counter", c.name, c.value) for c in counters)
        rows.extend(("gauge", g.name, g.value) for g in gauges)
        for h in hists:
            snap = h.snapshot()
            if self._bounds is None:
                self._bounds = list(h.bounds)
            rows.append(("histogram", h.name,
                         (snap["n"], snap["total"], tuple(snap["counts"]))))
        with self._lock:
            for kind, name, value in rows:
                s = self._series.get(name)
                if s is None:
                    s = self._series[name] = _Series(kind, self.capacity)
                s.samples.append((now, value))
            self.samples_total += 1
            self.last_sample_at = now

    # -- reads ---------------------------------------------------------------
    def metric_names(self) -> list[str]:
        with self._lock:
            return sorted(self._series)

    def _window(self, s: _Series, window_s: float | None,
                now: float) -> list[tuple]:
        if window_s is None:
            return list(s.samples)
        horizon = now - window_s
        samples = list(s.samples)
        # Keep the last pre-window sample too: a delta over the window
        # needs the value at its left edge, not just inside it.
        start = 0
        for i, (t, _) in enumerate(samples):
            if t >= horizon:
                start = max(0, i - 1)
                break
        else:
            start = max(0, len(samples) - 1)
        return samples[start:]

    def counter_increase(self, metric: str, window_s: float | None = None,
                         now: float | None = None) -> float | None:
        """Reset-safe increase of one counter over the window (None when
        the series is unknown or has < 2 samples)."""
        now = time.time() if now is None else now
        with self._lock:
            s = self._series.get(metric)
            if s is None or s.kind != "counter":
                return None
            samples = self._window(s, window_s, now)
        if len(samples) < 2:
            return None
        return sum(_increase(samples[i][1], samples[i + 1][1])
                   for i in range(len(samples) - 1))

    def histogram_delta(self, metric: str, window_s: float | None = None,
                        now: float | None = None) -> dict | None:
        """One histogram's window delta: n / total / per-bucket counts,
        reset-safe per bucket. None when unknown or < 2 samples."""
        now = time.time() if now is None else now
        with self._lock:
            s = self._series.get(metric)
            if s is None or s.kind != "histogram":
                return None
            samples = self._window(s, window_s, now)
        if len(samples) < 2:
            return None
        nb = len(samples[0][1][2])
        d_counts = [0.0] * nb
        d_n = 0.0
        d_total = 0.0
        for i in range(len(samples) - 1):
            (_, (n0, tot0, c0)), (_, (n1, tot1, c1)) = \
                samples[i], samples[i + 1]
            reset = n1 < n0
            d_n += n1 if reset else n1 - n0
            d_total += tot1 if reset else tot1 - tot0
            for j in range(nb):
                d_counts[j] += c1[j] if reset else _increase(c0[j], c1[j])
        return {"n": d_n, "total": d_total, "counts": d_counts,
                "span_s": samples[-1][0] - samples[0][0]}

    def history(self, metric: str,
                window_s: float | None = None) -> dict | None:
        """The /stats/history body for one series: raw samples plus the
        derived view — counters get per-step and window rates, histograms
        get window-delta count/mean/p50/p99. None for an unknown metric."""
        now = time.time()
        with self._lock:
            s = self._series.get(metric)
            if s is None:
                return None
            kind = s.kind
            samples = self._window(s, window_s, now)
        out: dict = {"metric": metric, "kind": kind,
                     "window_s": window_s, "n_samples": len(samples)}
        if kind in ("counter", "gauge"):
            out["t"] = [round(t, 3) for t, _ in samples]
            out["v"] = [v for _, v in samples]
            if kind == "counter" and len(samples) >= 2:
                rates = []
                for i in range(len(samples) - 1):
                    dt = samples[i + 1][0] - samples[i][0]
                    inc = _increase(samples[i][1], samples[i + 1][1])
                    rates.append(round(inc / dt, 6) if dt > 0 else 0.0)
                out["rate_per_s"] = rates
                span = samples[-1][0] - samples[0][0]
                inc = sum(_increase(samples[i][1], samples[i + 1][1])
                          for i in range(len(samples) - 1))
                out["increase"] = inc
                out["window_rate_per_s"] = \
                    round(inc / span, 6) if span > 0 else 0.0
        else:
            out["t"] = [round(t, 3) for t, _ in samples]
            out["n"] = [v[0] for _, v in samples]
            delta = self.histogram_delta(metric, window_s, now)
            if delta is not None:
                bounds = self._bounds or []
                p50 = quantile_from_counts(bounds, delta["counts"], 0.5)
                p99 = quantile_from_counts(bounds, delta["counts"], 0.99)
                out["delta"] = {
                    "n": delta["n"],
                    "mean_ms": (delta["total"] / delta["n"])
                    if delta["n"] else 0.0,
                    "p50_ms": p50 if p50 is None or math.isfinite(p50)
                    else (bounds[-1] if bounds else None),
                    "p99_ms": p99 if p99 is None or math.isfinite(p99)
                    else (bounds[-1] if bounds else None),
                    "rate_per_s": round(delta["n"] / delta["span_s"], 6)
                    if delta["span_s"] > 0 else 0.0,
                }
        return out

    def match(self, metric: str) -> list[str]:
        """Series whose full name OR base name (labels stripped) equals
        ``metric`` — `?metric=requests_total` pulls every model's series
        without spelling the labels."""
        with self._lock:
            names = list(self._series)
        if metric in names:
            return [metric]
        return [n for n in names if _split(n)[0] == metric]

    def stats(self) -> dict:
        """The /stats ``telemetry`` block: sampler heartbeat + occupancy."""
        with self._lock:
            n = len(self._series)
        return {
            "series": n,
            "capacity": self.capacity,
            "samples_total": self.samples_total,
            "last_sample_age_s": round(time.time() - self.last_sample_at, 3)
            if self.last_sample_at is not None else None,
        }


class MetricSampler(threading.Thread):
    """The background sampling thread: ticks the store every
    ``interval_s`` and then runs each hook (SLO evaluation, utilization
    derivation) on the fresh sample. Daemon + event-signalled stop so a
    drain always gets a prompt, clean shutdown (pinned by the sampler
    test: no dangling thread, no witness findings)."""

    def __init__(self, store: TimeSeriesStore, interval_s: float,
                 hooks: "list | None" = None) -> None:
        super().__init__(name="tpuserve-telemetry", daemon=True)
        self.store = store
        self.interval_s = max(0.01, float(interval_s))
        self.hooks = list(hooks or [])
        self._stop_ev = threading.Event()
        self.ticks = self.store.metrics.counter("telemetry_samples_total")

    def run(self) -> None:
        while not self._stop_ev.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # one bad tick must not end sampling
                log.exception("telemetry sample tick failed")

    def tick(self) -> None:
        """One sample + hook pass (callable directly from tests)."""
        self.store.sample()
        self.ticks.inc()
        for hook in self.hooks:
            hook()

    def stop(self, timeout: float = 5.0) -> None:
        """Signal and join (idempotent; called from drain AND stop)."""
        self._stop_ev.set()
        if self.is_alive():
            self.join(timeout)
