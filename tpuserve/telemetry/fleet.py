"""Fleet metric aggregation (ISSUE 14 tentpole part 2).

The primary router scrapes every live worker and peer router (host agents
have no HTTP surface — their liveness is already the primary's own
``host_up`` gauges, and a dead domain's workers show up here as stale
sources) and merges the expositions into ONE fleet view:

- **counters summed** across sources — ``requests_total{model=}`` on
  ``/metrics/fleet`` is exactly the Σ of every process's counter (the
  telemetry smoke gates byte-exact equality);
- **gauges labeled per process** — a gauge is a statement about one
  process (queue depth, worker_up, utilization), so each sample gains a
  ``proc=`` label instead of being meaninglessly summed;
- **histograms merged bucket-wise** — every process shares the same
  bucket bounds (obs module constants), so per-``le`` cumulative counts
  and the _sum/_count pair add EXACTLY; fleet quantiles computed from the
  merged histogram are true fleet quantiles, not averages of averages.

Degradation contract: a source that refuses/fails/times out is marked
stale — ``fleet_source_up{proc=}`` 0, a ``# STALE`` comment, and a row in
``/stats/fleet`` — and the merge proceeds with the survivors. The scrape
endpoints NEVER answer 5xx because a host died; a dead host is data, not
an error (pinned by the test_hosts degradation test).

Everything here is pure text/dict work over the exposition format this
repo itself renders (obs.Metrics.render_prometheus); exemplar suffixes
and ``# EOF`` are stripped on parse and re-emitted on render.
"""

from __future__ import annotations

import re

_LINE_RE = re.compile(
    r"^(?P<base>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?\s+(?P<value>[^\s#]+)")


def parse_exposition(text: str) -> dict:
    """Parse one /metrics body into ``{"types": {base: kind},
    "samples": [(base, labels_str, value)]}``. Exemplars (anything after
    ``#`` on a sample line) and comments are dropped; unparseable values
    are skipped rather than fatal (a torn scrape loses lines, not the
    merge)."""
    types: dict[str, str] = {}
    samples: list[tuple[str, str, float]] = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3]
            continue
        m = _LINE_RE.match(line)
        if m is None:
            continue
        try:
            value = float(m.group("value"))
        except ValueError:
            continue
        samples.append((m.group("base"), m.group("labels") or "", value))
    return {"types": types, "samples": samples}


def _hist_base(base: str) -> str | None:
    """The histogram family name for a _bucket/_sum/_count sample."""
    for suffix in ("_bucket", "_sum", "_count"):
        if base.endswith(suffix):
            return base[: -len(suffix)]
    return None


def _strip_le(labels: str) -> tuple[str, str | None]:
    """Split a _bucket label set into (labels-without-le, le value)."""
    parts = [p for p in labels.split(",") if p]
    le = None
    kept = []
    for p in parts:
        if p.startswith("le="):
            le = p[3:].strip('"')
        else:
            kept.append(p)
    return ",".join(kept), le


def _with_proc(labels: str, proc: str) -> str:
    extra = f'proc="{proc}"'
    return f"{labels},{extra}" if labels else extra


def merge_expositions(sources: "list[tuple[str, str | None]]") -> str:
    """Merge ``(proc_label, exposition_text | None)`` sources into one
    fleet exposition. ``None`` text = a stale source: it contributes a
    ``fleet_source_up`` 0 and a ``# STALE`` marker, nothing else."""
    types: dict[str, str] = {}
    counters: dict[tuple[str, str], float] = {}
    gauges: list[tuple[str, str, float]] = []
    # (family, labels-without-le) -> {le -> count}; sums/counts separately.
    hist_buckets: dict[tuple[str, str], dict[str, float]] = {}
    hist_sums: dict[tuple[str, str], float] = {}
    hist_counts: dict[tuple[str, str], float] = {}
    stale: list[str] = []

    for proc, text in sources:
        if text is None:
            stale.append(proc)
            continue
        parsed = parse_exposition(text)
        types.update(parsed["types"])
        src_types = parsed["types"]
        for base, labels, value in parsed["samples"]:
            family = _hist_base(base)
            if family is not None and src_types.get(family) == "histogram":
                key_labels, le = _strip_le(labels)
                if base.endswith("_bucket") and le is not None:
                    hist_buckets.setdefault(
                        (family, key_labels), {}).setdefault(le, 0.0)
                    hist_buckets[(family, key_labels)][le] += value
                elif base.endswith("_sum"):
                    hist_sums[(family, key_labels)] = \
                        hist_sums.get((family, key_labels), 0.0) + value
                elif base.endswith("_count"):
                    hist_counts[(family, key_labels)] = \
                        hist_counts.get((family, key_labels), 0.0) + value
                continue
            kind = src_types.get(base, "counter")
            if kind == "gauge":
                gauges.append((base, _with_proc(labels, proc), value))
            else:
                counters[(base, labels)] = \
                    counters.get((base, labels), 0.0) + value

    def fmt(v: float) -> str:
        return f"{int(v)}" if float(v).is_integer() else f"{v}"

    lines: list[str] = []
    typed: set[str] = set()

    def type_line(base: str, kind: str) -> None:
        if base not in typed:
            typed.add(base)
            lines.append(f"# TYPE {base} {kind}")

    for (base, labels), value in sorted(counters.items()):
        type_line(base, "counter")
        label_str = f"{{{labels}}}" if labels else ""
        lines.append(f"{base}{label_str} {fmt(value)}")
    for base, labels, value in sorted(gauges):
        type_line(base, "gauge")
        lines.append(f"{base}{{{labels}}} {fmt(value)}")
    for (family, labels), buckets in sorted(hist_buckets.items()):
        type_line(family, "histogram")
        sep = "," if labels else ""

        def le_key(le: str) -> float:
            return float("inf") if le == "+Inf" else float(le)

        for le in sorted(buckets, key=le_key):
            lines.append(
                f'{family}_bucket{{{labels}{sep}le="{le}"}} '
                f"{fmt(buckets[le])}")
        lines.append(f"{family}_sum{{{labels}}} "
                     f"{hist_sums.get((family, labels), 0.0)}")
        lines.append(f"{family}_count{{{labels}}} "
                     f"{fmt(hist_counts.get((family, labels), 0.0))}")
    for proc, _ in sources:
        type_line("fleet_source_up", "gauge")
        lines.append(f'fleet_source_up{{proc="{proc}"}} '
                     f"{0 if proc in stale else 1}")
    for proc in stale:
        lines.append(f"# STALE {proc}")
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


def sum_counter(merged_or_text: str, base: str,
                labels: str | None = None) -> float:
    """Sum one counter family (optionally one exact label set) out of an
    exposition body — the smoke's Σ-equality gate helper."""
    total = 0.0
    for b, ls, v in parse_exposition(merged_or_text)["samples"]:
        if b != base:
            continue
        if labels is not None and ls != labels:
            continue
        total += v
    return total
