"""Worker-process entry for the router split (docs/ROBUSTNESS.md).

A worker is deliberately NOT a new kind of server: it is the existing
single-process server (``tpuserve.server``) — batcher, hostpipe, runtime,
lifecycle, watchdog, graceful SIGTERM drain — built in its own process and
bound to loopback, so every property the single-process tests prove holds
unchanged behind the boundary. What the process split adds lives in the
supervisor and router, not here.

Differences from a standalone server, all applied to the config before
build:

- binds ``[worker] host`` (loopback) on ``port_base + id`` or an ephemeral
  port, and reports the bound port to the supervisor over a pipe handshake
  (``{"op": "ready", "port": ...}``) — the same handshake idiom as the
  deferred pool's workers;
- the result cache is forced OFF: caching + single-flight coalescing are
  router-owned (one shared cache beats N private ones, and a cached answer
  must survive the worker that computed it);
- ``[router]`` is forced off (a worker must never recurse into spawning
  its own workers);
- recycle-mode models are rejected up front: the deferred pool is its own
  process-isolation story, and workers run as daemonic children which
  cannot fork grandchildren.

Deadlines cross the boundary as REMAINING budget (the gRPC convention):
the router stamps the absolute deadline at admission and forwards
``X-Timeout-Ms`` = time left at dispatch, which the existing
``_requested_timeout_ms`` path re-stamps against this process's clock —
so a request 504s at the same absolute instant whether it dies in the
router, on the wire, or in here.

SIGTERM drains gracefully via ``serve_async`` exactly as a standalone
server does: stop admitting -> flush accepted -> exit. The supervisor
sequences this after the router itself stopped admitting, so a rolling
restart of the whole deployment drops zero accepted requests.
"""

from __future__ import annotations

import copy
import os
import time

from tpuserve.config import ServerConfig
from tpuserve.telemetry.events import redirect_stderr, resolve_blackbox_dir


def worker_config(cfg: ServerConfig, worker_id: int) -> ServerConfig:
    """Derive one worker's ServerConfig from the deployment config."""
    for m in cfg.models:
        if m.session_mode == "recycle":
            raise ValueError(
                f"model {m.name!r}: recycle-mode models cannot run behind "
                "the router tier (the deferred pool is its own process "
                "split, and daemonic workers cannot fork grandchildren); "
                "serve them single-process")
    wcfg = copy.deepcopy(cfg)
    wcfg.host = cfg.worker.host
    wcfg.port = (cfg.worker.port_base + worker_id
                 if cfg.worker.port_base else 0)
    if cfg.worker.drain_timeout_s > 0:
        wcfg.drain_timeout_s = cfg.worker.drain_timeout_s
    # Router-owned layers never run in the worker. Tenancy admits at the
    # tier that fronts clients: the router resolves X-Api-Key once and
    # relays the tenant as the loopback X-Tenant header — a worker-side
    # ledger would 401 every relay (no key crosses the hop) and
    # double-charge the window.
    wcfg.router.enabled = False
    wcfg.cache.enabled = False
    wcfg.tenants.enabled = False
    wcfg.autopilot.enabled = False
    # Black box (ISSUE 15, docs/OBSERVABILITY.md "The third pillar"): the
    # supervisor resolves ONE black-box directory for the deployment
    # (stable across respawns — it runs in the supervisor's process) and
    # assigns the slot's stderr capture + postmortem-snapshot files. The
    # worker redirects its own fd 2 at spawn and checkpoints snapshots;
    # the supervisor reads both back at reap time.
    if cfg.events.enabled and not wcfg.events.stderr_path:
        bb = resolve_blackbox_dir(cfg.events)
        wcfg.events.dir = bb
        wcfg.events.stderr_path = os.path.join(
            bb, f"worker{worker_id}.stderr")
        wcfg.events.snapshot_path = os.path.join(
            bb, f"worker{worker_id}.snapshot.json")
    return wcfg


def worker_main(cfg: ServerConfig, worker_id: int, conn) -> None:
    """Process entry (multiprocessing spawn target).

    ``cfg`` is the WORKER config (worker_config already applied — the
    supervisor derives it once so every respawn serves identical config).
    ``conn`` carries the ready handshake; it stays open afterward purely so
    an EOF can tell this worker the supervisor vanished.
    """
    # Black box step 1 (ISSUE 15): redirect fd 2 to the slot's capture
    # file BEFORE any import can write to it — a native crash's abort
    # message, an OOM killer's aftermath, a Python traceback: all of it
    # lands in a file the supervisor folds into the postmortem instead of
    # interleaving onto the supervisor's tty and dying with the process.
    redirect_stderr(cfg.events.stderr_path,
                    f"worker {worker_id} boot pid {os.getpid()} "
                    f"ts {time.time():.3f}")
    # Spawned children re-run sitecustomize, which may re-force a hardware
    # platform via jax.config; re-assert the env's platform choice before
    # any backend init (mirrors tpuserve.deferred._worker_run).
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")

    import asyncio
    import logging

    from tpuserve.server import ServerState, configure_logging, serve_async

    configure_logging(cfg)
    logging.getLogger("tpuserve.workerproc").info(
        "worker %d: building models (pid %d)", worker_id, os.getpid())
    try:
        state = ServerState(cfg)
        state.worker_id = worker_id
        if state.injector is not None:
            # Worker-pinned [[faults.rule]] entries (rule.worker >= 0) only
            # fire in the matching worker process.
            state.injector.worker_id = worker_id
        if state.events is not None:
            # Events carry the same process-lane vocabulary as spans
            # (0 = router, worker id + 1 behind it) so a stitched trace's
            # interleaved events land on the right lane.
            state.events.pid = worker_id + 1
        state.build()
    except Exception as e:  # noqa: BLE001 — report any boot death upward
        try:
            conn.send({"op": "died", "error": f"{type(e).__name__}: {e}"})
        finally:
            conn.close()
        raise

    async def _serve() -> None:
        loop = asyncio.get_running_loop()
        ready = asyncio.Event()
        serve_task = loop.create_task(serve_async(state, ready))
        ready_task = loop.create_task(ready.wait())
        # First of: listener up (-> handshake) or an early serve failure
        # (port bind, startup canary) — the latter must surface as a
        # "died" message, not a supervisor handshake timeout.
        await asyncio.wait({serve_task, ready_task},
                           return_when=asyncio.FIRST_COMPLETED)
        if serve_task.done():
            ready_task.cancel()
            serve_task.result()  # raises the boot failure
            return
        conn.send({"op": "ready", "port": state.serving_addresses[0][1],
                   "pid": os.getpid()})
        await serve_task

    try:
        asyncio.run(_serve())
    except Exception as e:  # noqa: BLE001 — report any death upward
        try:
            conn.send({"op": "died", "error": f"{type(e).__name__}: {e}"})
        except (BrokenPipeError, OSError):
            pass
        raise
    finally:
        conn.close()
