"""Router tier: the HTTP front door over N isolated worker processes.

The router owns everything that must survive a worker death (Clipper's
front tier, PAPERS.md P1): HTTP/JSON, admission + per-request deadline
stamping, the content-addressed result cache with single-flight coalescing
(PR 5's layer, hoisted above the process boundary so a cached answer
outlives the worker that computed it), and per-model circuit breakers. It
never touches a device — a worker taking its runtime down cannot take the
front door with it.

Relay semantics (the robustness contract, docs/ROBUSTNESS.md):

- **Deadline stamping** — the absolute deadline is stamped once at router
  admission; every forward carries ``X-Timeout-Ms`` = the budget REMAINING
  at dispatch, so the worker re-stamps the same absolute instant on its own
  clock. A request 504s at that instant whether it dies in the router, on
  the wire, or inside a worker — and no retry or hedge ever extends it.
- **Retry** — transport failures (connection refused/reset, a worker dying
  mid-request) re-dispatch to a different healthy worker, up to
  ``retry_max`` times within the deadline. Inference is idempotent, so
  re-dispatching unanswered work is safe; a DEFINITIVE worker answer
  (anything but a 503-not-admitted) is never re-dispatched — a 500 means
  the work already executed and failed, and re-running it would
  double-execute.
- **Hedging** — with ``hedge_ms > 0``, an attempt silent that long gets a
  duplicate dispatched to another worker; the first definitive answer wins
  and the loser is cancelled. Covers the wedged-but-alive worker that
  liveness checks can't see yet.
- **Degradation** — a lost worker is lost capacity, not lost availability:
  with any healthy worker the fleet keeps answering; with none, requests
  shed fast with 503 + ``Retry-After`` derived from the supervisor's live
  respawn backoff ETA. Breaker 503s carry the half-open probe ETA.
- **Drain** — SIGTERM sequences across the boundary: the router stops
  admitting (503 + Retry-After), waits for its in-flight relays, and only
  then SIGTERMs the workers, each of which flushes its accepted batches
  before exiting. Zero accepted requests dropped.
- **Tracing** (ISSUE 12, docs/OBSERVABILITY.md) — the router mints each
  request's 128-bit trace context and every relay attempt crosses the
  boundary as ``X-Trace-Id`` + ``X-Parent-Span``, so hedged/retried
  attempts are sibling spans under one trace with the worker's own span
  tree hanging under each. ``X-Trace-Id`` rides every response;
  ``/debug/trace?trace_id=`` stitches router + worker records into one
  Chrome trace (worker id as pid — the hop is a visible gap between
  process lanes).
"""

from __future__ import annotations

import asyncio
import functools
import json
import logging
import math
import signal
import time

import aiohttp
from aiohttp import web

from tpuserve import frame
from tpuserve.analysis import witness
from tpuserve.cache import ModelCache
from tpuserve.config import ServerConfig, SloConfig
from tpuserve.faults import CircuitBreaker, Watchdog
from tpuserve.obs import (ROUTER_STREAM_REASONS, FlightRecorder, Metrics,
                          TraceContext, exposition_content_type,
                          spans_to_chrome)
from tpuserve.scheduler.autopilot import (Action, AutopilotLoop,
                                          DomainSignal, ModelSignal, Signals)
from tpuserve.scheduler.tenants import TenantLedger
from tpuserve.server import (_err, _requested_stream, _requested_timeout_ms,
                             configure_logging)
from tpuserve.telemetry import (AuditLog, EventLog, MetricSampler,
                                PostmortemLog, SloEngine, TimeSeriesStore,
                                merge_expositions, parse_exposition)
from tpuserve.telemetry import events as events_mod
from tpuserve.workerproc.hosts import HostSupervisor, host_name
from tpuserve.workerproc.peers import (
    TENANT_HEADER,
    HashRing,
    PassiveWorkerView,
    PeerRouterSupervisor,
    TopologyClient,
)
from tpuserve.workerproc.supervisor import WorkerHandle, WorkerSupervisor

log = logging.getLogger("tpuserve.workerproc")

_VERBS = ("predict", "classify", "detect", "generate")

# Same backstop grace as the single-process HTTP timer: the worker enforces
# the deadline precisely (fast 504 at the instant), the router's own wait
# runs slightly late so the two never race.
_DEADLINE_GRACE_S = 0.25


class NoHealthyWorker(Exception):
    """Every worker slot is dead/unhealthy; ``eta_s`` is the live respawn
    backoff ETA (-> 503 + Retry-After)."""

    def __init__(self, eta_s: float) -> None:
        super().__init__("no healthy worker")
        self.eta_s = eta_s


class RelayDeadline(Exception):
    """The request's absolute deadline expired while relaying (-> 504)."""


class UpstreamFailed(Exception):
    """Transport failures exhausted the retry budget (-> 503, retryable:
    the work was never definitively executed)."""


class _Answer:
    """One complete worker response (body fully read — never torn)."""

    __slots__ = ("status", "content_type", "body", "retry_after")

    def __init__(self, status: int, content_type: str, body: bytes,
                 retry_after: str | None) -> None:
        self.status = status
        self.content_type = content_type
        self.body = body
        self.retry_after = retry_after

    def to_response(self) -> web.Response:
        headers = {"Retry-After": self.retry_after} if self.retry_after else None
        return web.Response(body=self.body, status=self.status,
                            content_type=self.content_type, headers=headers)


class _RelayedError(Exception):
    """Non-200 relay outcome crossing the cache's single-flight machinery
    (errors must fan out to coalesced waiters but never populate)."""

    def __init__(self, ans: _Answer) -> None:
        super().__init__(f"upstream answered {ans.status}")
        self.ans = ans


# Response header a worker stamps on a committed stream (ISSUE 17). Its
# presence IS the router's first-byte latch: the worker will write body
# bytes to this connection, so retries and hedges are no longer legal —
# a re-dispatch could replay tokens the client already consumed.
_STREAM_HEADER = "X-Tpuserve-Stream"


class _StreamAnswer:
    """A streaming worker response claimed at the response headers — the
    body is deliberately NOT read (``_Answer``'s never-torn guarantee does
    not apply): the relay forwards it chunk-by-chunk instead. Owns the
    open upstream response AND the worker's inflight count until
    ``close()``; closing with the body unread aborts the upstream
    connection, which is exactly the worker's client-disconnect signal
    (its engine cancels the slot and folds the capacity back in)."""

    __slots__ = ("status", "content_type", "resp", "worker", "_state",
                 "_closed")

    def __init__(self, status: int, content_type: str, resp,
                 worker: WorkerHandle, state: "RouterState") -> None:
        self.status = status
        self.content_type = content_type
        self.resp = resp
        self.worker = worker
        self._state = state
        self._closed = False

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self.resp.close()
        self._state.supervisor.track_inflight(self.worker, -1)


class RouterHandles:
    """Per-model hot-path metric handles, prebound once (PR 5 discipline)."""

    __slots__ = ("mcfg", "requests", "retries", "hedges", "timeouts",
                 "latency", "streams", "first_unit", "peer_hops",
                 "peer_errors", "peer_serves")

    def __init__(self, name: str, mcfg, metrics: Metrics) -> None:
        self.mcfg = mcfg
        self.requests = metrics.counter(f"router_requests_total{{model={name}}}")
        self.retries = metrics.counter(f"router_retries_total{{model={name}}}")
        self.hedges = metrics.counter(f"router_hedges_total{{model={name}}}")
        self.timeouts = metrics.counter(f"router_timeouts_total{{model={name}}}")
        self.latency = metrics.histogram(f"router_latency_ms{{model={name}}}")
        # Streamed relays (ISSUE 17): committed streams forwarded, and the
        # client-observed first-byte latency — the router-tier input for
        # the "<model>:first_unit" SLO subject (queue + relay included).
        self.streams = metrics.counter(f"router_streams_total{{model={name}}}")
        self.first_unit = metrics.histogram(
            f"router_first_unit_ms{{model={name}}}")
        # Sharded-cache peer hops (ISSUE 13): forwards to a key's owning
        # router, hops that failed transport (and degraded to local-only),
        # and requests this router served on a peer's behalf.
        self.peer_hops = metrics.counter(
            f"cache_peer_hops_total{{model={name}}}")
        self.peer_errors = metrics.counter(
            f"cache_peer_errors_total{{model={name}}}")
        self.peer_serves = metrics.counter(
            f"cache_peer_serves_total{{model={name}}}")


class RouterState:
    """Everything a running router process owns.

    ``router_id`` 0 (the default) is the PRIMARY: it owns the worker/host
    supervisor and, with ``[router] routers > 1``, the peer-router
    supervisor. Peer routers (``router_id >= 1``, spawned by the primary
    via ``tpuserve.workerproc.peers``) own no processes — they sync the
    worker topology and hash-ring membership from the primary's peer
    listener and serve the same public port through SO_REUSEPORT."""

    def __init__(self, cfg: ServerConfig, router_id: int = 0,
                 primary_peer_url: str | None = None) -> None:
        self.cfg = cfg
        self.rcfg = cfg.router
        self.router_id = router_id
        self.is_primary = router_id == 0
        self.metrics = Metrics(cfg.trace_capacity,
                               exemplars=cfg.trace.exemplars)
        # Router-side flight recorder (ISSUE 12): retains the front door's
        # view of slow/errored requests — root + per-attempt spans (pid 0).
        # /debug/trace?trace_id= stitches the matching worker records in
        # (worker spans carry pid = worker id + 1), so one Chrome trace
        # shows the request crossing the process boundary.
        self.recorder = FlightRecorder(
            slow_n=cfg.trace.slow_n,
            error_capacity=cfg.trace.error_capacity,
            always_record_errors=cfg.trace.always_record_errors,
            metrics=self.metrics)
        # Structured event plane + black box + audit trail (ISSUE 15,
        # docs/OBSERVABILITY.md "The third pillar"). The router's
        # postmortem ledger is THE fleet-wide one: its supervisors reap
        # every worker, host agent, and peer router.
        self.events: EventLog | None = None
        self.audit: AuditLog | None = None
        self.postmortems: PostmortemLog | None = None
        if cfg.events.enabled:
            ecfg = cfg.events
            self.events = EventLog(self.metrics, ecfg.capacity,
                                   jsonl_path=ecfg.jsonl_path)
            self.audit = AuditLog(self.metrics, ecfg.audit_capacity,
                                  events=self.events)
            self.postmortems = PostmortemLog(
                self.metrics, ecfg.postmortem_capacity,
                tail_bytes=ecfg.stderr_tail_bytes, events=self.events)
            events_mod.install_bridge(self.events, ecfg.bridge_level)
            events_mod.set_active(self.events)
        if not self.is_primary:
            # Peer router: a passive worker view synced from the primary.
            self.supervisor = PassiveWorkerView(cfg, self.metrics)
        elif cfg.router.hosts > 0:
            # Host failure domains (ISSUE 13): workers grouped under host
            # agents, each agent one SIGKILL-able process group.
            self.supervisor = HostSupervisor(cfg, self.metrics,
                                             postmortems=self.postmortems)
        else:
            self.supervisor = WorkerSupervisor(cfg, self.metrics,
                                               postmortems=self.postmortems)
        self.watchdog = Watchdog(cfg.watchdog_interval_s, self.metrics)
        # Horizontal router tier (ISSUE 13): the consistent-hash ring over
        # every live router's peer listener. None until membership is known
        # (single-router deployments keep it None: always-local).
        self.ring: HashRing | None = None
        self.peer_port: int | None = None
        self.peer_url: str | None = None
        self._peer_runner = None
        # (host, port) of the shared public listener — the caller binds the
        # SO_REUSEPORT socket BEFORE start() so peer routers can join it.
        self.public_addr: tuple[str, int] | None = None
        self.peer_sup = (PeerRouterSupervisor(cfg, self.metrics,
                                              self._rebuild_ring,
                                              postmortems=self.postmortems)
                         if self.is_primary and cfg.router.routers > 1
                         else None)
        self.topo = (TopologyClient(self, primary_peer_url,
                                    cfg.router.peer_sync_interval_s)
                     if not self.is_primary else None)
        self.handles: dict[str, RouterHandles] = {}
        self.breakers: dict[str, CircuitBreaker] = {}
        self.caches: dict[str, ModelCache] = {}
        # Per-model config generation: bumped on every successful reload
        # fan-out, and baked into every cache key (the router-tier analog
        # of PR 5's version binding — a fleet-wide publish atomically
        # invalidates all older entries).
        self.generations: dict[str, int] = {}
        # Last machine-readable shed reason each model's workers answered
        # (the `reason` key on scheduler sheds, obs.SCHED_SHED_REASONS):
        # surfaced on this router's own breaker 503s so a client shed at
        # the front door still learns WHY the fleet is refusing work.
        self.last_shed_reason: dict[str, str] = {}
        # Next allowed breaker probe per model (time.monotonic): while a
        # breaker is open, one request per breaker_retry_after_s is let
        # through as the recovery probe; everyone else sheds with the
        # half-open ETA as Retry-After.
        self._probe_at: dict[str, float] = {}
        self.draining = False
        self._inflight = 0
        # Absolute instant (time.monotonic) after which in-flight STREAMS
        # are terminated by their forward loops with a well-formed "drain"
        # error event — set by drain(); None while serving (ISSUE 17: a
        # long generation must not pin a drain to its full timeout).
        self._stream_kill_at: float | None = None
        self.serving_addresses: list = []
        self._session: aiohttp.ClientSession | None = None
        # Tenant containment (ISSUE 16): resolve X-Api-Key once at ingress,
        # admit against the weighted device-seconds ledger, charge at
        # completion. EVERY router process fronts clients (SO_REUSEPORT),
        # so every router owns a ledger — enforcement is per-process, and
        # a tenant's effective quota is (configured quota x routers); set
        # per-tenant budgets with the router count in mind
        # (docs/OPERATIONS.md "Tenant containment").
        self.tenants: TenantLedger | None = None
        self.tenant_slo: SloEngine | None = None
        if cfg.tenants.enabled:
            self.tenants = TenantLedger(cfg.tenants, self.metrics)
            self.tenants.saturated_fn = self._fleet_saturated
        # Models the autopilot has engaged shed-on-burn for at the ROUTER
        # front door: batch-priority work for these models sheds before it
        # costs a relay. The primary's autopilot owns membership; peers
        # adopt it from /peer/state so the whole tier sheds together.
        self.burn_shed: set[str] = set()
        # Telemetry plane, router tier (ISSUE 14): history over the
        # router's own registry plus the SLO engine evaluated over
        # router_latency_ms{model=} — the CLIENT-observed latency, queue +
        # retries + hedges included, which is the tier an availability SLO
        # is honestly judged at. The fleet scrape (/metrics/fleet) is
        # assembled on demand from workers + peers, below.
        self.store: TimeSeriesStore | None = None
        self.sampler: MetricSampler | None = None
        self.slo: SloEngine | None = None
        if cfg.telemetry.enabled:
            tcfg = cfg.telemetry
            self.store = TimeSeriesStore(
                self.metrics,
                capacity=int(tcfg.history_s / tcfg.sample_interval_s))
            self.slo = SloEngine(
                self.metrics, self.store, tcfg.burn_windows_s,
                metric_fmt="router_latency_ms{{model={name}}}")
            hooks = [self.slo.tick]
            if self.tenants is not None and cfg.tenants.slo_latency_ms > 0:
                # Per-tenant burn gauges (ISSUE 16 satellite): the same
                # burn-rate machinery evaluated over tenant_latency_ms —
                # one shared objective from [tenants], labeled tenant= so
                # the drill (and an operator) can watch a victim tenant's
                # budget while a neighbor floods.
                self.tenant_slo = SloEngine(
                    self.metrics, self.store, tcfg.burn_windows_s,
                    metric_fmt="tenant_latency_ms{{tenant={name}}}",
                    label="tenant")
                tenant_slo_cfg = SloConfig(
                    latency_ms=cfg.tenants.slo_latency_ms,
                    availability=cfg.tenants.slo_availability,
                    burn_alert=cfg.tenants.slo_burn_alert)
                for tname in self.tenants.names():
                    self.tenant_slo.register(tname, tenant_slo_cfg)
                hooks.append(self.tenant_slo.tick)
            self.sampler = MetricSampler(self.store, tcfg.sample_interval_s,
                                         hooks=hooks)
            for mcfg in cfg.models:
                self.slo.register(mcfg.name, mcfg.slo)
                # First-token objective (ISSUE 17): a second SLO subject
                # per streaming model, evaluated over the router's own
                # first-byte histogram — the client-observed time-to-
                # first-token, which is the latency a streaming UX is
                # honestly judged at (total duration would be nonsense:
                # long answers aren't slow answers).
                if mcfg.slo is not None and mcfg.slo.first_unit_ms > 0:
                    self.slo.register(
                        f"{mcfg.name}:first_unit",
                        SloConfig(latency_ms=mcfg.slo.first_unit_ms,
                                  availability=mcfg.slo.availability,
                                  burn_alert=mcfg.slo.burn_alert),
                        metric=f"router_first_unit_ms{{model={mcfg.name}}}")
        self.fleet_scrapes = self.metrics.counter("fleet_scrapes_total")
        self.fleet_scrape_errors = self.metrics.counter(
            "fleet_scrape_errors_total")
        for mcfg in cfg.models:
            name = mcfg.name
            self.handles[name] = RouterHandles(name, mcfg, self.metrics)
            self.breakers[name] = CircuitBreaker(
                name, mcfg.breaker_threshold, self.metrics,
                retry_after_s=mcfg.breaker_retry_after_s)
            self.generations[name] = 1
            # cacheable = false keeps a model out of the router's
            # wire-level cache too: the wire key digests the raw body, so
            # only models whose results are a pure function of the body
            # (every sampling param — seed, temperature, steps — rides IN
            # the body for the generative families) may populate it.
            if cfg.cache.enabled and mcfg.cacheable:
                self.caches[name] = ModelCache(
                    name, cfg.cache, self.metrics,
                    version_fn=functools.partial(self.generations.get, name, 0))
        if self.tenants is not None:
            # Tenant-partitioned cache capacity (ISSUE 16): each tenant's
            # weighted share bounds how many entries its misses may pin,
            # so a flooding tenant churns its OWN share first. Hits stay
            # content-addressed across tenants — identical bytes are
            # identical answers, not a leak.
            weights = self.tenants.weights()
            for c in self.caches.values():
                c.set_tenant_weights(weights)
        # Self-healing controller (ISSUE 16 tentpole): the reconcile loop
        # runs on the PRIMARY only — it owns the supervisors (the scale
        # actuator) and the audit trail, the same serialization admin
        # verbs already follow. Peers see its effects through /peer/state
        # (burn_shed) and the supervisor topology.
        self.autopilot: AutopilotLoop | None = None
        if self.is_primary and cfg.autopilot.enabled:
            self.autopilot = AutopilotLoop(
                cfg.autopilot, self._collect_signals, self._actuate,
                audit=self.audit, metrics=self.metrics)

    # -- lifecycle -----------------------------------------------------------
    async def start(self) -> None:
        if witness.maybe_install():
            log.info("lock witness installed (TPUSERVE_LOCK_WITNESS)")
        self._session = aiohttp.ClientSession()
        if self.sampler is not None:
            self.sampler.start()
        if not self.is_primary:
            # Peer router: bind the peer listener (cache hops land here).
            # The topology sync is sequenced by _peer_serve AFTER the ready
            # handshake — the primary can only put this peer in the ring
            # once it has learned the peer port, so syncing before the
            # handshake would always observe a ring missing ourselves.
            await self._start_peer_listener()
            return
        await self.supervisor.start()
        # Process-liveness sweep rides the same Watchdog as PR 1's group
        # loops: a reaped+respawn-scheduled worker (or whole host) lands in
        # watchdog_restarts_total{model=_router,component=worker|host}.
        component = "host" if self.rcfg.hosts > 0 else "worker"
        self.watchdog.register("_router", component, self.supervisor.sweep)
        if self.peer_sup is not None or self.rcfg.routers > 1:
            await self._start_peer_listener()
        if self.peer_sup is not None:
            if self.public_addr is None:
                raise RuntimeError(
                    "[router] routers > 1 needs the shared public address "
                    "bound before start(): set state.public_addr (serve_"
                    "router_async does this via the SO_REUSEPORT socket)")
            await self.peer_sup.start(self.public_addr[0],
                                      self.public_addr[1], self.peer_url)
            self.watchdog.register("_router", "router", self.peer_sup.sweep)
            self._rebuild_ring()
        self.watchdog.start()
        if self.autopilot is not None:
            self.autopilot.start()
            log.info("autopilot engaged (interval %.2fs, hysteresis %d "
                     "ticks, budget %d/%gs)",
                     self.cfg.autopilot.interval_s,
                     self.cfg.autopilot.hysteresis_ticks,
                     self.cfg.autopilot.max_actions_per_window,
                     self.cfg.autopilot.window_s)

    async def _start_peer_listener(self) -> None:
        """Bind this router's loopback control plane: /peer/state topology,
        /peer/models (sharded-cache hops from sibling routers), and the
        primary's /peer/admin fan-out entry."""
        self._peer_runner = web.AppRunner(make_peer_app(self),
                                          access_log=None)
        await self._peer_runner.setup()
        port = self.rcfg.peer_port if (self.is_primary
                                       and self.rcfg.peer_port) else 0
        site = web.TCPSite(self._peer_runner, "127.0.0.1", port)
        await site.start()
        self.peer_port = self._peer_runner.addresses[0][1]
        self.peer_url = f"http://127.0.0.1:{self.peer_port}"

    def _rebuild_ring(self) -> None:
        """Primary: rebuild the hash ring from itself + live peers (called
        at start and on every peer death/respawn). Peers rebuild theirs
        from /peer/state instead."""
        members = {self.router_id: self.peer_url}
        if self.peer_sup is not None:
            members.update(self.peer_sup.members())
        self.ring = HashRing(members)

    def apply_topology(self, data: dict) -> None:
        """Peer side: adopt one /peer/state snapshot — worker addresses,
        ring membership, and cache generations (a generation bump clears
        the local shard, the poll-path half of reload invalidation)."""
        self.supervisor.update(data.get("workers") or [])
        members = {int(r["router"]): r["peer_url"]
                   for r in (data.get("ring") or [])}
        if members and (self.ring is None or members != self.ring.members):
            self.ring = HashRing(members)
        for name, gen in (data.get("generations") or {}).items():
            gen = int(gen)
            if name in self.generations and self.generations[name] != gen:
                self.generations[name] = gen
                cache = self.caches.get(name)
                if cache is not None:
                    cache.clear()
        # Adopt the primary autopilot's shed-on-burn set: the whole
        # router tier sheds together (within one peer_sync_interval_s).
        if "burn_shed" in data:
            self.burn_shed = {str(n) for n in (data["burn_shed"] or [])
                              if str(n) in self.handles}

    def peer_state(self) -> dict:
        """The /peer/state body a peer syncs from (primary's authority)."""
        sup = self.supervisor
        workers = [{"wid": w.wid, "host": sup.host_of(w),
                    "url": w.base_url, "healthy": w.healthy}
                   for w in sup.live_workers()]
        if self.ring is not None:
            ring = [{"router": rid, "peer_url": url}
                    for rid, url in sorted(self.ring.members.items())]
        else:
            ring = [{"router": self.router_id, "peer_url": self.peer_url}]
        return {"ring": ring, "workers": workers,
                "generations": dict(self.generations),
                "draining": self.draining,
                "burn_shed": sorted(self.burn_shed)}

    # -- autopilot (ISSUE 16) -------------------------------------------------
    def _fleet_saturated(self) -> bool:
        """The tenant ledger's fair-share gate: is the fleet queueing?
        More in-flight relays than healthy workers means every worker has
        work and new arrivals wait — the regime where a tenant over its
        weighted share must yield to its neighbors."""
        healthy = len(self.supervisor.healthy_workers())
        return healthy == 0 or self._inflight >= healthy

    def _collect_signals(self) -> Signals:
        """One reconcile tick's input (primary only): per-domain queue
        pressure from the supervisor, per-model burn state from the SLO
        engine, the shed set the controller itself maintains."""
        domains = []
        scale_state = getattr(self.supervisor, "scale_state", None)
        if scale_state is not None:
            for row in scale_state():
                denom = max(1, min(row["active"], row["healthy"]))
                domains.append(DomainSignal(
                    hid=row["host"], up=row["up"], active=row["active"],
                    max_slots=row["max_slots"], healthy=row["healthy"],
                    pressure=(row["inflight"] / denom if row["up"]
                              else 0.0)))
        models = [
            ModelSignal(
                name=name,
                burn_state=(self.slo.state_of(name)
                            if self.slo is not None else "ok"),
                shed_engaged=name in self.burn_shed)
            for name in self.handles]
        return Signals(now=time.monotonic(), domains=domains, models=models)

    async def _actuate(self, action: Action) -> str:
        """Turn one controller decision into the SAME operation an
        operator's admin verb performs. Raising is fine — the loop audits
        the failure as the action's outcome."""
        kind, target = action.kind, action.target
        if kind in ("scale_up", "scale_down"):
            hid = int(target.split(":", 1)[1])
            sup = self.supervisor
            if not hasattr(sup, "scale_domain"):
                return "error: no host domains to scale ([router] hosts = 0)"
            delta = 1 if kind == "scale_up" else -1
            out = sup.scale_domain(hid, sup.active_slots(hid) + delta)
            action.signals["active_after"] = out["active"]
            return "ok"
        if kind == "shed_on":
            self.burn_shed.add(target)
            return "ok"
        if kind == "shed_off":
            self.burn_shed.discard(target)
            return "ok"
        if kind in ("warm", "demote"):
            workers = self.live_workers()
            if not workers:
                return "error: no live worker"
            results = await asyncio.gather(
                *(self._admin_call(w, "POST",
                                   f"/admin/models/{target}:{kind}")
                  for w in workers))
            bad = [f"worker{wid}:{status}" for wid, status, _ in results
                   if status != 200]
            return "ok" if not bad else "error: " + ", ".join(bad)
        return f"error: unknown action kind {kind!r}"

    def begin_drain(self) -> None:
        self.draining = True

    async def drain(self) -> bool:
        """SIGTERM step 1+2: stop the revival machinery (same discipline as
        the single-process fix — the watchdog must not respawn a worker
        this drain is about to SIGTERM), stop admitting, then wait for
        every in-flight relay to resolve within the budget."""
        t0 = time.perf_counter()
        await self.watchdog.stop()
        if self.autopilot is not None:
            # The controller must not fight the drain (scaling a domain
            # this shutdown is about to SIGTERM) — same discipline as
            # stopping the watchdog's respawns above.
            await self.autopilot.stop()
        if self.sampler is not None:
            await asyncio.get_running_loop().run_in_executor(
                None, self.sampler.stop)
        self.begin_drain()
        # Streams get a bounded budget of their own: after stream_drain_s
        # every forward loop ends its stream with a "drain" error terminal
        # (well-formed, never a silent truncation), so the inflight wait
        # below converges even with long generations mid-flight.
        self._stream_kill_at = time.monotonic() + self.rcfg.stream_drain_s
        deadline = time.monotonic() + self.cfg.drain_timeout_s
        while self._inflight > 0 and time.monotonic() < deadline:
            await asyncio.sleep(0.02)
        drained = self._inflight == 0
        if self.audit is not None:
            self.audit.record(
                "drain", "server", "ok" if drained else "budget_expired",
                duration_ms=(time.perf_counter() - t0) * 1e3,
                router_id=self.router_id,
                drain_timeout_s=self.cfg.drain_timeout_s)
        return drained

    async def stop(self) -> None:
        await self.watchdog.stop()
        if self.autopilot is not None:
            await self.autopilot.stop()
        if self.sampler is not None:
            await asyncio.get_running_loop().run_in_executor(
                None, self.sampler.stop)
        if self.topo is not None:
            await self.topo.stop()
        if self.peer_sup is not None:
            # Peer routers first: they drain their own in-flight relays on
            # SIGTERM, and must do so while workers still answer.
            await self.peer_sup.stop()
        if self.is_primary:
            # Workers drain their accepted batches on SIGTERM; with the
            # router already drained there is nothing in flight to lose.
            await self.supervisor.stop(drain=True)
        if self._peer_runner is not None:
            await self._peer_runner.cleanup()
            self._peer_runner = None
        if self._session is not None:
            await self._session.close()
            self._session = None
        if self.events is not None:
            self.events.close()  # flush/close the JSONL sink fd

    # -- shed hints ----------------------------------------------------------
    def no_worker_retry_after(self) -> int:
        return max(1, math.ceil(self.supervisor.respawn_eta_s()))

    def shed_retry_after(self) -> int:
        return max(1, math.ceil(self.cfg.shed_retry_after_s))

    # -- relay ---------------------------------------------------------------
    async def _attempt(self, w: WorkerHandle, name: str, verb: str,
                       body: bytes, ctype: str, deadline_at: float,
                       priority: str | None = None,
                       ctx: "TraceContext | None" = None,
                       stream: bool = False,
                       committed: "list[_StreamAnswer] | None" = None,
                       ) -> "_Answer | _StreamAnswer":
        """One complete request/response against one worker. The body is
        fully read before returning, so a relayed response is never torn:
        a worker dying mid-body surfaces as a transport error (and a
        retry), not a truncated 200. ``priority`` relays the client's
        X-Priority so the worker's fleet scheduler arbitrates with the
        class the client asked for (header -> worker -> batcher).

        With ``stream`` the client's ``?stream=true`` rides the forward,
        and a worker answering with the stream header commits this attempt
        at the HEADERS: the open response is handed up as a _StreamAnswer
        (body unread — the forward loop relays it), which keeps the
        worker's inflight count until the stream closes. Pre-commit
        failures (connect refused, plain-status answers: a fast 504, a
        429, a shed) still carry no body bytes, so the caller's retry and
        hedge machinery stays legal for them.

        Trace propagation (ISSUE 12): the request's trace id crosses as
        ``X-Trace-Id`` and this attempt's pre-allocated span id as
        ``X-Parent-Span``, so the worker's root span parents under THIS
        attempt — hedged/retried attempts each appear as sibling attempt
        spans under one trace, each with its own worker subtree."""
        remaining = deadline_at - time.perf_counter()
        timeout = aiohttp.ClientTimeout(
            total=max(0.001, remaining + _DEADLINE_GRACE_S),
            connect=self.rcfg.connect_timeout_ms / 1e3)
        headers = {"X-Timeout-Ms": f"{max(1.0, remaining * 1e3):.0f}"}
        span_id = None
        if ctx is not None:
            span_id = ctx.new_span_id()
            headers["X-Trace-Id"] = ctx.trace_id
            headers["X-Parent-Span"] = span_id
        if priority:
            headers["X-Priority"] = priority
        if ctype:
            headers["Content-Type"] = ctype
        self.supervisor.track_inflight(w, +1)
        w0 = time.time()
        outcome: "int | str" = "transport_error"
        handed_off = False
        try:
            r = await self._session.post(
                f"{w.base_url}/v1/models/{name}:{verb}", data=body,
                params={"stream": "true"} if stream else None,
                headers=headers, timeout=timeout)
            try:
                if r.headers.get(_STREAM_HEADER) == "1":
                    outcome = r.status
                    handed_off = True
                    sa = _StreamAnswer(
                        r.status, r.content_type or "text/event-stream",
                        r, w, self)
                    if committed is not None:
                        # Registered BEFORE this attempt can lose a race:
                        # _relay's finally closes losers from this list
                        # without touching task results.
                        committed.append(sa)
                    return sa
                raw = await r.read()
                outcome = r.status
                return _Answer(r.status, r.content_type or "application/json",
                               raw, r.headers.get("Retry-After"))
            finally:
                if not handed_off:
                    r.release()
        finally:
            if not handed_off:
                self.supervisor.track_inflight(w, -1)
            if ctx is not None:
                ctx.span("attempt", w0, time.time(), span_id=span_id,
                         tid=name, worker=w.wid, status=outcome,
                         **({"streamed": True} if handed_off else {}))

    async def _relay(self, name: str, verb: str, body: bytes, ctype: str,
                     deadline_at: float,
                     priority: str | None = None,
                     ctx: "TraceContext | None" = None,
                     stream: bool = False) -> "_Answer | _StreamAnswer":
        """Dispatch to the least-loaded healthy worker with retry + hedging
        under the absolute deadline. Returns the first definitive answer;
        raises NoHealthyWorker / RelayDeadline / UpstreamFailed.

        A _StreamAnswer is definitive the instant it exists (the
        first-byte latch): the loop returns it untouched, and the cleanup
        below closes any LOSING stream commitments (a hedge that also
        committed) — closing aborts the loser's upstream connection, which
        the worker treats as a disconnect and reclaims the slot."""
        h = self.handles[name]
        tasks: dict[asyncio.Task, WorkerHandle] = {}
        tried: set[int] = set()
        retries_left = self.rcfg.retry_max
        hedges_left = 1 if self.rcfg.hedge_ms > 0 else 0
        last_503: _Answer | None = None
        last_exc: Exception | None = None
        committed: list[_StreamAnswer] = []
        winner: _StreamAnswer | None = None
        loop = asyncio.get_running_loop()

        def remaining() -> float:
            return deadline_at - time.perf_counter()

        def launch(hedge: bool = False) -> bool:
            exclude_hosts: set[int] = set()
            if hedge:
                # A hedge exists to cover a wedged/dying FAILURE DOMAIN:
                # placing it beside its primary would make one host death
                # kill both copies, so the in-flight attempts' hosts are
                # hard-excluded (no fallback) — if every other host is
                # busy or down, we simply don't hedge.
                for w2 in tasks.values():
                    hid = self.supervisor.host_of(w2)
                    if hid is not None:
                        exclude_hosts.add(hid)
            w = self.supervisor.pick(exclude=tried,
                                     exclude_hosts=exclude_hosts)
            if w is None and tried and not hedge:
                # Every healthy worker was already tried: allow a
                # re-dispatch (the failure may have been transient and the
                # fleet may be down to one survivor).
                w = self.supervisor.pick()
            if w is None:
                return False
            tried.add(w.wid)
            t = loop.create_task(
                self._attempt(w, name, verb, body, ctype, deadline_at,
                              priority, ctx, stream, committed))
            tasks[t] = w
            return True

        def can_hedge() -> bool:
            return (hedges_left > 0 and len(tasks) == 1
                    and len(self.supervisor.healthy_workers()) > 1)

        try:
            if not launch():
                raise NoHealthyWorker(self.supervisor.respawn_eta_s())
            while True:
                rem = remaining()
                if rem <= -_DEADLINE_GRACE_S:
                    raise RelayDeadline()
                wait_s = rem + _DEADLINE_GRACE_S
                if can_hedge():
                    wait_s = min(wait_s, self.rcfg.hedge_ms / 1e3)
                done, _ = await asyncio.wait(
                    set(tasks), timeout=max(0.0, wait_s),
                    return_when=asyncio.FIRST_COMPLETED)
                if not done:
                    if can_hedge() and remaining() > 0:
                        # Primary silent past hedge_ms: race a duplicate on
                        # another worker — never on the primary's host (a
                        # hedge that shares its primary's failure domain
                        # covers nothing). Safe for idempotent inference;
                        # first definitive answer wins below.
                        if launch(hedge=True):
                            hedges_left -= 1
                            h.hedges.inc()
                        else:
                            hedges_left = 0
                        continue
                    if remaining() <= -_DEADLINE_GRACE_S:
                        raise RelayDeadline()
                    continue
                for t in done:
                    w_done = tasks.pop(t)
                    if t.cancelled():
                        continue
                    exc = t.exception()
                    if exc is None:
                        ans = await t  # already done: no suspension
                        self.supervisor.note_success(w_done)
                        if ans.status != 503:
                            # Definitive: the worker admitted and answered
                            # (200, 4xx, 500, 504). NEVER re-dispatched —
                            # a 500 already executed; re-running it would
                            # double-execute.
                            if isinstance(ans, _StreamAnswer):
                                winner = ans
                            return ans
                        # 503 = not admitted (worker draining / its own
                        # breaker): the work never ran, so another worker
                        # may take it.
                        last_503 = ans
                    elif isinstance(exc, (aiohttp.ClientError,
                                          asyncio.TimeoutError, OSError)):
                        if isinstance(exc, asyncio.TimeoutError) \
                                and remaining() <= 0:
                            raise RelayDeadline() from exc
                        if isinstance(exc, (aiohttp.ClientConnectionError,
                                            ConnectionError)):
                            # Refused/reset — the "this machine just died"
                            # signal. Feeds the host breaker so a whole
                            # dead host is routed around in milliseconds,
                            # not after a health-probe cycle.
                            self.supervisor.note_transport_failure(w_done)
                        last_exc = exc
                    else:
                        raise exc  # programming error — surface it
                    if remaining() > 0 and retries_left > 0 and launch():
                        retries_left -= 1
                        h.retries.inc()
                if not tasks:
                    if last_503 is not None:
                        return last_503
                    raise UpstreamFailed() from last_exc
        finally:
            for t in tasks:
                if not t.done():
                    t.cancel()
            # Any attempt that committed a stream but did not win — a
            # hedge completing in the same wait() round as the winner, or
            # outstanding when an error raised — must not leak its open
            # upstream response (or the worker's inflight count). Losing
            # streams registered themselves in `committed` at the headers.
            for sa in committed:
                if sa is not winner:
                    sa.close()

    async def relay_cacheable(self, name: str, verb: str, body: bytes,
                              ctype: str, deadline_at: float,
                              priority: str | None = None,
                              ctx: "TraceContext | None" = None) -> tuple:
        """Cache-value form of _relay: returns ``(content_type, body)`` for
        a 200 (what the single-flight leader populates), raises
        _RelayedError for any other definitive answer (fans out to
        coalesced waiters, populates nothing)."""
        ans = await self._relay(name, verb, body, ctype, deadline_at,
                                priority, ctx)
        if ans.status == 200:
            return (ans.content_type, ans.body)
        raise _RelayedError(ans)

    def _count_stream_termination(self, name: str, reason: str) -> None:
        """Tick router_stream_terminated_total{model=,reason=}. Created
        on demand per reason — Metrics.counter dedups by full name, so
        the handle is stable after the first tick. Emission is guarded
        against the closed vocabulary (TPS404): an off-list reason would
        fragment the metric and dodge the docs/tests contract."""
        if reason not in ROUTER_STREAM_REASONS:
            raise ValueError(f"unknown stream-termination reason {reason!r} "
                             f"(add it to obs.ROUTER_STREAM_REASONS)")
        self.metrics.counter(
            "router_stream_terminated_total"
            f"{{model={name},reason={reason}}}").inc()

    def note_shed_reason(self, name: str, ans: _Answer) -> None:
        """Remember the machine-readable shed reason a worker answered
        (503/504 JSON with a `reason` key — the fleet scheduler's sheds),
        so this router's own breaker 503s can carry the live cause."""
        if ans.status not in (503, 504) or not ans.body:
            return
        try:
            reason = json.loads(ans.body).get("reason")
        except ValueError:
            return
        if isinstance(reason, str):
            self.last_shed_reason[name] = reason

    # -- admin fan-out -------------------------------------------------------
    def live_workers(self) -> list[WorkerHandle]:
        """Every worker with a live process — admin fan-outs must reach
        unhealthy-but-alive workers too, or the fleet's versions diverge."""
        return self.supervisor.live_workers()

    def _per_host_outcomes(self, per_worker: dict) -> dict | None:
        """Group per-worker admin outcomes by failure domain (host mode
        only): the operator-facing view of a partial fan-out."""
        if self.rcfg.hosts <= 0:
            return None
        out: dict[str, dict] = {}
        per = self.rcfg.workers
        for wid, row in per_worker.items():
            out.setdefault(host_name(int(wid) // per), {})[wid] = row
        return out

    async def _admin_call(self, w: WorkerHandle, method: str,
                          path: str) -> tuple[int, int, dict]:
        try:
            async with self._session.request(
                    method, f"{w.base_url}{path}",
                    timeout=aiohttp.ClientTimeout(total=120.0)) as r:
                try:
                    body = await r.json()
                except Exception:  # noqa: BLE001 — non-JSON admin answer
                    body = {"error": (await r.text())[:512]}
                return w.wid, r.status, body
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — worker died mid-admin
            return w.wid, 0, {"error": f"{type(e).__name__}: {e}"}

    def _audit_fanout(self, verb: str, name: str, status: int, body: dict,
                      t0: float) -> None:
        """Fold one admin fan-out into the audit trail (ISSUE 15): verb,
        outcome, duration, the post-action cache generation, and the
        per-host (or per-worker) outcome map — the operator-facing answer
        to "what did that reload actually touch"."""
        if self.audit is None:
            return
        outcome = ("ok" if status == 200
                   else "rejected" if status in (409, 503)
                   else "error")
        fields: dict = {"status": status,
                        "generation": self.generations.get(name)}
        if "version" in body:
            fields["version"] = body["version"]
        if body.get("down"):
            fields["down"] = body["down"]
        per_host = body.get("per_host")
        if per_host is not None:
            # Per-domain rollup, not the full per-worker bodies: the audit
            # record must stay small enough to keep 256 of.
            fields["per_host"] = {
                host: {wid: row.get("status") for wid, row in rows.items()}
                for host, rows in per_host.items()}
        elif body.get("workers"):
            fields["per_worker"] = {
                str(wid): row.get("status")
                for wid, row in body["workers"].items()}
        if body.get("rolled_back_workers"):
            fields["rolled_back_workers"] = list(
                body["rolled_back_workers"])
        self.audit.record(verb, name, outcome,
                          duration_ms=(time.perf_counter() - t0) * 1e3,
                          **fields)

    async def fanout_reload(self, name: str) -> tuple[int, dict]:
        """Atomic fleet reload: POST ``:reload`` to every live worker; if
        any worker fails its gates, roll the succeeded ones back so the
        fleet never serves mixed versions. On success the router cache
        generation bumps, atomically invalidating every older cached
        answer (the cross-process analog of PR 5's version binding).
        Every outcome — refusal included — lands in the audit trail."""
        t0 = time.perf_counter()
        status, body = await self._fanout_reload(name)
        self._audit_fanout("reload", name, status, body, t0)
        return status, body

    async def _fanout_reload(self, name: str) -> tuple[int, dict]:
        workers = self.live_workers()
        if not workers:
            return 503, {"error": "no live worker to reload",
                         "workers": {}}
        # Degraded-fleet gate (ISSUE 13 satellite): a dead/respawning
        # failure domain — a whole host, or a worker its agent is still
        # re-booting — must be a FAST partial-failure answer, not a hang
        # and not a divergent fleet. The missing domain respawns from the
        # BOOT config, so publishing to the survivors would leave the fleet
        # on two versions the moment it comes back. Refuse up front with
        # the per-domain picture; nobody is touched, one version stands.
        down = self.supervisor.down_domains()
        if down:
            body = {"error": f"fleet degraded ({', '.join(down)} down/"
                             "respawning); reload refused — a respawning "
                             "domain boots the original config and would "
                             "diverge from the new version",
                    "down": down, "workers": {}}
            per_host = self._per_host_outcomes(
                {w.wid: {"status": "skipped"} for w in workers})
            if per_host is not None:
                body["per_host"] = per_host
            return 409, body
        results = await asyncio.gather(
            *(self._admin_call(w, "POST", f"/admin/models/{name}:reload")
              for w in workers))
        per_worker = {wid: {"status": status, **body}
                      for wid, status, body in results}
        if all(status == 200 for _, status, _ in results):
            self.generations[name] = self.generations.get(name, 1) + 1
            cache = self.caches.get(name)
            if cache is not None:
                cache.clear()
            await self._broadcast_generation(name)
            versions = {body.get("version") for _, _, body in results}
            out = {"workers": per_worker,
                   "version": results[0][2].get("version"),
                   "fleet_consistent": len(versions) == 1}
            per_host = self._per_host_outcomes(per_worker)
            if per_host is not None:
                out["per_host"] = per_host
            return 200, out
        # Partial failure: restore the workers that DID publish, so the
        # fleet stays on one version (all-or-nothing).
        succeeded = [w for w, (_, status, _) in zip(workers, results)
                     if status == 200]
        rolled_back = {}
        if succeeded:
            rb = await asyncio.gather(
                *(self._admin_call(w, "POST",
                                   f"/admin/models/{name}:rollback")
                  for w in succeeded))
            rolled_back = {wid: status for wid, status, _ in rb}
        # A worker that published-then-rolled-back on its own (post-publish
        # canary) means bad weights briefly served: 500 so operators page;
        # a clean pre-publish rejection everywhere is a 409 conflict.
        any_rb = any(body.get("rolled_back") for _, _, body in results)
        status = 500 if (any_rb or succeeded) else 409
        out = {"error": "reload rejected by at least one worker; "
                        "fleet kept on one version",
               "workers": per_worker,
               "rolled_back_workers": rolled_back}
        per_host = self._per_host_outcomes(per_worker)
        if per_host is not None:
            out["per_host"] = per_host
        return status, out

    async def _broadcast_generation(self, name: str) -> None:
        """Push the bumped cache generation to every live peer router
        (best-effort: the poll sync is the backstop, so a lost push costs
        at most one peer_sync_interval_s of stale shard)."""
        if self.peer_sup is None:
            return
        gen = self.generations.get(name, 1)

        async def _push(url: str) -> None:
            try:
                async with self._session.post(
                        f"{url}/peer/invalidate",
                        json={"model": name, "generation": gen},
                        timeout=aiohttp.ClientTimeout(total=2.0)) as r:
                    await r.read()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — poll sync is the backstop
                pass

        await asyncio.gather(
            *(_push(url) for url in self.peer_sup.members().values()))

    async def fanout_simple(self, name: str, op: str) -> tuple[int, dict]:
        """Best-effort fan-out for ``:rollback`` (every live worker must
        restore the same retained version) and ``/versions``. Rollbacks
        are audited; version reads are not (reads mutate nothing)."""
        t0 = time.perf_counter()
        status, body = await self._fanout_simple(name, op)
        if op == "rollback":
            self._audit_fanout("rollback", name, status, body, t0)
        return status, body

    async def _fanout_simple(self, name: str, op: str) -> tuple[int, dict]:
        workers = self.live_workers()
        if not workers:
            return 503, {"error": "no live worker", "workers": {}}
        if op == "rollback":
            results = await asyncio.gather(
                *(self._admin_call(w, "POST",
                                   f"/admin/models/{name}:rollback")
                  for w in workers))
        else:
            results = await asyncio.gather(
                *(self._admin_call(w, "GET",
                                   f"/admin/models/{name}/versions")
                  for w in workers))
        per_worker = {wid: {"status": status, **body}
                      for wid, status, body in results}
        ok = all(status == 200 for _, status, _ in results)
        if ok and op == "rollback":
            self.generations[name] = self.generations.get(name, 1) + 1
            cache = self.caches.get(name)
            if cache is not None:
                cache.clear()
            await self._broadcast_generation(name)
        return (200 if ok else 409), {"workers": per_worker}

    # -- fleet scrape (ISSUE 14) ---------------------------------------------
    async def _scrape_one(self, proc: str, url: str) -> tuple[str, str | None]:
        """Scrape one source's /metrics; None = stale (counted, never an
        error up the stack — a dead host is data)."""
        timeout = aiohttp.ClientTimeout(
            total=self.cfg.telemetry.fleet_timeout_ms / 1e3)
        try:
            async with self._session.get(url, timeout=timeout) as r:
                if r.status != 200:
                    self.fleet_scrape_errors.inc()
                    return proc, None
                return proc, await r.text()
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 — stale-marked, never 5xx
            self.fleet_scrape_errors.inc()
            return proc, None

    async def scrape_fleet(self) -> list[tuple[str, str | None]]:
        """Every process's exposition, stale-marked where unreachable:
        this router, every CONFIGURED worker slot (a dead host's workers
        scrape as stale, exactly the degradation the merge must survive),
        and — on the primary — every configured peer router."""
        self.fleet_scrapes.inc()
        jobs: list = []
        sources: list[tuple[str, str | None]] = [
            (f"router{self.router_id}", self.metrics.render_prometheus())]
        for wid in range(self.supervisor.n):
            w = self.supervisor.worker_by_id(wid)
            if w is None:
                sources.append((f"worker{wid}", None))
            else:
                jobs.append(self._scrape_one(f"worker{wid}",
                                             f"{w.base_url}/metrics"))
        if self.is_primary and self.peer_sup is not None:
            members = self.peer_sup.members()
            for rid in range(1, self.rcfg.routers):
                url = members.get(rid)
                if url is None:
                    sources.append((f"router{rid}", None))
                else:
                    jobs.append(self._scrape_one(f"router{rid}",
                                                 f"{url}/peer/metrics"))
        if jobs:
            sources.extend(await asyncio.gather(*jobs))
        return sources

    def fleet_rollup(self, sources: list[tuple[str, str | None]],
                     merged: str) -> dict:
        """The /stats/fleet body: per-source liveness, down failure
        domains, and per-model fleet-summed serving counters with true
        fleet latency quantiles from the bucket-merged histogram."""
        from tpuserve.telemetry.store import quantile_from_counts

        per_model: dict[str, dict] = {
            n: {"requests_total": 0.0, "items_total": 0.0,
                "batches_total": 0.0, "deadline_exceeded_total": 0.0}
            for n in self.handles}
        hist: dict[str, dict[float, float]] = {}
        parsed = parse_exposition(merged)
        for base, labels, value in parsed["samples"]:
            if base == "latency_ms_bucket" and 'phase="total"' in labels:
                for n in per_model:
                    if f'model="{n}"' in labels:
                        le = next((p[3:].strip('"')
                                   for p in labels.split(",")
                                   if p.startswith("le=")), None)
                        if le is not None:
                            b = (float("inf") if le == "+Inf"
                                 else float(le))
                            hist.setdefault(n, {})[b] = value
                continue
            row_key = base if base in ("requests_total", "items_total",
                                       "batches_total",
                                       "deadline_exceeded_total") else None
            if row_key is None:
                continue
            for n, row in per_model.items():
                if f'model="{n}"' in labels:
                    row[row_key] += value
        for n, buckets in hist.items():
            bounds = sorted(b for b in buckets if math.isfinite(b))
            cum = [buckets[b] for b in bounds] + \
                [buckets.get(float("inf"), 0.0)]
            # cumulative -> per-bucket deltas for the quantile math
            deltas = [cum[0]] + [max(0.0, cum[i] - cum[i - 1])
                                 for i in range(1, len(cum))]
            p50 = quantile_from_counts(bounds, deltas, 0.5)
            p99 = quantile_from_counts(bounds, deltas, 0.99)
            per_model[n]["fleet_latency_p50_ms"] = \
                round(p50, 3) if p50 is not None and math.isfinite(p50) \
                else None
            per_model[n]["fleet_latency_p99_ms"] = \
                round(p99, 3) if p99 is not None and math.isfinite(p99) \
                else None
        out = {
            "sources": {proc: ("up" if text is not None else "stale")
                        for proc, text in sources},
            "stale": sorted(p for p, t in sources if t is None),
            "down_domains": self.supervisor.down_domains(),
            "models": per_model,
            "scrapes_total": int(self.fleet_scrapes.value),
            "scrape_errors_total": int(self.fleet_scrape_errors.value),
        }
        return out


# -- handlers ----------------------------------------------------------------

ROUTER_KEY: "web.AppKey[RouterState]" = web.AppKey("tpuserve_router", object)


def _predict_handler(verb: str):
    """One closure per verb: aiohttp's literal ``:predict`` path segments
    don't capture the verb into match_info, and the relay must forward the
    verb the client used."""

    async def handler(request: web.Request) -> web.Response:
        return await handle_predict(request, verb)

    return handler


async def handle_predict(request: web.Request, verb: str) -> web.Response:
    """Router predict entry: mints the request's trace context (adopting a
    client-supplied ``X-Trace-Id`` when well-formed), delegates to the
    relay, then stamps ``X-Trace-Id`` on EVERY response — relayed worker
    answers included — records the router-side root span, and offers the
    trace to the router's flight recorder (ISSUE 12)."""
    state: RouterState = request.app[ROUTER_KEY]
    name = request.match_info["name"]
    ctx = TraceContext.from_headers(request.headers, pid=0)
    wall0 = time.time()
    t0 = time.perf_counter()
    resp = await _predict_relayed(request, state, name, verb, ctx)
    dur_s = time.perf_counter() - t0
    ctx.root_span("request", wall0, wall0 + dur_s, tid=name,
                  status=resp.status)
    if "X-Trace-Id" not in resp.headers:
        resp.headers["X-Trace-Id"] = ctx.trace_id
    # Streams score by first-byte latency + worst stall (stamped by the
    # forward loop), not wall duration — a long generation is not slow.
    score_ms = getattr(resp, "tpuserve_stream_score_ms", None)
    kinds = state.recorder.finish(
        ctx, name, resp.status,
        score_ms if score_ms is not None else dur_s * 1e3)
    if state.events is not None:
        # Trace-correlated flight data (ISSUE 15): the single-process
        # discipline at the front door — errored/shed and retained-slow
        # requests leave an event the stitched /debug/trace interleaves.
        if resp.status >= 400:
            state.events.emit(
                "error" if resp.status >= 500 else "warning", "router",
                "request_error", model=name, trace_id=ctx.trace_id,
                status=resp.status, duration_ms=round(dur_s * 1e3, 3))
        elif "slow" in kinds:
            state.events.emit(
                "info", "router", "slow_request", model=name,
                trace_id=ctx.trace_id, status=resp.status,
                duration_ms=round(dur_s * 1e3, 3))
    return resp


async def _predict_relayed(request: web.Request, state: RouterState,
                           name: str, verb: str,
                           ctx: TraceContext) -> web.Response:
    h = state.handles.get(name)
    if h is None:
        return _err(404, f"unknown model {name!r}", trace=ctx)
    # Shed checks BEFORE the body read, single-process discipline: a
    # draining router, a tripped breaker, or an empty fleet answers in
    # microseconds with a live-state Retry-After.
    if state.draining:
        return _err(503, "router draining; retry against another replica",
                    retry_after=state.shed_retry_after(), trace=ctx)
    # Tenant containment (ISSUE 16): identity, rate, quota, and fair
    # share are judged HERE — before the body read, before any relay —
    # so a hostile tenant's flood is refused in microseconds and never
    # occupies a worker. The resolved tenant (not the key) rides every
    # downstream hop.
    tenant: str | None = None
    if state.tenants is not None:
        tenant = state.tenants.resolve(request.headers.get("X-Api-Key"))
        if tenant is None:
            shed = state.tenants.shed_unknown()
            return _err(shed.status, shed.message, reason=shed.reason,
                        trace=ctx)
        shed = state.tenants.admit(tenant)
        if shed is not None:
            return _err(shed.status, shed.message,
                        retry_after=shed.retry_after, reason=shed.reason,
                        trace=ctx)
    # Shed-on-burn (autopilot actuator): while a model is burning its SLO
    # error budget, batch-priority work sheds at the front door so the
    # remaining capacity serves interactive traffic — the router-tier
    # mirror of the fleet scheduler's burn_shed gate.
    if name in state.burn_shed \
            and request.headers.get("X-Priority") == "batch":
        return _err(503, f"model {name!r} is burning its SLO error "
                         "budget; batch work shed until the alert clears",
                    retry_after=state.shed_retry_after(),
                    reason="burn_shed", trace=ctx)
    breaker = state.breakers[name]
    if not breaker.allow():
        now = time.monotonic()
        probe_at = state._probe_at.get(name, 0.0)
        if now < probe_at:
            breaker.on_shed()
            # The live shed reason the workers last answered (the fleet
            # scheduler's machine-readable cause) rides on the breaker
            # 503, so a front-door shed still says WHY the model refuses.
            return _err(503, f"circuit open for model {name!r}; recovery "
                             "probe in progress",
                        retry_after=max(1, math.ceil(probe_at - now)),
                        reason=state.last_shed_reason.get(name), trace=ctx)
        # This request IS the recovery probe: open -> half_open, let it
        # through; its outcome closes or re-opens the breaker.
        breaker.probe()
        state._probe_at[name] = now + h.mcfg.breaker_retry_after_s
    if not state.supervisor.healthy_workers():
        return _err(503, "no healthy worker; capacity respawning",
                    retry_after=state.no_worker_retry_after(), trace=ctx)
    h.requests.inc()
    t_start = time.perf_counter()

    # Priority rides the wire verbatim (header -> worker -> batcher): the
    # router validates nothing here — the worker's scheduler owns the
    # class vocabulary and 400s junk — and the cache key below NEVER sees
    # it (same bytes must hit the same entry regardless of priority).
    priority = request.headers.get("X-Priority")

    w_read = time.time()
    body = await request.read()
    ctx.span("body_read", w_read, time.time(), tid=name, bytes=len(body))
    ctype = request.content_type or ""
    try:
        timeout_ms = _requested_timeout_ms(request, body, ctype)
        # Same validator as the worker's front door (ISSUE 17): a typo'd
        # ?stream= flag 400s HERE, it never silently serves unary.
        want_stream = _requested_stream(request)
    except ValueError as e:
        return _err(400, str(e), trace=ctx)
    timeout_s = (timeout_ms if timeout_ms is not None
                 else h.mcfg.request_timeout_ms) / 1e3
    deadline_at = t_start + timeout_s

    state._inflight += 1
    try:
        ans = await _dispatch(state, name, verb, body, ctype, deadline_at,
                              priority, ctx, tenant, stream=want_stream)
    except NoHealthyWorker as e:
        breaker.record_failure()
        return _err(503, "no healthy worker; capacity respawning",
                    retry_after=max(1, math.ceil(e.eta_s)), trace=ctx)
    except (RelayDeadline, asyncio.TimeoutError):
        h.timeouts.inc()
        return _err(504,
                    f"request deadline ({timeout_s * 1e3:.0f} ms) exceeded",
                    trace=ctx)
    except UpstreamFailed:
        breaker.record_failure()
        return _err(503, "workers unreachable; retry",
                    retry_after=state.no_worker_retry_after(), trace=ctx)
    finally:
        state._inflight -= 1

    if isinstance(ans, _StreamAnswer):
        # The latch fired: the worker committed a stream. The breaker
        # judged admission; total-duration latency would poison the
        # router_latency_ms SLO (long answers are not slow answers), so
        # streams score first-byte + worst stall inside the forward
        # instead. No await between the decrement above and this
        # re-increment, so drain's inflight poll can never observe the
        # stream missing.
        breaker.record_success()
        state._inflight += 1
        try:
            return await _forward_stream(request, state, name, h, ans, ctx,
                                         tenant, t_start, deadline_at)
        finally:
            state._inflight -= 1

    if ans.status == 200:
        breaker.record_success()
    elif ans.status >= 500:
        breaker.record_failure()
    state.note_shed_reason(name, ans)
    dur_ms = (time.perf_counter() - t_start) * 1e3
    h.latency.observe(dur_ms, trace_id=ctx.trace_id)
    if state.tenants is not None and tenant is not None:
        # Charge the tenant's sliding-window ledger with the wall time the
        # request occupied the fleet (the device-time proxy the quota and
        # fair share enforce) and feed its latency series (the per-tenant
        # SLO burn input).
        state.tenants.record(tenant, dur_ms / 1e3, latency_ms=dur_ms)
    return ans.to_response()


def _stream_error_bytes(content_type: str, reason: str,
                        message: str) -> bytes:
    """A well-formed error terminal in the stream's own wire format — what
    the router appends when the worker no longer can (ISSUE 17: a torn
    stream must end in a terminal event naming its cause, never a silent
    truncation). Binary streams get a KIND_EVENT frame, everything else
    the SSE error event, matching the worker's own terminal encoding."""
    data = {"error": reason, "message": message}
    if content_type == frame.CONTENT_TYPE:
        payload = json.dumps({"type": "error", **data})
        return frame.encode_stream_event(payload.encode("utf-8"))
    return (f"event: error\ndata: {json.dumps(data)}\n\n").encode("utf-8")


async def _forward_stream(request: web.Request, state: RouterState,
                          name: str, h: RouterHandles, ans: _StreamAnswer,
                          ctx: TraceContext, tenant: str | None,
                          t_start: float,
                          deadline_at: float) -> web.StreamResponse:
    """Bidirectional relay of one committed stream (ISSUE 17 tentpole).

    The first-byte latch has fired — _StreamAnswer is definitive — so from
    here every failure ends the CLIENT's stream with a well-formed error
    terminal and never a re-dispatch (replaying a new attempt's tokens
    after bytes reached the client would corrupt its transcript):

    - worker death mid-stream (SIGKILL, crash): the chunked upstream read
      raises -> "upstream_error" terminal + transport-failure note (the
      host breaker routes around the corpse) + breaker failure;
    - a stall past [router] stream_idle_timeout_ms with no bytes (the
      worker's heartbeats normally cover idle generation gaps) ->
      "idle_timeout" or, past the absolute deadline, "deadline_exceeded";
    - router drain past its stream budget -> "drain" terminal;
    - client disconnect: the upstream close IS the worker's signal to
      cancel the slot and fold the capacity back in.
    """
    h.streams.inc()
    w = ans.worker
    resp = web.StreamResponse(status=ans.status)
    resp.content_type = ans.content_type
    resp.headers[_STREAM_HEADER] = "1"
    resp.headers["X-Trace-Id"] = ctx.trace_id
    idle_s = state.rcfg.stream_idle_timeout_ms / 1e3
    first_unit_ms: float | None = None
    last_chunk: float | None = None
    max_gap_ms = 0.0
    reason = "done"
    failure: str | None = None  # != None -> append our own error terminal
    bytes_out = 0
    w0 = time.time()
    try:
        try:
            await resp.prepare(request)
        except (ConnectionResetError, ConnectionError):
            reason = "client_disconnect"
        else:
            it = ans.resp.content.iter_any()
            while True:
                if state._stream_kill_at is not None \
                        and time.monotonic() >= state._stream_kill_at:
                    reason = "drain"
                    failure = "router draining; stream budget spent"
                    break
                wait_s = idle_s if idle_s > 0 else None
                if state._stream_kill_at is not None:
                    till_kill = max(0.0,
                                    state._stream_kill_at - time.monotonic())
                    wait_s = till_kill if wait_s is None \
                        else min(wait_s, till_kill)
                try:
                    chunk = await asyncio.wait_for(it.__anext__(),
                                                   timeout=wait_s)
                except StopAsyncIteration:
                    # Clean upstream EOF: the worker authored the terminal
                    # (done or error) as its last bytes — already relayed.
                    break
                except asyncio.TimeoutError:
                    if state._stream_kill_at is not None \
                            and time.monotonic() >= state._stream_kill_at:
                        continue  # the drain check at the loop top fires
                    if deadline_at - time.perf_counter() <= 0:
                        reason = "deadline_exceeded"
                        failure = "absolute deadline exceeded mid-stream"
                    else:
                        reason = "idle_timeout"
                        failure = (f"no bytes from worker {w.wid} for "
                                   f"{idle_s:g}s")
                    state.supervisor.note_transport_failure(w)
                    state.breakers[name].record_failure()
                    break
                except (aiohttp.ClientError, OSError) as e:
                    reason = "upstream_error"
                    failure = f"worker {w.wid} died mid-stream: {e}"
                    state.supervisor.note_transport_failure(w)
                    state.breakers[name].record_failure()
                    break
                now = time.perf_counter()
                if first_unit_ms is None:
                    first_unit_ms = (now - t_start) * 1e3
                    h.first_unit.observe(first_unit_ms,
                                         trace_id=ctx.trace_id)
                elif last_chunk is not None:
                    max_gap_ms = max(max_gap_ms, (now - last_chunk) * 1e3)
                last_chunk = now
                bytes_out += len(chunk)
                try:
                    await resp.write(chunk)
                except (ConnectionResetError, ConnectionError):
                    reason, failure = "client_disconnect", None
                    break
            if failure is not None:
                try:
                    await resp.write(_stream_error_bytes(
                        ans.content_type, reason, failure))
                except (ConnectionResetError, ConnectionError):
                    pass
    finally:
        # Closing the upstream (body possibly unread) is the worker's
        # disconnect signal: its engine cancels the slot. Also releases
        # the worker's inflight count held since the latch.
        ans.close()
    state._count_stream_termination(name, reason)
    ctx.span("stream_relay", w0, time.time(), tid=name, worker=w.wid,
             reason=reason, bytes=bytes_out,
             first_unit_ms=round(first_unit_ms, 3)
             if first_unit_ms is not None else None,
             max_gap_ms=round(max_gap_ms, 3))
    if state.events is not None and reason != "done":
        state.events.emit(
            "warning", "router", "stream_terminated", model=name,
            trace_id=ctx.trace_id, reason=reason, worker=w.wid,
            bytes=bytes_out)
    # Recorder scoring (handle_predict): a stream's health is its
    # first-byte latency and worst stall, not its total duration.
    resp.tpuserve_stream_score_ms = max(first_unit_ms or 0.0, max_gap_ms)
    if state.tenants is not None and tenant is not None:
        dur_s = time.perf_counter() - t_start
        state.tenants.record(
            tenant, dur_s,
            latency_ms=first_unit_ms if first_unit_ms is not None
            else dur_s * 1e3)
    try:
        await resp.write_eof()
    except (ConnectionResetError, ConnectionError):
        pass
    return resp


async def _dispatch(state: RouterState, name: str, verb: str, body: bytes,
                    ctype: str, deadline_at: float,
                    priority: str | None = None,
                    ctx: "TraceContext | None" = None,
                    tenant: str | None = None,
                    stream: bool = False) -> "_Answer | _StreamAnswer":
    """Cache/single-flight front of the relay (router-owned PR-5 layer),
    sharded across the router tier (ISSUE 13).

    The cache key is content-addressed at the WIRE level — the router has
    no models to decode with — so byte-identical uploads hit, and the
    per-model config generation in every key makes a fleet reload an
    atomic invalidation. Priority deliberately stays OUT of the key: it
    schedules the work, it does not change the answer.

    With N routers, the consistent-hash ring names ONE owner per key: a
    non-owner forwards the whole request to the owner's peer listener so
    the owner's cache + single-flight lead — coalescing and re-upload
    semantics hold across routers. An unreachable owner degrades to the
    local path (counted), never to an error."""
    cache = state.caches.get(name)
    if cache is None or stream:
        # Streams bypass EVERY cache tier — local shard, single-flight
        # coalescing, and the peer-forward hop (ISSUE 17): a stream is a
        # live connection, not a cacheable byte answer, and coalescing a
        # stream under another request's flight would hand one client's
        # tokens to another.
        return await state._relay(name, verb, body, ctype, deadline_at,
                                  priority, ctx, stream=stream)
    key = cache.key_for((verb, ctype, body))
    if state.ring is not None:
        owner = state.ring.owner(key)
        if owner is not None and owner[0] != state.router_id:
            ans = await _peer_forward(state, owner, name, verb, body, ctype,
                                      deadline_at, priority, ctx, tenant)
            if ans is not None:
                return ans
            # Owner unreachable: fall through to the LOCAL cache path —
            # shard locality is lost until the owner respawns, coalescing
            # within this router still works, and the client sees nothing.
    return await _dispatch_local(state, cache, key, name, verb, body, ctype,
                                 deadline_at, priority, ctx, tenant)


async def _peer_forward(state: RouterState, owner: tuple[int, str],
                        name: str, verb: str, body: bytes, ctype: str,
                        deadline_at: float, priority: str | None,
                        ctx: "TraceContext | None",
                        tenant: str | None = None) -> _Answer | None:
    """Forward one request to the owning router's peer listener. Returns
    its complete answer, or None on a transport failure (counted in
    cache_peer_errors_total — the caller degrades to local-only)."""
    h = state.handles[name]
    remaining = deadline_at - time.perf_counter()
    headers = {"X-Timeout-Ms": f"{max(1.0, remaining * 1e3):.0f}"}
    if priority:
        headers["X-Priority"] = priority
    if tenant:
        # The RESOLVED tenant (never the key) crosses the loopback-only
        # peer listener so the owner's shard partitions by the same
        # identity the origin admitted (peers.TENANT_HEADER).
        headers[TENANT_HEADER] = tenant
    if ctype:
        headers["Content-Type"] = ctype
    span_id = None
    if ctx is not None:
        span_id = ctx.new_span_id()
        headers["X-Trace-Id"] = ctx.trace_id
        headers["X-Parent-Span"] = span_id
    timeout = aiohttp.ClientTimeout(
        total=max(0.001, remaining + _DEADLINE_GRACE_S),
        connect=state.rcfg.connect_timeout_ms / 1e3)
    h.peer_hops.inc()
    w0 = time.time()
    outcome: "int | str" = "transport_error"
    try:
        async with state._session.post(
                f"{owner[1]}/peer/models/{name}:{verb}", data=body,
                headers=headers, timeout=timeout) as r:
            raw = await r.read()
            outcome = r.status
            return _Answer(r.status, r.content_type or "application/json",
                           raw, r.headers.get("Retry-After"))
    except asyncio.CancelledError:
        raise
    except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
        h.peer_errors.inc()
        return None
    finally:
        if ctx is not None:
            ctx.span("peer_hop", w0, time.time(), span_id=span_id, tid=name,
                     owner_router=owner[0], status=outcome)


async def _dispatch_local(state: RouterState, cache: ModelCache, key: str,
                          name: str, verb: str, body: bytes, ctype: str,
                          deadline_at: float, priority: str | None = None,
                          ctx: "TraceContext | None" = None,
                          tenant: str | None = None) -> _Answer:
    """This router's own cache shard: hit fast path, else single-flight
    into the worker relay (the pre-ISSUE-13 _dispatch body)."""
    entry = cache.get(key)
    if entry is not None:
        ct, raw = entry.value
        if ctx is not None:
            now = time.time()
            ctx.span("cache_hit", now, now, tid=name)
        return _Answer(200, ct, raw, None)
    loop = asyncio.get_running_loop()
    fut = cache.submit_through(
        key, lambda: loop.create_task(
            state.relay_cacheable(name, verb, body, ctype, deadline_at,
                                  priority, ctx)), ctx=ctx, tenant=tenant)
    # A coalesced waiter still honors ITS deadline: cancelling the waiter
    # never cancels the leader's flight (ModelCache contract).
    remaining = deadline_at - time.perf_counter()
    try:
        ct, raw = await asyncio.wait_for(
            fut, max(0.0, remaining) + _DEADLINE_GRACE_S)
    except _RelayedError as e:
        return e.ans
    return _Answer(200, ct, raw, None)


async def handle_healthz(request: web.Request) -> web.Response:
    """Router health for an external LB fronting N routers (ISSUE 13
    satellite): 503 only when THIS router can serve nothing (draining, or
    zero healthy workers anywhere). Lost hosts, dead peer routers, and
    missing workers all answer 200 "degraded" — degraded capacity is NOT
    downtime, and an LB that pulls a degraded replica turns a capacity
    incident into an availability one (docs/ROBUSTNESS.md)."""
    state: RouterState = request.app[ROUTER_KEY]
    sup = state.supervisor.stats()
    if state.draining:
        return web.json_response(
            {"status": "draining", "router_id": state.router_id,
             "workers": sup}, status=503)
    healthy = sup["healthy"]
    if healthy == 0:
        return web.json_response(
            {"status": "no_workers", "router_id": state.router_id,
             "workers": sup}, status=503,
            headers={"Retry-After": str(state.no_worker_retry_after())})
    degraded = healthy < sup["configured"]
    body: dict = {"router_id": state.router_id}
    if "hosts_configured" in sup:
        body["hosts"] = {"configured": sup["hosts_configured"],
                         "up": sup["hosts_up"]}
        degraded = degraded or sup["hosts_up"] < sup["hosts_configured"]
    if state.ring is not None:
        body["routers"] = {"configured": state.rcfg.routers,
                           "in_ring": len(state.ring.members)}
        if state.is_primary:
            degraded = degraded \
                or len(state.ring.members) < state.rcfg.routers
    body["status"] = "degraded" if degraded else "ok"
    body["workers"] = sup
    return web.json_response(body, status=200)


async def handle_metrics(request: web.Request) -> web.Response:
    """Router /metrics: same OpenMetrics envelope + content negotiation as
    the single-process server (ISSUE 14 satellite)."""
    state: RouterState = request.app[ROUTER_KEY]
    ctype = exposition_content_type(request.headers.get("Accept"))
    return web.Response(
        body=state.metrics.render_prometheus().encode("utf-8"),
        headers={"Content-Type": ctype})


async def handle_router_history(request: web.Request) -> web.Response:
    """GET /stats/history on the router: the router tier's own series
    (router_latency_ms, relay/hedge counters, supervision gauges) from
    its telemetry rings — same query surface as the worker endpoint."""
    state: RouterState = request.app[ROUTER_KEY]
    if state.store is None:
        return _err(409, "[telemetry] is disabled; no history is recorded")
    metric = request.query.get("metric")
    if not metric:
        return web.json_response({"metrics": state.store.metric_names(),
                                  **state.store.stats()})
    try:
        window_s = (float(request.query["window_s"])
                    if "window_s" in request.query else None)
        if window_s is not None and window_s <= 0:
            raise ValueError(window_s)
    except (TypeError, ValueError):
        return _err(400, "window_s must be a positive number")
    names = state.store.match(metric)
    if not names:
        return _err(404, f"no recorded series matches {metric!r} "
                         "(GET /stats/history lists the inventory)")
    series = [state.store.history(n, window_s) for n in names]
    return web.json_response(
        {"series": [s for s in series if s is not None]})


async def handle_router_alerts(request: web.Request) -> web.Response:
    """GET /alerts on the router: burn-rate states over the CLIENT-
    observed latency (router_latency_ms — retries, hedges, and queue time
    included), which is the tier an availability SLO is honestly judged
    at."""
    state: RouterState = request.app[ROUTER_KEY]
    if state.slo is None:
        return _err(409, "[telemetry] is disabled; no SLO evaluation runs")
    return web.json_response(state.slo.alerts())


async def handle_fleet_metrics(request: web.Request) -> web.Response:
    """GET /metrics/fleet — ONE merged exposition for the whole fleet:
    counters summed across every process, gauges labeled ``proc=``,
    histograms merged bucket-wise (exact — bucket bounds are shared).
    Unreachable sources are stale-marked (``fleet_source_up`` 0 + a
    ``# STALE`` comment); a dead host NEVER makes this endpoint 5xx.
    Peer routers proxy to the primary — one process owns the scrape."""
    state: RouterState = request.app[ROUTER_KEY]
    if not state.is_primary:
        return await _proxy_admin_to_primary(state, "GET",
                                             "/peer/fleet/metrics")
    sources = await state.scrape_fleet()
    ctype = exposition_content_type(request.headers.get("Accept"))
    return web.Response(body=merge_expositions(sources).encode("utf-8"),
                        headers={"Content-Type": ctype})


async def handle_fleet_stats(request: web.Request) -> web.Response:
    """GET /stats/fleet — the JSON rollup of the same scrape: per-source
    up/stale, down failure domains, and per-model fleet-summed counters
    with true fleet latency quantiles from the merged buckets."""
    state: RouterState = request.app[ROUTER_KEY]
    if not state.is_primary:
        return await _proxy_admin_to_primary(state, "GET",
                                             "/peer/fleet/stats")
    sources = await state.scrape_fleet()
    merged = merge_expositions(sources)
    return web.json_response(state.fleet_rollup(sources, merged))


async def handle_worker_history(request: web.Request) -> web.Response:
    """GET /workers/{wid}/stats/history — operator passthrough to one
    worker's history endpoint (workers bind loopback), query included."""
    state: RouterState = request.app[ROUTER_KEY]
    try:
        wid = int(request.match_info["wid"])
    except ValueError:
        return _err(400, "worker id must be an integer")
    if not 0 <= wid < state.supervisor.n:
        return _err(404, f"no worker slot {wid}")
    w = state.supervisor.worker_by_id(wid)
    if w is None:
        return _err(503, f"worker {wid} is down (respawning)")
    try:
        async with state._session.get(
                f"{w.base_url}/stats/history",
                params=dict(request.query),
                timeout=aiohttp.ClientTimeout(total=10.0)) as r:
            raw = await r.read()
            return web.Response(body=raw, status=r.status,
                                content_type=r.content_type or "text/plain")
    except asyncio.CancelledError:
        raise
    except Exception as e:  # noqa: BLE001
        return _err(503, f"worker {wid} unreachable: {e}")


async def handle_stats(request: web.Request) -> web.Response:
    state: RouterState = request.app[ROUTER_KEY]
    out = state.metrics.summary()
    out["robustness"] = {
        "draining": state.draining,
        "breakers": {n: br.describe() for n, br in state.breakers.items()},
    }
    if state.burn_shed:
        out["robustness"]["burn_shed"] = sorted(state.burn_shed)
    if witness.enabled():
        out["robustness"]["lock_witness"] = witness.snapshot()
    out["workers"] = state.supervisor.stats()
    out["router"] = {
        "router_id": state.router_id,
        "is_primary": state.is_primary,
        "generations": dict(state.generations),
        "retry_max": state.rcfg.retry_max,
        "hedge_ms": state.rcfg.hedge_ms,
    }
    if state.ring is not None:
        out["router"]["ring"] = {
            "members": {str(rid): url
                        for rid, url in sorted(state.ring.members.items())},
            "size": len(state.ring.members),
        }
    if state.peer_sup is not None:
        out["routers"] = state.peer_sup.stats()
    # Topology block (ISSUE 13 satellite: the multi-machine seam,
    # tpuserve.parallel.distributed, surfaces its counterpart on every
    # WORKER's /stats — the router is device-free, so its topology is the
    # failure-domain layout instead).
    out["topology"] = {
        "router_id": state.router_id,
        "routers_configured": state.rcfg.routers,
        "hosts_configured": state.rcfg.hosts,
        "workers_per_domain": state.rcfg.workers,
    }
    out["trace"] = state.recorder.stats()
    # Event plane (ISSUE 15): ring/audit/postmortem occupancy — the
    # records live at /debug/events, /debug/audit, /debug/postmortems.
    if state.events is not None:
        out["events"] = {
            **state.events.stats(),
            "audit": state.audit.stats(),
            "postmortems": state.postmortems.stats(),
        }
    # Telemetry plane (ISSUE 14): sampler heartbeat + the router-tier SLO
    # view (burn over client-observed latency). History at /stats/history,
    # the fleet merge at /metrics/fleet + /stats/fleet.
    if state.store is not None:
        out["telemetry"] = {
            **state.store.stats(),
            "sample_interval_s": state.cfg.telemetry.sample_interval_s,
        }
    if state.slo is not None:
        alerts = state.slo.alerts()
        if alerts["models"]:
            out["slo"] = alerts
    if state.caches:
        out["cache"] = {n: c.stats() for n, c in state.caches.items()}
    # Tenant containment + controller (ISSUE 16): live window usage and
    # the reconcile loop's counters. Full decision history is one hop
    # away at /debug/autopilot, the per-tenant view at /tenants.
    if state.tenants is not None:
        out["tenants"] = state.tenants.usage()
    if state.autopilot is not None:
        ap = state.autopilot.describe()
        ap.pop("decisions", None)  # keep /stats bounded
        out["autopilot"] = ap
    return web.json_response(out)


async def handle_slow(request: web.Request) -> web.Response:
    """GET /debug/slow — the ROUTER's flight recorder: the front-door view
    (root + per-attempt spans) of the slowest-N requests per model plus
    every errored/shed request. Pull the stitched cross-process tree for
    any entry via /debug/trace?trace_id=."""
    state: RouterState = request.app[ROUTER_KEY]
    return web.json_response(state.recorder.dump(
        model=request.query.get("model")))


async def handle_trace(request: web.Request) -> web.Response:
    """GET /debug/trace?trace_id= — one request's STITCHED span tree.

    The router's own record (pid 0: request + attempt spans) is merged
    with every live worker's record for the same trace id (their spans
    carry pid = worker id + 1), rendered as one Chrome trace — the
    router→worker hop reads as a gap between the attempt span on lane 0
    and the worker's request span on its lane. Matching structured events
    from the router's ring AND every worker's (each worker's record
    carries its own, ISSUE 15) interleave as instant events, so the one
    artifact shows the spans and what each process was saying.
    ``&format=record`` returns the merged raw spans + events instead
    (what a higher tier would stitch)."""
    state: RouterState = request.app[ROUTER_KEY]
    trace_id = request.query.get("trace_id")
    if not trace_id:
        return _err(400, "the router trace endpoint needs ?trace_id=... "
                         "(find recorded ids at /debug/slow)")
    spans: list[dict] = []
    events: list[dict] = (state.events.query(trace_id=trace_id, limit=200)
                          if state.events is not None else [])
    meta: dict = {"trace_id": trace_id, "sources": []}
    rec = state.recorder.get(trace_id)
    if rec is not None:
        spans.extend(rec["spans"])
        meta["sources"].append("router")
        meta["model"] = rec["model"]
        meta["status"] = rec["status"]
        meta["duration_ms"] = rec["duration_ms"]
    workers = state.live_workers()
    if workers:
        results = await asyncio.gather(
            *(state._admin_call(
                w, "GET", f"/debug/trace?trace_id={trace_id}&format=record")
              for w in workers))
        for wid, status, body in results:
            if status == 200 and isinstance(body.get("spans"), list):
                spans.extend(body["spans"])
                if isinstance(body.get("events"), list):
                    events.extend(body["events"])
                meta["sources"].append(f"worker{wid}")
    if not spans:
        return _err(404, f"trace {trace_id!r} is not recorded on the "
                         "router or any live worker")
    if request.query.get("format") == "record":
        meta["spans"] = spans
        meta["events"] = events
        return web.json_response(meta)
    return web.Response(text=spans_to_chrome(spans, events=events),
                        content_type="application/json")


async def handle_events(request: web.Request) -> web.Response:
    """GET /debug/events — the ROUTER's structured event ring (supervision
    events, relay errors, audit mirror). Worker rings are one hop away at
    /workers/{wid}/debug/events. Same query surface + junk-param 400s as
    the worker endpoint."""
    state: RouterState = request.app[ROUTER_KEY]
    if state.events is None:
        return _err(409, "[events] is disabled; no events are recorded")
    try:
        q = events_mod.parse_events_query(request.query)
    except ValueError as e:
        return _err(400, str(e))
    return web.json_response({"events": state.events.query(**q),
                              **state.events.stats()})


async def handle_postmortems(request: web.Request) -> web.Response:
    """GET /debug/postmortems — the fleet-wide crash-forensics ledger: one
    record per reaped worker / host agent / peer router, each carrying
    exit code + killing signal, the dead process's stderr tail, and its
    last black-box snapshot (docs/OBSERVABILITY.md "The third pillar").
    The primary's supervisors reap everything, so the primary's ledger is
    authoritative; peers proxy to it."""
    state: RouterState = request.app[ROUTER_KEY]
    if state.postmortems is None:
        return _err(409, "[events] is disabled; no postmortems are kept")
    if not state.is_primary:
        return await _proxy_admin_to_primary(state, "GET",
                                             "/peer/debug/postmortems")
    return web.json_response({"postmortems": state.postmortems.dump(),
                              **state.postmortems.stats()})


async def handle_audit(request: web.Request) -> web.Response:
    """GET /debug/audit — the fleet admin audit trail. Admin verbs are
    serialized through the PRIMARY (the PR-13 reload contract), so the
    primary's trail is the fleet's; peers proxy to it."""
    state: RouterState = request.app[ROUTER_KEY]
    if state.audit is None:
        return _err(409, "[events] is disabled; no audit trail is kept")
    if not state.is_primary:
        return await _proxy_admin_to_primary(state, "GET",
                                             "/peer/debug/audit")
    return web.json_response({"audit": state.audit.dump(),
                              **state.audit.stats()})


async def handle_worker_events(request: web.Request) -> web.Response:
    """GET /workers/{wid}/debug/events — operator passthrough to one
    worker's event ring (workers bind loopback), query included."""
    state: RouterState = request.app[ROUTER_KEY]
    try:
        wid = int(request.match_info["wid"])
    except ValueError:
        return _err(400, "worker id must be an integer")
    if not 0 <= wid < state.supervisor.n:
        return _err(404, f"no worker slot {wid}")
    w = state.supervisor.worker_by_id(wid)
    if w is None:
        return _err(503, f"worker {wid} is down (respawning)")
    try:
        async with state._session.get(
                f"{w.base_url}/debug/events",
                params=dict(request.query),
                timeout=aiohttp.ClientTimeout(total=10.0)) as r:
            raw = await r.read()
            return web.Response(body=raw, status=r.status,
                                content_type=r.content_type or "text/plain")
    except asyncio.CancelledError:
        raise
    except Exception as e:  # noqa: BLE001
        return _err(503, f"worker {wid} unreachable: {e}")


async def handle_models(request: web.Request) -> web.Response:
    """Proxy the model inventory from the first healthy worker (every
    worker serves an identical config)."""
    state: RouterState = request.app[ROUTER_KEY]
    w = state.supervisor.pick()
    if w is None:
        return _err(503, "no healthy worker",
                    retry_after=state.no_worker_retry_after())
    _, status, body = await state._admin_call(w, "GET", "/v1/models")
    return web.json_response(body, status=status if status else 503)


async def handle_worker_proxy(request: web.Request) -> web.Response:
    """GET /workers/{wid}/{metrics|stats|healthz} — operator passthrough to
    one worker's own introspection endpoints (workers bind loopback and are
    otherwise unreachable from outside the host)."""
    state: RouterState = request.app[ROUTER_KEY]
    try:
        wid = int(request.match_info["wid"])
    except ValueError:
        return _err(400, "worker id must be an integer")
    page = request.match_info["page"]
    if page not in ("metrics", "stats", "healthz"):
        return _err(404, f"unknown worker page {page!r}")
    if not 0 <= wid < state.supervisor.n:
        return _err(404, f"no worker slot {wid}")
    w = state.supervisor.worker_by_id(wid)
    if w is None:
        return _err(503, f"worker {wid} is down (respawning)")
    try:
        async with state._session.get(
                f"{w.base_url}/{page}",
                timeout=aiohttp.ClientTimeout(total=10.0)) as r:
            raw = await r.read()
            return web.Response(body=raw, status=r.status,
                                content_type=r.content_type or "text/plain")
    except asyncio.CancelledError:
        raise
    except Exception as e:  # noqa: BLE001
        return _err(503, f"worker {wid} unreachable: {e}")


async def _proxy_admin_to_primary(state: RouterState, method: str,
                                  path: str) -> web.Response:
    """A peer router never fans admin out itself — the PRIMARY owns the
    generation counter and the all-or-nothing reload contract, so one
    router must serialize fleet transitions. Proxy over its peer listener
    (the public port is SO_REUSEPORT-shared and cannot address the primary
    specifically)."""
    if state.topo is None:
        return _err(503, "no primary to proxy the admin fan-out to")
    try:
        async with state._session.request(
                method, f"{state.topo.url}{path}",
                timeout=aiohttp.ClientTimeout(total=180.0)) as r:
            raw = await r.read()
            return web.Response(
                body=raw, status=r.status,
                content_type=r.content_type or "application/json")
    except asyncio.CancelledError:
        raise
    except Exception as e:  # noqa: BLE001 — primary down mid-admin
        return _err(503, f"primary router unreachable for admin fan-out: "
                         f"{type(e).__name__}: {e}")


async def handle_reload(request: web.Request) -> web.Response:
    state: RouterState = request.app[ROUTER_KEY]
    name = request.match_info["name"]
    if name not in state.handles:
        return _err(404, f"unknown model {name!r}")
    if not state.is_primary:
        return await _proxy_admin_to_primary(
            state, "POST", f"/peer/admin/{name}:reload")
    status, body = await state.fanout_reload(name)
    return web.json_response(body, status=status)


async def handle_rollback(request: web.Request) -> web.Response:
    state: RouterState = request.app[ROUTER_KEY]
    name = request.match_info["name"]
    if name not in state.handles:
        return _err(404, f"unknown model {name!r}")
    if not state.is_primary:
        return await _proxy_admin_to_primary(
            state, "POST", f"/peer/admin/{name}:rollback")
    status, body = await state.fanout_simple(name, "rollback")
    return web.json_response(body, status=status)


async def handle_versions(request: web.Request) -> web.Response:
    state: RouterState = request.app[ROUTER_KEY]
    name = request.match_info["name"]
    if name not in state.handles:
        return _err(404, f"unknown model {name!r}")
    if not state.is_primary:
        return await _proxy_admin_to_primary(
            state, "GET", f"/peer/admin/{name}/versions")
    status, body = await state.fanout_simple(name, "versions")
    return web.json_response(body, status=status)


async def handle_scale_host(request: web.Request) -> web.Response:
    """POST /admin/hosts/{hid}:scale?active=N — set one host domain's
    active worker-slot target (ISSUE 16). The SAME audited verb the
    autopilot's scale actuator uses, so an operator's manual scale and a
    controller decision read identically in /debug/audit. Serialized
    through the primary like every fleet transition."""
    state: RouterState = request.app[ROUTER_KEY]
    try:
        events_mod.reject_unknown_query(request.query, {"active"})
    except ValueError as e:
        return _err(400, str(e))
    try:
        hid = int(request.match_info["hid"])
        active = int(request.query["active"])
    except KeyError:
        return _err(400, "?active=<slots> is required")
    except ValueError:
        return _err(400, "host id and active must be integers")
    if not state.is_primary:
        return await _proxy_admin_to_primary(
            state, "POST", f"/peer/admin/hosts/{hid}:scale?active={active}")
    if not hasattr(state.supervisor, "scale_domain"):
        return _err(409, "[router] hosts = 0: there are no host domains "
                         "to scale")
    t0 = time.perf_counter()
    try:
        out = state.supervisor.scale_domain(hid, active)
    except ValueError as e:
        return _err(400, str(e))
    except RuntimeError as e:
        if state.audit is not None:
            state.audit.record(
                "scale", f"host:{hid}", "rejected",
                duration_ms=(time.perf_counter() - t0) * 1e3,
                active=active, error=str(e))
        return _err(409, str(e))
    if state.audit is not None:
        state.audit.record(
            "scale", f"host:{hid}", "ok",
            duration_ms=(time.perf_counter() - t0) * 1e3, **out)
    return web.json_response(out)


async def handle_autopilot(request: web.Request) -> web.Response:
    """GET /debug/autopilot — the controller's decision history: every
    action with its triggering signal values, outcome, and the damping
    state (open watches, rollbacks, budget deferrals). The loop runs on
    the primary; peers proxy like the audit trail."""
    state: RouterState = request.app[ROUTER_KEY]
    try:
        events_mod.reject_unknown_query(request.query, set())
    except ValueError as e:
        return _err(400, str(e))
    if not state.is_primary:
        return await _proxy_admin_to_primary(state, "GET",
                                             "/peer/debug/autopilot")
    if state.autopilot is None:
        return _err(409, "[autopilot] is disabled; no controller runs")
    body = state.autopilot.describe()
    body["burn_shed"] = sorted(state.burn_shed)
    return web.json_response(body)


async def handle_tenants(request: web.Request) -> web.Response:
    """GET /tenants — per-tenant containment envelopes + live window
    usage on THIS router (each router process admits independently; with
    N routers a tenant's effective budget is N x its configured one).
    ``?tenant=`` narrows to one tenant's row."""
    state: RouterState = request.app[ROUTER_KEY]
    try:
        events_mod.reject_unknown_query(request.query, {"tenant"})
    except ValueError as e:
        return _err(400, str(e))
    if state.tenants is None:
        return _err(409, "[tenants] is disabled; no tenant ledger is kept")
    body = state.tenants.usage()
    body["router_id"] = state.router_id
    if state.tenant_slo is not None:
        body["slo"] = state.tenant_slo.alerts()
    want = request.query.get("tenant")
    if want is not None:
        if want not in body["tenants"]:
            return _err(404, f"unknown tenant {want!r}")
        body["tenants"] = {want: body["tenants"][want]}
    return web.json_response(body)


# -- peer control plane (ISSUE 13) -------------------------------------------

async def handle_peer_state(request: web.Request) -> web.Response:
    """GET /peer/state — the topology peers sync: worker addresses, ring
    membership, cache generations (authoritative on the primary)."""
    state: RouterState = request.app[ROUTER_KEY]
    return web.json_response(state.peer_state())


async def handle_peer_invalidate(request: web.Request) -> web.Response:
    """POST /peer/invalidate {model, generation} — push-path half of the
    fleet-reload invalidation (the poll sync is the backstop)."""
    state: RouterState = request.app[ROUTER_KEY]
    try:
        data = await request.json()
        name = data["model"]
        gen = int(data["generation"])
    except (ValueError, KeyError, TypeError):
        return _err(400, "body must be {model, generation}")
    if name in state.generations and state.generations[name] != gen:
        state.generations[name] = gen
        cache = state.caches.get(name)
        if cache is not None:
            cache.clear()
    return web.json_response({"ok": True, "generation":
                              state.generations.get(name)})


def _peer_relay_handler(verb: str):
    async def handler(request: web.Request) -> web.Response:
        return await handle_peer_relay(request, verb)

    return handler


async def handle_peer_relay(request: web.Request, verb: str) -> web.Response:
    """POST /peer/models/{name}:{verb} — a sibling router forwarded a
    request whose cache key THIS router owns. Serve it through the LOCAL
    shard (hit → single-flight → worker relay), never re-forward: the
    origin did admission/shed checks and owns breaker accounting, and a
    ring disagreement mid-membership-change must terminate here, not
    loop."""
    state: RouterState = request.app[ROUTER_KEY]
    name = request.match_info["name"]
    h = state.handles.get(name)
    if h is None:
        return _err(404, f"unknown model {name!r}")
    ctx = TraceContext.from_headers(request.headers, pid=0)
    priority = request.headers.get("X-Priority")
    # The origin router resolved the API key; the resolved tenant rides
    # the loopback hop so this shard partitions by the same identity.
    tenant = request.headers.get(TENANT_HEADER) or None
    t_start = time.perf_counter()
    body = await request.read()
    ctype = request.content_type or ""
    try:
        timeout_ms = _requested_timeout_ms(request, body, ctype)
    except ValueError as e:
        return _err(400, str(e), trace=ctx)
    timeout_s = (timeout_ms if timeout_ms is not None
                 else h.mcfg.request_timeout_ms) / 1e3
    deadline_at = t_start + timeout_s
    h.peer_serves.inc()
    state._inflight += 1
    wall0 = time.time()
    try:
        cache = state.caches.get(name)
        if cache is None:
            ans = await state._relay(name, verb, body, ctype, deadline_at,
                                     priority, ctx)
        else:
            key = cache.key_for((verb, ctype, body))
            ans = await _dispatch_local(state, cache, key, name, verb, body,
                                        ctype, deadline_at, priority, ctx,
                                        tenant)
    except NoHealthyWorker as e:
        return _err(503, "no healthy worker; capacity respawning",
                    retry_after=max(1, math.ceil(e.eta_s)), trace=ctx)
    except (RelayDeadline, asyncio.TimeoutError):
        return _err(504,
                    f"request deadline ({timeout_s * 1e3:.0f} ms) exceeded",
                    trace=ctx)
    except UpstreamFailed:
        return _err(503, "workers unreachable; retry",
                    retry_after=state.no_worker_retry_after(), trace=ctx)
    finally:
        state._inflight -= 1
        dur_s = time.perf_counter() - t_start
        ctx.root_span("peer_serve", wall0, wall0 + dur_s, tid=name)
    resp = ans.to_response()
    resp.headers["X-Trace-Id"] = ctx.trace_id
    return resp


def make_peer_app(state: RouterState) -> web.Application:
    """The loopback control-plane app every router binds next to its
    public listener: topology for peers, forwarded cache hops, push
    invalidation, and (on the primary) the admin fan-out entry that peer
    routers proxy to."""
    app = web.Application(client_max_size=64 * 1024 * 1024)
    app[ROUTER_KEY] = state
    app.router.add_get("/peer/state", handle_peer_state)
    app.router.add_post("/peer/invalidate", handle_peer_invalidate)
    for verb in _VERBS:
        app.router.add_post(f"/peer/models/{{name}}:{verb}",
                            _peer_relay_handler(verb))
    app.router.add_post("/peer/admin/{name}:reload", handle_reload)
    app.router.add_post("/peer/admin/{name}:rollback", handle_rollback)
    app.router.add_get("/peer/admin/{name}/versions", handle_versions)
    app.router.add_post("/peer/admin/hosts/{hid}:scale", handle_scale_host)
    app.router.add_get("/peer/stats", handle_stats)
    app.router.add_get("/peer/healthz", handle_healthz)
    # Telemetry (ISSUE 14): /peer/metrics is what the PRIMARY scrapes for
    # the fleet merge (a peer's own registry); the /peer/fleet/* pair is
    # the proxy target peers forward their public fleet endpoints to.
    app.router.add_get("/peer/metrics", handle_metrics)
    app.router.add_get("/peer/fleet/metrics", handle_fleet_metrics)
    app.router.add_get("/peer/fleet/stats", handle_fleet_stats)
    # Event plane (ISSUE 15): peers proxy their public audit/postmortem
    # endpoints to these on the primary (the primary's ledgers are the
    # fleet's — admin verbs serialize through it, its supervisors reap
    # every process).
    app.router.add_get("/peer/debug/audit", handle_audit)
    app.router.add_get("/peer/debug/postmortems", handle_postmortems)
    # Controller plane (ISSUE 16): peers proxy /debug/autopilot here — the
    # loop runs on the primary, its decision history is the fleet's.
    app.router.add_get("/peer/debug/autopilot", handle_autopilot)
    return app


async def handle_index(request: web.Request) -> web.Response:
    from tpuserve.server import _INDEX_HTML

    return web.Response(text=_INDEX_HTML, content_type="text/html")


# -- app wiring --------------------------------------------------------------

def make_router_app(state: RouterState,
                    own_lifecycle: bool = True) -> web.Application:
    """The public-port app. ``own_lifecycle=False`` (peer processes, and
    fixtures that sequence start/stop themselves) skips the startup/cleanup
    hooks."""
    app = web.Application(client_max_size=64 * 1024 * 1024)
    app[ROUTER_KEY] = state
    for verb in _VERBS:
        app.router.add_post(f"/v1/models/{{name}}:{verb}",
                            _predict_handler(verb))
    app.router.add_get("/v1/models", handle_models)
    app.router.add_post("/admin/models/{name}:reload", handle_reload)
    app.router.add_post("/admin/models/{name}:rollback", handle_rollback)
    app.router.add_get("/admin/models/{name}/versions", handle_versions)
    app.router.add_post("/admin/hosts/{hid}:scale", handle_scale_host)
    app.router.add_get("/workers/{wid}/stats/history", handle_worker_history)
    app.router.add_get("/workers/{wid}/debug/events", handle_worker_events)
    app.router.add_get("/workers/{wid}/{page}", handle_worker_proxy)
    app.router.add_get("/healthz", handle_healthz)
    app.router.add_get("/metrics", handle_metrics)
    # Telemetry plane (ISSUE 14): router-tier history/alerts + the fleet
    # scrape (peers proxy the fleet endpoints to the primary).
    app.router.add_get("/metrics/fleet", handle_fleet_metrics)
    app.router.add_get("/stats", handle_stats)
    app.router.add_get("/stats/history", handle_router_history)
    app.router.add_get("/stats/fleet", handle_fleet_stats)
    app.router.add_get("/alerts", handle_router_alerts)
    app.router.add_get("/debug/slow", handle_slow)
    app.router.add_get("/debug/trace", handle_trace)
    # Event plane (ISSUE 15): the router's ring, the fleet postmortem
    # ledger, and the primary-serialized audit trail.
    app.router.add_get("/debug/events", handle_events)
    app.router.add_get("/debug/postmortems", handle_postmortems)
    app.router.add_get("/debug/audit", handle_audit)
    # Self-operating fleet (ISSUE 16): controller history + tenant view.
    app.router.add_get("/debug/autopilot", handle_autopilot)
    app.router.add_get("/tenants", handle_tenants)
    app.router.add_get("/", handle_index)

    if own_lifecycle:
        async def on_startup(app: web.Application) -> None:
            await state.start()

        async def on_cleanup(app: web.Application) -> None:
            await state.stop()

        app.on_startup.append(on_startup)
        app.on_cleanup.append(on_cleanup)
    return app


def bind_public_socket(host: str, port: int):
    """Bind (and return) the shared public listener socket with
    SO_REUSEPORT so N router processes can serve one port (PR 11's
    listener trick one tier up). Returns ``(sock, bound_port)``."""
    import socket as _socket

    sock = _socket.socket(_socket.AF_INET, _socket.SOCK_STREAM)
    try:
        if hasattr(_socket, "SO_REUSEPORT"):
            sock.setsockopt(_socket.SOL_SOCKET, _socket.SO_REUSEPORT, 1)
        sock.bind((host, port))
    except OSError:
        sock.close()
        raise
    return sock, sock.getsockname()[1]


async def serve_router_async(state: RouterState,
                             ready: asyncio.Event | None = None) -> None:
    """Serve the router until SIGTERM/SIGINT, then drain across the
    process boundary: stop admitting -> in-flight relays resolve ->
    workers flush accepted work and exit. Zero dropped requests."""
    cfg = state.cfg
    app = make_router_app(state)
    runner = web.AppRunner(app, access_log=None)
    if cfg.router.routers > 1:
        # Bind the SO_REUSEPORT socket BEFORE state.start() runs (at
        # runner.setup): the peer routers it spawns must join the final
        # (host, port), ephemeral included.
        sock, port = bind_public_socket(cfg.host, cfg.port)
        state.public_addr = (cfg.host, port)
        await runner.setup()
        site = web.SockSite(runner, sock)
    else:
        await runner.setup()
        site = web.TCPSite(runner, cfg.host, cfg.port)
    await site.start()
    state.serving_addresses = list(runner.addresses)
    log.info("router %d serving on %s (%d router(s), %d host(s), "
             "%d worker(s)%s)", state.router_id, state.serving_addresses,
             cfg.router.routers, cfg.router.hosts, cfg.router.workers,
             " per host" if cfg.router.hosts else "")

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    installed: list[signal.Signals] = []
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            loop.add_signal_handler(sig, stop.set)
            installed.append(sig)
        except (NotImplementedError, RuntimeError):
            pass
    if ready is not None:
        ready.set()
    try:
        await stop.wait()
        log.info("shutdown signal: draining router (budget %.0fs)",
                 cfg.drain_timeout_s)
        drained = await state.drain()
        if not drained:
            log.warning("router drain budget expired with relays in flight")
    finally:
        for sig in installed:
            loop.remove_signal_handler(sig)
        await runner.cleanup()  # on_cleanup -> state.stop() (workers drain)


def serve_router(cfg: ServerConfig) -> None:
    """Blocking entry point for `[router] enabled = true` deployments."""
    configure_logging(cfg)
    state = RouterState(cfg)
    asyncio.run(serve_router_async(state))
