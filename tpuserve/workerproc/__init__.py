"""Router/worker process split (ISSUE 8; docs/ROBUSTNESS.md "Process
failure domains").

The single-process server is one GIL, one event loop, one failure domain: a
wedged handler or a native crash in the runtime takes the HTTP front door
down with it. This package splits the deployment into **failure domains**
(Clipper's layered architecture, PAPERS.md P1):

- ``worker``   — the process entry for one isolated serving process: a full
  single-process tpuserve server (batching, hostpipe, runtime, lifecycle,
  its own watchdog and graceful drain) bound to loopback, announced to the
  supervisor over a pipe handshake.
- ``supervisor`` — spawns/owns N workers, health-checks them over HTTP,
  reaps dead processes, and respawns them with exponential backoff
  (extending PR 1's Watchdog: the process-liveness sweep is registered with
  it, so respawns land in ``watchdog_restarts_total``).
- ``router``   — the front tier: owns HTTP/JSON, admission + deadline
  stamping, the result cache + single-flight coalescing, and per-model
  circuit breakers; relays requests to the least-loaded healthy worker with
  transport-failure retry and tail-latency hedging, never past a request's
  absolute deadline.
- ``hosts``    — host failure domains (ISSUE 13): workers grouped into
  named hosts, each locally a supervisor subprocess in its own process
  group (one ``killpg`` = one machine death), with host breakers,
  host-aware hedging, and whole-domain respawn.
- ``peers``    — the horizontal router tier (ISSUE 13): N router
  processes on one SO_REUSEPORT port sharing a consistent-hash-sharded
  result cache; peers forward hits/single-flight leadership to each key's
  owning router and degrade to local-only when it dies.
- ``drill``    — the ``python -m tpuserve chaos --drill worker_kill`` and
  ``--drill host_kill`` backends: SIGKILL a worker (or an entire host's
  process group) under closed-loop load and measure that availability
  holds, the supervisor respawns within its backoff budget, and no
  response is torn or duplicated (PAPERS.md P6).

Enable with ``[router] enabled = true``; the default single-process path is
untouched. ``[router] hosts`` and ``[router] routers`` grow the failure
domains outward (docs/ROBUSTNESS.md "Host failure domains").
"""

from tpuserve.workerproc.hosts import HostSupervisor
from tpuserve.workerproc.peers import HashRing, PeerRouterSupervisor
from tpuserve.workerproc.router import RouterState, make_router_app, serve_router
from tpuserve.workerproc.supervisor import WorkerHandle, WorkerSupervisor
from tpuserve.workerproc.worker import worker_main

__all__ = [
    "HashRing",
    "HostSupervisor",
    "PeerRouterSupervisor",
    "RouterState",
    "WorkerHandle",
    "WorkerSupervisor",
    "make_router_app",
    "serve_router",
    "worker_main",
]
