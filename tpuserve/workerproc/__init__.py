"""Router/worker process split (ISSUE 8; docs/ROBUSTNESS.md "Process
failure domains").

The single-process server is one GIL, one event loop, one failure domain: a
wedged handler or a native crash in the runtime takes the HTTP front door
down with it. This package splits the deployment into **failure domains**
(Clipper's layered architecture, PAPERS.md P1):

- ``worker``   — the process entry for one isolated serving process: a full
  single-process tpuserve server (batching, hostpipe, runtime, lifecycle,
  its own watchdog and graceful drain) bound to loopback, announced to the
  supervisor over a pipe handshake.
- ``supervisor`` — spawns/owns N workers, health-checks them over HTTP,
  reaps dead processes, and respawns them with exponential backoff
  (extending PR 1's Watchdog: the process-liveness sweep is registered with
  it, so respawns land in ``watchdog_restarts_total``).
- ``router``   — the front tier: owns HTTP/JSON, admission + deadline
  stamping, the result cache + single-flight coalescing, and per-model
  circuit breakers; relays requests to the least-loaded healthy worker with
  transport-failure retry and tail-latency hedging, never past a request's
  absolute deadline.
- ``drill``    — the ``python -m tpuserve chaos --drill worker_kill``
  backend: SIGKILL a worker under closed-loop load and measure that
  availability holds, the supervisor respawns within its backoff budget,
  and no response is torn or duplicated (PAPERS.md P6).

Enable with ``[router] enabled = true``; the default single-process path is
untouched.
"""

from tpuserve.workerproc.router import RouterState, make_router_app, serve_router
from tpuserve.workerproc.supervisor import WorkerHandle, WorkerSupervisor
from tpuserve.workerproc.worker import worker_main

__all__ = [
    "RouterState",
    "WorkerHandle",
    "WorkerSupervisor",
    "make_router_app",
    "serve_router",
    "worker_main",
]
