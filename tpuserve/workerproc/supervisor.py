"""Worker supervision: spawn, health-check, reap, respawn with backoff.

The supervisor owns N worker slots. Each slot holds one worker process
(a full loopback-bound tpuserve server, ``tpuserve.workerproc.worker``) or
is empty while a respawn is pending. Three loops keep the fleet honest:

- **Process liveness** — ``sweep()`` is registered with the router's
  Watchdog (extending PR 1's revive machinery to whole processes): a slot
  whose process exited any way other than supervisor stop is reaped and
  scheduled for respawn, counted in
  ``watchdog_restarts_total{model=_router,component=worker}``.
- **HTTP health** — an async probe loop GETs each worker's ``/healthz`` on
  ``health_interval_s``; ``unhealthy_after`` consecutive bad probes route
  traffic around a live-but-wedged worker without killing it (it may be
  draining, compiling, or briefly overloaded).
- **Respawn with exponential backoff** — a dead slot respawns after
  ``min(respawn_max_s, respawn_initial_s * respawn_multiplier^fails)``;
  a successful boot resets the slot's failure count. A crash-looping
  worker therefore converges to one (cheap) boot attempt per
  ``respawn_max_s`` instead of a fork bomb, and ``respawn_eta_s()`` gives
  the router an honest ``Retry-After`` when no worker is healthy.

Thread/loop ownership: every roster field is mutated on the event loop
only; the blocking parts of a spawn (``Process.start`` + the ready-pipe
handshake) run on executor threads and hand the finished handle back to
the loop. There is deliberately no lock to witness.

Workers are daemonic: if the router process itself is SIGKILLed (no drain
path runs), the children are torn down by the interpreter instead of being
orphaned on loopback ports.
"""

from __future__ import annotations

import asyncio
import logging
import multiprocessing as mp
import time

from tpuserve.config import ServerConfig
from tpuserve.obs import Metrics
from tpuserve.workerproc.worker import worker_config, worker_main

log = logging.getLogger("tpuserve.workerproc")


class WorkerHandle:
    """Supervisor-side handle for one live worker process."""

    __slots__ = ("wid", "proc", "conn", "port", "pid", "base_url",
                 "healthy", "health_fails", "inflight", "picked_seq",
                 "started_at", "host")

    def __init__(self, wid: int, proc, conn, port: int, pid: int,
                 host: str) -> None:
        self.wid = wid
        self.proc = proc
        self.conn = conn
        self.port = port
        self.pid = pid
        self.base_url = f"http://{host}:{port}"
        # Healthy until probed otherwise: the ready handshake proves the
        # listener is up, which is a stronger signal than one HTTP probe.
        self.healthy = True
        self.health_fails = 0
        self.inflight = 0
        self.picked_seq = 0
        self.started_at = time.monotonic()
        # Failure-domain id. The flat supervisor has no host layer: every
        # worker is its own domain (host-aware hedging degrades to the
        # PR-8 different-worker rule). HostSupervisor's refs carry a real
        # host id here (tpuserve.workerproc.hosts).
        self.host: int | None = None

    def close(self) -> None:
        try:
            self.conn.close()
        except OSError:
            pass


def spawn_worker_blocking(wcfg, wid: int, spawn_timeout_s: float):
    """Spawn one worker process and wait for its ready handshake. Blocking
    (Process.start + the pipe poll) — call from an executor thread in the
    router, or from the host agent's own process (tpuserve.workerproc.hosts,
    which runs the same handshake one level down).

    Returns ``(proc, parent_conn, port, pid)``; raises on boot failure with
    the child killed and the pipe closed."""
    ctx = mp.get_context("spawn")
    parent, child = ctx.Pipe()
    proc = ctx.Process(target=worker_main, args=(wcfg, wid, child),
                       daemon=True, name=f"tpuserve-worker-{wid}")
    proc.start()
    child.close()
    try:
        if not parent.poll(spawn_timeout_s):
            raise TimeoutError(
                f"worker {wid} not ready after {spawn_timeout_s:.0f}s")
        msg = parent.recv()
        if msg.get("op") != "ready":
            raise RuntimeError(f"worker {wid} failed at boot: {msg}")
    except BaseException:
        if proc.is_alive():
            proc.kill()
        proc.join(5.0)
        parent.close()
        raise
    return proc, parent, int(msg["port"]), int(msg.get("pid", proc.pid))


class WorkerSupervisor:
    """Owns the worker fleet for one router process.

    ``postmortems`` (ISSUE 15): when the router's event plane is on, every
    reaped worker death is folded into a forensics record — exit
    code/signal, the slot's stderr-capture tail, and its last black-box
    snapshot — on an executor thread (the file reads must not block the
    loop the sweep runs on)."""

    def __init__(self, cfg: ServerConfig, metrics: Metrics,
                 postmortems=None) -> None:
        self.cfg = cfg
        self.rcfg = cfg.router
        self.metrics = metrics
        self.postmortems = postmortems
        self.n = cfg.router.workers
        # Derived once so every respawn serves an identical config (and so
        # recycle-mode rejection fires at construction, not mid-respawn).
        self._worker_cfgs = [worker_config(cfg, i) for i in range(self.n)]
        self.slots: list[WorkerHandle | None] = [None] * self.n
        self._fails = [0] * self.n          # consecutive failed boots
        self._next_up_at = [0.0] * self.n   # respawn ETA (monotonic)
        self._respawning: set[int] = set()
        self._bg: set[asyncio.Task] = set()
        self._health_task: asyncio.Task | None = None
        self._session = None  # aiohttp.ClientSession for health probes
        self._stopping = False
        self._pick_seq = 0
        self.deaths_total = 0
        # Prebound per-slot metrics (never formatted per probe/pick).
        self._g_up = [metrics.worker_up_gauge(i) for i in range(self.n)]
        self._g_backoff = [metrics.worker_backoff_gauge(i)
                           for i in range(self.n)]
        self._g_inflight = [metrics.worker_inflight_gauge(i)
                            for i in range(self.n)]
        self._c_respawns = [metrics.worker_respawns_counter(i)
                            for i in range(self.n)]

    # -- lifecycle -----------------------------------------------------------
    async def start(self) -> None:
        """Spawn the fleet and start the health loop. With a persistent
        compile cache configured, the first worker boots alone so it
        populates the cache and the rest (and every future respawn) hit
        it — the deferred pool's prewarm trick at process scale."""
        import aiohttp

        loop = asyncio.get_running_loop()
        self._session = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(
                total=self.rcfg.health_timeout_ms / 1e3))
        first_alone = bool(self.cfg.compilation_cache_dir) and self.n > 1
        rest = range(self.n)
        if first_alone:
            self.slots[0] = await loop.run_in_executor(
                None, self._spawn_blocking, 0)
            self._g_up[0].set(1.0)
            rest = range(1, self.n)
        spawned = await asyncio.gather(
            *(loop.run_in_executor(None, self._spawn_blocking, i)
              for i in rest))
        for h in spawned:
            self.slots[h.wid] = h
            self._g_up[h.wid].set(1.0)
        self._health_task = loop.create_task(self._health_loop())
        log.info("worker fleet up: %s",
                 [f"{h.wid}@{h.port}" for h in self.slots if h])

    def _spawn_blocking(self, wid: int) -> WorkerHandle:
        """Spawn one worker and wait for its ready handshake (executor
        thread — Process.start and the pipe poll both block)."""
        proc, parent, port, pid = spawn_worker_blocking(
            self._worker_cfgs[wid], wid, self.rcfg.spawn_timeout_s)
        if self._stopping:
            # The supervisor stopped while this spawn was in flight on its
            # executor thread (the awaiting task was cancelled, so nobody
            # will adopt the handle): tear the fresh worker down instead of
            # orphaning a live server on a loopback port.
            proc.kill()
            proc.join(5.0)
            parent.close()
            raise RuntimeError(f"supervisor stopping; discarded worker {wid}")
        return WorkerHandle(wid, proc, parent, port, pid,
                            self.cfg.worker.host)

    async def stop(self, drain: bool = True) -> None:
        """SIGTERM the fleet and wait for graceful exits (each worker runs
        its own accepted-work drain), then SIGKILL stragglers. The router
        sequences this AFTER it stopped admitting and its in-flight relays
        resolved, so the cross-process drain drops zero accepted requests."""
        self._stopping = True
        if self._health_task is not None:
            self._health_task.cancel()
            try:
                await self._health_task
            except asyncio.CancelledError:
                pass
            self._health_task = None
        for t in list(self._bg):
            t.cancel()
        if self._bg:
            await asyncio.gather(*self._bg, return_exceptions=True)
        live = [h for h in self.slots if h is not None and h.proc.is_alive()]
        for h in live:
            h.proc.terminate()
        budget = self.cfg.drain_timeout_s if drain else 2.0
        deadline = time.monotonic() + budget
        while any(h.proc.is_alive() for h in live) \
                and time.monotonic() < deadline:
            await asyncio.sleep(0.05)
        killed = 0
        for h in live:
            if h.proc.is_alive():
                h.proc.kill()
                killed += 1
        if killed:
            log.warning("%d worker(s) outlived the %.1fs drain budget and "
                        "were killed", killed, budget)
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(None, self._join_all, live)
        for i, h in enumerate(self.slots):
            if h is not None:
                h.close()
            self._g_up[i].set(0.0)
        if self._session is not None:
            await self._session.close()
            self._session = None

    @staticmethod
    def _join_all(handles: list[WorkerHandle]) -> None:
        for h in handles:
            h.proc.join(10.0)

    # -- liveness / health ---------------------------------------------------
    def sweep(self) -> int:
        """Watchdog hook (event loop, non-blocking): reap worker slots
        whose process exited and schedule their backoff respawns. Returns
        how many newly-dead workers were found — these are real failures
        (supervisor stop goes through stop(), not here)."""
        if self._stopping:
            return 0
        died = 0
        for i, h in enumerate(self.slots):
            if h is not None and not h.proc.is_alive():
                died += 1
                self._on_dead(i, h, f"process exited (code {h.proc.exitcode})")
        return died

    def _on_dead(self, wid: int, h: WorkerHandle, why: str) -> None:
        log.error("worker %d (pid %d) died: %s", wid, h.pid, why)
        self.deaths_total += 1
        self._schedule_postmortem(wid, h)
        h.close()
        self.slots[wid] = None
        self._g_up[wid].set(0.0)
        self._g_inflight[wid].set(0.0)
        self._schedule_respawn(wid)

    def _schedule_postmortem(self, wid: int, h: WorkerHandle) -> None:
        """Fold the dead worker's black box into a postmortem record on an
        executor thread (sweep/_on_dead run on the event loop and must not
        read files there). The capture races the eventual respawn's boot
        banner by the whole backoff window, so the tail it reads is the
        dead incarnation's."""
        if self.postmortems is None:
            return
        ecfg = self._worker_cfgs[wid].events
        exitcode = h.proc.exitcode
        loop = asyncio.get_running_loop()

        async def _capture() -> None:
            await loop.run_in_executor(
                None, lambda: self.postmortems.capture_blocking(
                    "worker", f"worker{wid}", h.pid, exitcode,
                    stderr_path=ecfg.stderr_path or None,
                    snapshot_path=ecfg.snapshot_path or None,
                    worker=wid))

        t = loop.create_task(_capture())
        self._bg.add(t)
        t.add_done_callback(self._bg.discard)

    def _schedule_respawn(self, wid: int) -> None:
        if self._stopping or wid in self._respawning:
            return
        self._respawning.add(wid)
        t = asyncio.get_running_loop().create_task(self._respawn(wid))
        self._bg.add(t)
        t.add_done_callback(self._bg.discard)

    async def _respawn(self, wid: int) -> None:
        """Respawn one slot with exponential backoff until it boots or the
        supervisor stops; a successful boot resets the slot's failure
        count."""
        loop = asyncio.get_running_loop()
        try:
            while not self._stopping:
                delay = min(self.rcfg.respawn_max_s,
                            self.rcfg.respawn_initial_s
                            * self.rcfg.respawn_multiplier ** self._fails[wid])
                self._g_backoff[wid].set(delay)
                self._next_up_at[wid] = time.monotonic() + delay
                await asyncio.sleep(delay)
                if self._stopping:
                    return
                try:
                    h = await loop.run_in_executor(
                        None, self._spawn_blocking, wid)
                except Exception:
                    self._fails[wid] += 1
                    log.exception("worker %d respawn failed (consecutive "
                                  "failures: %d)", wid, self._fails[wid])
                    continue
                self.slots[wid] = h
                self._fails[wid] = 0
                self._g_backoff[wid].set(0.0)
                self._g_up[wid].set(1.0)
                self._c_respawns[wid].inc()
                log.info("worker %d respawned (pid %d, port %d)",
                         wid, h.pid, h.port)
                return
        except asyncio.CancelledError:
            raise
        finally:
            self._respawning.discard(wid)

    async def _health_loop(self) -> None:
        while True:
            await asyncio.sleep(self.rcfg.health_interval_s)
            try:
                await self._probe_all()
            except asyncio.CancelledError:
                raise
            except Exception:  # one bad cycle must not end health checking
                log.exception("worker health probe cycle failed")

    async def _probe_all(self) -> None:
        # Liveness first (no HTTP needed to notice a corpse), then the
        # probes run concurrently so one slow worker can't stale the rest.
        for i, h in enumerate(self.slots):
            if h is not None and not h.proc.is_alive():
                self._on_dead(i, h, f"process exited (code {h.proc.exitcode})")
        await asyncio.gather(
            *(self._probe(h) for h in self.slots if h is not None))

    async def _probe(self, h: WorkerHandle) -> None:
        try:
            async with self._session.get(f"{h.base_url}/healthz") as r:
                ok = r.status == 200
                await r.read()
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 — refused/reset/timeout all count
            ok = False
        if ok:
            if not h.healthy:
                log.info("worker %d healthy again", h.wid)
            h.health_fails = 0
            h.healthy = True
        else:
            h.health_fails += 1
            if h.healthy and h.health_fails >= self.rcfg.unhealthy_after:
                log.warning("worker %d unhealthy after %d failed probes — "
                            "routing around it", h.wid, h.health_fails)
                h.healthy = False
        self._g_up[h.wid].set(1.0 if h.healthy else 0.0)

    # -- routing -------------------------------------------------------------
    def healthy_workers(self) -> list[WorkerHandle]:
        return [h for h in self.slots if h is not None and h.healthy]

    def live_workers(self) -> list[WorkerHandle]:
        """Every slot with a live process — admin fan-outs must reach
        unhealthy-but-alive workers too, or the fleet's versions diverge."""
        return [h for h in self.slots
                if h is not None and h.proc.is_alive()]

    def worker_by_id(self, wid: int) -> WorkerHandle | None:
        if not 0 <= wid < self.n:
            return None
        return self.slots[wid]

    def down_domains(self) -> list[str]:
        """Failure domains currently dead/respawning — a fleet-wide reload
        must refuse while any exists (a dead slot respawns from the boot
        config and would diverge from a freshly published version)."""
        return [f"worker{i}" for i, h in enumerate(self.slots)
                if h is None or not h.proc.is_alive()]

    def host_of(self, h: WorkerHandle) -> int | None:
        return h.host

    def note_transport_failure(self, h: WorkerHandle) -> None:
        """Host-breaker food (tpuserve.workerproc.hosts). The flat
        supervisor has no host layer: health probes + retry already route
        around a dead worker, so this is a no-op."""

    def note_success(self, h: WorkerHandle) -> None:
        pass

    def pick(self, exclude: set[int] = frozenset(),
             exclude_hosts: set[int] = frozenset()) -> WorkerHandle | None:
        """Least-loaded healthy worker not in ``exclude``; ties break to
        the least-recently-picked so equal load round-robins instead of
        piling onto slot 0. ``exclude_hosts`` is the host-aware hedging
        seam — with no host layer every worker's host is None, so the
        different-worker rule (``exclude``) is the whole constraint."""
        best: WorkerHandle | None = None
        for h in self.slots:
            if h is None or not h.healthy or h.wid in exclude:
                continue
            if h.host is not None and h.host in exclude_hosts:
                continue
            if best is None \
                    or (h.inflight, h.picked_seq) < (best.inflight,
                                                     best.picked_seq):
                best = h
        if best is not None:
            self._pick_seq += 1
            best.picked_seq = self._pick_seq
        return best

    def track_inflight(self, h: WorkerHandle, delta: int) -> None:
        h.inflight += delta
        self._g_inflight[h.wid].set(h.inflight)

    def respawn_eta_s(self) -> float:
        """Soonest respawn ETA across dead slots — the live Retry-After
        basis when no worker is healthy. Falls back to the health interval
        (the soonest a wedged-but-alive worker can be probed healthy)."""
        now = time.monotonic()
        etas = [max(0.0, self._next_up_at[i] - now)
                for i in self._respawning]
        if etas:
            return min(etas)
        return self.rcfg.health_interval_s

    # -- introspection -------------------------------------------------------
    def stats(self) -> dict:
        """The /stats ``workers`` block (docs/ROBUSTNESS.md)."""
        now = time.monotonic()
        rows = []
        for i in range(self.n):
            h = self.slots[i]
            if h is None:
                rows.append({
                    "worker": i,
                    "state": "respawning" if i in self._respawning
                    else "down",
                    "consecutive_boot_failures": self._fails[i],
                    "respawn_eta_s": round(
                        max(0.0, self._next_up_at[i] - now), 3),
                    "respawns_total": self._c_respawns[i].value,
                })
            else:
                rows.append({
                    "worker": i,
                    "state": "ready" if h.healthy else "unhealthy",
                    "pid": h.pid,
                    "port": h.port,
                    "inflight": h.inflight,
                    "health_fails": h.health_fails,
                    "uptime_s": round(now - h.started_at, 1),
                    "respawns_total": self._c_respawns[i].value,
                })
        return {
            "configured": self.n,
            "healthy": len(self.healthy_workers()),
            "deaths_total": self.deaths_total,
            "workers": rows,
        }
