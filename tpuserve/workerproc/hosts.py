"""Host failure domains (ISSUE 13; docs/ROBUSTNESS.md "Host failure
domains").

The PR-8 supervisor contains failures at the PROCESS level: one worker dies,
the router routes around it. This module adds the level above — the MACHINE.
Workers are grouped into named hosts (``[router] hosts``); locally each host
is a **host agent**: a supervisor subprocess in its own session/process
group that spawns and owns its share of the worker fleet, so a single
``killpg(SIGKILL)`` takes out the entire failure domain at once — agent and
every worker — exactly the blast radius of a machine losing power. (On real
multi-machine deployments the same seam is one agent per box, with
``parallel/distributed.py`` supplying the process coordinates; the router
side of this module is agnostic to where the agent runs.)

Division of labor:

- **Host agent** (``host_main``) — synchronous, single-threaded, device-free.
  Spawns its workers with the same ready-pipe handshake the flat supervisor
  uses, respawns a dead worker with exponential backoff (a worker crash is a
  HOST-local event: the router only learns the new port), reports
  ``worker_up``/``worker_down`` over the pipe, and drains its fleet on
  SIGTERM or on pipe EOF (the router vanished — don't serve as an orphan).
- **HostSupervisor** (router-side) — supervises AGENTS: process-liveness
  sweep via the Watchdog (a dead host is killpg'd to finish off any straggler
  workers, then respawned with exponential backoff, ``host_up``/
  ``host_respawns_total``), HTTP health probes straight at every worker (the
  data plane never transits the agent), and a **host breaker**: a few
  consecutive relay transport failures against one host's workers route the
  whole host around in milliseconds — connection-refused from a freshly dead
  machine must not wait for a probe cycle. ``respawn_eta_s`` feeds the
  router's Retry-After with the minimum respawn ETA across everything dead.

Thread/loop ownership mirrors the flat supervisor: all roster state is
mutated on the router's event loop only; blocking pipe reads and spawns run
on executor threads and hand results back to the loop. There is deliberately
no lock to witness. The agent process is single-threaded.
"""

from __future__ import annotations

import asyncio
import logging
import multiprocessing as mp
import os
import signal
import time

from tpuserve.config import ServerConfig
from tpuserve.obs import Metrics
from tpuserve.telemetry.events import (read_snapshot, read_tail,
                                       redirect_stderr,
                                       resolve_blackbox_dir)
from tpuserve.workerproc.supervisor import spawn_worker_blocking
from tpuserve.workerproc.worker import worker_config

log = logging.getLogger("tpuserve.workerproc")

_EOF = object()


def host_name(hid: int) -> str:
    return f"host{hid}"


# ---------------------------------------------------------------------------
# Host agent (runs in its own process + process group)
# ---------------------------------------------------------------------------

class _AgentSlot:
    """One worker slot inside the host agent."""

    __slots__ = ("wid", "cfg", "proc", "conn", "port", "pid",
                 "fails", "next_at", "stopping", "stop_at")

    def __init__(self, wid: int, cfg) -> None:
        self.wid = wid
        self.cfg = cfg
        self.proc = None
        self.conn = None
        self.port = 0
        self.pid = 0
        self.fails = 0
        self.next_at = 0.0  # monotonic respawn ETA while down
        # Scale-down drain in progress (ISSUE 16): the slot was told to
        # stop on purpose — its exit is NOT a death.
        self.stopping = False
        self.stop_at = 0.0  # monotonic SIGKILL deadline while stopping


def host_main(host_id: int, wids: list[int], wcfgs: list[ServerConfig],
              opts: dict, conn) -> None:
    """Host-agent process entry (multiprocessing spawn target).

    ``wids``/``wcfgs`` are this host's worker ids and their pre-derived
    configs (the router derives them once, same rule as the flat
    supervisor). ``opts`` carries the spawn/backoff/drain knobs. ``conn``
    is the control pipe: the ready handshake goes up, worker_up/worker_down
    events follow, and EOF coming down means the router died — drain and
    exit rather than serve as an orphan fleet.
    """
    # Black box (ISSUE 15): the agent's own stderr goes to its per-host
    # capture file — an agent dying with its whole domain must leave its
    # last words where the router's postmortem reader can find them.
    redirect_stderr(opts.get("stderr_path"),
                    f"{host_name(host_id)} boot pid {os.getpid()} "
                    f"ts {time.time():.3f}")
    # Own session = own process group = one addressable failure domain:
    # killpg(pgid, SIGKILL) takes agent + workers down in one syscall,
    # exactly like the machine losing power.
    try:
        os.setsid()
    except OSError:
        pass  # already a session leader (unusual but not fatal)

    stop_flag = {"stop": False}

    def _sigterm(signum, frame):  # noqa: ARG001 — signal handler shape
        stop_flag["stop"] = True

    signal.signal(signal.SIGTERM, _sigterm)
    signal.signal(signal.SIGINT, signal.SIG_IGN)  # router's ^C drains us

    name = host_name(host_id)
    slots = [_AgentSlot(wid, cfg) for wid, cfg in zip(wids, wcfgs)]
    # Active slot count (ISSUE 16 autopilot scaling): slots at index >=
    # active stay cold until a "scale" op raises it — capacity held in
    # reserve at zero cost.
    active = max(1, min(len(slots), int(opts.get("active", len(slots)))))

    def _spawn(slot: _AgentSlot) -> None:
        slot.proc, slot.conn, slot.port, slot.pid = spawn_worker_blocking(
            slot.cfg, slot.wid, opts["spawn_timeout_s"])
        slot.fails = 0
        slot.next_at = 0.0
        slot.stopping = False

    try:
        for slot in slots[:active]:
            _spawn(slot)
    except Exception as e:  # noqa: BLE001 — report any boot death upward
        for slot in slots:
            if slot.proc is not None and slot.proc.is_alive():
                slot.proc.kill()
        try:
            conn.send({"op": "died", "host": host_id,
                       "error": f"{type(e).__name__}: {e}"})
        finally:
            conn.close()
        raise

    conn.send({"op": "ready", "host": host_id, "pgid": os.getpgrp(),
               "pid": os.getpid(), "active": active,
               "workers": [{"wid": s.wid, "port": s.port, "pid": s.pid}
                           for s in slots[:active]]})

    def _send(msg: dict) -> bool:
        try:
            conn.send(msg)
            return True
        except (BrokenPipeError, OSError):
            return False

    router_gone = False
    while not stop_flag["stop"] and not router_gone:
        now = time.monotonic()
        for idx, slot in enumerate(slots):
            if slot.proc is not None and not slot.proc.is_alive():
                code = slot.proc.exitcode
                slot.proc.join(0)
                slot.proc = None
                if slot.conn is not None:
                    try:
                        slot.conn.close()
                    except OSError:
                        pass
                    slot.conn = None
                if slot.stopping:
                    # Scale-down drain finished: an intentional exit, not
                    # a death — no postmortem, no respawn clock.
                    slot.stopping = False
                    router_gone |= not _send(
                        {"op": "worker_scaled_down", "wid": slot.wid,
                         "exitcode": code})
                    continue
                # Worker died: a HOST-local failure. Reap, tell the router
                # (it stops routing here instantly), schedule the respawn.
                delay = min(opts["respawn_max_s"],
                            opts["respawn_initial_s"]
                            * opts["respawn_multiplier"] ** slot.fails)
                slot.next_at = now + delay
                # The agent folds the black box into the worker_down
                # message itself (ISSUE 15): on a real multi-machine
                # deployment the capture files live on THIS box, so the
                # evidence must cross the control pipe, not a filesystem.
                ecfg = slot.cfg.events
                router_gone |= not _send(
                    {"op": "worker_down", "wid": slot.wid, "exitcode": code,
                     "eta_s": delay, "pid": slot.pid,
                     "stderr_tail": read_tail(ecfg.stderr_path or None,
                                              ecfg.stderr_tail_bytes),
                     "snapshot": read_snapshot(ecfg.snapshot_path or None)})
            elif slot.stopping and slot.proc is not None \
                    and now >= slot.stop_at:
                slot.proc.kill()  # drain budget spent: finish the scale-down
            elif slot.proc is None and not slot.stopping and idx < active \
                    and now >= slot.next_at:
                try:
                    _spawn(slot)
                except Exception:  # noqa: BLE001 — boot failed, back off
                    slot.fails += 1
                    delay = min(opts["respawn_max_s"],
                                opts["respawn_initial_s"]
                                * opts["respawn_multiplier"] ** slot.fails)
                    slot.next_at = time.monotonic() + delay
                else:
                    router_gone |= not _send(
                        {"op": "worker_up", "wid": slot.wid,
                         "port": slot.port, "pid": slot.pid})
        try:
            if conn.poll(0.2):
                msg = conn.recv()
                op = msg.get("op")
                if op == "stop":
                    break
                if op == "scale":
                    # Adjust the active slot count live: surplus slots
                    # drain (SIGTERM, bounded, then SIGKILL above);
                    # re-activated slots ride the normal respawn branch.
                    active = max(1, min(len(slots), int(msg["active"])))
                    now = time.monotonic()
                    for idx, slot in enumerate(slots):
                        if idx >= active and slot.proc is not None \
                                and not slot.stopping:
                            slot.proc.terminate()
                            slot.stopping = True
                            slot.stop_at = now + opts["drain_timeout_s"]
                        elif idx >= active and slot.proc is None:
                            router_gone |= not _send(
                                {"op": "worker_scaled_down",
                                 "wid": slot.wid, "exitcode": None})
                        elif idx < active and slot.proc is None \
                                and not slot.stopping:
                            slot.next_at = 0.0  # activate next loop pass
        except (EOFError, OSError):
            router_gone = True

    # Drain: SIGTERM the fleet (each worker flushes accepted work), bounded
    # wait, SIGKILL stragglers — the flat supervisor's stop() one level down.
    live = [s for s in slots if s.proc is not None and s.proc.is_alive()]
    for slot in live:
        slot.proc.terminate()
    deadline = time.monotonic() + opts["drain_timeout_s"]
    while any(s.proc.is_alive() for s in live) and time.monotonic() < deadline:
        time.sleep(0.05)
    for slot in live:
        if slot.proc.is_alive():
            slot.proc.kill()
        slot.proc.join(10.0)
    try:
        conn.close()
    except OSError:
        pass


# ---------------------------------------------------------------------------
# Router-side supervision of host agents
# ---------------------------------------------------------------------------

class WorkerRef:
    """Router-side view of one worker living under a host agent. Exposes
    the relay surface of supervisor.WorkerHandle (wid/base_url/healthy/
    inflight/picked_seq/host) without owning the process — the agent does."""

    __slots__ = ("wid", "host", "port", "pid", "base_url", "healthy",
                 "health_fails", "inflight", "picked_seq", "started_at",
                 "up")

    def __init__(self, wid: int, host: int, port: int, pid: int,
                 bind_host: str) -> None:
        self.wid = wid
        self.host = host
        self.port = port
        self.pid = pid
        self.base_url = f"http://{bind_host}:{port}"
        self.healthy = True
        self.health_fails = 0
        self.inflight = 0
        self.picked_seq = 0
        self.started_at = time.monotonic()
        self.up = True


class HostHandle:
    """One live host agent."""

    __slots__ = ("hid", "proc", "conn", "pgid", "pid", "workers",
                 "started_at")

    def __init__(self, hid: int, proc, conn, pgid: int, pid: int) -> None:
        self.hid = hid
        self.proc = proc
        self.conn = conn
        self.pgid = pgid
        self.pid = pid
        self.workers: dict[int, WorkerRef] = {}
        self.started_at = time.monotonic()

    def close(self) -> None:
        try:
            self.conn.close()
        except OSError:
            pass


def _poll_recv(conn, timeout: float):
    """Blocking pipe read step (executor thread): one message, None on
    timeout, _EOF when the agent is gone."""
    try:
        if conn.poll(timeout):
            return conn.recv()
        return None
    except (EOFError, OSError):
        return _EOF


class HostSupervisor:
    """Owns the host-agent fleet for the primary router process. Same
    routing surface as WorkerSupervisor (pick / healthy_workers /
    live_workers / track_inflight / respawn_eta_s / sweep / stats), one
    level of failure domain up."""

    def __init__(self, cfg: ServerConfig, metrics: Metrics,
                 postmortems=None) -> None:
        self.cfg = cfg
        self.rcfg = cfg.router
        self.metrics = metrics
        self.postmortems = postmortems
        self.n_hosts = cfg.router.hosts
        self.per_host = cfg.router.workers
        self.n = self.n_hosts * self.per_host
        # Derived once so every respawn (host or worker) serves identical
        # config; recycle rejection fires here, at construction.
        self._worker_cfgs = [worker_config(cfg, i) for i in range(self.n)]
        self.hosts: list[HostHandle | None] = [None] * self.n_hosts
        # wid -> last known ref (kept across down/up so /stats can show a
        # down row and inflight gauges drain cleanly).
        self._refs: dict[int, WorkerRef] = {}
        self._fails = [0] * self.n_hosts
        self._next_up_at = [0.0] * self.n_hosts
        self._respawning: set[int] = set()
        # Autopilot scaling (ISSUE 16): per-host ACTIVE slot target (a
        # respawned host resumes its scaled level) and the wids currently
        # scaled out on purpose — intentionally-down capacity that must
        # not read as a failure domain (down_domains) or a death.
        self._active = [cfg.router.active_workers or self.per_host
                        ] * self.n_hosts
        self._scaled_down: set[int] = set()
        self._bg: set[asyncio.Task] = set()
        self._health_task: asyncio.Task | None = None
        self._session = None
        self._stopping = False
        self._pick_seq = 0
        self.deaths_total = 0        # worker-level deaths (host kills incl.)
        self.host_deaths_total = 0
        # Host breaker: consecutive relay TRANSPORT failures per host trip
        # it; picks shed until the cooldown, then half-open.
        self._hb_fails = [0] * self.n_hosts
        self._hb_until = [0.0] * self.n_hosts
        # Prebound metrics (never formatted per probe/pick).
        self._g_worker_up = [metrics.worker_up_gauge(i) for i in range(self.n)]
        self._g_worker_inflight = [metrics.worker_inflight_gauge(i)
                                   for i in range(self.n)]
        self._c_worker_respawns = [metrics.worker_respawns_counter(i)
                                   for i in range(self.n)]
        self._g_host_up = [metrics.host_up_gauge(i)
                           for i in range(self.n_hosts)]
        self._g_host_backoff = [metrics.host_backoff_gauge(i)
                                for i in range(self.n_hosts)]
        self._g_host_breaker = [metrics.host_breaker_gauge(i)
                                for i in range(self.n_hosts)]
        self._c_host_respawns = [metrics.host_respawns_counter(i)
                                 for i in range(self.n_hosts)]

    def _host_wids(self, hid: int) -> list[int]:
        return list(range(hid * self.per_host, (hid + 1) * self.per_host))

    # -- lifecycle -----------------------------------------------------------
    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        import aiohttp

        self._session = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(
                total=self.rcfg.health_timeout_ms / 1e3))
        spawned = await asyncio.gather(
            *(loop.run_in_executor(None, self._spawn_host_blocking, hid)
              for hid in range(self.n_hosts)))
        for h in spawned:
            self._adopt_host(h)
        self._health_task = loop.create_task(self._health_loop())
        log.info("host fleet up: %s",
                 [f"{host_name(h.hid)}(pgid {h.pgid}): "
                  f"{sorted(h.workers)}" for h in spawned])

    def _spawn_host_blocking(self, hid: int) -> HostHandle:
        """Spawn one host agent and wait for its ready handshake (executor
        thread). The agent is deliberately NOT daemonic — daemonic
        processes cannot have children, and spawning the workers is its
        whole job; it exits on pipe EOF instead if the router dies."""
        wids = self._host_wids(hid)
        opts = {
            "spawn_timeout_s": self.rcfg.spawn_timeout_s,
            "respawn_initial_s": self.rcfg.respawn_initial_s,
            "respawn_max_s": self.rcfg.respawn_max_s,
            "respawn_multiplier": self.rcfg.respawn_multiplier,
            "drain_timeout_s": self.cfg.drain_timeout_s,
            "active": self._active[hid],
        }
        if self.cfg.events.enabled:
            # Agent stderr capture (ISSUE 15): per-host file beside the
            # workers' — a killpg'd domain leaves the agent's last words.
            opts["stderr_path"] = os.path.join(
                resolve_blackbox_dir(self.cfg.events),
                f"{host_name(hid)}.stderr")
        ctx = mp.get_context("spawn")
        parent, child = ctx.Pipe()
        proc = ctx.Process(
            target=host_main,
            args=(hid, wids, [self._worker_cfgs[w] for w in wids], opts,
                  child),
            daemon=False, name=f"tpuserve-{host_name(hid)}")
        proc.start()
        child.close()
        try:
            if not parent.poll(self.rcfg.spawn_timeout_s):
                raise TimeoutError(
                    f"{host_name(hid)} not ready after "
                    f"{self.rcfg.spawn_timeout_s:.0f}s")
            msg = parent.recv()
            if msg.get("op") != "ready":
                raise RuntimeError(
                    f"{host_name(hid)} failed at boot: {msg}")
        except BaseException:
            if proc.is_alive():
                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except (OSError, ProcessLookupError):
                    proc.kill()
            proc.join(5.0)
            parent.close()
            raise
        if self._stopping:
            try:
                os.killpg(int(msg["pgid"]), signal.SIGKILL)
            except (OSError, ProcessLookupError):
                proc.kill()
            proc.join(5.0)
            parent.close()
            raise RuntimeError(
                f"supervisor stopping; discarded {host_name(hid)}")
        h = HostHandle(hid, proc, parent, int(msg["pgid"]),
                       int(msg.get("pid", proc.pid)))
        for row in msg["workers"]:
            h.workers[int(row["wid"])] = WorkerRef(
                int(row["wid"]), hid, int(row["port"]), int(row["pid"]),
                self.cfg.worker.host)
        return h

    def _adopt_host(self, h: HostHandle) -> None:
        """Event loop: install a freshly booted host + its worker refs."""
        self.hosts[h.hid] = h
        self._g_host_up[h.hid].set(1.0)
        self._g_host_backoff[h.hid].set(0.0)
        self._hb_fails[h.hid] = 0
        self._hb_until[h.hid] = 0.0
        self._g_host_breaker[h.hid].set(0.0)
        for wid, ref in h.workers.items():
            self._refs[wid] = ref
            self._g_worker_up[wid].set(1.0)
            self._g_worker_inflight[wid].set(0.0)
        for wid in self._host_wids(h.hid):
            # Slots the agent booted cold (active < per_host) are scaled
            # down, not dead.
            if wid not in h.workers:
                self._scaled_down.add(wid)
        t = asyncio.get_running_loop().create_task(self._pipe_loop(h))
        self._bg.add(t)
        t.add_done_callback(self._bg.discard)

    async def stop(self, drain: bool = True) -> None:
        """SIGTERM every host agent (each drains its own workers), bounded
        wait, then killpg stragglers — the whole domain, never just the
        agent."""
        self._stopping = True
        if self._health_task is not None:
            self._health_task.cancel()
            try:
                await self._health_task
            except asyncio.CancelledError:
                pass
            self._health_task = None
        for t in list(self._bg):
            t.cancel()
        if self._bg:
            await asyncio.gather(*self._bg, return_exceptions=True)
        live = [h for h in self.hosts if h is not None and h.proc.is_alive()]
        for h in live:
            h.proc.terminate()
        budget = (self.cfg.drain_timeout_s if drain else 2.0) + 2.0
        deadline = time.monotonic() + budget
        while any(h.proc.is_alive() for h in live) \
                and time.monotonic() < deadline:
            await asyncio.sleep(0.05)
        for h in live:
            if h.proc.is_alive():
                try:
                    os.killpg(h.pgid, signal.SIGKILL)
                except (OSError, ProcessLookupError):
                    h.proc.kill()
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(
            None, lambda: [h.proc.join(10.0) for h in live])
        for hid, h in enumerate(self.hosts):
            if h is not None:
                h.close()
            self._g_host_up[hid].set(0.0)
        for wid in range(self.n):
            self._g_worker_up[wid].set(0.0)
        if self._session is not None:
            await self._session.close()
            self._session = None

    # -- pipe events ---------------------------------------------------------
    async def _pipe_loop(self, h: HostHandle) -> None:
        loop = asyncio.get_running_loop()
        while not self._stopping and self.hosts[h.hid] is h:
            msg = await loop.run_in_executor(None, _poll_recv, h.conn, 0.25)
            if msg is _EOF:
                return  # agent gone; the liveness sweep reaps the host
            if msg is None or self.hosts[h.hid] is not h:
                continue
            op = msg.get("op")
            if op == "worker_down":
                self._on_worker_down(h, int(msg["wid"]), msg)
            elif op == "worker_up":
                self._on_worker_up(h, int(msg["wid"]), int(msg["port"]),
                                   int(msg["pid"]))
            elif op == "worker_scaled_down":
                self._on_worker_scaled_down(h, int(msg["wid"]))

    def _on_worker_down(self, h: HostHandle, wid: int, msg: dict) -> None:
        log.warning("%s: worker %d died (exit %s); agent respawning in "
                    "%.1fs", host_name(h.hid), wid, msg.get("exitcode"),
                    msg.get("eta_s", 0.0))
        self.deaths_total += 1
        ref = h.workers.get(wid)
        if ref is not None:
            ref.up = False
            ref.healthy = False
        self._g_worker_up[wid].set(0.0)
        self._g_worker_inflight[wid].set(0.0)
        if self.postmortems is not None:
            # The agent already folded the black box into the pipe message
            # (tail + snapshot read on ITS machine) — pure bookkeeping
            # here, safe on the loop.
            self.postmortems.add(
                "worker", f"worker{wid}",
                msg.get("pid", ref.pid if ref is not None else None),
                msg.get("exitcode"),
                stderr_tail=msg.get("stderr_tail"),
                snapshot=msg.get("snapshot"),
                worker=wid, host=h.hid, respawn_eta_s=msg.get("eta_s"))

    def _on_worker_up(self, h: HostHandle, wid: int, port: int,
                      pid: int) -> None:
        ref = WorkerRef(wid, h.hid, port, pid, self.cfg.worker.host)
        h.workers[wid] = ref
        self._refs[wid] = ref
        self._scaled_down.discard(wid)
        self._c_worker_respawns[wid].inc()
        self._g_worker_up[wid].set(1.0)
        log.info("%s: worker %d respawned (pid %d, port %d)",
                 host_name(h.hid), wid, pid, port)

    def _on_worker_scaled_down(self, h: HostHandle, wid: int) -> None:
        """A scale-down drain completed: intentionally-released capacity,
        not a death — no deaths_total, no postmortem."""
        ref = h.workers.get(wid)
        if ref is not None:
            ref.up = False
            ref.healthy = False
        self._scaled_down.add(wid)
        self._g_worker_up[wid].set(0.0)
        self._g_worker_inflight[wid].set(0.0)
        log.info("%s: worker %d scaled down", host_name(h.hid), wid)

    # -- scaling (the autopilot's actuator) -----------------------------------
    def active_slots(self, hid: int) -> int:
        return self._active[hid]

    def scale_domain(self, hid: int, active: int) -> dict:
        """Set one host domain's active worker-slot target. Raises
        ValueError on a bad target, RuntimeError when the host is down
        (its respawn will honor the previous target)."""
        if not 0 <= hid < self.n_hosts:
            raise ValueError(f"no host domain {hid} (hosts: {self.n_hosts})")
        if not 1 <= active <= self.per_host:
            raise ValueError(
                f"active must be in [1, {self.per_host}], got {active}")
        h = self.hosts[hid]
        if h is None or not h.proc.is_alive():
            raise RuntimeError(f"{host_name(hid)} is down")
        before = self._active[hid]
        self._active[hid] = active
        h.conn.send({"op": "scale", "active": active})
        return {"host": hid, "active_before": before, "active": active,
                "max_slots": self.per_host}

    def scale_state(self) -> list[dict]:
        """Per-domain scaling signal for the autopilot collector: live
        state, active/max slots, healthy count, and summed in-flight."""
        out = []
        for hid in range(self.n_hosts):
            h = self.hosts[hid]
            up = h is not None and h.proc.is_alive()
            healthy = inflight = 0
            if up:
                for ref in h.workers.values():
                    if ref.up and ref.healthy:
                        healthy += 1
                        inflight += ref.inflight
            out.append({"host": hid, "up": up,
                        "active": self._active[hid],
                        "max_slots": self.per_host,
                        "healthy": healthy, "inflight": inflight})
        return out

    # -- liveness / health ---------------------------------------------------
    def sweep(self) -> int:
        """Watchdog hook (event loop, non-blocking): reap host slots whose
        AGENT process died and schedule their backoff respawns. A dead
        agent's process group is killpg'd first so no straggler worker
        outlives its failure domain."""
        if self._stopping:
            return 0
        died = 0
        for hid, h in enumerate(self.hosts):
            if h is not None and not h.proc.is_alive():
                died += 1
                self._on_host_dead(hid, h,
                                   f"agent exited (code {h.proc.exitcode})")
        return died

    def _on_host_dead(self, hid: int, h: HostHandle, why: str) -> None:
        log.error("%s (pgid %d) is DOWN: %s — %d worker(s) lost with it",
                  host_name(hid), h.pgid, why,
                  sum(1 for r in h.workers.values() if r.up))
        try:
            os.killpg(h.pgid, signal.SIGKILL)  # no orphan half-domain
        except (OSError, ProcessLookupError):
            pass
        self._schedule_host_postmortem(hid, h)
        self.host_deaths_total += 1
        for ref in h.workers.values():
            if ref.up:
                self.deaths_total += 1
            ref.up = False
            ref.healthy = False
            self._g_worker_up[ref.wid].set(0.0)
            self._g_worker_inflight[ref.wid].set(0.0)
        h.close()
        self.hosts[hid] = None
        self._g_host_up[hid].set(0.0)
        self._schedule_respawn(hid)

    def _schedule_host_postmortem(self, hid: int, h: HostHandle) -> None:
        """Fold a dead DOMAIN into one postmortem record: the agent's exit
        code/signal + stderr tail, plus every lost worker's last black-box
        snapshot (an agent killed wholesale cannot report them over the
        pipe, so the router reads the slot files itself). File IO on an
        executor thread."""
        if self.postmortems is None:
            return
        exitcode = h.proc.exitcode
        agent_pid = h.pid
        worker_rows = [(r.wid, r.pid,
                        self._worker_cfgs[r.wid].events.snapshot_path)
                       for r in h.workers.values()]
        stderr_path = (os.path.join(resolve_blackbox_dir(self.cfg.events),
                                    f"{host_name(hid)}.stderr")
                       if self.cfg.events.enabled else None)
        loop = asyncio.get_running_loop()

        def _collect() -> None:
            workers = [{"worker": wid, "pid": pid,
                        "snapshot": read_snapshot(snap or None)}
                       for wid, pid, snap in worker_rows]
            self.postmortems.capture_blocking(
                "host", host_name(hid), agent_pid, exitcode,
                stderr_path=stderr_path, host=hid, workers=workers,
                workers_lost=len(worker_rows))

        async def _capture() -> None:
            await loop.run_in_executor(None, _collect)

        t = loop.create_task(_capture())
        self._bg.add(t)
        t.add_done_callback(self._bg.discard)

    def _schedule_respawn(self, hid: int) -> None:
        if self._stopping or hid in self._respawning:
            return
        self._respawning.add(hid)
        t = asyncio.get_running_loop().create_task(self._respawn(hid))
        self._bg.add(t)
        t.add_done_callback(self._bg.discard)

    async def _respawn(self, hid: int) -> None:
        loop = asyncio.get_running_loop()
        try:
            while not self._stopping:
                delay = min(self.rcfg.respawn_max_s,
                            self.rcfg.respawn_initial_s
                            * self.rcfg.respawn_multiplier ** self._fails[hid])
                self._g_host_backoff[hid].set(delay)
                self._next_up_at[hid] = time.monotonic() + delay
                await asyncio.sleep(delay)
                if self._stopping:
                    return
                try:
                    h = await loop.run_in_executor(
                        None, self._spawn_host_blocking, hid)
                except Exception:
                    self._fails[hid] += 1
                    log.exception("%s respawn failed (consecutive "
                                  "failures: %d)", host_name(hid),
                                  self._fails[hid])
                    continue
                self._fails[hid] = 0
                self._g_host_backoff[hid].set(0.0)
                self._c_host_respawns[hid].inc()
                self._adopt_host(h)
                log.info("%s respawned (pgid %d, workers %s)",
                         host_name(hid), h.pgid, sorted(h.workers))
                return
        except asyncio.CancelledError:
            raise
        finally:
            self._respawning.discard(hid)

    async def _health_loop(self) -> None:
        while True:
            await asyncio.sleep(self.rcfg.health_interval_s)
            try:
                for hid, h in enumerate(self.hosts):
                    if h is not None and not h.proc.is_alive():
                        self._on_host_dead(
                            hid, h, f"agent exited (code {h.proc.exitcode})")
                await asyncio.gather(
                    *(self._probe(r) for r in self._live_refs()))
            except asyncio.CancelledError:
                raise
            except Exception:  # one bad cycle must not end health checking
                log.exception("host health probe cycle failed")

    async def _probe(self, ref: WorkerRef) -> None:
        try:
            async with self._session.get(f"{ref.base_url}/healthz") as r:
                ok = r.status == 200
                await r.read()
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 — refused/reset/timeout all count
            ok = False
        if ok:
            if not ref.healthy:
                log.info("worker %d healthy again", ref.wid)
            ref.health_fails = 0
            ref.healthy = True
        else:
            ref.health_fails += 1
            if ref.healthy and ref.health_fails >= self.rcfg.unhealthy_after:
                log.warning("worker %d unhealthy after %d failed probes — "
                            "routing around it", ref.wid, ref.health_fails)
                ref.healthy = False
        self._g_worker_up[ref.wid].set(1.0 if ref.up and ref.healthy else 0.0)

    # -- host breaker --------------------------------------------------------
    def host_tripped(self, hid: int) -> bool:
        return time.monotonic() < self._hb_until[hid]

    def note_transport_failure(self, ref) -> None:
        """Relay-observed connection refused/reset against one of this
        host's workers. Threshold consecutive failures trip the host
        breaker: every worker on the host sheds from pick() for the
        cooldown, then half-opens (the next pick is the probe). This is
        what routes around a freshly SIGKILLed machine in milliseconds —
        health probes take a cycle, refused connections don't."""
        if self.rcfg.host_breaker_threshold <= 0:
            return
        hid = getattr(ref, "host", None)
        if hid is None:
            return
        self._hb_fails[hid] += 1
        if self._hb_fails[hid] >= self.rcfg.host_breaker_threshold:
            if not self.host_tripped(hid):
                log.warning("%s breaker OPEN after %d consecutive transport "
                            "failures; shedding picks for %.1fs",
                            host_name(hid), self._hb_fails[hid],
                            self.rcfg.host_breaker_cooldown_s)
            self._hb_until[hid] = (time.monotonic()
                                   + self.rcfg.host_breaker_cooldown_s)
            self._g_host_breaker[hid].set(1.0)

    def note_success(self, ref) -> None:
        hid = getattr(ref, "host", None)
        if hid is None or self._hb_fails[hid] == 0:
            return
        self._hb_fails[hid] = 0
        self._hb_until[hid] = 0.0
        self._g_host_breaker[hid].set(0.0)

    # -- routing -------------------------------------------------------------
    def _live_refs(self):
        for h in self.hosts:
            # The agent-liveness check matters between a killpg and the
            # next sweep: a freshly dead host's refs must not count as
            # live for admin fan-outs (the flat supervisor makes the same
            # per-call is_alive check).
            if h is None or not h.proc.is_alive():
                continue
            for ref in h.workers.values():
                if ref.up:
                    yield ref

    def healthy_workers(self) -> list[WorkerRef]:
        return [r for r in self._live_refs() if r.healthy]

    def live_workers(self) -> list[WorkerRef]:
        """Every worker on a live host (unhealthy-but-up included): the
        admin fan-out set."""
        return list(self._live_refs())

    def worker_by_id(self, wid: int) -> WorkerRef | None:
        ref = self._refs.get(wid)
        if ref is None or not ref.up:
            return None
        h = self.hosts[ref.host]
        if h is None or h.workers.get(wid) is not ref:
            return None
        return ref

    def host_of(self, ref) -> int | None:
        return getattr(ref, "host", None)

    def down_domains(self) -> list[str]:
        """Dead/respawning failure domains: whole hosts, plus workers the
        host agent is still re-booting. A fleet reload must refuse while
        any exists — a respawn serves the BOOT config and would diverge
        from a freshly published version (docs/ROBUSTNESS.md)."""
        out = [host_name(hid) for hid, h in enumerate(self.hosts)
               if h is None or not h.proc.is_alive()]
        for h in self.hosts:
            if h is None:
                continue
            # Scaled-down slots are intentionally cold capacity, not a
            # recovering failure domain — they never block a reload.
            out.extend(f"{host_name(h.hid)}:worker{r.wid}"
                       for r in h.workers.values()
                       if not r.up and r.wid not in self._scaled_down)
        return out

    def pick(self, exclude: set[int] = frozenset(),
             exclude_hosts: set[int] = frozenset()) -> WorkerRef | None:
        """Least-loaded healthy worker on an untripped host, skipping
        ``exclude`` wids and ``exclude_hosts`` domains (the hedge rule: a
        hedge and its primary must not share a failure domain)."""
        best: WorkerRef | None = None
        for h in self.hosts:
            if h is None or h.hid in exclude_hosts \
                    or self.host_tripped(h.hid):
                continue
            for ref in h.workers.values():
                if not ref.up or not ref.healthy or ref.wid in exclude:
                    continue
                if best is None \
                        or (ref.inflight, ref.picked_seq) < (best.inflight,
                                                             best.picked_seq):
                    best = ref
        if best is not None:
            self._pick_seq += 1
            best.picked_seq = self._pick_seq
        return best

    def track_inflight(self, ref: WorkerRef, delta: int) -> None:
        ref.inflight += delta
        self._g_worker_inflight[ref.wid].set(ref.inflight)

    def respawn_eta_s(self) -> float:
        """Minimum respawn ETA across everything dead — respawning hosts
        first (the big capacity), worker-level agent respawns otherwise —
        the live Retry-After basis when no worker is healthy."""
        now = time.monotonic()
        etas = [max(0.0, self._next_up_at[hid] - now)
                for hid in self._respawning]
        if etas:
            return min(etas)
        return self.rcfg.health_interval_s

    # -- introspection -------------------------------------------------------
    def stats(self) -> dict:
        """The /stats ``workers`` block, host-sharded form."""
        now = time.monotonic()
        host_rows = []
        worker_rows = []
        for hid in range(self.n_hosts):
            h = self.hosts[hid]
            if h is None:
                host_rows.append({
                    "host": hid, "name": host_name(hid),
                    "state": "respawning" if hid in self._respawning
                    else "down",
                    "consecutive_boot_failures": self._fails[hid],
                    "respawn_eta_s": round(
                        max(0.0, self._next_up_at[hid] - now), 3),
                    "respawns_total": self._c_host_respawns[hid].value,
                })
                for wid in self._host_wids(hid):
                    worker_rows.append({"worker": wid, "host": hid,
                                        "state": "down"})
                continue
            rows = []
            for wid in self._host_wids(hid):
                ref = h.workers.get(wid)
                if ref is None or not ref.up:
                    row = {"worker": wid, "host": hid,
                           "state": "scaled_down"
                           if wid in self._scaled_down else "down"}
                else:
                    row = {
                        "worker": wid, "host": hid,
                        "state": "ready" if ref.healthy else "unhealthy",
                        "pid": ref.pid, "port": ref.port,
                        "inflight": ref.inflight,
                        "health_fails": ref.health_fails,
                        "uptime_s": round(now - ref.started_at, 1),
                    }
                rows.append(row)
                worker_rows.append(row)
            host_rows.append({
                "host": hid, "name": host_name(hid),
                "state": "tripped" if self.host_tripped(hid) else "up",
                "pgid": h.pgid, "pid": h.pid,
                "uptime_s": round(now - h.started_at, 1),
                "active_slots": self._active[hid],
                "respawns_total": self._c_host_respawns[hid].value,
                "workers": rows,
            })
        return {
            "configured": self.n,
            "healthy": len(self.healthy_workers()),
            "deaths_total": self.deaths_total,
            "hosts_configured": self.n_hosts,
            "hosts_up": sum(1 for h in self.hosts
                            if h is not None and h.proc.is_alive()),
            "host_deaths_total": self.host_deaths_total,
            "hosts": host_rows,
            "workers": worker_rows,
        }
