"""Horizontal router tier (ISSUE 13): N router processes, one port, one
consistent-hash-sharded result cache.

One router process is one SIGKILL away from zero availability no matter how
many workers it fronts. This module makes the router tier itself horizontal:

- **SO_REUSEPORT fan-in** — every router binds the SAME serving port with
  ``SO_REUSEPORT`` (PR 11's listener machinery one tier up); the kernel
  spreads connections, an external LB needs exactly one address, and a dead
  router just stops receiving new connections while its siblings keep
  serving.
- **Consistent-hash cache sharding** (``HashRing``) — every cache key has
  ONE owning router. A router holding a miss for a key it doesn't own
  forwards the whole request to the owner's peer listener over loopback
  HTTP, so the owner's cache + single-flight lead the computation: N
  identical concurrent misses through N different routers still cost ONE
  worker execution, and a byte-identical re-upload hits no matter which
  router the kernel handed it to. When the owner is unreachable the hop
  **degrades to local-only** — counted in ``cache_peer_errors_total``,
  never surfaced as an error — so a router death costs shard locality, not
  availability.
- **Peer supervision** — router 0 (the primary) owns the worker/host
  supervisor and supervises the peer router processes with the same
  exponential respawn backoff (``router_up``/``router_respawns_total``); a
  respawned peer re-syncs topology and rejoins the ring.
- **Topology sync** (``TopologyClient``) — peers poll the primary's
  ``/peer/state`` for worker addresses, ring membership, and cache
  generations; a fleet reload additionally pushes an invalidation to every
  live peer so no router serves a stale generation for longer than one
  sync interval even if the push is lost.

Ownership: every structure here is mutated on its router's event loop only
(blocking spawns/pipe reads run on executors) — no lock to witness. The
hash ring itself is immutable once built; membership changes build a new
one.
"""

from __future__ import annotations

import asyncio
import bisect
import hashlib
import logging
import multiprocessing as mp
import os
import time

from tpuserve.config import ServerConfig
from tpuserve.obs import Metrics
from tpuserve.workerproc.hosts import WorkerRef

log = logging.getLogger("tpuserve.workerproc")

_VNODES = 64

# Tenant identity crosses the router tier as one header (ISSUE 16): the
# ingress router resolves the client's X-Api-Key ONCE and forwards the
# resolved tenant name on cache-shard hops, so the owning router charges
# the right cache partition without re-authenticating. The peer listener
# is loopback-only — the header is unforgeable from outside.
TENANT_HEADER = "X-Tenant"


def _point(data: str) -> int:
    return int.from_bytes(
        hashlib.blake2b(data.encode(), digest_size=8).digest(), "big")


class HashRing:
    """Consistent-hash ring over router ids. ``vnodes`` virtual points per
    member keep the key space balanced; membership changes move only the
    keys adjacent to the joining/leaving member's points (the property that
    makes a router respawn cheap: the survivors' shards stay put)."""

    def __init__(self, members: dict[int, str], vnodes: int = _VNODES) -> None:
        self.members = dict(members)
        self._points: list[tuple[int, int]] = sorted(
            (_point(f"router{rid}:{v}"), rid)
            for rid in self.members for v in range(vnodes))

    def owner(self, key: str) -> tuple[int, str] | None:
        """(router id, peer url) owning ``key``, or None on an empty ring."""
        if not self._points:
            return None
        h = _point(key)
        i = bisect.bisect_left(self._points, (h, -1)) % len(self._points)
        rid = self._points[i][1]
        return rid, self.members[rid]


# ---------------------------------------------------------------------------
# Peer-side worker view (synced from the primary)
# ---------------------------------------------------------------------------

class PassiveWorkerView:
    """A peer router's view of the worker fleet: addresses + health synced
    from the primary's ``/peer/state``, refined by locally observed
    transport failures. Exposes the same routing surface as the real
    supervisors but owns no process — the primary does the supervising."""

    def __init__(self, cfg: ServerConfig, metrics: Metrics) -> None:
        self.cfg = cfg
        self.rcfg = cfg.router
        self.metrics = metrics
        self.n = cfg.router.workers * (cfg.router.hosts or 1)
        self._refs: dict[int, WorkerRef] = {}
        self._local_bad: set[int] = set()
        self._pick_seq = 0
        self.deaths_total = 0
        self.synced_at = 0.0

    def update(self, rows: list[dict]) -> None:
        """Apply one topology snapshot. Locally observed badness is wiped:
        the primary's health probes are the authority, and a snapshot is at
        most one sync interval old."""
        seen = set()
        for row in rows:
            wid = int(row["wid"])
            seen.add(wid)
            ref = self._refs.get(wid)
            if ref is None or ref.base_url != row["url"]:
                ref = WorkerRef(wid, row.get("host"), 0, int(row.get("pid", 0)),
                                "127.0.0.1")
                ref.base_url = row["url"]
                self._refs[wid] = ref
            ref.up = True
            ref.healthy = bool(row.get("healthy", True))
        for wid, ref in self._refs.items():
            if wid not in seen:
                ref.up = False
                ref.healthy = False
        self._local_bad.clear()
        self.synced_at = time.monotonic()

    # -- routing surface -----------------------------------------------------
    def healthy_workers(self) -> list[WorkerRef]:
        return [r for r in self._refs.values() if r.up and r.healthy]

    def live_workers(self) -> list[WorkerRef]:
        return [r for r in self._refs.values() if r.up]

    def worker_by_id(self, wid: int) -> WorkerRef | None:
        ref = self._refs.get(wid)
        return ref if ref is not None and ref.up else None

    def host_of(self, ref) -> int | None:
        return getattr(ref, "host", None)

    def down_domains(self) -> list[str]:
        return []  # admin fan-outs run on the primary, never here

    def note_transport_failure(self, ref) -> None:
        """Mark a worker locally bad until the next topology sync — don't
        keep relaying at a corpse for the rest of the sync interval."""
        ref.healthy = False
        self._local_bad.add(ref.wid)

    def note_success(self, ref) -> None:
        if ref.wid in self._local_bad:
            self._local_bad.discard(ref.wid)
            ref.healthy = True

    def pick(self, exclude: set[int] = frozenset(),
             exclude_hosts: set[int] = frozenset()) -> WorkerRef | None:
        best: WorkerRef | None = None
        for ref in self._refs.values():
            if not ref.up or not ref.healthy or ref.wid in exclude:
                continue
            if ref.host is not None and ref.host in exclude_hosts:
                continue
            if best is None \
                    or (ref.inflight, ref.picked_seq) < (best.inflight,
                                                         best.picked_seq):
                best = ref
        if best is not None:
            self._pick_seq += 1
            best.picked_seq = self._pick_seq
        return best

    def track_inflight(self, ref: WorkerRef, delta: int) -> None:
        ref.inflight += delta

    def respawn_eta_s(self) -> float:
        return self.rcfg.health_interval_s

    def sweep(self) -> int:
        return 0

    def stats(self) -> dict:
        return {
            "configured": self.n,
            "healthy": len(self.healthy_workers()),
            "deaths_total": self.deaths_total,
            "view": "peer",
            "synced_age_s": round(time.monotonic() - self.synced_at, 3)
            if self.synced_at else None,
            "workers": [{
                "worker": r.wid, "host": r.host,
                "state": ("ready" if r.healthy else "unhealthy") if r.up
                else "down",
                "inflight": r.inflight,
            } for r in sorted(self._refs.values(), key=lambda r: r.wid)],
        }


# ---------------------------------------------------------------------------
# Topology sync (peer side)
# ---------------------------------------------------------------------------

class TopologyClient:
    """Polls the primary's ``/peer/state`` and applies it to a peer
    RouterState (worker view, hash ring, cache generations)."""

    def __init__(self, state, primary_peer_url: str,
                 interval_s: float) -> None:
        self.state = state
        self.url = primary_peer_url.rstrip("/")
        self.interval_s = interval_s
        self._task: asyncio.Task | None = None
        self._c_errors = state.metrics.counter("peer_sync_errors_total")
        self._c_syncs = state.metrics.counter("peer_syncs_total")

    async def start(self, boot_timeout_s: float = 30.0) -> None:
        """Boot sync, then the poll task. Called AFTER the ready handshake:
        the sync is retried until the observed ring is COMPLETE — contains
        this router and all ``[router] routers`` members — so a peer never
        opens its public listener with a ring that would mis-shard keys
        (the primary adopts peers as their handshakes land; a sibling still
        booting keeps the ring short for a moment). On timeout with ANY
        topology, proceed degraded — the poll loop heals membership; with
        none at all, raise (the primary respawns us)."""
        state = self.state
        want = state.rcfg.routers
        deadline = time.monotonic() + boot_timeout_s
        while True:
            try:
                await self.sync()
                ring = state.ring
                if ring is not None and state.router_id in ring.members \
                        and len(ring.members) >= want:
                    break
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — primary not up yet
                pass
            if time.monotonic() >= deadline:
                if state.ring is None:
                    raise RuntimeError(
                        f"router {state.router_id}: no topology from "
                        f"{self.url} within {boot_timeout_s:.0f}s")
                log.warning("router %d: boot ring incomplete (%d/%d "
                            "members); serving degraded until the poll "
                            "sync heals it", state.router_id,
                            len(state.ring.members), want)
                break
            await asyncio.sleep(0.1)
        self._task = asyncio.get_running_loop().create_task(self._loop())

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.interval_s)
            try:
                await self.sync()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — keep last-known topology
                self._c_errors.inc()

    async def sync(self) -> None:
        import aiohttp

        async with self.state._session.get(
                f"{self.url}/peer/state",
                timeout=aiohttp.ClientTimeout(total=2.0)) as r:
            if r.status != 200:
                raise RuntimeError(f"/peer/state answered {r.status}")
            data = await r.json()
        self.state.apply_topology(data)
        self._c_syncs.inc()

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None


# ---------------------------------------------------------------------------
# Peer router process + primary-side supervision
# ---------------------------------------------------------------------------

def peer_main(cfg: ServerConfig, router_id: int, public_host: str,
              public_port: int, primary_peer_url: str, conn,
              stderr_path: str = "") -> None:
    """Peer-router process entry (multiprocessing spawn target). Device-free
    like every router: it builds no models, owns no workers — it binds the
    shared public port with SO_REUSEPORT, owns its cache shard, and relays
    to the worker addresses it syncs from the primary. ``stderr_path``
    (ISSUE 15) captures this process's stderr for the primary's postmortem
    reader."""
    from tpuserve.server import configure_logging
    from tpuserve.telemetry.events import redirect_stderr

    redirect_stderr(stderr_path,
                    f"router {router_id} boot pid {os.getpid()} "
                    f"ts {time.time():.3f}")

    configure_logging(cfg)
    log.info("peer router %d: starting (pid %d)", router_id, os.getpid())
    try:
        asyncio.run(_peer_serve(cfg, router_id, public_host, public_port,
                                primary_peer_url, conn))
    except Exception as e:  # noqa: BLE001 — report any death upward
        try:
            conn.send({"op": "died", "error": f"{type(e).__name__}: {e}"})
        except (BrokenPipeError, OSError):
            pass
        raise
    finally:
        try:
            conn.close()
        except OSError:
            pass


async def _peer_serve(cfg: ServerConfig, router_id: int, public_host: str,
                      public_port: int, primary_peer_url: str,
                      conn) -> None:
    import signal as _signal
    import socket as _socket

    from aiohttp import web

    from tpuserve.workerproc.router import RouterState, make_router_app

    state = RouterState(cfg, router_id=router_id,
                        primary_peer_url=primary_peer_url)
    await state.start()  # session + peer listener (no public serving yet)

    # Handshake FIRST: the primary can only add this router to the ring
    # once it knows the peer port. Then sync until the ring is complete,
    # and only then open the public listener — a peer never takes public
    # traffic with a ring that would mis-shard keys.
    conn.send({"op": "ready", "peer_port": state.peer_port,
               "pid": os.getpid()})
    # Peer handshakes are fast (no model builds): a ring that is still
    # incomplete after 30s means a sibling died at boot — serve degraded
    # and let the poll sync heal membership when it respawns.
    await state.topo.start(
        boot_timeout_s=min(30.0, cfg.router.spawn_timeout_s))

    sock = _socket.socket(_socket.AF_INET, _socket.SOCK_STREAM)
    try:
        sock.setsockopt(_socket.SOL_SOCKET, _socket.SO_REUSEPORT, 1)
        sock.bind((public_host, public_port))
    except OSError:
        sock.close()
        await state.stop()
        raise
    runner = web.AppRunner(make_router_app(state, own_lifecycle=False),
                           access_log=None)
    await runner.setup()
    site = web.SockSite(runner, sock)
    await site.start()
    log.info("peer router %d serving on %s:%d (peer port %d, ring %s)",
             router_id, public_host, public_port, state.peer_port,
             sorted(state.ring.members) if state.ring else None)

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (_signal.SIGTERM, _signal.SIGINT):
        try:
            loop.add_signal_handler(sig, stop.set)
        except (NotImplementedError, RuntimeError):
            pass

    async def _watch_parent() -> None:
        # The primary vanished (pipe EOF): drain and exit rather than keep
        # a half-fleet serving with no supervisor.
        from tpuserve.workerproc.hosts import _EOF, _poll_recv

        while True:
            msg = await loop.run_in_executor(None, _poll_recv, conn, 0.25)
            if msg is _EOF:
                stop.set()
                return
            if msg is not None and msg.get("op") == "stop":
                stop.set()
                return

    watcher = loop.create_task(_watch_parent())
    try:
        await stop.wait()
        await state.drain()
    finally:
        watcher.cancel()
        await asyncio.gather(watcher, return_exceptions=True)
        await runner.cleanup()
        await state.stop()


class PeerHandle:
    """Primary-side handle for one live peer router process."""

    __slots__ = ("rid", "proc", "conn", "peer_port", "peer_url", "pid",
                 "started_at")

    def __init__(self, rid: int, proc, conn, peer_port: int,
                 pid: int) -> None:
        self.rid = rid
        self.proc = proc
        self.conn = conn
        self.peer_port = peer_port
        self.peer_url = f"http://127.0.0.1:{peer_port}"
        self.pid = pid
        self.started_at = time.monotonic()

    def close(self) -> None:
        try:
            self.conn.close()
        except OSError:
            pass


class PeerRouterSupervisor:
    """Spawns and supervises the N-1 peer router processes (router 0 is
    the caller). Same liveness-sweep + exponential-backoff respawn pattern
    as the worker supervisor; ``on_change`` fires on every membership
    change so the primary rebuilds its hash ring."""

    def __init__(self, cfg: ServerConfig, metrics: Metrics,
                 on_change, postmortems=None) -> None:
        self.cfg = cfg
        self.rcfg = cfg.router
        self.metrics = metrics
        self.on_change = on_change
        self.postmortems = postmortems
        self.rids = list(range(1, cfg.router.routers))
        self.peers: dict[int, PeerHandle] = {}
        self._fails = {rid: 0 for rid in self.rids}
        self._next_up_at = {rid: 0.0 for rid in self.rids}
        self._respawning: set[int] = set()
        self._bg: set[asyncio.Task] = set()
        self._stopping = False
        self.deaths_total = 0
        self._public: tuple[str, int] | None = None
        self._primary_peer_url: str | None = None
        self._g_up = {rid: metrics.router_up_gauge(rid) for rid in self.rids}
        self._c_respawns = {rid: metrics.router_respawns_counter(rid)
                            for rid in self.rids}

    async def start(self, public_host: str, public_port: int,
                    primary_peer_url: str) -> None:
        self._public = (public_host, public_port)
        self._primary_peer_url = primary_peer_url
        loop = asyncio.get_running_loop()
        spawned = await asyncio.gather(
            *(loop.run_in_executor(None, self._spawn_blocking, rid)
              for rid in self.rids))
        for h in spawned:
            self.peers[h.rid] = h
            self._g_up[h.rid].set(1.0)
        if spawned:
            self.on_change()
        log.info("peer routers up: %s",
                 [f"{h.rid}@{h.peer_port}" for h in spawned])

    def _peer_stderr_path(self, rid: int) -> str:
        """The peer router's stderr capture file (ISSUE 15); "" when the
        event plane is off."""
        if not self.cfg.events.enabled:
            return ""
        from tpuserve.telemetry.events import resolve_blackbox_dir

        return os.path.join(resolve_blackbox_dir(self.cfg.events),
                            f"router{rid}.stderr")

    def _spawn_blocking(self, rid: int) -> PeerHandle:
        ctx = mp.get_context("spawn")
        parent, child = ctx.Pipe()
        host, port = self._public
        proc = ctx.Process(
            target=peer_main,
            args=(self.cfg, rid, host, port, self._primary_peer_url, child,
                  self._peer_stderr_path(rid)),
            daemon=True, name=f"tpuserve-router-{rid}")
        proc.start()
        child.close()
        try:
            if not parent.poll(self.rcfg.spawn_timeout_s):
                raise TimeoutError(
                    f"peer router {rid} not ready after "
                    f"{self.rcfg.spawn_timeout_s:.0f}s")
            msg = parent.recv()
            if msg.get("op") != "ready":
                raise RuntimeError(f"peer router {rid} failed at boot: {msg}")
        except BaseException:
            if proc.is_alive():
                proc.kill()
            proc.join(5.0)
            parent.close()
            raise
        if self._stopping:
            proc.kill()
            proc.join(5.0)
            parent.close()
            raise RuntimeError(
                f"supervisor stopping; discarded peer router {rid}")
        return PeerHandle(rid, proc, parent, int(msg["peer_port"]),
                          int(msg.get("pid", proc.pid)))

    def members(self) -> dict[int, str]:
        """Live ring members among the peers (the primary adds itself)."""
        return {rid: h.peer_url for rid, h in self.peers.items()
                if h.proc.is_alive()}

    def sweep(self) -> int:
        """Watchdog hook: reap dead peer routers, drop them from the ring,
        respawn with backoff."""
        if self._stopping:
            return 0
        died = 0
        for rid in list(self.peers):
            h = self.peers[rid]
            if not h.proc.is_alive():
                died += 1
                log.error("peer router %d (pid %d) died (code %s)",
                          rid, h.pid, h.proc.exitcode)
                self.deaths_total += 1
                self._schedule_postmortem(rid, h)
                h.close()
                del self.peers[rid]
                self._g_up[rid].set(0.0)
                self.on_change()
                self._schedule_respawn(rid)
        return died

    def _schedule_postmortem(self, rid: int, h: PeerHandle) -> None:
        """A dead peer router gets the same forensics as a dead worker:
        exit code/signal + its stderr-capture tail (ISSUE 15). Peers write
        no black-box snapshots — they own no models, so the stderr tail
        and the primary's event ring are the evidence."""
        if self.postmortems is None:
            return
        exitcode = h.proc.exitcode
        stderr_path = self._peer_stderr_path(rid) or None
        loop = asyncio.get_running_loop()

        async def _capture() -> None:
            await loop.run_in_executor(
                None, lambda: self.postmortems.capture_blocking(
                    "router", f"router{rid}", h.pid, exitcode,
                    stderr_path=stderr_path, router=rid))

        t = loop.create_task(_capture())
        self._bg.add(t)
        t.add_done_callback(self._bg.discard)

    def _schedule_respawn(self, rid: int) -> None:
        if self._stopping or rid in self._respawning:
            return
        self._respawning.add(rid)
        t = asyncio.get_running_loop().create_task(self._respawn(rid))
        self._bg.add(t)
        t.add_done_callback(self._bg.discard)

    async def _respawn(self, rid: int) -> None:
        loop = asyncio.get_running_loop()
        try:
            while not self._stopping:
                delay = min(self.rcfg.respawn_max_s,
                            self.rcfg.respawn_initial_s
                            * self.rcfg.respawn_multiplier ** self._fails[rid])
                self._next_up_at[rid] = time.monotonic() + delay
                await asyncio.sleep(delay)
                if self._stopping:
                    return
                try:
                    h = await loop.run_in_executor(
                        None, self._spawn_blocking, rid)
                except Exception:
                    self._fails[rid] += 1
                    log.exception("peer router %d respawn failed "
                                  "(consecutive failures: %d)",
                                  rid, self._fails[rid])
                    continue
                self.peers[rid] = h
                self._fails[rid] = 0
                self._g_up[rid].set(1.0)
                self._c_respawns[rid].inc()
                self.on_change()
                log.info("peer router %d respawned (pid %d, peer port %d)",
                         rid, h.pid, h.peer_port)
                return
        except asyncio.CancelledError:
            raise
        finally:
            self._respawning.discard(rid)

    async def stop(self) -> None:
        self._stopping = True
        for t in list(self._bg):
            t.cancel()
        if self._bg:
            await asyncio.gather(*self._bg, return_exceptions=True)
        live = [h for h in self.peers.values() if h.proc.is_alive()]
        for h in live:
            h.proc.terminate()
        deadline = time.monotonic() + self.cfg.drain_timeout_s + 2.0
        while any(h.proc.is_alive() for h in live) \
                and time.monotonic() < deadline:
            await asyncio.sleep(0.05)
        for h in live:
            if h.proc.is_alive():
                h.proc.kill()
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(
            None, lambda: [h.proc.join(10.0) for h in live])
        for rid, h in list(self.peers.items()):
            h.close()
            self._g_up[rid].set(0.0)

    def stats(self) -> dict:
        now = time.monotonic()
        rows = []
        for rid in self.rids:
            h = self.peers.get(rid)
            if h is None or not h.proc.is_alive():
                rows.append({
                    "router": rid,
                    "state": "respawning" if rid in self._respawning
                    else "down",
                    "respawn_eta_s": round(
                        max(0.0, self._next_up_at[rid] - now), 3),
                    "respawns_total": self._c_respawns[rid].value,
                })
            else:
                rows.append({
                    "router": rid, "state": "up", "pid": h.pid,
                    "peer_port": h.peer_port,
                    "uptime_s": round(now - h.started_at, 1),
                    "respawns_total": self._c_respawns[rid].value,
                })
        return {"configured": len(self.rids) + 1,
                "deaths_total": self.deaths_total,
                "peers": rows}
