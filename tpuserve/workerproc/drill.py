"""Kill-worker chaos drill (``python -m tpuserve chaos --drill worker_kill``;
PAPERS.md P6 — a resilience property you haven't injected a fault against
is a hope, not a property).

The drill serves a REAL router + N worker processes on an ephemeral port,
drives the closed-loop load generator at one model, then SIGKILLs one
worker mid-load (uncatchable — exactly a native crash / OOM kill) and
measures the properties the process split promises:

- **availability** — n_ok / (n_ok + n_err) over the whole run, kill
  included, must hold the bound (default >= 99%): in-flight requests on
  the victim surface as transport errors the router retries on survivors.
- **respawn_s** — time from the SIGKILL until the victim's slot is healthy
  again; gated against the configured backoff plus a spawn budget.
- **torn / duplicate responses** — a validator task runs one known payload
  in a closed loop throughout and byte-compares every 200 body against a
  pre-kill reference (workers build identical seeded weights, so answers
  are deterministic): any mismatch is a torn or mixed response, and every
  validator request is counted exactly once, so a duplicated answer would
  surface as a protocol error. Both must be zero.
"""

from __future__ import annotations

import asyncio
import logging
import os
import signal
import time

from tpuserve.config import ServerConfig

log = logging.getLogger("tpuserve.workerproc")


async def _validator(url: str, payload: bytes, ctype: str, ref: bytes,
                     stop: asyncio.Event, out: dict) -> None:
    """Closed-loop correctness probe: every 200 body must equal the
    reference byte-for-byte; non-200s are availability's business."""
    import aiohttp

    async with aiohttp.ClientSession() as session:
        while not stop.is_set():
            try:
                async with session.post(
                        url, data=payload, headers={"Content-Type": ctype},
                        timeout=aiohttp.ClientTimeout(total=30.0)) as r:
                    body = await r.read()
                    if r.status == 200:
                        out["validated"] += 1
                        if body != ref:
                            out["mismatched"] += 1
                            log.error("torn/mixed response: %r != ref",
                                      body[:128])
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — resets count via loadgen
                out["transport_errors"] += 1
            await asyncio.sleep(0.01)


async def run_worker_kill_drill(cfg: ServerConfig, model_name: str | None = None,
                                duration_s: float = 20.0, warmup_s: float = 1.0,
                                concurrency: int = 16,
                                kill_after_s: float | None = None,
                                respawn_budget_s: float = 120.0) -> dict:
    """Serve a router fleet, SIGKILL one worker mid-load, report the
    availability / respawn / integrity numbers. The caller owns asserting
    the bounds (CLI gates availability; scripts/worker_drill.sh gates the
    rest)."""
    from aiohttp import web

    from tpuserve.bench.loadgen import run_load, synthetic_image_npy
    from tpuserve.workerproc.router import RouterState, make_router_app

    cfg.router.enabled = True
    cfg.router.workers = max(2, cfg.router.workers)
    # Every validated response must be a real execution: a cache would
    # happily serve perfect answers from a fleet of corpses.
    cfg.cache.enabled = False
    model = model_name or cfg.models[0].name

    state = RouterState(cfg)
    app = make_router_app(state)
    runner = web.AppRunner(app, access_log=None)
    await runner.setup()  # on_startup spawns the fleet
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = runner.addresses[0][1]
    url = f"http://127.0.0.1:{port}/v1/models/{model}:predict"
    payload = synthetic_image_npy(edge=cfg.model(model).wire_size)
    ctype = "application/x-npy"

    kill_info: dict = {}
    integrity = {"validated": 0, "mismatched": 0, "transport_errors": 0}
    stop_validator = asyncio.Event()
    loop = asyncio.get_running_loop()

    async def _reference() -> bytes:
        import aiohttp

        async with aiohttp.ClientSession() as s:
            async with s.post(url, data=payload,
                              headers={"Content-Type": ctype}) as r:
                body = await r.read()
                if r.status != 200:
                    raise RuntimeError(
                        f"reference request failed: {r.status} {body[:200]}")
                return body

    async def _killer() -> None:
        await asyncio.sleep(warmup_s + (kill_after_s
                                        if kill_after_s is not None
                                        else duration_s * 0.25))
        victim = state.supervisor.pick()
        if victim is None:
            kill_info["error"] = "no healthy worker to kill"
            return
        wid, pid = victim.wid, victim.pid
        log.warning("drill: SIGKILL worker %d (pid %d)", wid, pid)
        t0 = time.monotonic()
        os.kill(pid, signal.SIGKILL)
        kill_info.update(killed_worker=wid, killed_pid=pid)
        deadline = t0 + respawn_budget_s
        while time.monotonic() < deadline:
            h = state.supervisor.slots[wid]
            if h is not None and h.pid != pid and h.healthy:
                kill_info["respawn_s"] = round(time.monotonic() - t0, 2)
                return
            await asyncio.sleep(0.05)
        kill_info["respawn_s"] = None  # did not come back in budget

    try:
        ref = await _reference()
        validator_task = loop.create_task(
            _validator(url, payload, ctype, ref, stop_validator, integrity))
        load_task = loop.create_task(
            run_load(url, payload, ctype, duration_s, concurrency, warmup_s))
        kill_task = loop.create_task(_killer())
        result = await load_task
        await kill_task
        stop_validator.set()
        await validator_task
        workers = state.supervisor.stats()
    finally:
        await runner.cleanup()  # on_cleanup -> state.stop() -> fleet drain

    out = result.summary()
    total = result.n_ok + result.n_err
    out["availability"] = round(result.n_ok / total, 5) if total else 0.0
    out["drill"] = "worker_kill"
    out["kill"] = kill_info
    out["integrity"] = integrity
    out["workers"] = workers
    out["router"] = {
        "retries_total": state.handles[model].retries.value,
        "hedges_total": state.handles[model].hedges.value,
        "respawn_budget_s": respawn_budget_s,
        "respawn_backoff_initial_s": cfg.router.respawn_initial_s,
    }
    return out
